"""Shared CLI plumbing for example models (reference per-example ``main()``,
e.g. ``examples/paxos.rs:314-395``): subcommands ``check [args]``,
``check-sym``, ``explore [addr]``, ``spawn``, with positional arguments.
Beyond the reference's verbs: ``check-tpu`` / ``check-sym-tpu`` (device
engines; ``--checked`` runs them under checkify instrumentation —
``CheckerBuilder.checked()``, the sanitizer's dynamic guard),
``check-auto`` (measured engine selection, ``CheckerBuilder.spawn_auto``),
``audit`` (the static preflight auditor, ``stateright_tpu/analysis/``),
``sanitize`` (the interval/bounds soundness sanitizer, JX2xx rules —
``docs/analysis.md``), and ``profile`` (a telemetry-instrumented run:
flight-recorder JSONL + optional Chrome trace,
``stateright_tpu/telemetry/``, ``docs/telemetry.md``).

Fleet mode — ``python -m stateright_tpu.models._cli audit|sanitize
[MODULE...]`` — audits/sanitizes every shipped example (each module
exposes ``_audit_models()``), printing one report per configuration and
exiting non-zero on any error-severity finding; CI gates on both.
``python -m stateright_tpu.models._cli profile [MODULE] [--out=F]
[--chrome=F] [ARGS...]`` profiles one example's configurations through
the same ``_audit_models`` hook (CI runs it as a smoke and uploads the
JSONL as a workflow artifact).
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Iterable, Optional


def run_cli(
    usage: str,
    check: Callable[[list], None],
    check_sym: Optional[Callable[[list], None]] = None,
    check_tpu: Optional[Callable[[list], None]] = None,
    check_sym_tpu: Optional[Callable[[list], None]] = None,
    check_auto: Optional[Callable[[list], None]] = None,
    explore: Optional[Callable[[list], None]] = None,
    spawn: Optional[Callable[[list], None]] = None,
    audit: Optional[Callable[[list], None]] = None,
    profile: Optional[Callable[[list], None]] = None,
    sanitize: Optional[Callable[[list], None]] = None,
    report: Optional[Callable[[list], None]] = None,
    independence: Optional[Callable[[list], None]] = None,
    capacity: Optional[Callable[[list], None]] = None,
    costmodel: Optional[Callable[[list], None]] = None,
    compare: Optional[Callable[[list], None]] = None,
    supervise: Optional[Callable[[list], None]] = None,
    sweep: Optional[Callable[[list], None]] = None,
    argv: Optional[list] = None,
) -> None:
    argv = sys.argv[1:] if argv is None else argv
    cmd = argv[0] if argv else None
    rest = argv[1:]
    if cmd == "check":
        check(rest)
    elif cmd == "check-sym" and check_sym is not None:
        check_sym(rest)
    elif cmd == "check-tpu" and check_tpu is not None:
        check_tpu(rest)
    elif cmd == "check-sym-tpu" and check_sym_tpu is not None:
        check_sym_tpu(rest)
    elif cmd == "check-auto" and check_auto is not None:
        check_auto(rest)
    elif cmd == "explore" and explore is not None:
        explore(rest)
    elif cmd == "spawn" and spawn is not None:
        spawn(rest)
    elif cmd == "audit" and audit is not None:
        audit(rest)
    elif cmd == "profile" and profile is not None:
        profile(rest)
    elif cmd == "sanitize" and sanitize is not None:
        sanitize(rest)
    elif cmd == "report" and report is not None:
        report(rest)
    elif cmd == "independence" and independence is not None:
        independence(rest)
    elif cmd == "capacity" and capacity is not None:
        capacity(rest)
    elif cmd == "costmodel" and costmodel is not None:
        costmodel(rest)
    elif cmd == "compare" and compare is not None:
        compare(rest)
    elif cmd == "supervise" and supervise is not None:
        supervise(rest)
    elif cmd == "sweep" and sweep is not None:
        sweep(rest)
    else:
        print("USAGE:")
        print(usage)
        if check_tpu is not None:
            print("  device verbs also take --checked, --prewarm, "
                  "--prededup, --por, --per-channel, --spill, --mxu, "
                  "--mesh, --compile-cache=DIR "
                  "(docs/perf.md, docs/analysis.md, docs/spill.md, "
                  "docs/roofline.md) and "
                  "--watch (live status line, docs/telemetry.md)")
        if audit is not None:
            print("  <example> audit    # static preflight audit "
                  "(docs/analysis.md)")
        if sanitize is not None:
            print("  <example> sanitize # interval/bounds soundness "
                  "sanitizer (docs/analysis.md JX2xx)")
        if independence is not None:
            print("  <example> independence # static independence / "
                  "conflict-matrix analysis (docs/analysis.md JX3xx)")
        if profile is not None:
            print("  <example> profile [--out=F] [--chrome=F] [ARGS]  "
                  "# telemetry run (docs/telemetry.md)")
        if report is not None:
            print("  <example> report [--out=F] [ARGS]  "
                  "# post-run report: JSON + markdown (docs/telemetry.md)")
        if capacity is not None:
            print("  <example> capacity [ARGS]  # HBM capacity plan: "
                  "analytic footprint per growth rung (docs/telemetry.md)")
        if costmodel is not None:
            print("  <example> costmodel [--out=F] [--mxu] [ARGS]  "
                  "# roofline cost ledger: per-stage FLOPs/bytes, XLA "
                  "reconciliation, MXU candidates; --mxu prices the "
                  "recast program (docs/roofline.md)")
        if compare is not None:
            print("  <example> compare A B [--registry=DIR] "
                  "[--expect=VERDICT]  # contract-aware run diff: "
                  "report files or registry run ids "
                  "(docs/telemetry.md \"Comparing runs\")")
        if sweep is not None:
            print("  <example> sweep [N] [--runs=DIR] [--batch=N] "
                  "[--steps=N] [--capacity=N]  # hyper-batched instance "
                  "sweep: one compiled program per shape cohort checks "
                  "the whole family (docs/sweep.md)")
        if supervise is not None:
            print("  <example> supervise [ARGS] --autosave=DIR "
                  "[--every=SECS] [--keep=K] [--max-restarts=N] "
                  "[--runs=DIR] [--batch=N] [--steps=N] "
                  "[--fault-plan=F] [--fault-log=F]  "
                  "# supervised run: periodic atomic checkpoints + "
                  "retry/backoff resume (docs/robustness.md)")


def pop_checked(rest: list) -> tuple:
    """Strip ``--checked`` from a verb's arguments: ``(checked, rest)``.
    The device verbs pass the flag to ``CheckerBuilder.checked()`` — the
    sanitizer's dynamic guard (``docs/analysis.md``)."""
    rest = list(rest)
    checked = "--checked" in rest
    while "--checked" in rest:
        rest.remove("--checked")
    return checked, rest


def pop_perf(rest: list) -> tuple:
    """Strip the wavefront-throughput flags (``docs/perf.md``) from a device
    verb's arguments: ``(cfg, rest)`` where ``cfg`` holds ``prewarm``/
    ``prededup`` (bool) and ``compile_cache`` (dir or None).  Apply with
    :func:`apply_perf`.  Env knobs (``STATERIGHT_TPU_PREWARM`` etc.) still
    work without the flags — these exist so one-off CLI runs can A/B."""
    rest = list(rest)
    cfg = {"prewarm": False, "prededup": False, "compile_cache": None,
           "por": False, "spill": False, "per_channel": False,
           "mxu": False, "mesh": False}
    kept = []
    for a in rest:
        if a == "--prewarm":
            cfg["prewarm"] = True
        elif a == "--prededup":
            cfg["prededup"] = True
        elif a == "--mxu":
            cfg["mxu"] = True
        elif a == "--mesh":
            cfg["mesh"] = True
        elif a == "--por":
            cfg["por"] = True
        elif a == "--spill":
            cfg["spill"] = True
        elif a == "--per-channel":
            cfg["per_channel"] = True
        elif a.startswith("--compile-cache="):
            cfg["compile_cache"] = a[len("--compile-cache="):]
        else:
            kept.append(a)
    return cfg, kept


def apply_perf(builder, cfg: dict):
    """Apply a :func:`pop_perf` config onto a ``CheckerBuilder``.
    ``per_channel`` is NOT applied here — it is a model-level encoding
    choice that must land before the tensor twin resolves; device verbs
    call :func:`apply_encoding` on the model first."""
    if cfg.get("prewarm"):
        builder = builder.prewarm()
    if cfg.get("prededup"):
        builder = builder.prededup()
    if cfg.get("por"):
        builder = builder.por()
    if cfg.get("spill"):
        builder = builder.spill()
    if cfg.get("mxu"):
        builder = builder.mxu()
    if cfg.get("mesh"):
        builder = builder.mesh()
    if cfg.get("compile_cache"):
        builder = builder.compile_cache(cfg["compile_cache"])
    return builder


def apply_encoding(model, cfg: dict):
    """Apply the :func:`pop_perf` ``--per-channel`` flag onto the MODEL
    (``ActorModel.per_channel_()``): the per-(src,dst)-channel network
    packing for the compiled device twin (docs/analysis.md "Per-channel
    encoding").  Must run before the twin resolves — the encoding is the
    fingerprint scheme.  Models without the builder method (non-actor
    models like 2pc) get a LOUD one-liner instead of a silent no-op —
    an ignored flag must never masquerade as "per-channel buys
    nothing"."""
    if cfg.get("per_channel"):
        if hasattr(model, "per_channel_"):
            model.per_channel_()
        else:
            print(
                f"stateright-tpu: --per-channel ignored: "
                f"{type(model).__name__} is not an actor model (the "
                "encoding applies to compiled actor twins; "
                "docs/analysis.md)",
                file=sys.stderr,
            )
    return model


# -- live watch view (--watch on the device verbs) ---------------------------


def pop_watch(rest: list) -> tuple:
    """Strip ``--watch`` from a verb's arguments: ``(watch, rest)``.
    Apply with :func:`apply_watch` + :func:`watch_checker`."""
    rest = list(rest)
    watch = "--watch" in rest
    while "--watch" in rest:
        rest.remove("--watch")
    return watch, rest


def apply_watch(builder, watch: bool):
    """Arm a builder for the live watch view: the status line reads the
    health model, the cartography block, and the HBM ledger, so
    ``--watch`` implies ``.telemetry(cartography=True, memory=True)``
    (docs/telemetry.md)."""
    if not watch:
        return builder
    return builder.cartography().memory_ledger()


def watch_line(checker) -> str:
    """One live status line: depth, cumulative counters, smoothed
    throughput, table load, HBM footprint (vs the device budget when one
    is known), health phase (+ stall / OOM-risk flags), drain ETA."""
    rec = checker.flight_recorder
    h = rec.health() if rec is not None else {}
    last = (rec.last_step() if rec is not None else None) or {}
    sps = h.get("ewma_states_per_sec")
    load = last.get("load_factor")
    depth = last.get("depth", checker.max_depth())
    parts = [
        f"depth={depth}",
        f"states={checker.state_count()}",
        f"unique={checker.unique_state_count()}",
        f"states/s={sps if sps is not None else '-'}",
        f"load={load if load is not None else '-'}",
        f"hbm={_watch_hbm(rec)}",
        f"phase={h.get('phase', '-')}",
    ]
    sp = _watch_spill(rec)
    if sp:
        parts.append(f"spill={sp}")
    dur_fn = getattr(checker, "durability_status", None)
    dur = dur_fn() if callable(dur_fn) else None
    if dur:
        auto = dur.get("autosave") or {}
        age = auto.get("last_checkpoint_age_secs")
        if auto:
            parts.append(
                "ckpt=" + ("-" if age is None else f"{age:.0f}s")
            )
        if dur.get("restarts"):
            parts.append(f"restarts={dur['restarts']}")
    if h.get("spill_degraded"):
        parts.append("SPILL-DEGRADED(disk tier lost; host RAM only)")
    if h.get("stalled"):
        parts.append(f"STALLED({h.get('stall_reason') or '?'})")
    if h.get("oom_risk"):
        parts.append("OOM-RISK(next growth rung does not fit)")
    if h.get("spill_forecast"):
        parts.append("spill-forecast(next rung evicts to host)")
    if h.get("eta_secs") is not None:
        parts.append(f"eta={h['eta_secs']}s")
    return " ".join(parts)


def _watch_hbm(rec) -> str:
    """The ``hbm=`` column: live device bytes when the backend reports
    them, else the ledger's analytic carry bytes; '/budget (x%)' when a
    budget is known.  '-' when the run has no memory ledger."""
    mem = rec.memory() if rec is not None else None
    if not mem:
        return "-"
    from ..telemetry.memory import fmt_bytes

    live = mem.get("device") or {}
    used = live.get("bytes_in_use", mem.get("total_bytes"))
    budget = mem.get("budget_bytes")
    if budget:
        return (
            f"{fmt_bytes(used)}/{fmt_bytes(budget)}"
            f"({100.0 * used / budget:.1f}%)"
        )
    return fmt_bytes(used)


def _watch_spill(rec) -> str:
    """The ``spill=`` column: spilled-state count + per-tier bytes once
    the tier has evicted anything; '' when the tier is off or idle."""
    sp = rec.spill() if rec is not None else None
    if not sp or not sp.get("spilled_fps"):
        return ""
    from ..telemetry.memory import fmt_bytes

    out = (
        f"{sp['spilled_fps']}fp/host:{fmt_bytes(sp.get('host_bytes'))}"
    )
    if sp.get("disk_bytes"):
        out += f"/disk:{fmt_bytes(sp['disk_bytes'])}"
    return out


def watch_checker(
    checker, stream=None, interval: float = 0.25, plain_every: float = 2.0
):
    """Render the live status until the run completes, then one final
    line.  On a TTY the line rewrites in place (plain ``\\r`` + padding —
    no ANSI sequences, no dependencies); on a non-TTY stream it degrades
    to one plain line every ``plain_every`` seconds, so piped/CI output
    stays readable instead of turning into control-character soup."""
    import time

    stream = stream or sys.stderr
    tty = bool(getattr(stream, "isatty", lambda: False)())
    last_plain = -plain_every  # always emit the first line promptly
    width = 0

    def put(txt: str, end: str = "") -> None:
        nonlocal width
        if tty:
            pad = " " * max(width - len(txt), 0)
            stream.write("\r" + txt + pad + end)
            width = len(txt)
        else:
            stream.write(txt + "\n")
        stream.flush()

    t0 = time.monotonic()
    while not checker.is_done():
        now = time.monotonic() - t0
        if tty:
            put(watch_line(checker))
        elif now - last_plain >= plain_every:
            put(watch_line(checker))
            last_plain = now
        time.sleep(interval)
    put(watch_line(checker), end="\n")
    return checker


def spawn_watched(builder, watch: bool, spawn):
    """Device-verb helper: ``spawn`` is ``builder -> checker`` (async).
    With ``watch`` the live view renders until done; either way the
    joined checker is returned (callers chain ``.report()``)."""
    builder = apply_watch(builder, watch)
    checker = spawn(builder)
    if watch:
        watch_checker(checker)
    return checker


def default_threads() -> int:
    return os.cpu_count() or 1


# -- audit verb --------------------------------------------------------------


def audit_and_report(
    models: Iterable[tuple], stream=None, deep: bool = True
) -> bool:
    """Audit ``(label, model)`` pairs, print one report each; True iff no
    error-severity findings anywhere."""
    from ..analysis import audit_model

    stream = stream or sys.stdout
    ok = True
    for label, model in models:
        report = audit_model(model, deep=deep)
        print(f"--- {label}", file=stream)
        print(report.format(), file=stream)
        ok = ok and report.ok
    return ok


def make_audit_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as an ``audit``
    CLI verb that exits 1 on error findings."""

    def _audit(rest: list) -> None:
        if not audit_and_report(factory(rest)):
            raise SystemExit(1)

    return _audit


# -- sanitize verb -----------------------------------------------------------


def sanitize_and_report(
    models: Iterable[tuple], stream=None, deep: bool = False
) -> tuple:
    """Run the soundness sanitizer view over ``(label, model)`` pairs: one
    summary line + the JX2xx findings each.  Returns ``(ok, rule_ids)``:
    ``ok`` iff no error-severity JX2xx finding anywhere, ``rule_ids`` the
    machine-readable offending rules (the CLI exit path prints them, same
    contract as ``AuditError.rule_ids``).  The LIGHT audit tier suffices:
    the sanitizer runs in it, and the deep extras (closure probe, drift
    re-resolve) contribute no JX2xx findings — the fleet gate should not
    pay for them twice when CI also runs the audit gate."""
    from ..analysis import Severity, audit_model

    stream = stream or sys.stdout
    ok, bad_rules = True, set()
    for label, model in models:
        report = audit_model(model, deep=deep)
        summary = (report.metrics or {}).get("sanitizer")
        findings = [
            f for f in report.findings if f.rule_id.startswith("JX2")
        ]
        errors = [f for f in findings if f.severity == Severity.ERROR]
        print(f"--- {label}", file=stream)
        if summary is None:
            print(
                "sanitize: no device twin for this configuration "
                "(host checkers unaffected)",
                file=stream,
            )
        else:
            rules = ", ".join(summary.get("rules") or []) or "none"
            print(
                f"sanitize: {summary['sites']} indexed site(s) — "
                f"{summary['proved']} proved in range, "
                f"{summary['undecided']} undecided (checked-mode "
                f"candidates); rules fired: {rules}",
                file=stream,
            )
        for f in findings:
            print("  " + f.format(), file=stream)
        if errors:
            ok = False
            bad_rules.update(f.rule_id for f in errors)
    return ok, tuple(sorted(bad_rules))


def make_sanitize_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as a ``sanitize``
    CLI verb that exits 1 (naming the rule ids) on error findings."""

    def _sanitize(rest: list) -> None:
        ok, rules = sanitize_and_report(factory(rest))
        if not ok:
            print(f"sanitize: FAILED ({', '.join(rules)})")
            raise SystemExit(1)

    return _sanitize


def fleet_sanitize(names: Optional[list] = None, stream=None) -> int:
    """Sanitize the whole example fleet (or just ``names``); 0 iff no
    JX2xx error anywhere.  Same coverage contract as ``fleet_audit``: a
    module without ``_audit_models`` fails the gate rather than silently
    shrinking it."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    ok, bad = True, set()
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        mok, rules = sanitize_and_report(factory([]), stream=stream)
        ok = ok and mok
        bad.update(rules)
    verdict = "CLEAN" if ok else f"FAILED ({', '.join(sorted(bad))})"
    print(f"sanitize fleet: {verdict}", file=stream)
    return 0 if ok else 1


# -- independence verb -------------------------------------------------------


def independence_and_report(
    models: Iterable[tuple], stream=None
) -> tuple:
    """Static independence / conflict-matrix view over ``(label, model)``
    pairs (``analysis/independence.py``; docs/analysis.md JX3xx): one
    summary line + the JX3xx findings each.  Returns ``(ok, rule_ids)``:
    ``ok`` iff every twin-bearing model yields a WELL-FORMED conflict
    matrix (square, symmetric, dependent diagonal) and no error-severity
    JX3xx finding fires anywhere — the CI fleet gate's contract."""
    import numpy as _np

    from ..analysis import Severity, run_independence
    from ..parallel.tensor_model import twin_or_none

    stream = stream or sys.stdout
    ok, bad_rules = True, set()
    for label, model in models:
        twin = twin_or_none(model)
        print(f"--- {label}", file=stream)
        if twin is None:
            print(
                "independence: no device twin for this configuration "
                "(host checkers unaffected)",
                file=stream,
            )
            continue
        rep = run_independence(twin, list(model.properties()))
        s = rep.summary()
        c = _np.asarray(rep.conflict)
        well_formed = (
            c.ndim == 2
            and c.shape == (rep.n_actions, rep.n_actions)
            and bool(_np.array_equal(c, c.T))
            and bool(c.diagonal().all())
        )
        print(
            f"independence: {s['actions']} action(s), "
            f"{s['independent_pairs']} independent pair(s), "
            f"{s['visible_actions']} visible, "
            f"{s['undecided_actions']} undecided; "
            f"decomposed={s['decomposed']}"
            + (
                f"; encoding={s['encoding']}"
                if s.get("encoding") else ""
            )
            + f"; rules fired: {', '.join(s['rules']) or 'none'}",
            file=stream,
        )
        if not well_formed:
            ok = False
            print("  MALFORMED conflict matrix", file=stream)
        for f in rep.findings:
            print("  " + f.format(), file=stream)
            if f.severity == Severity.ERROR:
                ok = False
                bad_rules.add(f.rule_id)
    return ok, tuple(sorted(bad_rules))


def make_independence_cmd(
    factory: Callable[[list], Iterable[tuple]]
) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as an
    ``independence`` CLI verb that exits 1 on error findings or a
    malformed matrix."""

    def _independence(rest: list) -> None:
        ok, rules = independence_and_report(factory(rest))
        if not ok:
            print(f"independence: FAILED ({', '.join(rules) or 'matrix'})")
            raise SystemExit(1)

    return _independence


def fleet_independence(names: Optional[list] = None, stream=None) -> int:
    """Run the independence analysis over the whole example fleet (or
    just ``names``); 0 iff every bundled example produces a well-formed
    conflict matrix and no ERROR-level JX3xx finding.  Same coverage
    contract as ``fleet_audit``/``fleet_sanitize``: a module without
    ``_audit_models`` fails the gate."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    ok, bad = True, set()
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        mok, rules = independence_and_report(factory([]), stream=stream)
        ok = ok and mok
        bad.update(rules)
    verdict = "CLEAN" if ok else f"FAILED ({', '.join(sorted(bad)) or 'matrix'})"
    print(f"independence fleet: {verdict}", file=stream)
    return 0 if ok else 1


# -- capacity verb -----------------------------------------------------------


def capacity_and_report(
    models: Iterable[tuple], stream=None, spill: bool = False
) -> bool:
    """HBM capacity plan over ``(label, model)`` pairs
    (``telemetry/memory.py``; docs/telemetry.md "Memory ledger"): the
    analytic per-rung footprint ladder of the wavefront engine at its
    default spawn capacities, the growth-migration transient per rung,
    and — when a device budget is known (live ``memory_stats`` or the
    ``STATERIGHT_TPU_DEVICE_BYTES`` override) — the max reachable unique
    count before the run would spill.  ``spill=True`` (the ``--spill``
    flag) plans WITH the spill tier armed: ``max_unique`` extends past
    the largest-fitting rung by the host tier's reach (docs/spill.md)
    instead of capping at HBM/4.  Pure host arithmetic: no device run,
    no compile; on CPU (no budget) it degrades to the analytic table
    alone, never crashes.  Returns True iff every configuration produced
    a plan (twin-less models are reported and skipped)."""
    from ..parallel.tensor_model import twin_or_none
    from ..telemetry.memory import (
        capacity_plan,
        device_budget,
        fmt_bytes,
        wavefront_specs,
    )

    stream = stream or sys.stdout
    budget, src = device_budget()
    ok = True
    for label, model in models:
        print(f"--- {label}", file=stream)
        twin = twin_or_none(model)
        if twin is None:
            print(
                "capacity: no device twin for this configuration "
                "(host checkers hold states in host RAM)",
                file=stream,
            )
            continue
        n_props = len(list(model.properties()))
        # the wavefront engine's default spawn capacities
        # (parallel/wavefront.TpuChecker): the ladder starts where an
        # un-tuned spawn_tpu() starts
        cap, batch = 1 << 17, 1 << 11
        caps = {"cap": cap, "qcap": max(cap // 2, 4 * batch),
                "batch": batch}

        def spec_fn(c, twin=twin, n_props=n_props):
            return wavefront_specs(
                twin, n_props, int(c["cap"]), int(c["qcap"]),
                int(c["batch"]),
            )

        try:
            plan = capacity_plan(
                spec_fn, caps, budget=budget,
                rungs=24 if budget is not None else 10,
                spill=spill,
            )
        except Exception as e:  # noqa: BLE001 - a plan failure is a
            # verdict, not a crash (the CI smoke's contract)
            ok = False
            print(f"capacity: plan failed: {type(e).__name__}: {e}",
                  file=stream)
            continue
        if budget is not None:
            print(
                f"capacity plan (wavefront engine; device budget "
                f"{fmt_bytes(budget)}, {src}):",
                file=stream,
            )
        else:
            print(
                "capacity plan (wavefront engine; no device memory "
                "limit known — analytic footprint only; set "
                "STATERIGHT_TPU_DEVICE_BYTES to plan against a budget):",
                file=stream,
            )
        print(f"  {'capacity':>12}  {'carry':>9}  {'transient':>9}  fits",
              file=stream)
        for r in plan["rungs"]:
            fits = r.get("fits")
            print(
                f"  {r['capacity']:>12}  {fmt_bytes(r['total_bytes']):>9}"
                f"  {fmt_bytes(r['transient_bytes']):>9}  "
                f"{'-' if fits is None else ('yes' if fits else 'NO')}",
                file=stream,
            )
        sp = plan.get("spill")
        if sp is not None:
            print(
                f"with --spill, {label} reaches "
                f"~{sp['hot_max_unique']:,} unique states on-device, then "
                f"~{sp.get('host_max_unique', 0):,} more in the host tier "
                f"({fmt_bytes(sp.get('host_budget_bytes'))} at "
                f"{sp['bytes_per_spilled']}B/state), disk tier unbounded "
                f"behind it — max_unique ~{plan['max_unique']:,} "
                "(docs/spill.md)",
                file=stream,
            )
        elif plan.get("max_unique") is not None:
            print(
                f"on this device, {label} reaches ~{plan['max_unique']:,} "
                "unique states before spilling (largest rung whose "
                "growth transient fits; extend past it with --spill / "
                "CheckerBuilder.spill(), docs/spill.md)",
                file=stream,
            )
        elif budget is not None:
            print(
                f"on this device, {label} cannot hold even the first "
                "rung — shrink capacity= or raise the budget",
                file=stream,
            )
    return ok


def pop_spill(rest: list) -> tuple:
    """Strip ``--spill`` from a verb's arguments: ``(spill, rest)``."""
    rest = list(rest)
    spill = "--spill" in rest
    while "--spill" in rest:
        rest.remove("--spill")
    return spill, rest


def make_capacity_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as a ``capacity``
    CLI verb (exit 1 only when the plan itself crashes).  ``--spill``
    plans with the spill tier armed (docs/spill.md)."""

    def _capacity(rest: list) -> None:
        spill, rest = pop_spill(rest)
        if not capacity_and_report(factory(rest), spill=spill):
            raise SystemExit(1)

    return _capacity


def fleet_capacity(names: Optional[list] = None, stream=None) -> int:
    """Capacity-plan the whole example fleet (or just ``names``); 0 iff
    every module's configurations produced a plan (twin-less models are
    disclosed, not failures — host checkers have no device footprint)."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    spill, names = pop_spill(list(names or []))
    ok = True
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        ok = capacity_and_report(factory([]), stream=stream, spill=spill) and ok
    print("capacity fleet: " + ("OK" if ok else "FAILED"), file=stream)
    return 0 if ok else 1


# -- costmodel verb ----------------------------------------------------------

# the verb's trace/compile shapes: smaller than a default spawn so the
# fleet gate stays seconds-per-model (the static ledger scales linearly
# in batch — the RANKING and the reconciliation verdict are what the
# gate checks, and both are batch-stable)
_COSTMODEL_BATCH = 256
_COSTMODEL_CAP = 1 << 14


def costmodel_and_report(
    models: Iterable[tuple], stream=None, out=None, mxu: bool = False,
) -> bool:
    """Roofline cost ledger over ``(label, model)`` pairs
    (``analysis/costmodel.py`` + ``telemetry/roofline.py``;
    docs/roofline.md): per-stage FLOPs/bytes table with op classes and
    arithmetic intensity, memory-vs-compute-bound verdicts where a
    device spec is known (``STATERIGHT_TPU_DEVICE_SPEC``), the
    XLA-reconciliation verdict, and the JX4xx MXU-candidate findings.
    ``out`` collects the per-config live blocks into a JSON file (the
    schema round-trip fixture / CI artifact).  ``mxu`` prices the
    ``--mxu``-flagged engine program instead (docs/roofline.md
    "Executing the hot-spot list"): the coalesced expand kernel, the
    slim queue mirror, and the BLEST probe — landed-recast findings go
    silent (the JX305 pattern).  Returns True iff every
    twin-bearing configuration produced a well-formed, XLA-reconciling
    ledger (twin-less models are disclosed and skipped — host checkers
    have no device pipeline to price)."""
    import json

    from ..analysis.costmodel import wavefront_costs
    from ..ops.mxu import MxuConfig
    from ..parallel.tensor_model import twin_or_none
    from ..telemetry.memory import fmt_bytes
    from ..telemetry.roofline import classify_stages, device_spec

    stream = stream or sys.stdout
    spec = device_spec()
    ok = True
    blocks = []
    for label, model in models:
        print(f"--- {label}", file=stream)
        twin = twin_or_none(model)
        if twin is None:
            print(
                "costmodel: no device twin for this configuration "
                "(host checkers have no device pipeline)",
                file=stream,
            )
            continue
        try:
            rep = wavefront_costs(
                twin, _COSTMODEL_CAP, _COSTMODEL_CAP // 2,
                _COSTMODEL_BATCH,
                mxu=MxuConfig() if mxu else None,
            )
        except Exception as e:  # noqa: BLE001 - a ledger crash is a
            # verdict, not a crash (the capacity-verb contract)
            ok = False
            print(f"costmodel: ledger failed: {type(e).__name__}: {e}",
                  file=stream)
            continue
        if rep is None:
            ok = False
            print("costmodel: twin kernels did not trace (see the "
                  "structural audit)", file=stream)
            continue
        static = rep.static_block()
        recon = rep.recon_block()
        verdicts = classify_stages(static, spec)
        print(
            f"costmodel: {len(static['stages'])} stage(s), "
            f"{static['totals']['flops']:,} FLOPs / "
            f"{fmt_bytes(static['totals']['bytes'])} per step "
            f"(batch {static['batch']}); XLA reconciliation: "
            + ("ok" if recon["ok"] else "FAILED"),
            file=stream,
        )
        for name, s in static["stages"].items():
            v = verdicts.get(name, {})
            extra = (
                f" — {v['verdict']}"
                if v.get("verdict") not in (None, "unknown") else ""
            )
            print(
                f"  {name:>13}: {s['flops']:>12,} FLOPs  "
                f"{fmt_bytes(s['bytes_read'] + s['bytes_written']):>9}  "
                f"AI={s.get('intensity', '-')}" + extra,
                file=stream,
            )
        for f in rep.findings:
            print("  " + f.format(), file=stream)
        if not recon["ok"]:
            ok = False
            for name, v in recon["stages"].items():
                for p in v.get("problems", []):
                    print(f"  RECONCILE {name}: {p}", file=stream)
        blocks.append({
            "label": label, **static, "reconciliation": recon,
            **({"device_spec": spec} if spec else {}),
            "verdicts": verdicts,
        })
    if out:
        with open(out, "w") as f:
            json.dump({"v": blocks[0]["v"] if blocks else 1,
                       "configs": blocks}, f, indent=1)
            f.write("\n")
    return ok


def make_costmodel_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as a
    ``costmodel`` CLI verb (``--out=F`` collects the JSON blocks; exit 1
    on a malformed or non-reconciling ledger)."""

    def _costmodel(rest: list) -> None:
        out, _chrome, rest = _split_profile_args(rest, default_out="")
        mxu = "--mxu" in rest
        rest = [a for a in rest if a != "--mxu"]
        if not costmodel_and_report(
            factory(rest), out=out or None, mxu=mxu
        ):
            print("costmodel: FAILED")
            raise SystemExit(1)

    return _costmodel


def fleet_costmodel(args: Optional[list] = None, stream=None) -> int:
    """Roofline-cost-ledger the whole example fleet (or just the named
    modules); 0 iff every twin-bearing configuration produced a
    well-formed, XLA-reconciling ledger.  Same coverage contract as the
    other fleet gates: a module without ``_audit_models`` fails."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    out, _chrome, names = _split_profile_args(list(args or []),
                                              default_out="")
    ok = True
    blocks_out = out or None
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        # one --out file per module would clobber; the fleet gate
        # appends the module name when an out path is given
        mod_out = None
        if blocks_out:
            stem, ext = os.path.splitext(blocks_out)
            mod_out = f"{stem}-{name}{ext or '.json'}"
        ok = costmodel_and_report(
            factory([]), stream=stream, out=mod_out
        ) and ok
    print("costmodel fleet: " + ("OK" if ok else "FAILED"), file=stream)
    return 0 if ok else 1


# -- compare / runs verbs (telemetry/registry.py + telemetry/diff.py) --------


def _load_report_arg(arg: str, registry_dir: Optional[str]) -> tuple:
    """``(doc, headline)`` for one compare argument: a report JSON file
    path, or a run id resolved against the registry (``--registry=DIR``
    or ``STATERIGHT_TPU_RUN_DIR``).  Headline (wall-clock metrics) only
    exists for registry-resolved runs."""
    import json

    from ..telemetry.registry import RunRegistry, resolve_run_dir

    if os.path.isfile(arg):
        with open(arg) as f:
            return json.load(f), None
    root = resolve_run_dir(registry_dir)
    if root is None:
        raise SystemExit(
            f"compare: {arg!r} is neither a report file nor a resolvable "
            "run id (pass --registry=DIR or set STATERIGHT_TPU_RUN_DIR)"
        )
    reg = RunRegistry(root)
    doc = reg.find(arg)
    if doc is None:
        raise SystemExit(f"compare: run {arg!r} not found in {root}")
    return doc, reg.headline(arg)


def compare_reports_cmd(rest: list, stream=None) -> int:
    """The ``compare`` verb: contract-aware diff of two run reports
    (``telemetry/diff.py``; docs/telemetry.md "Comparing runs").

    Arguments are report JSON files or registry run ids.  Prints the
    human rendering plus one machine-readable JSON line (the diff
    document).  Exit 0 unless the pair classifies DIVERGENT (a promised
    contract is broken) or ``--expect=VERDICT`` names a different
    class."""
    import json

    from ..telemetry.diff import DIVERGENT, diff_reports, render_diff

    stream = stream or sys.stdout
    registry, expect, args = None, None, []
    for a in rest:
        if a.startswith("--registry="):
            registry = a[len("--registry="):]
        elif a.startswith("--expect="):
            expect = a[len("--expect="):].upper().replace("_", "-")
        else:
            args.append(a)
    if len(args) != 2:
        print(
            "usage: compare A B [--registry=DIR] [--expect=IDENTICAL|"
            "ISOMORPHIC|PERF-ONLY|DIVERGENT]  (A/B: report JSON files "
            "or registry run ids)",
            file=stream,
        )
        return 2
    a_doc, a_head = _load_report_arg(args[0], registry)
    b_doc, b_head = _load_report_arg(args[1], registry)
    d = diff_reports(a_doc, b_doc, a_headline=a_head, b_headline=b_head)
    print(render_diff(d, label_a=args[0], label_b=args[1]), file=stream)
    print(json.dumps(d), file=stream)
    if expect:
        # an explicit expectation is the whole judgement — including
        # --expect=DIVERGENT asserting a known-bad pair stays caught
        if d["verdict"] != expect:
            print(
                f"compare: verdict {d['verdict']} != expected {expect}",
                file=stream,
            )
            return 1
        return 0
    return 1 if d["verdict"] == DIVERGENT else 0


def make_compare_cmd() -> Callable:
    """The ``compare`` CLI verb (model-independent: it reads report
    artifacts, not models — every verb-bearing example mounts the same
    one so the A/B workflow stays next to the verbs that produce the
    reports)."""

    def _compare(rest: list) -> None:
        rc = compare_reports_cmd(rest)
        if rc:
            raise SystemExit(rc)

    return _compare


def pop_sweep_opts(rest: list) -> tuple:
    """Strip the sweep verb's flags: ``(opts, rest)`` — ``runs``
    (registry dir), ``batch``/``steps``/``capacity`` (engine knobs)."""
    opts = {"runs": None, "batch": None, "steps": None, "capacity": None}
    kept = []
    for a in rest:
        if a.startswith("--runs="):
            opts["runs"] = a[len("--runs="):]
        elif a.startswith("--batch="):
            opts["batch"] = int(a[len("--batch="):])
        elif a.startswith("--steps="):
            opts["steps"] = int(a[len("--steps="):])
        elif a.startswith("--capacity="):
            opts["capacity"] = int(a[len("--capacity="):])
        else:
            kept.append(a)
    return opts, kept


def make_sweep_cmd(
    family: Callable[[int], "object"], default_n: int = 8
) -> Callable:
    """The per-example ``sweep`` verb (docs/sweep.md): build the
    example's default family (``family(N)`` -> SweepSpec), run it as ONE
    device sweep, and print one line per instance plus the cohort/compile
    summary the CI smoke greps."""

    def cmd(rest):
        opts, rest = pop_sweep_opts(rest)
        n = int(rest[0]) if rest else default_n
        spec = family(n)
        print(
            f"Sweeping {len(spec.instances)} instances in one device run "
            "(docs/sweep.md)."
        )
        b = (
            spec.instances[0].model.checker()
            .telemetry(cartography=True)
            .sweep(spec)
        )
        if opts["runs"]:
            b = b.runs(opts["runs"])
        kw = {}
        if opts["batch"]:
            kw["batch"] = opts["batch"]
        if opts["steps"]:
            kw["steps_per_call"] = opts["steps"]
        if opts["capacity"]:
            kw["capacity"] = opts["capacity"]
        c = b.spawn_tpu(sync=True, **kw)
        c.join()
        for inst in spec.instances:
            r = c.results[inst.key]
            disc = ",".join(sorted(r.chains)) or "-"
            print(
                f"  {inst.key}: unique={r.unique} states={r.states} "
                f"depth={r.max_depth} discoveries=[{disc}]"
            )
        print(
            f"sweep: {len(spec.instances)} instances over "
            f"{len(c.cohorts)} cohort(s), "
            f"{c.engine_compiles} engine compile(s), total "
            f"unique={c.unique_state_count()} "
            f"states={c.state_count()}"
        )
        if opts["runs"]:
            print(
                f"sweep: registered {len(spec.instances)} instance "
                f"record(s) under sweep_id={c.run_id} in {opts['runs']}"
            )

    return cmd


def fleet_runs(args: Optional[list] = None, stream=None) -> int:
    """``runs [DIR]``: list the persistent run registry — one line per
    archived run (id, config_key, model/engine, headline) plus the
    per-config trend summary the Explorer's dashboard draws."""
    from ..telemetry.registry import RunRegistry, resolve_run_dir

    stream = stream or sys.stdout
    args = list(args or [])
    root = resolve_run_dir(args[0] if args else None)
    if root is None:
        print(
            "runs: no registry configured (pass DIR or set "
            "STATERIGHT_TPU_RUN_DIR)",
            file=stream,
        )
        return 2
    reg = RunRegistry(root)
    recs = reg.index()
    if not recs:
        print(f"runs: registry at {root} is empty", file=stream)
        return 0
    def line(r, indent: str = "") -> None:
        h = r.get("headline") or {}
        bits = [
            indent + str(r.get("run_id")),
            str(r.get("config_key") or "-"),
            f"{r.get('model')}/{r.get('engine')}",
            f"unique={h.get('unique')}",
            f"done={h.get('done')}",
        ]
        if r.get("instance_key"):
            bits.insert(1, f"[{r['instance_key']}]")
        elif r.get("job_key"):
            bits.insert(1, f"[{r['job_key']}]")
        if h.get("states_per_sec") is not None:
            bits.append(f"{h['states_per_sec']}/s")
        if r.get("leg"):
            bits.append(f"leg={r['leg']}")
        if r.get("parent_run_id"):
            bits.append(f"parent={r['parent_run_id']}")
        bits.append(str(r.get("generated_at") or ""))
        print("  ".join(bits), file=stream)

    # sweep members group under one header row with a per-instance
    # verdict strip ('*' = at least one discovery, '.' = none), in the
    # ledger's append order (docs/sweep.md); campaign jobs group the
    # same way (docs/fleet.md) and win when a record carries both tags
    # (a packed cohort member is a sweep instance owned by a campaign)
    groups: list = []
    by_group: dict = {}
    for r in recs:
        if r.get("campaign_id"):
            gid = ("campaign", r["campaign_id"], "job")
        elif r.get("sweep_id"):
            gid = ("sweep", r["sweep_id"], "instance")
        else:
            groups.append(r)
            continue
        g = by_group.get(gid)
        if g is None:
            g = by_group[gid] = {
                "kind": gid[0], "id": gid[1], "noun": gid[2],
                "members": [],
            }
            groups.append(g)
        g["members"].append(r)
    for g in groups:
        if "members" not in g:
            line(g)
            continue
        strip = "".join(
            "*" if (m.get("headline") or {}).get("discoveries") else "."
            for m in g["members"]
        )
        print(
            f"{g['kind']} {g['id']}  {len(g['members'])} {g['noun']}(s)"
            f"  verdicts [{strip}]",
            file=stream,
        )
        for m in g["members"]:
            line(m, indent="  ")
    trends = reg.trends(recs)
    print(
        f"runs: {len(recs)} archived over {len(trends)} config(s) at "
        f"{root}",
        file=stream,
    )
    for key, series in sorted(trends.items()):
        if len(series) > 1:
            u = [s.get("unique") for s in series]
            print(
                f"  trend {key}: {len(series)} runs, unique "
                f"{u[0]} -> {u[-1]}",
                file=stream,
            )
    return 0


# -- fleet / campaign verbs (fleet/; docs/fleet.md) --------------------------


def _pop_fleet_opts(rest: list, defaults: dict) -> tuple:
    """Strip the fleet/campaign verbs' shared flags: ``(opts, rest)``.
    ``--slots``/``--budget``/``--spill``/``--no-pack`` shape the pool,
    ``--root`` hosts autosaves + artifacts, ``--runs`` the registry,
    ``--every`` the autosave cadence, ``--stall=KEY@STEP`` the
    deterministic preemption injection (``--stall=none`` disables)."""
    opts = dict(defaults)
    kept = []
    for a in rest:
        if a.startswith("--slots="):
            opts["slots"] = int(a[len("--slots="):])
        elif a.startswith("--budget="):
            opts["budget"] = int(a[len("--budget="):])
        elif a == "--spill":
            opts["spill"] = True
        elif a == "--no-pack":
            opts["pack"] = False
        elif a.startswith("--root="):
            opts["root"] = a[len("--root="):]
        elif a.startswith("--runs="):
            opts["runs"] = a[len("--runs="):]
        elif a.startswith("--every="):
            opts["every"] = float(a[len("--every="):])
        elif a.startswith("--stall="):
            opts["stall"] = a[len("--stall="):]
        elif a.startswith("--max-restarts="):
            opts["max_restarts"] = int(a[len("--max-restarts="):])
        elif a.startswith("--id="):
            opts["id"] = a[len("--id="):]
        elif a.startswith("--grid="):
            opts["grid"] = a[len("--grid="):]
        else:
            kept.append(a)
    return opts, kept


def _canned_fleet_jobs(runs_dir: Optional[str]) -> list:
    """The ``fleet`` verb's six-tenant workload: three packable
    TwoPhaseSys(3) jobs (one cohort, one compile), a TwoPhaseSys(4)
    and a TwoPhaseSys(5) singleton, and a paxos single-client job —
    mixed shapes over one pool, per docs/fleet.md "The chaos smoke"."""
    from ..checker.base import CheckerBuilder
    from ..fleet import Job
    from .paxos import paxos_model
    from .two_phase_commit import TwoPhaseSys

    def twopc(n):
        def build():
            b = CheckerBuilder(TwoPhaseSys(n))
            return b.runs(runs_dir) if runs_dir else b
        return build

    def paxos():
        def build():
            b = CheckerBuilder(paxos_model(1))
            return b.runs(runs_dir) if runs_dir else b
        return build

    return [
        Job(key="2pc-a", build=twopc(3), packable=True,
            capacity=1 << 12, batch=256, params={"rm": 3}),
        Job(key="2pc-b", build=twopc(3), packable=True,
            capacity=1 << 12, batch=256, params={"rm": 3}),
        Job(key="2pc-c", build=twopc(3), packable=True,
            capacity=1 << 12, batch=256, params={"rm": 3}),
        Job(key="2pc-4", build=twopc(4),
            capacity=1 << 13, batch=256, params={"rm": 4}),
        Job(key="2pc-5", build=twopc(5), priority=1,
            capacity=1 << 14, batch=512, params={"rm": 5}),
        Job(key="paxos-1", build=paxos(),
            capacity=1 << 12, batch=256, params={"clients": 1}),
    ]


def _print_job_results(res, stream) -> None:
    """One grep-able line per job result (the CI smoke's contract)."""
    from ..fleet import COMPLETED

    for r in res.results.values():
        bits = [f"fleet job {r.key}: status={r.status}",
                f"decision={r.decision}"]
        if r.status == COMPLETED:
            bits += [f"unique={r.unique}", f"states={r.states}",
                     f"depth={r.max_depth}"]
        if r.cohort:
            bits.append(f"cohort={r.cohort}")
        if r.preemptions:
            bits.append(f"preemptions={r.preemptions}")
        if r.run_id:
            bits.append(f"run_id={r.run_id}")
        if r.parent_run_id:
            bits.append(f"parent_run_id={r.parent_run_id}")
        if r.reason:
            bits.append(f"reason={r.reason}")
        print("  ".join(bits), file=stream)


def _audit_lineage(res, runs_dir: Optional[str], stream) -> int:
    """Exactly-once audit: every preempted-then-completed job must
    compare IDENTICAL against its yielded parent (``contract:
    lineage``); returns the worst compare exit code."""
    from ..fleet import COMPLETED

    rc = 0
    for r in res.results.values():
        if not (r.preemptions and r.status == COMPLETED):
            continue
        if not (runs_dir and r.run_id and r.parent_run_id):
            print(
                f"fleet lineage {r.key}: UNVERIFIABLE (no registry or "
                "run ids; pass --runs=DIR)",
                file=stream,
            )
            rc = rc or 1
            continue
        print(
            f"fleet lineage {r.key}: parent={r.parent_run_id} "
            f"child={r.run_id}",
            file=stream,
        )
        code = compare_reports_cmd(
            [r.parent_run_id, r.run_id, f"--registry={runs_dir}",
             "--expect=IDENTICAL"],
            stream=stream,
        )
        rc = rc or code
    return rc


def fleet_schedule(args: Optional[list] = None, stream=None) -> int:
    """The ``fleet`` verb: canned multi-tenant chaos smoke — six mixed
    2pc/paxos jobs over a simulated N-slot pool with one injected
    stall-preemption (docs/fleet.md).  Every job must complete with its
    pinned counts and the preempted job's resume must compare IDENTICAL
    against its yielded parent (the line CI greps for ``contract:
    lineage``).  Exit 0 iff all jobs completed and lineage verified."""
    import tempfile

    from ..fleet import FleetSpec, PreemptionPlan, run_fleet

    stream = stream or sys.stdout
    opts, rest = _pop_fleet_opts(list(args or []), {
        "slots": 2, "budget": None, "spill": False, "pack": True,
        "root": None, "runs": None, "every": 0.0, "stall": "2pc-5@5",
        "max_restarts": 2,
    })
    if rest:
        print(f"fleet: unknown argument(s) {rest}", file=stream)
        return 2
    root = opts["root"] or tempfile.mkdtemp(prefix="stateright-tpu-fleet-")
    runs_dir = opts["runs"] or os.path.join(root, "runs")
    jobs = _canned_fleet_jobs(runs_dir)
    spec = FleetSpec(
        jobs=jobs, slots=opts["slots"],
        slot_budget_bytes=opts["budget"], spill=opts["spill"],
        pack=opts["pack"], max_restarts=opts["max_restarts"],
    )
    plan = None
    if opts["stall"] and opts["stall"] != "none":
        key, _, step = opts["stall"].partition("@")
        plan = PreemptionPlan({key: int(step or 3)})
        print(
            f"fleet: injecting a stall-preemption into {key} at step "
            f"{int(step or 3)}",
            file=stream,
        )
    print(
        f"fleet: {len(jobs)} job(s) over {spec.slots} slot(s) "
        f"(pack={spec.pack}, spill={spec.spill}, root={root})",
        file=stream,
    )
    res = run_fleet(
        spec, root=root, preemption=plan, every_secs=opts["every"],
        stream=stream,
    )
    _print_job_results(res, stream)
    print(
        f"fleet: completed={res.completed} failed={res.failed} "
        f"refused={res.refused} preemptions={res.preemptions} "
        f"engine_compiles={res.engine_compiles} "
        f"packed={sum(len(p['jobs']) for p in res.packed)} "
        f"secs={res.secs:.1f}",
        file=stream,
    )
    rc = 0 if (res.failed == 0 and res.refused == 0) else 1
    return rc or _audit_lineage(res, runs_dir, stream)


#: the campaign verb's named model factories: name -> (factory, default
#: grid).  Factories take grid-point params as keyword arguments.
_CAMPAIGN_FACTORIES = {
    "2pc": (
        lambda rm=3: __import__(
            "stateright_tpu.models.two_phase_commit",
            fromlist=["TwoPhaseSys"],
        ).TwoPhaseSys(rm),
        {"rm": [3, 4]},
    ),
    "paxos": (
        lambda clients=1: __import__(
            "stateright_tpu.models.paxos", fromlist=["paxos_model"],
        ).paxos_model(clients),
        {"clients": [1]},
    ),
}


def fleet_campaign(args: Optional[list] = None, stream=None) -> int:
    """The ``campaign`` verb: expand a parameter grid into fleet jobs,
    schedule them over the pool, and write the campaign ledger
    (docs/fleet.md "Campaigns").  ``campaign 2pc --grid='{"rm":[3,4]}'``
    checks TwoPhaseSys at both sizes under one campaign id; the ledger
    (per-job wall-clock, compile accounting, aggregate states/s) lands
    at ``ROOT/campaign.json``.  Exit 0 iff no job failed."""
    import json
    import tempfile

    from ..fleet import LEDGER_NAME, campaign_spec, run_campaign

    stream = stream or sys.stdout
    opts, rest = _pop_fleet_opts(list(args or []), {
        "slots": 2, "budget": None, "spill": False, "pack": True,
        "root": None, "runs": None, "every": 0.0, "stall": None,
        "max_restarts": 2, "id": None, "grid": None,
    })
    name = rest[0] if rest else "2pc"
    if name not in _CAMPAIGN_FACTORIES or len(rest) > 1:
        print(
            "usage: campaign [2pc|paxos] [--grid=JSON] [--root=DIR] "
            "[--runs=DIR] [--slots=N] [--budget=BYTES] [--spill] "
            "[--no-pack] [--id=CID]",
            file=stream,
        )
        return 2
    factory, grid = _CAMPAIGN_FACTORIES[name]
    if opts["grid"]:
        grid = json.loads(opts["grid"])
    root = opts["root"] or tempfile.mkdtemp(
        prefix="stateright-tpu-campaign-"
    )
    spec = campaign_spec(
        factory, grid, campaign_id=opts["id"],
        slots=opts["slots"], slot_budget_bytes=opts["budget"],
        spill=opts["spill"], pack=opts["pack"],
        max_restarts=opts["max_restarts"],
        run_dir=opts["runs"] or os.path.join(root, "runs"),
    )
    print(
        f"campaign {spec.campaign_id}: {len(spec.jobs)} job(s) from "
        f"grid {json.dumps(grid, sort_keys=True)} over {spec.slots} "
        f"slot(s) (root={root})",
        file=stream,
    )
    res, ledger = run_campaign(
        spec, root=root, every_secs=opts["every"], stream=stream,
    )
    _print_job_results(res, stream)
    print(
        f"campaign {spec.campaign_id}: completed={ledger['completed']} "
        f"failed={ledger['failed']} refused={ledger['refused']} "
        f"preemptions={ledger['preemptions']} "
        f"engine_compiles={ledger['engine_compiles']} "
        f"secs={ledger['secs']} total_states={ledger['total_states']} "
        f"states_per_sec={ledger['states_per_sec']}",
        file=stream,
    )
    print(
        f"campaign: ledger written to {os.path.join(root, LEDGER_NAME)}",
        file=stream,
    )
    return 0 if ledger["failed"] == 0 else 1


# -- supervise verb (supervisor.py; docs/robustness.md) ----------------------


def pop_supervise_opts(rest: list) -> tuple:
    """Strip the supervise verb's flags: ``(opts, rest)``.  ``opts``
    carries ``autosave`` (dir; a temp dir when omitted, printed so the
    operator can resume), ``every``/``keep`` (cadence), ``max_restarts``,
    ``runs`` (registry dir), and ``fault_plan``/``fault_log`` (chaos:
    a JSON FaultPlan to install, and where to dump its fired trail)."""
    opts = {
        "autosave": None, "every": 60.0, "keep": 3, "max_restarts": 5,
        "runs": None, "fault_plan": None, "fault_log": None,
        "batch": None, "steps": None,
    }
    kept = []
    for a in rest:
        if a.startswith("--autosave="):
            opts["autosave"] = a[len("--autosave="):]
        elif a.startswith("--batch="):
            opts["batch"] = int(a[len("--batch="):])
        elif a.startswith("--steps="):
            opts["steps"] = int(a[len("--steps="):])
        elif a.startswith("--every="):
            opts["every"] = float(a[len("--every="):])
        elif a.startswith("--keep="):
            opts["keep"] = int(a[len("--keep="):])
        elif a.startswith("--max-restarts="):
            opts["max_restarts"] = int(a[len("--max-restarts="):])
        elif a.startswith("--runs="):
            opts["runs"] = a[len("--runs="):]
        elif a.startswith("--fault-plan="):
            opts["fault_plan"] = a[len("--fault-plan="):]
        elif a.startswith("--fault-log="):
            opts["fault_log"] = a[len("--fault-log="):]
        else:
            kept.append(a)
    return opts, kept


def run_supervised(builder, opts: dict, stream=None, **spawn_kw):
    """Drive one supervised run (``supervisor.supervise``) from a
    :func:`pop_supervise_opts` config; prints the one-line summary the
    CI chaos smoke greps and returns the :class:`SupervisedRun`."""
    from ..supervisor import supervise
    from ..testing.faults import FaultPlan

    stream = stream or sys.stdout
    if opts.get("autosave") is None:
        import tempfile

        opts = dict(opts)
        opts["autosave"] = tempfile.mkdtemp(
            prefix="stateright-tpu-autosave-"
        )
        print(
            f"supervise: no --autosave=DIR given; checkpointing into "
            f"{opts['autosave']} (pass the same dir to resume after a "
            "kill)",
            file=stream,
        )
    plan = None
    if opts.get("fault_plan"):
        plan = FaultPlan.from_file(opts["fault_plan"]).install()
    if opts.get("runs"):
        builder = builder.runs(opts["runs"])
    # a recorder is required for the checkpoint/restart ring records (and
    # costs nothing measurable; the telemetry overhead contract)
    if builder.telemetry_opts is None:
        builder = builder.telemetry()
    if opts.get("batch"):
        spawn_kw.setdefault("batch", int(opts["batch"]))
    if opts.get("steps"):
        spawn_kw.setdefault("steps_per_call", int(opts["steps"]))
    try:
        res = supervise(
            builder,
            autosave_dir=opts["autosave"],
            every_secs=float(opts.get("every", 60.0)),
            keep=int(opts.get("keep", 3)),
            max_restarts=int(opts.get("max_restarts", 5)),
            **spawn_kw,
        )
    finally:
        if plan is not None:
            plan.uninstall()
            if opts.get("fault_log"):
                plan.to_jsonl(opts["fault_log"])
    c = res.checker
    parent = getattr(c, "parent_run_id", None)
    print(
        f"supervised: done={c.is_done()} states={c.state_count()} "
        f"unique={c.unique_state_count()} restarts={res.restarts} "
        f"run_id={c.run_id}"
        + (f" parent_run_id={parent}" if parent else "")
        + (
            f" degradations={','.join(res.degradations)}"
            if res.degradations else ""
        ),
        file=stream,
    )
    return res


# -- profile verb ------------------------------------------------------------


def _split_profile_args(
    args: list, default_out: str = "telemetry.jsonl"
) -> tuple:
    """``(--out, --chrome, rest)`` from a profile/report verb's argument
    list — the single definition of the ``--out=`` parsing."""
    out, chrome, rest = default_out, None, []
    for a in args:
        if a.startswith("--out="):
            out = a[len("--out="):]
        elif a.startswith("--chrome="):
            chrome = a[len("--chrome="):]
        else:
            rest.append(a)
    return out, chrome, rest


def profile_models(
    models: Iterable[tuple], out: str, chrome: Optional[str] = None,
    stream=None,
) -> dict:
    """Run each ``(label, model)`` with the flight recorder enabled and
    append one JSONL export per run to ``out`` (Chrome trace of the LAST
    run to ``chrome`` if given).  The engine is the device wavefront (CPU
    backend off-hardware — same code path); models without a tensor twin
    fall back to host BFS so the verb works on every example.  Prints one
    summary line per run; returns the last summary."""
    import json

    from ..parallel.actor_compiler import CompileError

    stream = stream or sys.stdout
    summary: dict = {}
    first = True
    for label, model in models:
        builder = model.checker().telemetry(occupancy_every=4)
        # detect "no device form" EXPLICITLY (the spawn_auto twin probe)
        # instead of catching exception types from inside spawn_tpu:
        # genuine device-run failures (poison rows, growth bugs, wiring
        # TypeErrors) must PROPAGATE so the CI profile smoke fails on a
        # broken engine rather than quietly uploading host telemetry.
        twin_err = None
        try:
            cached = getattr(model, "_tensor_cached", None)
            twin = (
                cached()
                if cached is not None
                else getattr(model, "tensor_model", lambda: None)()
            )
        except CompileError as e:
            twin, twin_err = None, e
        if twin is None:
            why = type(twin_err).__name__ if twin_err else "no tensor twin"
            print(
                f"--- {label}: device engine unavailable ({why}); "
                "profiling host BFS", file=stream,
            )
            checker = builder.spawn_bfs().join()
        else:
            checker = builder.spawn_tpu(sync=True)
        rec = checker.flight_recorder
        rec.update_meta(label=label)
        rec.to_jsonl(out, append=not first)
        first = False
        if chrome:
            rec.to_chrome_trace(chrome)
        summary = rec.summary()
        print(f"--- {label}", file=stream)
        print(json.dumps(summary, default=str), file=stream)
    return summary


def make_profile_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as a ``profile``
    CLI verb (``--out=``/``--chrome=`` flags, remaining args to the
    factory)."""

    def _profile(rest: list) -> None:
        out, chrome, rest = _split_profile_args(rest)
        profile_models(factory(rest), out, chrome=chrome)
        print(f"telemetry JSONL written to {out}"
              + (f", Chrome trace to {chrome}" if chrome else ""))

    return _profile


def fleet_profile(args: Optional[list] = None, stream=None) -> int:
    """``profile [MODULE] [--out=F] [--chrome=F] [ARGS...]``: profile one
    example module's ``_audit_models`` configurations; 0 on success."""
    import importlib

    stream = stream or sys.stdout
    out, chrome, rest = _split_profile_args(list(args or []))
    name = rest.pop(0) if rest else "two_phase_commit"
    try:
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
    except ImportError as e:
        print(f"profile: cannot import models.{name}: {e}", file=stream)
        return 1
    factory = getattr(mod, "_audit_models", None)
    if factory is None:
        print(f"{name}: no _audit_models hook to profile", file=stream)
        return 1
    profile_models(factory(rest), out, chrome=chrome, stream=stream)
    print(f"telemetry JSONL written to {out}", file=stream)
    return 0


# -- report verb -------------------------------------------------------------


def _split_report_args(args: list) -> tuple:
    """``(--out, rest)`` from a report verb's argument list (the profile
    splitter with the ``--chrome=`` channel discarded)."""
    out, _chrome, rest = _split_profile_args(
        args, default_out="run-report.json"
    )
    return out, rest


def report_models(
    models: Iterable[tuple], out: str, stream=None
) -> list:
    """Run each ``(label, model)`` with cartography-instrumented telemetry
    and write one post-run report (``telemetry/report.py``: JSON + sibling
    markdown).  A single configuration writes exactly ``out``; multiple
    configurations write numbered siblings (``out`` stem + ``-N``).
    Models without a tensor twin run host BFS — their report simply
    carries no cartography block.  Returns the written JSON paths."""
    from ..parallel.tensor_model import twin_or_none

    stream = stream or sys.stdout
    models = list(models)
    paths = []
    for i, (label, model) in enumerate(models):
        if len(models) == 1:
            path = out
        else:
            stem, ext = os.path.splitext(out)
            path = f"{stem}-{i}{ext or '.json'}"
        builder = model.checker().report(path)
        if twin_or_none(model) is None:
            print(
                f"--- {label}: no device twin; reporting a host BFS run "
                "(no cartography block)", file=stream,
            )
            builder.spawn_bfs().join()
        else:
            builder.spawn_tpu(sync=True)
        print(f"--- {label}: report written to {path}", file=stream)
        paths.append(path)
    return paths


def make_report_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as a ``report``
    CLI verb (``--out=`` flag, remaining args to the factory)."""

    def _report(rest: list) -> None:
        out, rest = _split_report_args(rest)
        report_models(factory(rest), out)

    return _report


def fleet_report(args: Optional[list] = None, stream=None) -> int:
    """``report [MODULE] [--out=F] [ARGS...]``: post-run report for one
    example module's ``_audit_models`` configurations; 0 on success."""
    import importlib

    stream = stream or sys.stdout
    out, rest = _split_report_args(list(args or []))
    name = rest.pop(0) if rest else "two_phase_commit"
    try:
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
    except ImportError as e:
        print(f"report: cannot import models.{name}: {e}", file=stream)
        return 1
    factory = getattr(mod, "_audit_models", None)
    if factory is None:
        print(f"{name}: no _audit_models hook to report on", file=stream)
        return 1
    report_models(factory(rest), out, stream=stream)
    return 0


def fleet_audit(names: Optional[list] = None, stream=None) -> int:
    """Audit the whole example fleet (or just ``names``); 0 iff clean.
    Modules without an ``_audit_models`` hook are reported and skipped."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    ok = True
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            # a FAILURE, not a skip: the gate exists to keep every shipped
            # example audited — a new example without the hook would
            # otherwise silently shrink coverage while CI stays green
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        ok = audit_and_report(factory([]), stream=stream) and ok
    print("audit fleet: " + ("CLEAN" if ok else "FAILED"), file=stream)
    return 0 if ok else 1


def fleet_status(argv: Optional[list] = None, stream=None) -> int:
    """``status RUN_DIR``: tail the progress heartbeat of a headless run.

    Reads the atomic ``progress.json`` the engines (and the fleet
    scheduler) write next to autosave generations, plus any per-job
    heartbeats under ``RUN_DIR/jobs/*/``.  Works post-mortem: a SIGKILLed
    run leaves its last heartbeat behind, and a stale ``running`` status
    is reported as ``DEAD`` (where did it stall).  Exit 0 iff at least
    one heartbeat was found.
    """
    from ..checkpoint import read_progress

    stream = stream or sys.stdout
    argv = argv or []
    if not argv:
        print("usage: status RUN_DIR", file=stream)
        return 1
    root = argv[0]

    def _render(tag: str, doc: dict) -> None:
        verdict = doc.get("verdict", "?")
        bits = [f"--- {tag}: {verdict.upper()}"]
        if doc.get("age_secs") is not None:
            bits.append(f"age={doc['age_secs']:.1f}s")
        for k in ("states", "unique", "steps", "frontier", "queue",
                  "depth", "phase", "ewma_states_per_sec", "eta_secs",
                  "jobs", "running", "queued", "completed", "preemptions"):
            v = doc.get(k)
            if v is None:
                continue
            if isinstance(v, list):
                v = len(v)
            bits.append(f"{k}={v}")
        if doc.get("stalled"):
            bits.append(f"STALLED({doc.get('stall_reason') or '?'})")
        print("  ".join(bits), file=stream)

    found = 0
    top = read_progress(root)
    if top is not None:
        _render(root, top)
        found += 1
    jobs_dir = os.path.join(root, "jobs")
    if os.path.isdir(jobs_dir):
        for name in sorted(os.listdir(jobs_dir)):
            doc = read_progress(os.path.join(jobs_dir, name))
            if doc is not None:
                _render(f"jobs/{name}", doc)
                found += 1
    if not found:
        print(f"status: no progress.json under {root} (run without "
              "autosave, or not started yet)", file=stream)
        return 1
    return 0


def main(argv: Optional[list] = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "audit":
        raise SystemExit(fleet_audit(argv[1:]))
    if argv and argv[0] == "sanitize":
        raise SystemExit(fleet_sanitize(argv[1:]))
    if argv and argv[0] == "independence":
        raise SystemExit(fleet_independence(argv[1:]))
    if argv and argv[0] == "profile":
        raise SystemExit(fleet_profile(argv[1:]))
    if argv and argv[0] == "report":
        raise SystemExit(fleet_report(argv[1:]))
    if argv and argv[0] == "capacity":
        raise SystemExit(fleet_capacity(argv[1:]))
    if argv and argv[0] == "costmodel":
        raise SystemExit(fleet_costmodel(argv[1:]))
    if argv and argv[0] == "runs":
        raise SystemExit(fleet_runs(argv[1:]))
    if argv and argv[0] == "compare":
        raise SystemExit(compare_reports_cmd(argv[1:]))
    if argv and argv[0] == "fleet":
        raise SystemExit(fleet_schedule(argv[1:]))
    if argv and argv[0] == "campaign":
        raise SystemExit(fleet_campaign(argv[1:]))
    if argv and argv[0] == "status":
        raise SystemExit(fleet_status(argv[1:]))
    print("USAGE:")
    print("  python -m stateright_tpu.models._cli audit [MODULE...]")
    print("    static preflight audit over the example fleet "
          "(docs/analysis.md)")
    print("  python -m stateright_tpu.models._cli sanitize [MODULE...]")
    print("    interval/bounds soundness sanitizer over the fleet "
          "(docs/analysis.md JX2xx); exit 1 on any error finding")
    print("  python -m stateright_tpu.models._cli independence [MODULE...]")
    print("    static independence / conflict-matrix analysis over the "
          "fleet (docs/analysis.md JX3xx); exit 1 on any error finding")
    print("  python -m stateright_tpu.models._cli profile [MODULE] "
          "[--out=F] [--chrome=F] [ARGS...]")
    print("    telemetry-instrumented run; flight-recorder JSONL export "
          "(docs/telemetry.md)")
    print("  python -m stateright_tpu.models._cli report [MODULE] "
          "[--out=F] [ARGS...]")
    print("    post-run report (JSON + markdown): totals, cartography, "
          "memory, health timeline (docs/telemetry.md)")
    print("  python -m stateright_tpu.models._cli capacity [MODULE...]")
    print("    HBM capacity plan over the fleet: analytic per-rung "
          "footprint + max reachable states (docs/telemetry.md)")
    print("  python -m stateright_tpu.models._cli costmodel [--out=F] "
          "[MODULE...]")
    print("    roofline cost ledger over the fleet: per-stage "
          "FLOPs/bytes, XLA reconciliation, MXU candidates "
          "(docs/roofline.md); exit 1 on a non-reconciling ledger")
    print("  python -m stateright_tpu.models._cli runs [DIR]")
    print("    list the persistent run registry: archived runs, "
          "config keys, per-config trends (docs/telemetry.md "
          "\"Comparing runs\")")
    print("  python -m stateright_tpu.models._cli compare A B "
          "[--registry=DIR] [--expect=VERDICT]")
    print("    contract-aware diff of two run reports (files or "
          "registry run ids); exit 1 on DIVERGENT or an --expect "
          "mismatch")
    print("  python -m stateright_tpu.models._cli fleet [--slots=N] "
          "[--root=DIR] [--runs=DIR] [--stall=KEY@STEP|none] "
          "[--budget=BYTES] [--spill] [--no-pack]")
    print("    multi-tenant chaos smoke: six mixed 2pc/paxos jobs over "
          "a simulated pool with one injected stall-preemption; "
          "verifies pinned counts + resume lineage (docs/fleet.md)")
    print("  python -m stateright_tpu.models._cli campaign [2pc|paxos] "
          "[--grid=JSON] [--root=DIR] [--runs=DIR] [--slots=N] "
          "[--id=CID]")
    print("    parameter-grid campaign over the fleet scheduler; "
          "writes the ROOT/campaign.json ledger with per-job "
          "wall-clock + aggregate states/s (docs/fleet.md)")
    print("  python -m stateright_tpu.models._cli status RUN_DIR")
    print("    tail the progress.json heartbeat of a headless run "
          "(works post-mortem on a SIGKILLed run; stale running "
          "heartbeats report DEAD) (docs/observability.md)")


if __name__ == "__main__":
    main()
