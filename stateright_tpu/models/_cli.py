"""Shared CLI plumbing for example models (reference per-example ``main()``,
e.g. ``examples/paxos.rs:314-395``): subcommands ``check [args]``,
``check-sym``, ``explore [addr]``, ``spawn``, with positional arguments.
Beyond the reference's verbs: ``check-tpu`` / ``check-sym-tpu`` (device
engines) and ``check-auto`` (measured engine selection,
``CheckerBuilder.spawn_auto``)."""

from __future__ import annotations

import os
import sys
from typing import Callable, Optional


def run_cli(
    usage: str,
    check: Callable[[list], None],
    check_sym: Optional[Callable[[list], None]] = None,
    check_tpu: Optional[Callable[[list], None]] = None,
    check_sym_tpu: Optional[Callable[[list], None]] = None,
    check_auto: Optional[Callable[[list], None]] = None,
    explore: Optional[Callable[[list], None]] = None,
    spawn: Optional[Callable[[list], None]] = None,
    argv: Optional[list] = None,
) -> None:
    argv = sys.argv[1:] if argv is None else argv
    cmd = argv[0] if argv else None
    rest = argv[1:]
    if cmd == "check":
        check(rest)
    elif cmd == "check-sym" and check_sym is not None:
        check_sym(rest)
    elif cmd == "check-tpu" and check_tpu is not None:
        check_tpu(rest)
    elif cmd == "check-sym-tpu" and check_sym_tpu is not None:
        check_sym_tpu(rest)
    elif cmd == "check-auto" and check_auto is not None:
        check_auto(rest)
    elif cmd == "explore" and explore is not None:
        explore(rest)
    elif cmd == "spawn" and spawn is not None:
        spawn(rest)
    else:
        print("USAGE:")
        print(usage)


def default_threads() -> int:
    return os.cpu_count() or 1
