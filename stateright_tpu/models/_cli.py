"""Shared CLI plumbing for example models (reference per-example ``main()``,
e.g. ``examples/paxos.rs:314-395``): subcommands ``check [args]``,
``check-sym``, ``explore [addr]``, ``spawn``, with positional arguments.
Beyond the reference's verbs: ``check-tpu`` / ``check-sym-tpu`` (device
engines), ``check-auto`` (measured engine selection,
``CheckerBuilder.spawn_auto``), and ``audit`` (the static preflight
auditor, ``stateright_tpu/analysis/``).

Fleet mode — ``python -m stateright_tpu.models._cli audit [MODULE...]`` —
audits every shipped example (each module exposes ``_audit_models()``),
printing one report per configuration and exiting non-zero on any
error-severity finding; CI gates on it.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Iterable, Optional


def run_cli(
    usage: str,
    check: Callable[[list], None],
    check_sym: Optional[Callable[[list], None]] = None,
    check_tpu: Optional[Callable[[list], None]] = None,
    check_sym_tpu: Optional[Callable[[list], None]] = None,
    check_auto: Optional[Callable[[list], None]] = None,
    explore: Optional[Callable[[list], None]] = None,
    spawn: Optional[Callable[[list], None]] = None,
    audit: Optional[Callable[[list], None]] = None,
    argv: Optional[list] = None,
) -> None:
    argv = sys.argv[1:] if argv is None else argv
    cmd = argv[0] if argv else None
    rest = argv[1:]
    if cmd == "check":
        check(rest)
    elif cmd == "check-sym" and check_sym is not None:
        check_sym(rest)
    elif cmd == "check-tpu" and check_tpu is not None:
        check_tpu(rest)
    elif cmd == "check-sym-tpu" and check_sym_tpu is not None:
        check_sym_tpu(rest)
    elif cmd == "check-auto" and check_auto is not None:
        check_auto(rest)
    elif cmd == "explore" and explore is not None:
        explore(rest)
    elif cmd == "spawn" and spawn is not None:
        spawn(rest)
    elif cmd == "audit" and audit is not None:
        audit(rest)
    else:
        print("USAGE:")
        print(usage)
        if audit is not None:
            print("  <example> audit    # static preflight audit "
                  "(docs/analysis.md)")


def default_threads() -> int:
    return os.cpu_count() or 1


# -- audit verb --------------------------------------------------------------


def audit_and_report(
    models: Iterable[tuple], stream=None, deep: bool = True
) -> bool:
    """Audit ``(label, model)`` pairs, print one report each; True iff no
    error-severity findings anywhere."""
    from ..analysis import audit_model

    stream = stream or sys.stdout
    ok = True
    for label, model in models:
        report = audit_model(model, deep=deep)
        print(f"--- {label}", file=stream)
        print(report.format(), file=stream)
        ok = ok and report.ok
    return ok


def make_audit_cmd(factory: Callable[[list], Iterable[tuple]]) -> Callable:
    """Wrap a ``rest -> [(label, model), ...]`` factory as an ``audit``
    CLI verb that exits 1 on error findings."""

    def _audit(rest: list) -> None:
        if not audit_and_report(factory(rest)):
            raise SystemExit(1)

    return _audit


def fleet_audit(names: Optional[list] = None, stream=None) -> int:
    """Audit the whole example fleet (or just ``names``); 0 iff clean.
    Modules without an ``_audit_models`` hook are reported and skipped."""
    import importlib

    from . import __all__ as all_names

    stream = stream or sys.stdout
    ok = True
    for name in names or list(all_names):
        mod = importlib.import_module(f"stateright_tpu.models.{name}")
        factory = getattr(mod, "_audit_models", None)
        if factory is None:
            # a FAILURE, not a skip: the gate exists to keep every shipped
            # example audited — a new example without the hook would
            # otherwise silently shrink coverage while CI stays green
            print(
                f"--- {name}: FAILED — no _audit_models hook (add one so "
                "the fleet gate covers this example)",
                file=stream,
            )
            ok = False
            continue
        ok = audit_and_report(factory([]), stream=stream) and ok
    print("audit fleet: " + ("CLEAN" if ok else "FAILED"), file=stream)
    return 0 if ok else 1


def main(argv: Optional[list] = None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "audit":
        raise SystemExit(fleet_audit(argv[1:]))
    print("USAGE:")
    print("  python -m stateright_tpu.models._cli audit [MODULE...]")
    print("    static preflight audit over the example fleet "
          "(docs/analysis.md)")


if __name__ == "__main__":
    main()
