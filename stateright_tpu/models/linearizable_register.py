"""ABD linearizable quorum register (reference
``examples/linearizable-register.rs``), after "Sharing Memory Robustly in
Message-Passing Systems" by Attiya, Bar-Noy, and Dolev.

Each request runs two phases: a query phase establishing the latest
(sequencer, value) from a majority, then a record phase driving it (or the
new write, with a bumped sequencer) to a majority.  Sequencers are
``(logical clock, server id)`` pairs, so they are distinct across servers.

Pinned count (reference ``linearizable-register.rs:258,281``): 544 unique
states @ 2 clients / 2 servers on an unordered non-duplicating network.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from .. import Expectation
from ..actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from ..actor.register import (
    NULL_VALUE,
    GetOk,
    Internal,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
    value_chosen,
)
from ..parallel.tensor_model import TensorBackedModel
from ..semantics import LinearizabilityTester, Register
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    pop_checked,
    pop_perf,
    pop_watch,
    run_cli,
    spawn_watched,
)


def Query(req_id):
    return ("query", req_id)


def AckQuery(req_id, seq, value):
    return ("ack_query", req_id, seq, value)


def Record(req_id, seq, value):
    return ("record", req_id, seq, value)


def AckRecord(req_id):
    return ("ack_record", req_id)


@dataclass(frozen=True)
class AbdPhase1:
    request_id: int
    requester_id: Id
    write: Optional[str]  # value to write, None for reads
    responses: tuple  # sorted ((server id, (seq, value)), ...)


@dataclass(frozen=True)
class AbdPhase2:
    request_id: int
    requester_id: Id
    read: Optional[str]  # value read in phase 1, None for writes
    acks: frozenset  # server ids


@dataclass(frozen=True)
class AbdState:
    seq: tuple  # (logical clock, server id)
    val: str
    phase: Optional[object]  # AbdPhase1 | AbdPhase2 | None


@dataclass
class AbdServer(Actor):
    """One ABD replica (reference ``linearizable-register.rs:56-186``)."""

    peers: list

    def on_start(self, id: Id, out: Out):
        return AbdState(seq=(0, Id(id)), val=NULL_VALUE, phase=None)

    def _quorum(self) -> int:
        return majority(len(self.peers) + 1)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg, out: Out):
        kind = msg[0]

        if kind in ("put", "get") and state.phase is None:
            req_id = msg[1]
            out.broadcast(self.peers, Internal(Query(req_id)))
            return replace(
                state,
                phase=AbdPhase1(
                    request_id=req_id,
                    requester_id=Id(src),
                    write=msg[2] if kind == "put" else None,
                    responses=((Id(id), (state.seq, state.val)),),
                ),
            )

        if kind != "internal":
            return None
        imsg = msg[1]
        ikind = imsg[0]

        if ikind == "query":
            out.send(src, Internal(AckQuery(imsg[1], state.seq, state.val)))
            return state

        if ikind == "ack_query":
            req_id, seq, val = imsg[1], imsg[2], imsg[3]
            ph = state.phase
            if not (isinstance(ph, AbdPhase1) and ph.request_id == req_id):
                return None
            responses = dict(ph.responses)
            responses[Id(src)] = (seq, val)
            resp_tuple = tuple(sorted(responses.items()))
            if len(resp_tuple) == self._quorum():
                # quorum: pick latest (sequencers are distinct), move to
                # phase 2 (reference ``linearizable-register.rs:107-147``)
                best_seq, best_val = max(
                    responses.values(), key=lambda sv: sv[0]
                )
                if ph.write is not None:
                    new_seq = (best_seq[0] + 1, Id(id))
                    new_val = ph.write
                    read = None
                else:
                    new_seq, new_val = best_seq, best_val
                    read = best_val
                out.broadcast(
                    self.peers, Internal(Record(req_id, new_seq, new_val))
                )
                # self-send Record
                seq2, val2 = state.seq, state.val
                if new_seq > state.seq:
                    seq2, val2 = new_seq, new_val
                return replace(
                    state,
                    seq=seq2,
                    val=val2,
                    phase=AbdPhase2(
                        request_id=req_id,
                        requester_id=ph.requester_id,
                        read=read,
                        acks=frozenset({Id(id)}),
                    ),
                )
            return replace(state, phase=replace(ph, responses=resp_tuple))

        if ikind == "record":
            req_id, seq, val = imsg[1], imsg[2], imsg[3]
            out.send(src, Internal(AckRecord(req_id)))
            if seq > state.seq:
                return replace(state, seq=seq, val=val)
            return state

        if ikind == "ack_record":
            req_id = imsg[1]
            ph = state.phase
            if not (
                isinstance(ph, AbdPhase2)
                and ph.request_id == req_id
                and Id(src) not in ph.acks
            ):
                return None
            acks = ph.acks | {Id(src)}
            if len(acks) == self._quorum():
                if ph.read is not None:
                    out.send(ph.requester_id, GetOk(req_id, ph.read))
                else:
                    out.send(ph.requester_id, PutOk(req_id))
                return replace(state, phase=None)
            return replace(state, phase=replace(ph, acks=acks))

        return None


class AbdModel(TensorBackedModel, ActorModel):
    """ActorModel with a mechanically compiled device twin
    (``parallel/actor_compiler.py``): eligible configurations (unordered
    non-duplicating or ordered network; any uniform ``put_count``) run on
    the TPU wavefront engine with no protocol-specific device code."""

    def tensor_model(self):
        from ..actor.network import (
            OrderedNetwork,
            UnorderedNonDuplicatingNetwork,
        )
        from ..parallel.actor_compiler import CompileError, compile_actor_model

        if not isinstance(
            self.init_network,
            (UnorderedNonDuplicatingNetwork, OrderedNetwork),
        ):
            # the state_bound below assumes each message is delivered at most
            # once; under a duplicating network a redelivered put restarts a
            # write round, the clock exceeds the write total in REAL runs
            # (the space is unbounded), and the bound would poison reachable
            # transitions
            return None

        # total write ops: each bumps the ABD logical clock at most once
        W = sum(
            a.put_count
            for a in self.actors
            if isinstance(a, RegisterClient)
        )

        def state_bound(i, s):
            # ABD sequencers are (logical clock, server id); each of the W
            # writes bumps the clock by at most one, so clock <= W in any
            # real run — the bound only cuts closure over-approximation.
            return not isinstance(s, AbdState) or s.seq[0] <= W

        def env_bound(env):
            m = env.msg
            if m[0] == "internal" and m[1][0] in ("ack_query", "record"):
                return m[1][2][0] <= W
            return True

        try:
            return compile_actor_model(
                self, state_bound=state_bound, env_bound=env_bound
            )
        except (CompileError, ValueError):
            return None


def abd_model(
    client_count: int,
    server_count: int = 2,
    network: Optional[Network] = None,
    put_count: int = 1,
) -> ActorModel:
    """Build the checked system (reference ``linearizable-register.rs:195-230``;
    ``put_count`` as in reference ``register.rs:96,178-186``)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()
    m = AbdModel(
        cfg=None, init_history=LinearizabilityTester(Register(NULL_VALUE))
    )
    for i in range(server_count):
        m.actor(AbdServer(peers=model_peers(i, server_count)))
    for _ in range(client_count):
        m.actor(RegisterClient(put_count=put_count, server_count=server_count))
    m.init_network_(network)
    m.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda model, s: s.history.is_consistent(),
    )
    m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    m.record_msg_in(record_returns)
    m.record_msg_out(record_invocations)
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    c = int(rest[0]) if rest else 2
    return [(f"linearizable_register clients={c} servers=2", abd_model(c, 2))]


def main(argv=None):
    def check(rest):
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(f"Model checking a linearizable register with {client_count} clients.")
        abd_model(client_count, 2, network).checker().threads(
            default_threads()
        ).spawn_bfs().report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(
            f"Model checking a linearizable register with {client_count} "
            "clients on the device wavefront engine."
        )
        m = apply_encoding(abd_model(client_count, 2, network), perf)
        if m.tensor_model() is None:
            print(
                f"the {network.name} network has no device twin here: "
                "redelivery makes ABD clocks unbounded (state_bound); use "
                "`check` (CPU) or a non-duplicating/ordered network"
            )
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf), watch,
            lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        client_count = int(rest[0]) if rest else 2
        print(
            f"Model checking a linearizable register with {client_count} "
            "clients (auto engine selection)."
        )
        abd_model(client_count, 2).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        client_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        print(f"Exploring ABD state space with {client_count} clients on {addr}.")
        abd_model(client_count, 2).checker().serve(addr)

    def spawn_cmd(rest):
        from ..actor import spawn

        ids = [Id.from_addr("127.0.0.1", 3000 + i) for i in range(2)]
        for id in ids:
            print(f"  Server listening on {id.to_addr()}")
        spawn(
            [
                (id, AbdServer(peers=[p for p in ids if p != id]))
                for id in ids
            ],
            background=False,
        )

    run_cli(
        "  linearizable_register check [CLIENT_COUNT] [NETWORK]\n"
        "  linearizable_register check-tpu [CLIENT_COUNT] [NETWORK]\n"
        "  linearizable_register check-auto [CLIENT_COUNT]\n"
        "  linearizable_register explore [CLIENT_COUNT] [ADDRESS]\n"
        "  linearizable_register spawn",
        check,
        check_tpu=check_tpu,
        check_auto=check_auto,
        explore=explore,
        spawn=spawn_cmd,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
