"""Single-decree Paxos under linearizability checking
(reference ``examples/paxos.rs``).

Each server is simultaneously a potential leader (proposer) and an acceptor.
A client ``put`` triggers a new ballot: the leader broadcasts ``prepare``,
collects a majority of ``prepared`` replies (adopting the most recently
accepted proposal if any), broadcasts ``accept``, and on a majority of
``accepted`` declares the value decided, replying ``put_ok`` and broadcasting
``decided``.  Clients then ``get``; servers only answer once decided.

The model wires :class:`~stateright_tpu.actor.register.RegisterClient`
workloads and a :class:`~stateright_tpu.semantics.LinearizabilityTester`
history; the ``linearizable`` property runs the interleaving search per state.

Pinned count (reference ``examples/paxos.rs:291,311``): 16,668 unique states
@ 2 clients / 3 servers on an unordered non-duplicating network.
This workload is the driver's primary benchmark (``paxos check 3``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from .. import Expectation
from ..actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from ..actor.register import (
    NULL_VALUE,
    GetOk,
    Internal,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
    value_chosen,
)
from ..parallel.tensor_model import TensorBackedModel
from ..semantics import LinearizabilityTester, Register
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    make_sweep_cmd,
    pop_checked,
    pop_perf,
    pop_supervise_opts,
    pop_watch,
    run_cli,
    run_supervised,
    spawn_watched,
)

def _ballot_zero() -> tuple:
    return (0, Id(0))


@dataclass(frozen=True)
class PaxosState:
    """Per-server state (reference ``paxos.rs:78-91``)."""

    ballot: tuple  # (round, leader id)
    # leader state
    proposal: Optional[tuple]  # (request id, requester id, value)
    prepares: tuple  # sorted ((acceptor id, last_accepted), ...)
    accepts: frozenset  # acceptor ids
    # acceptor state
    accepted: Optional[tuple]  # (ballot, proposal)
    is_decided: bool


def _accepted_key(last_accepted):
    """Total order on Option<(Ballot, Proposal)> matching the reference's
    ``max`` over ``prepares.values()`` (None is least)."""
    if last_accepted is None:
        return (0,)
    return (1, last_accepted)


@dataclass
class PaxosServer(Actor):
    """One Paxos server (reference ``paxos.rs:96-222``)."""

    peer_ids: list

    def on_start(self, id: Id, out: Out):
        return PaxosState(
            ballot=_ballot_zero(),
            proposal=None,
            prepares=(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state: PaxosState, src: Id, msg, out: Out):
        kind = msg[0]
        if state.is_decided:
            if kind == "get":
                # A server that hasn't decided doesn't know whether a value
                # was decided elsewhere, so it never replies "no value"
                # (reference ``paxos.rs:117-129``).
                _ballot, proposal = state.accepted
                out.send(src, GetOk(msg[1], proposal[2]))
                return state  # reference registers a (possibly no-op) change
            return None

        if kind == "put" and state.proposal is None:
            req_id, value = msg[1], msg[2]
            ballot = (state.ballot[0] + 1, Id(id))
            out.broadcast(self.peer_ids, Internal(("prepare", ballot)))
            return replace(
                state,
                ballot=ballot,
                proposal=(req_id, Id(src), value),
                prepares=((Id(id), state.accepted),),  # self-send Prepared
                accepts=frozenset(),
            )

        if kind != "internal":
            return None
        imsg = msg[1]
        ikind = imsg[0]

        if ikind == "prepare":
            ballot = imsg[1]
            if state.ballot < ballot:
                out.send(src, Internal(("prepared", ballot, state.accepted)))
                return replace(state, ballot=ballot)
            return None

        if ikind == "prepared":
            ballot, last_accepted = imsg[1], imsg[2]
            if ballot != state.ballot:
                return None
            prepares = dict(state.prepares)
            prepares[Id(src)] = last_accepted
            new_prepares = tuple(sorted(prepares.items()))
            new_state = replace(state, prepares=new_prepares)
            quorum = majority(len(self.peer_ids) + 1)
            if len(new_prepares) == quorum:
                # leadership handoff: favor the most recently accepted
                # proposal from the prepare quorum (reference
                # ``paxos.rs:158-179``)
                best = max(
                    (la for _, la in new_prepares), key=_accepted_key
                )
                proposal = best[1] if best is not None else state.proposal
                out.broadcast(
                    self.peer_ids, Internal(("accept", ballot, proposal))
                )
                new_state = replace(
                    new_state,
                    proposal=proposal,
                    accepted=(ballot, proposal),  # self-send Accept
                    accepts=frozenset({Id(id)}),  # self-send Accepted
                )
            return new_state

        if ikind == "accept":
            ballot, proposal = imsg[1], imsg[2]
            if state.ballot <= ballot:
                out.send(src, Internal(("accepted", ballot)))
                return replace(
                    state, ballot=ballot, accepted=(ballot, proposal)
                )
            return None

        if ikind == "accepted":
            ballot = imsg[1]
            if ballot != state.ballot:
                return None
            accepts = state.accepts | {Id(src)}
            new_state = replace(state, accepts=accepts)
            quorum = majority(len(self.peer_ids) + 1)
            if len(accepts) == quorum:
                proposal = state.proposal
                out.broadcast(
                    self.peer_ids, Internal(("decided", ballot, proposal))
                )
                req_id, requester_id, _value = proposal
                out.send(requester_id, PutOk(req_id))
                new_state = replace(new_state, is_decided=True)
            return new_state

        if ikind == "decided":
            ballot, proposal = imsg[1], imsg[2]
            return replace(
                state,
                ballot=ballot,
                accepted=(ballot, proposal),
                is_decided=True,
            )

        return None


class PaxosModel(TensorBackedModel, ActorModel):
    """ActorModel specialization carrying a tensor (device) twin.

    The benchmark configuration — 3 servers, 1..7 clients doing one put
    each, unordered non-duplicating lossless network — uses the hand-tuned
    twin (``paxos_tensor.py``), which covers the reference's ``paxos check
    6`` bench config.  Other configurations (≠3 servers) fall back to the
    mechanical compiler (``parallel/actor_compiler.py``); configurations
    neither supports fall back to structural fingerprints and CPU checking.
    Eligibility is derived from the live builder state."""

    def sweep_family(self, n: int = 8):
        """Default hyper-batched sweep for the STATERIGHT_TPU_SWEEP env
        knob (docs/sweep.md): delegates to the module-level family."""
        return sweep_family(n)

    def tensor_model(self):
        from ..actor.network import UnorderedNonDuplicatingNetwork
        from .paxos_tensor import MAX_CLIENTS, PaxosTensor

        servers = sum(isinstance(a, PaxosServer) for a in self.actors)
        clients = self.actors[servers:]
        if (
            servers == 3
            and 1 <= len(clients) <= MAX_CLIENTS
            and all(
                isinstance(a, RegisterClient) and a.put_count == 1
                for a in clients
            )
            and not self.lossy
            and isinstance(self.init_network, UnorderedNonDuplicatingNetwork)
            # per-channel is a compiled-twin layout: the hand-tuned twin
            # packs its own slot multiset, so the builder flag OR the env
            # knob routes to the mechanical compiler (docs/analysis.md)
            and not self.per_channel_resolved()
        ):
            return PaxosTensor(self, len(clients))
        return self._compiled_tensor(len(clients))

    def _compiled_tensor(self, client_count: int):
        from ..actor.network import (
            OrderedNetwork,
            UnorderedNonDuplicatingNetwork,
        )
        from ..parallel.actor_compiler import CompileError, compile_actor_model

        if not isinstance(
            self.init_network,
            (UnorderedNonDuplicatingNetwork, OrderedNetwork),
        ):
            # the ballot bound below assumes at-most-once delivery; a
            # redelivered put starts extra ballots, exceeding C in real runs
            return None

        C = client_count

        def state_bound(i, s):
            # Each of the C puts starts exactly one new ballot, so ballot
            # rounds never exceed C in a real run; the bound only cuts the
            # closure's over-approximation (SURVEY §7.3: bounded domains).
            return not isinstance(s, PaxosState) or s.ballot[0] <= C

        def env_bound(env):
            m = env.msg
            if m[0] == "internal":
                return m[1][1][0] <= C
            return True

        try:
            return compile_actor_model(
                self, state_bound=state_bound, env_bound=env_bound
            )
        except (CompileError, ValueError):
            return None


def paxos_model(
    client_count: int, server_count: int = 3, network: Optional[Network] = None
) -> ActorModel:
    """Build the checked system (reference ``paxos.rs:231-266``)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()
    m = PaxosModel(
        cfg=None,
        init_history=LinearizabilityTester(Register(NULL_VALUE)),
    )
    for i in range(server_count):
        m.actor(PaxosServer(peer_ids=model_peers(i, server_count)))
    for _ in range(client_count):
        m.actor(RegisterClient(put_count=1, server_count=server_count))
    m.init_network_(network)
    m.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda model, s: s.history.is_consistent(),
    )
    m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    m.record_msg_in(record_returns)
    m.record_msg_out(record_invocations)
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    c = int(rest[0]) if rest else 2
    return [(f"paxos clients={c} servers=3", paxos_model(c, 3))]


def sweep_family(n: int = 8):
    """The paxos default sweep (docs/sweep.md; ``sweep`` verb +
    ``STATERIGHT_TPU_SWEEP``): ``n`` single-client instances alternating
    network lossiness — the non-lossy members run the hand-tuned twin,
    the lossy ones the compiled per-instance twin, so the sweep spans
    TWO shape cohorts (one engine compile each) and mixed table seeds
    widen the hash-fuzz net; every member must reconcile against its own
    sequential 482/265 (non-lossy) / lossy counts."""
    from ..sweep import SweepInstance, SweepSpec

    insts = []
    for i in range(max(1, int(n))):
        lossy = bool(i % 2)
        m = paxos_model(1, 3)
        if lossy:
            m.lossy_network(True)
        insts.append(SweepInstance(
            f"paxos1-{'lossy-' if lossy else ''}i{i}",
            m,
            params={"clients": 1, "lossy": lossy, "seed": i // 2},
            seed=i // 2,
        ))
    return SweepSpec(insts)


def main(argv=None):
    def check(rest):
        client_count = int(rest[0]) if rest else 2
        network = (
            Network.from_name(rest[1])
            if len(rest) > 1
            else Network.new_unordered_nonduplicating()
        )
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        paxos_model(client_count, 3, network).checker().threads(
            default_threads()
        ).spawn_dfs().report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        client_count = int(rest[0]) if rest else 2
        target = int(rest[1]) if len(rest) > 1 else None
        print(
            f"Model checking Single Decree Paxos with {client_count} clients "
            "on the device wavefront engine"
            + (" (checked mode)." if checked else ".")
        )
        m = apply_encoding(paxos_model(client_count, 3), perf)
        if m.tensor_model() is None:
            print(
                "this configuration has no device twin; use `check` (CPU)"
            )
            return
        b = apply_perf(m.checker().checked(checked), perf)
        if target:
            b = b.target_states(target)
        spawn_watched(b, watch, lambda b: b.spawn_tpu()).report()

    def check_auto(rest):
        client_count = int(rest[0]) if rest else 2
        print(
            f"Model checking Single Decree Paxos with {client_count} "
            "clients (auto engine selection)."
        )
        paxos_model(client_count, 3).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        client_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        print(f"Exploring Paxos state space with {client_count} clients on {addr}.")
        paxos_model(client_count, 3).checker().serve(addr)

    def supervise(rest):
        opts, rest = pop_supervise_opts(rest)
        client_count = int(rest[0]) if rest else 2
        print(
            f"Supervised Paxos check with {client_count} clients "
            "(autosave + retry/backoff; docs/robustness.md)."
        )
        run_supervised(paxos_model(client_count, 3).checker(), opts)

    def spawn_cmd(rest):
        from ..actor import spawn

        ids = [Id.from_addr("127.0.0.1", 3000 + i) for i in range(3)]
        print("  A set of servers that implement Single Decree Paxos.")
        print("  You can monitor and interact using tools such as nc or stateright-cli.")
        for id in ids:
            print(f"  Server listening on {id.to_addr()}")
        actors = [
            (
                id,
                PaxosServer(
                    peer_ids=[p for p in ids if p != id]
                ),
            )
            for id in ids
        ]
        spawn(actors, background=False)

    run_cli(
        "  paxos check [CLIENT_COUNT] [NETWORK]\n"
        "  paxos check-tpu [CLIENT_COUNT] [TARGET_STATES]\n"
        "  paxos check-auto [CLIENT_COUNT]\n"
        "  paxos explore [CLIENT_COUNT] [ADDRESS]\n"
        "  paxos sweep [N_INSTANCES]\n"
        "  paxos spawn",
        check,
        check_tpu=check_tpu,
        check_auto=check_auto,
        explore=explore,
        spawn=spawn_cmd,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        supervise=supervise,
        sweep=make_sweep_cmd(sweep_family),
        argv=argv,
    )


if __name__ == "__main__":
    main()
