"""Write-once register example: first write wins, later writes fail.

The reference ships the write-once *harness* (client + history recorder +
``Rewrite`` impls, ``src/actor/write_once_register.rs:119-299``) but never an
example server validated with it; this module closes that loop end-to-end.
Each server stores at most one value: the first ``put`` is acknowledged with
``put_ok`` and every later one with ``put_fail`` (recorded as the spec's
``write_fail`` return); ``get`` returns the stored value.

With one server the system is linearizable against the
:class:`~stateright_tpu.semantics.WORegister` spec.  With two independent
servers it is not — a client can read ``NULL`` from a server that never saw
the successful write — and the checker finds the violating trace.

Symmetry: servers are interchangeable, clients are not (they write distinct
values), so ``check-sym`` canonicalizes by sorting the *server block* only
and rewriting server ids through the network and history, the role-restricted
analogue of the reference's ``Rewrite`` impls
(``write_once_register.rs:269-299``).
"""

from __future__ import annotations

from typing import Optional

from .. import Expectation
from ..actor import Actor, ActorModel, Id, Network, Out
from ..actor.register import NULL_VALUE, GetOk, value_chosen
from ..actor.write_once_register import (
    PutFail,
    WORegisterClient,
    record_returns,
)
from ..actor.register import PutOk, record_invocations
from ..fingerprint import stable_hash
from ..parallel.tensor_model import TensorBackedModel
from ..semantics import LinearizabilityTester, WORegister
from ..symmetry import RewritePlan, rewrite_value
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    pop_checked,
    pop_perf,
    pop_watch,
    run_cli,
    spawn_watched,
)


class WOServer(Actor):
    """Stores the first value put; later puts fail (write-once)."""

    def on_start(self, id: Id, out: Out):
        return NULL_VALUE

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        kind = msg[0]
        if kind == "put":
            if state == NULL_VALUE:
                out.send(src, PutOk(msg[1]))
                return msg[2]
            out.send(src, PutFail(msg[1]))
            return None
        if kind == "get":
            out.send(src, GetOk(msg[1], state))
            return None
        return None


def server_representative(state, server_count: int):
    """Canonical member of ``state``'s class under server permutations only:
    the plan sorts indices ``< server_count`` by state hash and pins every
    client index, then rewrites ids through network/history."""
    keys = [
        (0, stable_hash(s)) if i < server_count else (1, i)
        for i, s in enumerate(state.actor_states)
    ]
    plan = RewritePlan.from_values_to_sort(keys)
    return type(state)(
        actor_states=tuple(
            rewrite_value(s, plan) for s in plan.reindex(state.actor_states)
        ),
        network=rewrite_value(state.network, plan),
        is_timer_set=tuple(plan.reindex(state.is_timer_set)),
        history=rewrite_value(state.history, plan),
    )


class WORegisterModel(TensorBackedModel, ActorModel):
    """ActorModel with a mechanically compiled device twin."""

    def tensor_model(self):
        from ..parallel.actor_compiler import CompileError, compile_actor_model

        try:
            return compile_actor_model(self)
        except (CompileError, ValueError):
            return None


def wo_register_model(
    client_count: int, server_count: int = 1, network: Optional[Network] = None
) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()
    m = WORegisterModel(
        cfg=None, init_history=LinearizabilityTester(WORegister(None))
    )
    for _ in range(server_count):
        m.actor(WOServer())
    for _ in range(client_count):
        m.actor(WORegisterClient(put_count=1, server_count=server_count))
    m.init_network_(network)
    m.property(
        Expectation.ALWAYS,
        "linearizable",
        lambda model, s: s.history.is_consistent(),
    )
    m.property(Expectation.SOMETIMES, "value chosen", value_chosen)
    m.record_msg_in(record_returns)
    m.record_msg_out(record_invocations)
    return m


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    c = int(rest[0]) if rest else 1
    return [(f"write_once_register clients={c} servers=2", wo_register_model(c, 2))]


def main(argv=None):
    def parse(rest):
        client_count = int(rest[0]) if rest else 2
        server_count = int(rest[1]) if len(rest) > 1 else 1
        network = (
            Network.from_name(rest[2])
            if len(rest) > 2
            else Network.new_unordered_nonduplicating()
        )
        return client_count, server_count, network

    def check(rest):
        client_count, server_count, network = parse(rest)
        print(
            f"Model checking a write-once register with {client_count} "
            f"clients and {server_count} servers."
        )
        wo_register_model(client_count, server_count, network).checker().threads(
            default_threads()
        ).spawn_dfs().report()

    def check_sym(rest):
        client_count, server_count, network = parse(rest)
        print(
            f"Checking a write-once register with {client_count} clients and "
            f"{server_count} servers using symmetry reduction."
        )
        wo_register_model(client_count, server_count, network).checker().threads(
            default_threads()
        ).symmetry_with(
            lambda s: server_representative(s, server_count)
        ).spawn_dfs().report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        client_count, server_count, network = parse(rest)
        print(
            f"Model checking a write-once register with {client_count} "
            f"clients and {server_count} servers on the device wavefront "
            "engine."
        )
        m = apply_encoding(
            wo_register_model(client_count, server_count, network), perf
        )
        if m.tensor_model() is None:
            print("this configuration has no device twin; use `check` (CPU)")
            return
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf), watch,
            lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        client_count, server_count, network = parse(rest)
        print(
            f"Model checking a write-once register with {client_count} "
            f"clients and {server_count} servers (auto engine selection)."
        )
        wo_register_model(
            client_count, server_count, network
        ).checker().threads(default_threads()).spawn_auto().report()

    def explore(rest):
        client_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        wo_register_model(client_count, 1).checker().serve(addr)

    def spawn_cmd(rest):
        from ..actor import spawn

        id = Id.from_addr("127.0.0.1", 3000)
        print(f"  Server listening on {id.to_addr()}")
        spawn([(id, WOServer())], background=False)

    run_cli(
        "  write_once_register check [CLIENT_COUNT] [SERVER_COUNT] [NETWORK]\n"
        "  write_once_register check-sym [CLIENT_COUNT] [SERVER_COUNT] [NETWORK]\n"
        "  write_once_register check-tpu [CLIENT_COUNT] [SERVER_COUNT] [NETWORK]\n"
        "  write_once_register check-auto [CLIENT_COUNT] [SERVER_COUNT] [NETWORK]\n"
        "  write_once_register explore [CLIENT_COUNT] [ADDRESS]\n"
        "  write_once_register spawn",
        check,
        check_sym=check_sym,
        check_tpu=check_tpu,
        check_auto=check_auto,
        explore=explore,
        spawn=spawn_cmd,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
