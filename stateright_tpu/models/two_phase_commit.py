"""Two-phase commit, after the Gray/Lamport TLA+ model "Consensus on
Transaction Commit" (reference ``examples/2pc.rs``).

A transaction manager (TM) coordinates N resource managers (RMs).  The
abstract model tracks each RM's state, the TM's state, which RMs the TM has
seen prepared, and a monotonic message set.  Properties: commit/abort
agreement is reachable (`sometimes`) and no RM ever aborts while another
commits (`always consistent`).

This is also the framework's flagship tensor-form model: :class:`TwoPhaseTensor`
below is the u64-row encoding checked by the TPU wavefront engine; both forms
agree on fingerprints bit-for-bit (``TwoPhaseSys`` is tensor-backed, so even
the CPU checkers fingerprint via the row encoding).

Pinned counts (reference ``examples/2pc.rs:125-140``): 288 @ 3 RMs,
8,832 @ 5 RMs, 665 @ 5 RMs with symmetry reduction.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from .. import Model, Property
from ..parallel.tensor_model import (
    BitPacker,
    FieldWriter,
    TensorBackedModel,
    TensorModel,
)
from ..symmetry import RewritePlan
from ._cli import (
    apply_encoding,
    apply_perf,
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    make_sweep_cmd,
    pop_checked,
    pop_perf,
    pop_supervise_opts,
    pop_watch,
    run_cli,
    run_supervised,
    spawn_watched,
)

# RM states, ordered so sorting gives a canonical symmetry representative
WORKING = "working"
PREPARED = "prepared"
COMMITTED = "committed"
ABORTED = "aborted"

# TM states
TM_INIT = "init"
TM_COMMITTED = "committed"
TM_ABORTED = "aborted"


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: tuple  # one of the RM states per RM
    tm_state: str
    tm_prepared: tuple  # bool per RM
    msgs: frozenset  # ("prepared", rm) | ("commit",) | ("abort",)

    def representative(self) -> "TwoPhaseState":
        """Sort RM states (with their tm_prepared flags) and rewrite RM
        indices inside messages (reference ``2pc.rs:165-182``)."""
        plan = RewritePlan.from_values_to_sort(self.rm_state)
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("prepared", plan.mapping[m[1]]) if m[0] == "prepared" else m
                for m in self.msgs
            ),
        )


@dataclass
class TwoPhaseSys(TensorBackedModel, Model):
    """Abstract 2PC over ``rm_count`` resource managers
    (reference ``2pc.rs:43-121``)."""

    rm_count: int

    def tensor_model(self) -> "TwoPhaseTensor":
        return TwoPhaseTensor(self)

    def sweep_family(self, n: int = 8):
        """Default hyper-batched sweep for the STATERIGHT_TPU_SWEEP env
        knob (docs/sweep.md): delegates to the module-level family."""
        return sweep_family(n)

    def init_states(self):
        n = self.rm_count
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * n,
                tm_state=TM_INIT,
                tm_prepared=(False,) * n,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState):
        acts = []
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            acts.append(("tm_commit",))
        if state.tm_state == TM_INIT:
            acts.append(("tm_abort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and ("prepared", rm) in state.msgs:
                acts.append(("tm_rcv_prepared", rm))
            if state.rm_state[rm] == WORKING:
                acts.append(("rm_prepare", rm))
                acts.append(("rm_choose_abort", rm))
            if ("commit",) in state.msgs:
                acts.append(("rm_rcv_commit", rm))
            if ("abort",) in state.msgs:
                acts.append(("rm_rcv_abort", rm))
        return acts

    def next_state(self, state: TwoPhaseState, action) -> Optional[TwoPhaseState]:
        kind = action[0]
        if kind == "tm_rcv_prepared":
            rm = action[1]
            prepared = list(state.tm_prepared)
            prepared[rm] = True
            return replace(state, tm_prepared=tuple(prepared))
        if kind == "tm_commit":
            return replace(
                state, tm_state=TM_COMMITTED, msgs=state.msgs | {("commit",)}
            )
        if kind == "tm_abort":
            return replace(
                state, tm_state=TM_ABORTED, msgs=state.msgs | {("abort",)}
            )
        rm = action[1]
        rm_state = list(state.rm_state)
        if kind == "rm_prepare":
            rm_state[rm] = PREPARED
            return replace(
                state,
                rm_state=tuple(rm_state),
                msgs=state.msgs | {("prepared", rm)},
            )
        if kind == "rm_choose_abort":
            rm_state[rm] = ABORTED
        elif kind == "rm_rcv_commit":
            rm_state[rm] = COMMITTED
        elif kind == "rm_rcv_abort":
            rm_state[rm] = ABORTED
        else:
            raise ValueError(action)
        return replace(state, rm_state=tuple(rm_state))

    def properties(self):
        return [
            Property.sometimes(
                "abort agreement",
                lambda m, s: all(x == ABORTED for x in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda m, s: all(x == COMMITTED for x in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda m, s: not (
                    ABORTED in s.rm_state and COMMITTED in s.rm_state
                ),
            ),
        ]


# ---------------------------------------------------------------------------
# Tensor form (device twin)
# ---------------------------------------------------------------------------

# Numeric RM-state codes for the row encoding.
_RM_CODE = {WORKING: 0, PREPARED: 1, COMMITTED: 2, ABORTED: 3}
_RM_NAME = {v: k for k, v in _RM_CODE.items()}
_TM_CODE = {TM_INIT: 0, TM_COMMITTED: 1, TM_ABORTED: 2}
_TM_NAME = {v: k for k, v in _TM_CODE.items()}


class TwoPhaseTensor(TensorModel):
    """u64-row encoding of :class:`TwoPhaseState` with a static-arity jittable
    transition (the SURVEY §7 "minimum end-to-end slice" model).

    Layout (word-aligned by :class:`BitPacker`): ``rm`` packs 2 bits per RM;
    ``tm`` 2 bits; ``tm_prepared`` / ``msg_prepared`` one bit per RM;
    ``msg_commit`` / ``msg_abort`` one bit each.  The monotone message *set*
    of the object form (reference ``2pc.rs:16-21``) becomes a bitmask, which
    is automatically canonical — equal sets encode to equal words.

    Static action arity A = 2 + 5·rm_count, slots ordered:
    ``tm_commit, tm_abort,`` then per RM ``tm_rcv_prepared, rm_prepare,
    rm_choose_abort, rm_rcv_commit, rm_rcv_abort``.
    """

    def __init__(self, sys: TwoPhaseSys):
        n = sys.rm_count
        if n > 29:
            raise ValueError("tensor 2PC supports up to 29 RMs per word")
        self.model = sys
        self.n = n
        self.packer = BitPacker(
            [
                ("rm", 2 * n),
                ("tm", 2),
                ("tm_prepared", n),
                ("msg_prepared", n),
                ("msg_commit", 1),
                ("msg_abort", 1),
            ]
        )
        self.width = self.packer.width
        self.max_actions = 2 + 5 * n

    # -- host bridge ---------------------------------------------------------

    def encode_state(self, s: TwoPhaseState) -> tuple:
        rm = 0
        for i, st in enumerate(s.rm_state):
            rm |= _RM_CODE[st] << (2 * i)
        prep = sum(1 << i for i, p in enumerate(s.tm_prepared) if p)
        mprep = sum(1 << m[1] for m in s.msgs if m[0] == "prepared")
        return self.packer.pack(
            rm=rm,
            tm=_TM_CODE[s.tm_state],
            tm_prepared=prep,
            msg_prepared=mprep,
            msg_commit=int(("commit",) in s.msgs),
            msg_abort=int(("abort",) in s.msgs),
        )

    def decode_state(self, row) -> TwoPhaseState:
        f = self.packer.unpack(row)
        n = self.n
        msgs = set()
        for i in range(n):
            if (f["msg_prepared"] >> i) & 1:
                msgs.add(("prepared", i))
        if f["msg_commit"]:
            msgs.add(("commit",))
        if f["msg_abort"]:
            msgs.add(("abort",))
        return TwoPhaseState(
            rm_state=tuple(_RM_NAME[(f["rm"] >> (2 * i)) & 3] for i in range(n)),
            tm_state=_TM_NAME[f["tm"]],
            tm_prepared=tuple(bool((f["tm_prepared"] >> i) & 1) for i in range(n)),
            msgs=frozenset(msgs),
        )

    def init_rows(self):
        import numpy as np

        rows = [self.encode_state(s) for s in self.model.init_states()]
        return np.asarray(rows, dtype=np.uint64)

    def representative_rows(self, rows):
        """Vectorized symmetry canonicalizer: the device analogue of
        :meth:`TwoPhaseState.representative` (stable sort of RM sub-states,
        reindexing ``tm_prepared`` and the ``prepared`` message bits by the
        same permutation).  Must replicate the object form *exactly* — the
        host sorts the RM state **strings** ("aborted" < "committed" <
        "prepared" < "working"), which is the reverse of the 2-bit codes, so
        the device sort key is ``3 - code``; stable argsort then yields the
        identical permutation, preserving the pinned symmetry counts
        (665 @ 5 RMs, reference ``2pc.rs:138``)."""
        import jax.numpy as jnp

        n, pk = self.n, self.packer
        u64 = jnp.uint64
        rm = pk.get(rows, "rm")
        tp = pk.get(rows, "tm_prepared")
        mp = pk.get(rows, "msg_prepared")
        rmv = jnp.stack(
            [((rm >> u64(2 * i)) & u64(3)).astype(jnp.int32) for i in range(n)],
            -1,
        )  # [..., n]
        tpv = jnp.stack(
            [((tp >> u64(i)) & u64(1)).astype(jnp.int32) for i in range(n)], -1
        )
        mpv = jnp.stack(
            [((mp >> u64(i)) & u64(1)).astype(jnp.int32) for i in range(n)], -1
        )
        order = jnp.argsort(3 - rmv, axis=-1, stable=True)  # new -> old
        rms = jnp.take_along_axis(rmv, order, axis=-1)
        tps = jnp.take_along_axis(tpv, order, axis=-1)
        mps = jnp.take_along_axis(mpv, order, axis=-1)
        zero = jnp.zeros_like(rm)
        rm_new, tp_new, mp_new = zero, zero, zero
        for i in range(n):
            rm_new = rm_new | (rms[..., i].astype(u64) << u64(2 * i))
            tp_new = tp_new | (tps[..., i].astype(u64) << u64(i))
            mp_new = mp_new | (mps[..., i].astype(u64) << u64(i))
        rows = pk.set(rows, "rm", rm_new)
        rows = pk.set(rows, "tm_prepared", tp_new)
        rows = pk.set(rows, "msg_prepared", mp_new)
        return rows

    # -- device --------------------------------------------------------------

    def step_rows(self, rows):
        return self._step_rows_impl(rows, coalesce=False)

    def step_rows_coalesced(self, rows):
        """Expand-scatter-coalesced step (``ops/mxu.py``, docs/roofline.md):
        the same transition function with each action's packed-word
        write-backs assembled as ONE word-stacked block (``FieldWriter``
        coalesced mode) instead of one full-block slice read + scatter
        per written field.  Successors and validity are bit-identical to
        :meth:`step_rows` (whole-space parity pinned in tests); only the
        assembly shape changes.  Selected by the engines under
        ``CheckerBuilder.mxu()`` / ``--mxu``."""
        return self._step_rows_impl(rows, coalesce=True)

    def _step_rows_impl(self, rows, coalesce):
        import jax.numpy as jnp

        pk, n = self.packer, self.n
        one = jnp.uint64(1)
        rm = pk.get(rows, "rm")
        tm = pk.get(rows, "tm")
        prep = pk.get(rows, "tm_prepared")
        mprep = pk.get(rows, "msg_prepared")
        mc = pk.get(rows, "msg_commit")
        ma = pk.get(rows, "msg_abort")

        tm_init = tm == jnp.uint64(0)
        all_prepared = prep == jnp.uint64((1 << n) - 1)

        succs, valids = [], []

        def emit(valid, fw):
            valids.append(valid)
            succs.append(fw.done())

        def w():  # one writer per action, all reads come from `rows`
            return FieldWriter(pk, rows, coalesce=coalesce)

        # tm_commit / tm_abort
        emit(
            tm_init & all_prepared,
            w().set("tm", jnp.uint64(1)).set("msg_commit", jnp.ones_like(mc)),
        )
        emit(
            tm_init,
            w().set("tm", jnp.uint64(2)).set("msg_abort", jnp.ones_like(ma)),
        )

        for i in range(n):
            bit = jnp.uint64(1 << i)
            rm_i = (rm >> jnp.uint64(2 * i)) & jnp.uint64(3)
            rm_clear = rm & jnp.uint64(~(3 << (2 * i)) & ((1 << (2 * n)) - 1))

            # tm_rcv_prepared(i)
            emit(
                tm_init & ((mprep >> jnp.uint64(i)) & one == one),
                w().set("tm_prepared", prep | bit),
            )
            # rm_prepare(i): rm working -> prepared + send prepared msg
            emit(
                rm_i == jnp.uint64(0),
                w()
                .set("rm", rm_clear | (jnp.uint64(1) << jnp.uint64(2 * i)))
                .set("msg_prepared", mprep | bit),
            )
            # rm_choose_abort(i)
            emit(
                rm_i == jnp.uint64(0),
                w().set("rm", rm_clear | (jnp.uint64(3) << jnp.uint64(2 * i))),
            )
            # rm_rcv_commit(i)
            emit(
                mc == one,
                w().set("rm", rm_clear | (jnp.uint64(2) << jnp.uint64(2 * i))),
            )
            # rm_rcv_abort(i)
            emit(
                ma == one,
                w().set("rm", rm_clear | (jnp.uint64(3) << jnp.uint64(2 * i))),
            )

        succ = jnp.stack(succs, axis=-2)  # [B, A, W]
        valid = jnp.stack(valids, axis=-1)  # [B, A]
        return succ, valid

    def property_masks(self, rows):
        import jax.numpy as jnp

        pk, n = self.packer, self.n
        rm = pk.get(rows, "rm")
        all_aborted = rm == jnp.uint64((1 << (2 * n)) - 1)  # 0b11 per RM
        all_committed = rm == jnp.uint64(int("10" * n, 2))  # 0b10 per RM
        any_committed = jnp.zeros(rows.shape[:-1], bool)
        any_aborted = jnp.zeros(rows.shape[:-1], bool)
        for i in range(n):
            rm_i = (rm >> jnp.uint64(2 * i)) & jnp.uint64(3)
            any_committed |= rm_i == jnp.uint64(2)
            any_aborted |= rm_i == jnp.uint64(3)
        consistent = ~(any_committed & any_aborted)
        # order matches TwoPhaseSys.properties()
        return jnp.stack([all_aborted, all_committed, consistent], axis=-1)


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    rm_count = int(rest[0]) if rest else 3
    return [(f"two_phase_commit rm={rm_count}", TwoPhaseSys(rm_count))]


def sweep_family(n: int = 8):
    """The 2pc default sweep (docs/sweep.md; ``sweep`` verb +
    ``STATERIGHT_TPU_SWEEP``): ``n`` rm=3 instances under distinct table
    seeds — same dynamics, disjoint fingerprint namespaces, ONE shape
    cohort / ONE engine compile; a hash/table-seed fuzz whose per-seed
    counts must all reconcile to the sequential 288/1146."""
    from ..sweep import SweepInstance, SweepSpec

    return SweepSpec([
        SweepInstance(
            f"2pc3-seed{i}", TwoPhaseSys(3),
            params={"rm": 3, "seed": i}, seed=i,
        )
        for i in range(max(1, int(n)))
    ])


def main(argv=None):
    def check(rest):
        rm_count = int(rest[0]) if rest else 2
        print(f"Checking two phase commit with {rm_count} resource managers.")
        TwoPhaseSys(rm_count).checker().threads(default_threads()).spawn_dfs().report()

    def check_sym(rest):
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Checking two phase commit with {rm_count} resource managers"
            " using symmetry reduction."
        )
        TwoPhaseSys(rm_count).checker().threads(
            default_threads()
        ).symmetry().spawn_dfs().report()

    def check_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Checking two phase commit with {rm_count} RMs on TPU"
            + (" (checked mode)." if checked else ".")
        )
        m = apply_encoding(TwoPhaseSys(rm_count), perf)
        spawn_watched(
            apply_perf(m.checker().checked(checked), perf),
            watch, lambda b: b.spawn_tpu(),
        ).report()

    def check_sym_tpu(rest):
        checked, rest = pop_checked(rest)
        perf, rest = pop_perf(rest)
        watch, rest = pop_watch(rest)
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Checking two phase commit with {rm_count} RMs on TPU "
            "using symmetry reduction"
            + (" (checked mode)." if checked else ".")
        )
        m = apply_encoding(TwoPhaseSys(rm_count), perf)
        spawn_watched(
            apply_perf(m.checker().checked(checked).symmetry(), perf),
            watch, lambda b: b.spawn_tpu(),
        ).report()

    def check_auto(rest):
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Checking two phase commit with {rm_count} RMs "
            "(auto engine selection)."
        )
        TwoPhaseSys(rm_count).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        rm_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        print(f"Exploring 2PC state space with {rm_count} RMs on {addr}.")
        TwoPhaseSys(rm_count).checker().serve(addr)

    def supervise(rest):
        opts, rest = pop_supervise_opts(rest)
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Supervised 2PC check with {rm_count} RMs "
            "(autosave + retry/backoff; docs/robustness.md)."
        )
        run_supervised(TwoPhaseSys(rm_count).checker(), opts)

    run_cli(
        "  two_phase_commit check [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-sym [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-tpu [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-sym-tpu [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-auto [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit explore [RESOURCE_MANAGER_COUNT] [ADDRESS]\n"
        "  two_phase_commit sweep [N_INSTANCES]",
        check,
        check_sym=check_sym,
        check_tpu=check_tpu,
        check_sym_tpu=check_sym_tpu,
        check_auto=check_auto,
        explore=explore,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        supervise=supervise,
        sweep=make_sweep_cmd(sweep_family),
        argv=argv,
    )


if __name__ == "__main__":
    main()
