"""Two-phase commit, after the Gray/Lamport TLA+ model "Consensus on
Transaction Commit" (reference ``examples/2pc.rs``).

A transaction manager (TM) coordinates N resource managers (RMs).  The
abstract model tracks each RM's state, the TM's state, which RMs the TM has
seen prepared, and a monotonic message set.  Properties: commit/abort
agreement is reachable (`sometimes`) and no RM ever aborts while another
commits (`always consistent`).

This is also the framework's flagship tensor-form model: see
``parallel/models/two_phase_commit.py`` for the u64-row encoding checked by
the TPU wavefront engine; both forms agree on fingerprints.

Pinned counts (reference ``examples/2pc.rs:125-140``): 288 @ 3 RMs,
8,832 @ 5 RMs, 665 @ 5 RMs with symmetry reduction.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

from .. import Model, Property
from ..symmetry import RewritePlan
from ._cli import default_threads, run_cli

# RM states, ordered so sorting gives a canonical symmetry representative
WORKING = "working"
PREPARED = "prepared"
COMMITTED = "committed"
ABORTED = "aborted"

# TM states
TM_INIT = "init"
TM_COMMITTED = "committed"
TM_ABORTED = "aborted"


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: tuple  # one of the RM states per RM
    tm_state: str
    tm_prepared: tuple  # bool per RM
    msgs: frozenset  # ("prepared", rm) | ("commit",) | ("abort",)

    def representative(self) -> "TwoPhaseState":
        """Sort RM states (with their tm_prepared flags) and rewrite RM
        indices inside messages (reference ``2pc.rs:165-182``)."""
        plan = RewritePlan.from_values_to_sort(self.rm_state)
        return TwoPhaseState(
            rm_state=tuple(plan.reindex(self.rm_state)),
            tm_state=self.tm_state,
            tm_prepared=tuple(plan.reindex(self.tm_prepared)),
            msgs=frozenset(
                ("prepared", plan.mapping[m[1]]) if m[0] == "prepared" else m
                for m in self.msgs
            ),
        )


@dataclass
class TwoPhaseSys(Model):
    """Abstract 2PC over ``rm_count`` resource managers
    (reference ``2pc.rs:43-121``)."""

    rm_count: int

    def init_states(self):
        n = self.rm_count
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * n,
                tm_state=TM_INIT,
                tm_prepared=(False,) * n,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState):
        acts = []
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            acts.append(("tm_commit",))
        if state.tm_state == TM_INIT:
            acts.append(("tm_abort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and ("prepared", rm) in state.msgs:
                acts.append(("tm_rcv_prepared", rm))
            if state.rm_state[rm] == WORKING:
                acts.append(("rm_prepare", rm))
                acts.append(("rm_choose_abort", rm))
            if ("commit",) in state.msgs:
                acts.append(("rm_rcv_commit", rm))
            if ("abort",) in state.msgs:
                acts.append(("rm_rcv_abort", rm))
        return acts

    def next_state(self, state: TwoPhaseState, action) -> Optional[TwoPhaseState]:
        kind = action[0]
        if kind == "tm_rcv_prepared":
            rm = action[1]
            prepared = list(state.tm_prepared)
            prepared[rm] = True
            return replace(state, tm_prepared=tuple(prepared))
        if kind == "tm_commit":
            return replace(
                state, tm_state=TM_COMMITTED, msgs=state.msgs | {("commit",)}
            )
        if kind == "tm_abort":
            return replace(
                state, tm_state=TM_ABORTED, msgs=state.msgs | {("abort",)}
            )
        rm = action[1]
        rm_state = list(state.rm_state)
        if kind == "rm_prepare":
            rm_state[rm] = PREPARED
            return replace(
                state,
                rm_state=tuple(rm_state),
                msgs=state.msgs | {("prepared", rm)},
            )
        if kind == "rm_choose_abort":
            rm_state[rm] = ABORTED
        elif kind == "rm_rcv_commit":
            rm_state[rm] = COMMITTED
        elif kind == "rm_rcv_abort":
            rm_state[rm] = ABORTED
        else:
            raise ValueError(action)
        return replace(state, rm_state=tuple(rm_state))

    def properties(self):
        return [
            Property.sometimes(
                "abort agreement",
                lambda m, s: all(x == ABORTED for x in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda m, s: all(x == COMMITTED for x in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda m, s: not (
                    ABORTED in s.rm_state and COMMITTED in s.rm_state
                ),
            ),
        ]


def main(argv=None):
    def check(rest):
        rm_count = int(rest[0]) if rest else 2
        print(f"Checking two phase commit with {rm_count} resource managers.")
        TwoPhaseSys(rm_count).checker().threads(default_threads()).spawn_dfs().report()

    def check_sym(rest):
        rm_count = int(rest[0]) if rest else 2
        print(
            f"Checking two phase commit with {rm_count} resource managers"
            " using symmetry reduction."
        )
        TwoPhaseSys(rm_count).checker().threads(
            default_threads()
        ).symmetry().spawn_dfs().report()

    def check_tpu(rest):
        rm_count = int(rest[0]) if rest else 2
        print(f"Checking two phase commit with {rm_count} RMs on TPU.")
        TwoPhaseSys(rm_count).checker().spawn_tpu().report()

    def explore(rest):
        rm_count = int(rest[0]) if rest else 2
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        print(f"Exploring 2PC state space with {rm_count} RMs on {addr}.")
        TwoPhaseSys(rm_count).checker().serve(addr)

    import sys

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "check-tpu":
        check_tpu(argv[1:])
        return
    run_cli(
        "  two_phase_commit check [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-sym [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit check-tpu [RESOURCE_MANAGER_COUNT]\n"
        "  two_phase_commit explore [RESOURCE_MANAGER_COUNT] [ADDRESS]",
        check,
        check_sym=check_sym,
        explore=explore,
        argv=argv,
    )


if __name__ == "__main__":
    main()
