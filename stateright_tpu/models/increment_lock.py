"""Shared counter with a lock (reference ``examples/increment_lock.rs``).

Same as :mod:`.increment` but each thread takes a global lock around its
read-modify-write, so ``always "fin"`` holds, and ``always "mutex"`` pins
that at most one thread is in the critical section.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from .. import Model, Property
from ._cli import (
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    run_cli,
)


@dataclass(frozen=True)
class LockState:
    i: int
    lock: bool
    s: tuple  # per-thread (local value t, program counter pc)

    def representative(self) -> "LockState":
        return LockState(i=self.i, lock=self.lock, s=tuple(sorted(self.s)))


@dataclass
class IncrementLock(Model):
    thread_count: int

    def init_states(self):
        return [LockState(i=0, lock=False, s=((0, 0),) * self.thread_count)]

    def actions(self, state: LockState):
        acts = []
        for n, (_t, pc) in enumerate(state.s):
            if pc == 0 and not state.lock:
                acts.append(("lock", n))
            elif pc == 1:
                acts.append(("read", n))
            elif pc == 2:
                acts.append(("write", n))
            elif pc == 3 and state.lock:
                acts.append(("release", n))
        return acts

    def next_state(self, state: LockState, action):
        kind, n = action
        s = list(state.s)
        t, pc = s[n]
        if kind == "lock":
            s[n] = (t, 1)
            return replace(state, s=tuple(s), lock=True)
        if kind == "read":
            s[n] = (state.i, 2)
            return replace(state, s=tuple(s))
        if kind == "write":
            s[n] = (t, 3)
            return replace(state, s=tuple(s), i=(t + 1) % 256)
        s[n] = (t, 4)
        return replace(state, s=tuple(s), lock=False)

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, st: sum(1 for _t, pc in st.s if pc >= 3) == st.i,
            ),
            Property.always(
                "mutex",
                lambda m, st: sum(1 for _t, pc in st.s if 1 <= pc < 4) <= 1,
            ),
        ]


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    n = int(rest[0]) if rest else 2
    return [(f"increment_lock threads={n}", IncrementLock(n))]


def main(argv=None):
    def check(rest):
        n = int(rest[0]) if rest else 3
        print(f"Model checking increment-lock with {n} threads.")
        IncrementLock(n).checker().threads(default_threads()).spawn_dfs().report()

    def check_sym(rest):
        n = int(rest[0]) if rest else 3
        IncrementLock(n).checker().threads(
            default_threads()
        ).symmetry().spawn_dfs().report()

    def check_auto(rest):
        n = int(rest[0]) if rest else 3
        print(f"Model checking increment-lock with {n} threads (auto engine).")
        IncrementLock(n).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        n = int(rest[0]) if rest else 3
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        IncrementLock(n).checker().serve(addr)

    run_cli(
        "  increment_lock check [THREAD_COUNT]\n"
        "  increment_lock check-sym [THREAD_COUNT]\n"
        "  increment_lock check-auto [THREAD_COUNT]\n"
        "  increment_lock explore [THREAD_COUNT] [ADDRESS]",
        check,
        check_sym=check_sym,
        check_auto=check_auto,
        explore=explore,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
