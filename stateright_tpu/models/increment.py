"""Racy shared counter (reference ``examples/increment.rs``).

N threads each run ``read; write(local+1)`` without synchronization; the
``always "fin"`` property — the counter equals the number of finished threads
— is violated by interleaved read-modify-write races.  The docstring of the
reference enumerates the full 13-state space at 2 threads and its 8-state
symmetric reduction (``increment.rs:36-105``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from .. import Model, Property
from ._cli import (
    default_threads,
    make_audit_cmd,
    make_profile_cmd,
    make_capacity_cmd,
    make_compare_cmd,
    make_costmodel_cmd,
    make_report_cmd,
    make_independence_cmd,
    make_sanitize_cmd,
    run_cli,
)


@dataclass(frozen=True)
class IncState:
    i: int  # shared counter
    s: tuple  # per-thread (local value t, program counter pc)

    def representative(self) -> "IncState":
        return IncState(i=self.i, s=tuple(sorted(self.s)))


@dataclass
class Increment(Model):
    thread_count: int

    def init_states(self):
        return [IncState(i=0, s=((0, 1),) * self.thread_count)]

    def actions(self, state: IncState):
        acts = []
        for n, (_t, pc) in enumerate(state.s):
            if pc == 1:
                acts.append(("read", n))
            elif pc == 2:
                acts.append(("write", n))
        return acts

    def next_state(self, state: IncState, action):
        kind, n = action
        s = list(state.s)
        if kind == "read":
            s[n] = (state.i, 2)
            return replace(state, s=tuple(s))
        t, _pc = s[n]
        s[n] = (t, 3)
        return IncState(i=(t + 1) % 256, s=tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda m, st: sum(1 for _t, pc in st.s if pc == 3) == st.i,
            )
        ]


def _audit_models(rest=()):
    """Default configurations for the static auditor (``audit`` verb and
    the fleet runner, ``_cli.fleet_audit``)."""
    n = int(rest[0]) if rest else 2
    return [(f"increment threads={n}", Increment(n))]


def main(argv=None):
    def check(rest):
        n = int(rest[0]) if rest else 3
        print(f"Model checking increment with {n} threads.")
        Increment(n).checker().threads(default_threads()).spawn_dfs().report()

    def check_sym(rest):
        n = int(rest[0]) if rest else 3
        print(f"Model checking increment with {n} threads using symmetry reduction.")
        Increment(n).checker().threads(
            default_threads()
        ).symmetry().spawn_dfs().report()

    def check_auto(rest):
        n = int(rest[0]) if rest else 3
        print(f"Model checking increment with {n} threads (auto engine).")
        Increment(n).checker().threads(
            default_threads()
        ).spawn_auto().report()

    def explore(rest):
        n = int(rest[0]) if rest else 3
        addr = rest[1] if len(rest) > 1 else "localhost:3000"
        Increment(n).checker().serve(addr)

    run_cli(
        "  increment check [THREAD_COUNT]\n"
        "  increment check-sym [THREAD_COUNT]\n"
        "  increment check-auto [THREAD_COUNT]\n"
        "  increment explore [THREAD_COUNT] [ADDRESS]",
        check,
        check_sym=check_sym,
        check_auto=check_auto,
        explore=explore,
        audit=make_audit_cmd(_audit_models),
        sanitize=make_sanitize_cmd(_audit_models),
        independence=make_independence_cmd(_audit_models),
        profile=make_profile_cmd(_audit_models),
        report=make_report_cmd(_audit_models),
        capacity=make_capacity_cmd(_audit_models),
        costmodel=make_costmodel_cmd(_audit_models),
        compare=make_compare_cmd(),
        argv=argv,
    )


if __name__ == "__main__":
    main()
