"""In-model network semantics (reference ``src/actor/network.rs``).

The network is *state data*, not I/O: pending messages are part of the
checked system state, and delivery/drop/duplication are state-space actions.
Three semantics, as in the reference (``network.rs:44-64``):

 - **unordered_duplicating** — a set of envelopes; delivery leaves the
   envelope in place (redelivery allowed), drop removes it forever.
 - **unordered_nonduplicating** — a multiset (envelope -> count); delivery
   and drop each consume one copy.
 - **ordered** — per directed ``(src, dst)`` pair, a FIFO queue; only heads
   are deliverable.

All three are persistent (functional) values: mutation returns a new network,
because system states must be immutable and shareable.  Equality and stable
hashing are order-insensitive, mirroring the reference's sorted-pre-hash
containers (``util.rs:124-145``).

For the TPU tensor form these become fixed-capacity encodings in the state
row (see ``parallel/actor_compiler.py``); this module is the object-form
oracle they are tested against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Iterator, Tuple

from ..fingerprint import stable_hash, stable_words


@dataclass(frozen=True)
class Envelope:
    """A message in flight (reference ``network.rs:24-26``)."""

    src: Any  # Id
    dst: Any  # Id
    msg: Any

    @property
    def channel(self) -> Tuple[int, int]:
        """The directed ``(src, dst)`` channel this envelope travels on —
        the unit of the per-channel device packing
        (``parallel/actor_compiler.py``) and, for ordered networks, the
        FIFO flow key."""
        return (int(self.src), int(self.dst))

    def __repr__(self):
        return f"Envelope(src={self.src!r}, dst={self.dst!r}, msg={self.msg!r})"


class Network:
    """Base class + constructors (reference ``network.rs:66-140``)."""

    name: str = ""

    @staticmethod
    def new_ordered(envelopes: Iterable[Envelope] = ()) -> "OrderedNetwork":
        n = OrderedNetwork({})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def new_unordered_duplicating(
        envelopes: Iterable[Envelope] = (),
    ) -> "UnorderedDuplicatingNetwork":
        n = UnorderedDuplicatingNetwork({})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def new_unordered_nonduplicating(
        envelopes: Iterable[Envelope] = (),
    ) -> "UnorderedNonDuplicatingNetwork":
        n = UnorderedNonDuplicatingNetwork({})
        for env in envelopes:
            n = n.send(env)
        return n

    @staticmethod
    def names() -> list[str]:
        return ["ordered", "unordered_duplicating", "unordered_nonduplicating"]

    @staticmethod
    def from_name(name: str) -> "Network":
        try:
            return {
                "ordered": Network.new_ordered,
                "unordered_duplicating": Network.new_unordered_duplicating,
                "unordered_nonduplicating": Network.new_unordered_nonduplicating,
            }[name]()
        except KeyError:
            raise ValueError(f"unable to parse network name: {name}") from None

    # -- interface -----------------------------------------------------------

    def send(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def on_deliver(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def on_drop(self, env: Envelope) -> "Network":
        raise NotImplementedError

    def iter_deliverable(self) -> Iterator[Envelope]:
        """Distinct deliverable envelopes (heads only for ordered flows)."""
        raise NotImplementedError

    def iter_all(self) -> Iterator[Envelope]:
        """Every envelope, with multiplicity."""
        raise NotImplementedError

    def channels(self) -> list:
        """Sorted directed ``(src, dst)`` channels currently carrying
        traffic.  All three semantics share the definition: the channel
        partition of the in-flight set — for ordered networks the
        channels ARE the FIFO flows; for the unordered semantics they are
        the per-destination confinement the per-channel device packing
        exploits (``parallel/actor_compiler.py``)."""
        return sorted({env.channel for env in self.iter_all()})

    def __len__(self) -> int:
        raise NotImplementedError


class UnorderedDuplicatingNetwork(Network):
    """Messages race and can be redelivered (reference ``network.rs:47-48``).
    Delivery is a no-op; only an explicit drop removes an envelope
    (``network.rs:203-205,242-244``)."""

    name = "unordered_duplicating"
    __slots__ = ("_envs",)

    def __init__(self, envs: dict):
        # dict[Envelope, None] as an insertion-ordered set: deterministic
        # iteration within a process, order-insensitive equality
        self._envs = envs

    def send(self, env: Envelope) -> "UnorderedDuplicatingNetwork":
        if env in self._envs:
            return self
        d = dict(self._envs)
        d[env] = None
        return UnorderedDuplicatingNetwork(d)

    def on_deliver(self, env: Envelope) -> "UnorderedDuplicatingNetwork":
        return self  # redelivery allowed

    def on_drop(self, env: Envelope) -> "UnorderedDuplicatingNetwork":
        d = dict(self._envs)
        d.pop(env, None)
        return UnorderedDuplicatingNetwork(d)

    def iter_deliverable(self):
        return iter(self._envs)

    def iter_all(self):
        return iter(self._envs)

    def __len__(self):
        return len(self._envs)

    def __eq__(self, other):
        return (
            isinstance(other, UnorderedDuplicatingNetwork)
            and self._envs.keys() == other._envs.keys()
        )

    def __hash__(self):
        return stable_hash(frozenset(stable_hash(e) for e in self._envs))

    def stable_words(self, out: list) -> None:
        out.append(0xD0)
        out.append(len(self._envs))
        out.extend(sorted(stable_hash(e) for e in self._envs))

    def rewrite(self, plan):
        from ..symmetry import rewrite_value

        n = UnorderedDuplicatingNetwork({})
        for env in self._envs:
            n = n.send(rewrite_value(env, plan))
        return n

    def __repr__(self):
        return f"UnorderedDuplicating({list(self._envs)!r})"


class UnorderedNonDuplicatingNetwork(Network):
    """Multiset of envelopes: no ordering, no redelivery
    (reference ``network.rs:50-51,188-190``)."""

    name = "unordered_nonduplicating"
    __slots__ = ("_counts",)

    def __init__(self, counts: dict):
        self._counts = counts  # Envelope -> positive count

    def send(self, env: Envelope) -> "UnorderedNonDuplicatingNetwork":
        d = dict(self._counts)
        d[env] = d.get(env, 0) + 1
        return UnorderedNonDuplicatingNetwork(d)

    def _consume(self, env: Envelope) -> "UnorderedNonDuplicatingNetwork":
        if env not in self._counts:
            raise KeyError(f"envelope not found: {env!r}")
        d = dict(self._counts)
        if d[env] == 1:
            del d[env]
        else:
            d[env] -= 1
        return UnorderedNonDuplicatingNetwork(d)

    on_deliver = _consume
    on_drop = _consume

    def iter_deliverable(self):
        return iter(self._counts)

    def iter_all(self):
        for env, count in self._counts.items():
            for _ in range(count):
                yield env

    def __len__(self):
        return sum(self._counts.values())

    def __eq__(self, other):
        return (
            isinstance(other, UnorderedNonDuplicatingNetwork)
            and self._counts == other._counts
        )

    def __hash__(self):
        return stable_hash(
            frozenset((stable_hash(e), c) for e, c in self._counts.items())
        )

    def stable_words(self, out: list) -> None:
        out.append(0xD1)
        out.append(len(self._counts))
        out.extend(
            sorted(
                stable_hash((stable_hash(e), c)) for e, c in self._counts.items()
            )
        )

    def rewrite(self, plan):
        from ..symmetry import rewrite_value

        d: dict = {}
        for env, count in self._counts.items():
            key = rewrite_value(env, plan)
            d[key] = d.get(key, 0) + count
        return UnorderedNonDuplicatingNetwork(d)

    def __repr__(self):
        return f"UnorderedNonDuplicating({dict(self._counts)!r})"


class OrderedNetwork(Network):
    """Per-directed-pair FIFO flows (reference ``network.rs:53-63``).  Only
    the head of each flow is deliverable; empty flows are removed so removal
    is the exact inverse of insertion (``network.rs:219-235``)."""

    name = "ordered"
    __slots__ = ("_flows",)

    def __init__(self, flows: dict):
        self._flows = flows  # (src, dst) -> tuple of msgs (non-empty)

    def send(self, env: Envelope) -> "OrderedNetwork":
        key = (env.src, env.dst)
        d = dict(self._flows)
        d[key] = d.get(key, ()) + (env.msg,)
        return OrderedNetwork(d)

    def _remove(self, env: Envelope) -> "OrderedNetwork":
        key = (env.src, env.dst)
        if key not in self._flows:
            raise KeyError(f"flow not found: {key!r}")
        flow = self._flows[key]
        try:
            i = flow.index(env.msg)
        except ValueError:
            raise KeyError(f"message not found in flow: {env!r}") from None
        d = dict(self._flows)
        if len(flow) == 1:
            del d[key]
        else:
            d[key] = flow[:i] + flow[i + 1 :]
        return OrderedNetwork(d)

    on_deliver = _remove
    on_drop = _remove

    def iter_deliverable(self):
        # sorted flow order like the reference's BTreeMap for determinism
        for key in sorted(self._flows):
            yield Envelope(key[0], key[1], self._flows[key][0])

    def iter_all(self):
        for key in sorted(self._flows):
            for msg in self._flows[key]:
                yield Envelope(key[0], key[1], msg)

    def __len__(self):
        return sum(len(f) for f in self._flows.values())

    def __eq__(self, other):
        return isinstance(other, OrderedNetwork) and self._flows == other._flows

    def __hash__(self):
        return stable_hash(
            frozenset(
                (int(k[0]), int(k[1]), stable_hash(tuple(v)))
                for k, v in self._flows.items()
            )
        )

    def stable_words(self, out: list) -> None:
        out.append(0xD2)
        out.append(len(self._flows))
        hashes = []
        for (src, dst), msgs in self._flows.items():
            words: list = [int(src), int(dst)]
            stable_words(tuple(msgs), words)
            from ..fingerprint import hash_words

            hashes.append(hash_words(words))
        out.extend(sorted(hashes))

    def rewrite(self, plan):
        from ..symmetry import rewrite_value

        return OrderedNetwork(
            {
                (plan.rewrite_id(k[0]), plan.rewrite_id(k[1])): tuple(
                    rewrite_value(m, plan) for m in v
                )
                for k, v in self._flows.items()
            }
        )

    def __repr__(self):
        return f"Ordered({dict(self._flows)!r})"
