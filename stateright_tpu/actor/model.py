"""ActorModel: compile actors + network + properties into a checkable Model
(reference ``src/actor/model.rs``, ``src/actor/model_state.rs``).

``C`` is an arbitrary config value, ``H`` an auxiliary history maintained
TLA-style alongside the system (e.g. a linearizability tester); both are
available to property conditions.  Transition semantics follow the reference
precisely (they determine state-space counts pinned by tests):

 - ``Deliver``: run ``on_msg``; a no-op handler result prunes the transition
   entirely (``model.rs:253-260`` — note the reference's documented caveat
   that this is only safe when properties don't inspect envelope existence);
   otherwise consume the envelope per network semantics, swap the actor
   state, update history via ``record_msg_in``, then apply emitted commands
   (sends → network + ``record_msg_out``; timer flags).
 - ``Timeout``: run ``on_timeout``; prune only if no-op AND the handler
   re-set its timer; otherwise the timer flag clears even on no-op
   (``model.rs:288-306``).
 - ``Drop``: lossy networks only; remove the envelope (``model.rs:243-247``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional, Sequence

from ..core import Expectation, Model, Property
from ..fingerprint import stable_hash, stable_words
from .network import Envelope, Network, OrderedNetwork
from . import Actor, CancelTimer, Id, Out, Send, SetTimer


# -- actions (reference ``model.rs:42-51``) ----------------------------------


@dataclass(frozen=True)
class Deliver:
    src: Id
    dst: Id
    msg: Any

    def __repr__(self):
        return f"{self.src!r} → {self.msg!r} → {self.dst!r}"


@dataclass(frozen=True)
class Drop:
    envelope: Envelope

    def __repr__(self):
        return f"Drop({self.envelope!r})"


@dataclass(frozen=True)
class Timeout:
    id: Id

    def __repr__(self):
        return f"Timeout({self.id!r})"


# -- system state (reference ``model_state.rs:10-15``) -----------------------


@dataclass(frozen=True)
class ActorModelState:
    """Snapshot of the whole system: per-actor states, in-flight network,
    timer flags, auxiliary history."""

    actor_states: tuple
    network: Network
    is_timer_set: tuple
    history: Any = None

    def __hash__(self):
        return stable_hash(self)

    def stable_words(self, out: list) -> None:
        out.append(0xA5)
        stable_words(tuple(self.actor_states), out)
        self.network.stable_words(out)
        stable_words(tuple(self.is_timer_set), out)
        stable_words(self.history, out)

    def representative(self) -> "ActorModelState":
        """Canonical member of this state's symmetry class: actor states
        sorted, ids rewritten across network/history
        (reference ``model_state.rs:103-118``)."""
        from ..symmetry import RewritePlan, rewrite_value

        plan = RewritePlan.from_values_to_sort(
            [stable_hash(s) for s in self.actor_states]
        )
        return ActorModelState(
            actor_states=tuple(
                rewrite_value(s, plan) for s in plan.reindex(self.actor_states)
            ),
            network=rewrite_value(self.network, plan),
            is_timer_set=tuple(plan.reindex(self.is_timer_set)),
            history=rewrite_value(self.history, plan),
        )


class _Draft:
    """Mutable builder for the immutable ActorModelState."""

    __slots__ = ("actor_states", "network", "is_timer_set", "history")

    def __init__(self, base: ActorModelState):
        self.actor_states = list(base.actor_states)
        self.network = base.network
        self.is_timer_set = list(base.is_timer_set)
        self.history = base.history

    def freeze(self) -> ActorModelState:
        return ActorModelState(
            actor_states=tuple(self.actor_states),
            network=self.network,
            is_timer_set=tuple(self.is_timer_set),
            history=self.history,
        )


def _xml_escape(text: str) -> str:
    return (
        text.replace("&", "&amp;")
        .replace("<", "&lt;")
        .replace(">", "&gt;")
        .replace("'", "&apos;")
    )


# -- the model ---------------------------------------------------------------


def _default_boundary(cfg, state) -> bool:
    """Module-level so compilers can recognize a trivial boundary."""
    return True


class ActorModel(Model):
    """Builder + Model implementation (reference ``model.rs:27-155,187-494``)."""

    def __init__(self, cfg: Any = None, init_history: Any = None):
        self.actors: list[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self.init_network: Network = Network.new_unordered_duplicating()
        self.lossy: bool = False
        # device-twin network packing (parallel/actor_compiler.py): None =
        # unset (the STATERIGHT_TPU_PER_CHANNEL env knob decides), else the
        # per_channel_() builder's explicit choice
        self.per_channel: Optional[bool] = None
        self._properties: list[Property] = []
        self._record_msg_in: Callable = lambda cfg, h, env: None
        self._record_msg_out: Callable = lambda cfg, h, env: None
        self._within_boundary: Callable = _default_boundary

    # -- builder (reference ``model.rs:80-155``) -----------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self._config_mutated()
        self.actors.append(actor)
        return self

    def actor_many(self, actors: Iterable[Actor]) -> "ActorModel":
        self._config_mutated()
        self.actors.extend(actors)
        return self

    def init_network_(self, network: Network) -> "ActorModel":
        self._config_mutated()
        self.init_network = network
        return self

    def lossy_network(self, lossy: bool) -> "ActorModel":
        self._config_mutated()
        self.lossy = lossy
        return self

    def per_channel_(self, enabled: bool = True) -> "ActorModel":
        """Request the per-(src,dst)-channel network packing for the
        compiled device twin (``parallel/actor_compiler.py``): the row
        reserves one slot region per directed channel instead of one
        global slot multiset, which makes a delivery's writes statically
        confined — the independence analysis can then decompose the
        action stack (no ``JX302``) and ``por()`` produces real reduction
        on consensus-shaped workloads (``docs/analysis.md``
        "Per-channel encoding").  Changes row fingerprints (an encoding
        choice, like the twin itself); unique/total counts and property
        verdicts are bit-identical to the slot-multiset packing, pinned.
        One capacity caveat: an ORDERED flow holding the same message at
        more ranks than its channel's distinct-code count poisons loudly
        (never silently diverges) — raise the region size with
        ``compile_actor_model(per_channel_depth=...)`` for retransmitting
        protocols.  CLI flag: ``--per-channel`` on the device verbs; env
        knob: ``STATERIGHT_TPU_PER_CHANNEL=1``."""
        self._config_mutated()
        self.per_channel = bool(enabled)
        return self

    def per_channel_resolved(self) -> bool:
        """The effective per-channel choice: the builder flag when set,
        else the ``STATERIGHT_TPU_PER_CHANNEL=1`` env knob — the ONE
        resolution rule, shared by the compiler and by ``tensor_model``
        implementations that must route between a hand-tuned slot-multiset
        twin and the compiled per-channel one (``models/paxos.py``)."""
        if self.per_channel is not None:
            return bool(self.per_channel)
        import os

        return os.environ.get("STATERIGHT_TPU_PER_CHANNEL", "") == "1"

    def property(
        self, expectation: Expectation, name: str, condition: Callable
    ) -> "ActorModel":
        self._config_mutated()
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, fn: Callable) -> "ActorModel":
        """``fn(cfg, history, envelope) -> Optional[new_history]``."""
        self._config_mutated()
        self._record_msg_in = fn
        return self

    def record_msg_out(self, fn: Callable) -> "ActorModel":
        self._config_mutated()
        self._record_msg_out = fn
        return self

    def within_boundary_(self, fn: Callable) -> "ActorModel":
        self._config_mutated()
        self._within_boundary = fn
        return self

    # -- Model implementation ------------------------------------------------

    def properties(self) -> Sequence[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)

    def init_states(self) -> list[ActorModelState]:
        draft = _Draft(
            ActorModelState(
                actor_states=(),
                network=self.init_network,
                is_timer_set=(False,) * len(self.actors),
                history=self.init_history,
            )
        )
        for index, actor in enumerate(self.actors):
            out = Out()
            state = actor.on_start(Id(index), out)
            draft.actor_states.append(state)
            self._process_commands(Id(index), out, draft)
        return [draft.freeze()]

    def actions(self, state: ActorModelState) -> list:
        acts: list = []
        for env in state.network.iter_deliverable():
            # option 1: message is lost (reference ``model.rs:218-220``)
            if self.lossy:
                acts.append(Drop(env))
            # option 2: delivered — unless the recipient doesn't exist
            if int(env.dst) < len(self.actors):
                acts.append(Deliver(src=env.src, dst=env.dst, msg=env.msg))
        # option 3: timeouts (reference ``model.rs:234-238``)
        for index, is_set in enumerate(state.is_timer_set):
            if is_set:
                acts.append(Timeout(Id(index)))
        return acts

    def next_state(
        self, sys: ActorModelState, action
    ) -> Optional[ActorModelState]:
        if isinstance(action, Drop):
            draft = _Draft(sys)
            draft.network = draft.network.on_drop(action.envelope)
            return draft.freeze()

        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(sys.actor_states):
                return None  # undeliverable (reference ``model.rs:253``)
            last_actor_state = sys.actor_states[index]
            out = Out()
            new_actor_state = self.actors[index].on_msg(
                Id(index), last_actor_state, action.src, action.msg, out
            )
            if new_actor_state is None and not out.commands:
                return None  # no-op prune (reference ``model.rs:260``)
            env = Envelope(src=action.src, dst=action.dst, msg=action.msg)
            history = self._record_msg_in(self.cfg, sys.history, env)
            draft = _Draft(sys)
            draft.network = draft.network.on_deliver(env)
            if new_actor_state is not None:
                draft.actor_states[index] = new_actor_state
            if history is not None:
                draft.history = history
            self._process_commands(Id(index), out, draft)
            return draft.freeze()

        if isinstance(action, Timeout):
            index = int(action.id)
            out = Out()
            new_actor_state = self.actors[index].on_timeout(
                Id(index), sys.actor_states[index], out
            )
            keep_timer = any(isinstance(c, SetTimer) for c in out.commands)
            if new_actor_state is None and not out.commands and keep_timer:
                return None
            draft = _Draft(sys)
            draft.is_timer_set[index] = False  # timer no longer valid
            if new_actor_state is not None:
                draft.actor_states[index] = new_actor_state
            self._process_commands(Id(index), out, draft)
            return draft.freeze()

        raise TypeError(f"unknown action {action!r}")

    # -- helpers -------------------------------------------------------------

    def _process_commands(self, id: Id, out: Out, draft: _Draft) -> None:
        """Apply emitted commands to the draft system state
        (reference ``model.rs:158-184``)."""
        index = int(id)
        for c in out.commands:
            if isinstance(c, Send):
                env = Envelope(src=id, dst=c.dst, msg=c.msg)
                history = self._record_msg_out(self.cfg, draft.history, env)
                if history is not None:
                    draft.history = history
                draft.network = draft.network.send(env)
            elif isinstance(c, SetTimer):
                while len(draft.is_timer_set) <= index:
                    draft.is_timer_set.append(False)
                draft.is_timer_set[index] = True
            elif isinstance(c, CancelTimer):
                draft.is_timer_set[index] = False

    def as_svg(self, path) -> Optional[str]:
        """Sequence diagram for an actor-system trace (reference
        ``src/actor/model.rs:384-475``): a vertical timeline per actor,
        an arrow per delivery from its send time to its delivery time, a
        circle per timeout, and message labels drawn last so they sit on
        top.  Send times are recovered by re-running the (pure) handlers
        along the path, exactly as the reference does."""
        entries = path.into_vec()  # [(state, action|None), ...]
        if not entries:
            return None
        actor_count = len(entries[-1][0].actor_states)

        def plot(x: int, y: int) -> tuple[int, int]:
            return x * 100, y * 30

        svg_w, svg_h = plot(actor_count, len(entries))
        svg_w += 300  # extra width for event labels, as in the reference
        out = [
            f"<svg version='1.1' baseProfile='full' "
            f"width='{svg_w}' height='{svg_h}' "
            f"viewBox='-20 -20 {svg_w + 20} {svg_h + 20}' "
            f"xmlns='http://www.w3.org/2000/svg'>",
            "<defs><marker class='svg-event-shape' id='arrow' "
            "markerWidth='12' markerHeight='10' refX='12' refY='5' "
            "orient='auto'><polygon points='0 0, 12 5, 0 10' />"
            "</marker></defs>",
        ]
        for index in range(actor_count):
            x1, y1 = plot(index, 0)
            x2, y2 = plot(index, len(entries))
            out.append(
                f"<line x1='{x1}' y1='{y1}' x2='{x2}' y2='{y2}' "
                "class='svg-actor-timeline' />"
            )
            out.append(
                f"<text x='{x1}' y='{y1}' class='svg-actor-label'>"
                f"{index}</text>"
            )

        def track_sends(handler_id: Id, cmds, time: int) -> None:
            for c in cmds:
                if isinstance(c, Send):
                    send_time[(handler_id, c.dst, c.msg)] = time

        # Arrows for deliveries, circles for timeouts.  ``time`` is the row
        # the action lands on (the action at entry i produces entry i+1).
        send_time: dict = {}
        for i, (state, action) in enumerate(entries):
            time = i + 1
            if isinstance(action, Deliver):
                src_time = send_time.get((action.src, action.dst, action.msg), 0)
                x1, y1 = plot(int(action.src), src_time)
                x2, y2 = plot(int(action.dst), time)
                out.append(
                    f"<line x1='{x1}' x2='{x2}' y1='{y1}' y2='{y2}' "
                    "marker-end='url(#arrow)' class='svg-event-line' />"
                )
                index = int(action.dst)
                if index < len(state.actor_states):
                    cmds = Out()
                    self.actors[index].on_msg(
                        Id(index),
                        state.actor_states[index],
                        action.src,
                        action.msg,
                        cmds,
                    )
                    track_sends(Id(index), cmds.commands, time)
            elif isinstance(action, Timeout):
                index = int(action.id)
                x, y = plot(index, time)
                out.append(
                    f"<circle cx='{x}' cy='{y}' r='10' "
                    "class='svg-event-shape' />"
                )
                if index < len(state.actor_states):
                    cmds = Out()
                    self.actors[index].on_timeout(
                        Id(index), state.actor_states[index], cmds
                    )
                    track_sends(Id(index), cmds.commands, time)

        # Event labels drawn last so they render over the shapes.
        for i, (_state, action) in enumerate(entries):
            time = i + 1
            if isinstance(action, Deliver):
                x, y = plot(int(action.dst), time)
                out.append(
                    f"<text x='{x}' y='{y}' class='svg-event-label'>"
                    f"{_xml_escape(repr(action.msg))}</text>"
                )
            elif isinstance(action, Timeout):
                x, y = plot(int(action.id), time)
                out.append(
                    f"<text x='{x}' y='{y}' class='svg-event-label'>"
                    "Timeout</text>"
                )
        out.append("</svg>")
        return "".join(out)

    def format_action(self, action) -> str:
        return repr(action)

    def format_step(self, last_state, action) -> Optional[str]:
        nxt = self.next_state(last_state, action)
        if nxt is None:
            return None
        lines = []
        for i, (a, b) in enumerate(zip(last_state.actor_states, nxt.actor_states)):
            mark = " *" if a != b else ""
            lines.append(f"actor {i}: {b!r}{mark}")
        lines.append(f"network: {sorted(map(repr, nxt.network.iter_all()))}")
        if nxt.history is not None:
            lines.append(f"history: {nxt.history!r}")
        return "\n".join(lines)
