"""Actor framework (reference L4–L6, ``src/actor.rs`` + ``src/actor/``).

An :class:`Actor` is an event-driven state machine: ``on_start`` produces the
initial state, ``on_msg``/``on_timeout`` react to events by returning an
updated state (or ``None`` for "unchanged") and emitting commands into an
:class:`Out` buffer.  An :class:`~stateright_tpu.actor.model.ActorModel`
compiles a set of actors + a network semantics + properties into a checkable
:class:`~stateright_tpu.core.Model`, and the same actor code can be deployed
over real UDP sockets via :func:`~stateright_tpu.actor.spawn.spawn`.

Differences from the reference, deliberately Pythonic:

 - Handlers return the new state instead of mutating a ``Cow``; returning
   ``None`` (with no commands) marks the no-op transitions the model prunes
   (reference ``actor.rs:238-240``).  States must be immutable values.
 - Heterogeneous actor systems rarely need a ``Choice`` combinator
   (reference ``actor.rs:298-426``): ``ActorModel.actors`` may freely mix
   actor classes that share a message vocabulary.  The explicit combinator
   still exists (``actor/choice.py``) for the case the reference built it
   for — wrapping differently-typed actors whose states could otherwise
   collide as equal values — with variant-tagged states.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Sequence, Tuple

__all__ = [
    "Id",
    "Command",
    "Send",
    "SetTimer",
    "CancelTimer",
    "Out",
    "Actor",
    "Choice",
    "ChoiceState",
    "ScriptedActor",
    "majority",
    "model_peers",
    "model_timeout",
    "Envelope",
    "Network",
    "ActorModel",
    "ActorModelState",
    "Deliver",
    "Drop",
    "Timeout",
    "spawn",
]


class Id(int):
    """Actor identity: an index for model checking, an IPv4 socket address for
    the UDP runtime (reference ``actor.rs:107-153``, ``spawn.rs:9-33``)."""

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def vec_from(ids: Iterable[int]) -> list["Id"]:
        return [Id(i) for i in ids]

    # -- sockaddr packing (reference ``spawn.rs:9-33``) ----------------------

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        parts = [int(p) for p in ip.split(".")]
        assert len(parts) == 4
        v = 0
        for p in parts:
            v = (v << 8) | p
        return Id((v << 16) | port)

    def to_addr(self) -> tuple[str, int]:
        port = int(self) & 0xFFFF
        ip_bits = int(self) >> 16
        ip = ".".join(str((ip_bits >> s) & 0xFF) for s in (24, 16, 8, 0))
        return ip, port


# -- commands (reference ``actor.rs:155-234``) -------------------------------


@dataclass(frozen=True)
class Send:
    dst: Id
    msg: Any


@dataclass(frozen=True)
class SetTimer:
    #: (low, high) seconds; irrelevant for model checking
    duration: Tuple[float, float] = (0.0, 0.0)


@dataclass(frozen=True)
class CancelTimer:
    pass


Command = (Send, SetTimer, CancelTimer)


def model_timeout() -> Tuple[float, float]:
    """Arbitrary timer range for model checking, where the specific value is
    irrelevant (reference ``model.rs:62-64``)."""
    return (0.0, 0.0)


class Out:
    """Buffer of commands an actor emits during a handler
    (reference ``actor.rs:156-234``)."""

    def __init__(self):
        self.commands: list = []

    def send(self, dst: Id, msg: Any) -> None:
        self.commands.append(Send(Id(dst), msg))

    def broadcast(self, dsts: Iterable[Id], msg: Any) -> None:
        for dst in dsts:
            self.send(dst, msg)

    def set_timer(self, duration: Tuple[float, float] = (0.0, 0.0)) -> None:
        self.commands.append(SetTimer(duration))

    def cancel_timer(self) -> None:
        self.commands.append(CancelTimer())

    def __iter__(self):
        return iter(self.commands)

    def __len__(self):
        return len(self.commands)

    def __repr__(self):
        return f"Out({self.commands!r})"


class Actor:
    """Event-driven actor (reference ``actor.rs:246-296``).

    States must be immutable hashable values.  ``on_msg``/``on_timeout``
    return the updated state, or ``None`` to signal "state unchanged"; an
    unchanged state with no emitted commands is a no-op transition, which the
    model checker prunes from the state space (reference ``model.rs:253-260``).
    """

    def on_start(self, id: Id, out: Out):
        raise NotImplementedError

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        return None  # no-op by default

    def on_timeout(self, id: Id, state, out: Out):
        return None  # no-op by default

    # -- runtime serde hooks (overridable; used by spawn) --------------------

    def serialize(self, msg) -> bytes:
        import json

        return json.dumps(msg).encode()

    def deserialize(self, data: bytes):
        import json

        def tuplize(v):
            if isinstance(v, list):
                return tuple(tuplize(x) for x in v)
            if isinstance(v, dict):
                return {k: tuplize(x) for k, x in v.items()}
            return v

        # JSON arrays become tuples so wire messages compare equal to the
        # tuples used in model checking
        return tuplize(json.loads(data.decode()))


@dataclass
class ScriptedActor(Actor):
    """Sends a scripted series of messages, one after each delivery it
    receives — useful for testing actor systems (reference
    ``actor.rs:440-469``, ``impl Actor for Vec<(Id, Msg)>``)."""

    script: Sequence[Tuple[Id, Any]]

    def on_start(self, id: Id, out: Out):
        if self.script:
            dst, msg = self.script[0]
            out.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if state < len(self.script):
            dst, m = self.script[state]
            out.send(dst, m)
            return state + 1
        return None


def majority(cluster_size: int) -> int:
    """Number of nodes constituting a majority (reference ``actor.rs:472-474``)."""
    return cluster_size // 2 + 1


def model_peers(self_ix: int, count: int) -> list[Id]:
    """All ids except one's own (reference ``model.rs:68-73``)."""
    return [Id(j) for j in range(count) if j != self_ix]


from .network import Envelope, Network  # noqa: E402
from .model import ActorModel, ActorModelState, Deliver, Drop, Timeout  # noqa: E402
from .spawn import spawn  # noqa: E402
from .choice import Choice, ChoiceState  # noqa: E402
