"""Register-like actor interface + test client (reference ``src/actor/register.rs``).

``RegisterMsg`` is the wire vocabulary between clients and register servers,
as tagged tuples:

 - ``("internal", msg)`` — server-to-server protocol internals
 - ``("put", req_id, value)`` / ``("get", req_id)`` — client requests
 - ``("put_ok", req_id)`` / ``("get_ok", req_id, value)`` — server replies

:func:`record_invocations` / :func:`record_returns` bridge these messages into
a :class:`~stateright_tpu.semantics.ConsistencyTester` history
(pass to ``ActorModel.record_msg_out`` / ``record_msg_in``), and
:class:`RegisterClient` is the scripted workload: ``put_count`` puts then one
get, round-robining servers.  Servers must precede clients in the actor list
so client ids can derive server ids by modulo (reference
``register.rs:116-135``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.register import READ, write
from . import Actor, Id, Out

#: The register's initial value (reference uses Rust's ``char::default()``).
NULL_VALUE = "\0"


def Internal(msg) -> tuple:
    return ("internal", msg)


def Put(req_id, value) -> tuple:
    return ("put", req_id, value)


def Get(req_id) -> tuple:
    return ("get", req_id)


def PutOk(req_id) -> tuple:
    return ("put_ok", req_id)


def GetOk(req_id, value) -> tuple:
    return ("get_ok", req_id, value)


def record_invocations(cfg, history, env):
    """Record Read on Get, Write on Put (reference ``register.rs:37-58``).
    Pass to ``ActorModel.record_msg_out``."""
    kind = env.msg[0]
    if kind == "get":
        return history.on_invoke(env.src, READ)
    if kind == "put":
        return history.on_invoke(env.src, write(env.msg[2]))
    return None


def record_returns(cfg, history, env):
    """Record ReadOk on GetOk, WriteOk on PutOk (reference
    ``register.rs:64-87``).  Pass to ``ActorModel.record_msg_in``."""
    kind = env.msg[0]
    if kind == "get_ok":
        return history.on_return(env.dst, ("read_ok", env.msg[2]))
    if kind == "put_ok":
        return history.on_return(env.dst, ("write_ok",))
    return None


def value_chosen(model, state) -> bool:
    """``sometimes`` condition: a non-null value is being returned to a
    client (shared by the register examples — reference
    ``paxos.rs:255-262``)."""
    for env in state.network.iter_deliverable():
        if env.msg[0] == "get_ok" and env.msg[2] != NULL_VALUE:
            return True
    return False


@dataclass(frozen=True)
class RegisterClientState:
    awaiting: Optional[int]
    op_count: int


@dataclass
class RegisterClient(Actor):
    """Puts ``put_count`` values then gets, awaiting each response
    (reference ``register.rs:90-216``).  Request ids are unique per client
    (``(op_count+1) * index``); values are letters derived from the client
    index ('A'.. for the first put, 'Z'-.. for subsequent)."""

    put_count: int
    server_count: int

    #: reply kinds acknowledging a put; the write-once variant also
    #: accepts ``put_fail``
    put_reply_kinds = ("put_ok",)

    @staticmethod
    def put_value(index: int, server_count: int, op_index: int) -> str:
        """Value the ``op_index``-th put (0-based) of client ``index``
        writes: 'A'.. for the first put, 'Z'-.. for every later one
        (reference ``register.rs:140,178``).  The single source of the
        scheme — the actor compiler derives per-client write scripts from
        it, so the real workload and the compiled history codec cannot
        drift."""
        if op_index == 0:
            return chr(ord("A") + index - server_count)
        return chr(ord("Z") - (index - server_count))

    def on_start(self, id: Id, out: Out):
        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return RegisterClientState(awaiting=None, op_count=0)
        req_id = index
        value = self.put_value(index, self.server_count, 0)
        out.send(Id(index % self.server_count), Put(req_id, value))
        return RegisterClientState(awaiting=req_id, op_count=1)

    def on_msg(self, id: Id, state, src: Id, msg, out: Out):
        if state.awaiting is None:
            return None
        index = int(id)
        kind = msg[0]
        if kind in self.put_reply_kinds and msg[1] == state.awaiting:
            req_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = self.put_value(
                    index, self.server_count, state.op_count
                )
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Put(req_id, value),
                )
            else:
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Get(req_id),
                )
            return RegisterClientState(
                awaiting=req_id, op_count=state.op_count + 1
            )
        if kind == "get_ok" and msg[1] == state.awaiting:
            return RegisterClientState(
                awaiting=None, op_count=state.op_count + 1
            )
        return None
