"""Real UDP actor runtime (reference ``src/actor/spawn.rs``).

The same actor code that was model checked can be deployed: one OS thread per
actor, a blocking UDP socket loop, timers implemented as receive timeouts
(reference ``spawn.rs:63-140``).  Ids encode IPv4 socket addresses
(``spawn.rs:9-33`` — see :meth:`Id.from_addr`/:meth:`Id.to_addr`).

Serialization is pluggable per actor via ``Actor.serialize``/``deserialize``
(JSON by default, as in the reference's examples).  Malformed or non-IPv4
input is logged and ignored (``spawn.rs:105-133``).
"""

from __future__ import annotations

import logging
import random
import socket
import threading
import time
from typing import Iterable, Optional, Tuple

from . import Actor, CancelTimer, Id, Out, Send, SetTimer

log = logging.getLogger(__name__)

#: Used when no timer is set (reference ``practically_never``, ``spawn.rs:36-38``).
_PRACTICALLY_NEVER = 60.0 * 60.0 * 24.0 * 365.0


class SpawnedActor:
    """Handle to a running actor thread."""

    def __init__(self, id: Id, actor: Actor):
        self.id = id
        self.actor = actor
        self.thread: Optional[threading.Thread] = None
        self.sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.state = None  # exposed for tests/debugging

    def stop(self):
        self._stop.set()

    def join(self, timeout: Optional[float] = None):
        if self.thread:
            self.thread.join(timeout)


def _run(handle: SpawnedActor) -> None:
    actor, id, sock = handle.actor, handle.id, handle.sock
    try:
        out = Out()
        state = actor.on_start(id, out)
        log.info("%r started: %r", id, state)
        timer_deadline: Optional[float] = None
        timer_deadline = _on_commands(actor, id, sock, out, timer_deadline)
        while not handle._stop.is_set():
            handle.state = state
            timeout = (
                max(0.0, timer_deadline - time.monotonic())
                if timer_deadline is not None
                else _PRACTICALLY_NEVER
            )
            # clamp to a small positive value: settimeout(0) would switch
            # the socket to non-blocking and make recvfrom raise
            # BlockingIOError instead of timing out
            sock.settimeout(min(max(timeout, 0.001), 0.2))
            out = Out()
            try:
                data, addr = sock.recvfrom(65536)
            except (socket.timeout, BlockingIOError):
                if (
                    timer_deadline is not None
                    and time.monotonic() >= timer_deadline
                ):
                    timer_deadline = None
                    new = actor.on_timeout(id, state, out)
                    if new is not None:
                        state = new
                    timer_deadline = _on_commands(
                        actor, id, sock, out, timer_deadline
                    )
                continue
            try:
                msg = actor.deserialize(data)
            except Exception as e:  # malformed input is logged and ignored
                log.warning("%r failed to deserialize %r: %r", id, data[:64], e)
                continue
            src = Id.from_addr(addr[0], addr[1])
            new = actor.on_msg(id, state, src, msg, out)
            if new is not None:
                state = new
            timer_deadline = _on_commands(actor, id, sock, out, timer_deadline)
    finally:
        sock.close()


def _on_commands(
    actor: Actor,
    id: Id,
    sock: socket.socket,
    out: Out,
    timer_deadline: Optional[float],
) -> Optional[float]:
    """Apply emitted commands: sends serialize + send_to; SetTimer samples the
    random range (reference ``spawn.rs:143-183``)."""
    for c in out.commands:
        if isinstance(c, Send):
            try:
                data = actor.serialize(c.msg)
            except Exception as e:
                log.warning("%r failed to serialize %r: %r", id, c.msg, e)
                continue
            ip, port = Id(c.dst).to_addr()
            log.info("%r sending %r to %r", id, c.msg, c.dst)
            sock.sendto(data, (ip, port))
        elif isinstance(c, SetTimer):
            low, high = c.duration
            timer_deadline = time.monotonic() + random.uniform(low, max(low, high))
        elif isinstance(c, CancelTimer):
            timer_deadline = None
    return timer_deadline


def spawn(
    actors: Iterable[Tuple[Id, Actor]], background: bool = True
) -> list[SpawnedActor]:
    """Run actors on real UDP sockets, one thread each
    (reference ``spawn.rs:63-140``).

    ``actors`` pairs each actor with the :class:`Id` encoding its socket
    address (e.g. ``Id.from_addr("127.0.0.1", 3000)``).  Returns handles;
    with ``background=False`` blocks until all threads exit.
    """
    handles = []
    try:
        for id, actor in actors:
            handle = SpawnedActor(Id(id), actor)
            # bind synchronously: callers may send to the actor the moment
            # spawn() returns, and a bind failure should raise here, not die
            # silently inside a daemon thread
            ip, port = handle.id.to_addr()
            handle.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            handles.append(handle)  # appended first so a bind failure below
            #                         still closes this handle's socket
            handle.sock.bind((ip, port))
            handle.thread = threading.Thread(
                target=_run, args=(handle,), daemon=True
            )
    except BaseException:
        # partial failure: no thread has started yet (so no _run/finally
        # will close anything) — release every socket bound so far, or the
        # ports stay stuck until GC.  BaseException, not just OSError:
        # Id()/to_addr() can raise for a malformed id and the earlier binds
        # must still be released.
        for h in handles:
            if h.sock is not None:
                h.sock.close()
        raise
    for h in handles:
        h.thread.start()
    if not background:
        for h in handles:
            h.join()
    return handles
