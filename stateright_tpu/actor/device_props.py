"""Factored, device-compilable property predicates for actor systems.

Reference properties are arbitrary closures over the whole system state
(``lib.rs:247``) — fine for host checking, opaque to compilation.  These
constructors express the common shapes that *factor through per-actor
states*:

 - :func:`forall_actors` / :func:`exists_actor` — a predicate of one
   actor's state, quantified over actors;
 - :func:`forall_actor_pairs` / :func:`exists_actor_pair` — a predicate
   of two actors' states, quantified over unordered pairs ``i < j``.

A factored predicate is an ordinary property condition — callable as
``cond(model, sys_state)`` and usable with every CPU checker — but the
actor compiler (``parallel/actor_compiler.py``) additionally recognizes
it and *tabulates* the predicate over the compiled per-actor state
universes, so the same property evaluates as table lookups fused over a
device wavefront.  Host and device agree by construction: both evaluate
the one predicate you wrote, the host directly and the device via its
tabulation.

Example — Raft's election safety::

    model.property(
        Expectation.ALWAYS,
        "at most one leader per term",
        forall_actor_pairs(
            lambda i, si, j, sj: not (
                si.role == LEADER and sj.role == LEADER
                and si.term == sj.term
            )
        ),
    )
"""

from __future__ import annotations

from itertools import combinations
from typing import Callable

__all__ = [
    "FactoredPredicate",
    "forall_actors",
    "exists_actor",
    "forall_actor_pairs",
    "exists_actor_pair",
]


class FactoredPredicate:
    """A property condition that factors through per-actor states.

    ``kind`` is one of ``"forall"``, ``"exists"`` (pred over one actor:
    ``pred(i, state_i)``) or ``"forall_pairs"``, ``"exists_pair"``
    (pred over an unordered pair ``i < j``:
    ``pred(i, state_i, j, state_j)``).
    """

    def __init__(self, kind: str, pred: Callable, label: str):
        assert kind in ("forall", "exists", "forall_pairs", "exists_pair")
        self.kind = kind
        self.pred = pred
        self._label = label

    def __repr__(self) -> str:
        return f"{self._label}({self.pred!r})"

    def __call__(self, model, sys_state) -> bool:
        states = sys_state.actor_states
        if self.kind == "forall":
            return all(self.pred(i, s) for i, s in enumerate(states))
        if self.kind == "exists":
            return any(self.pred(i, s) for i, s in enumerate(states))
        pairs = combinations(range(len(states)), 2)
        if self.kind == "forall_pairs":
            return all(
                self.pred(i, states[i], j, states[j]) for i, j in pairs
            )
        return any(self.pred(i, states[i], j, states[j]) for i, j in pairs)


def forall_actors(pred: Callable) -> FactoredPredicate:
    """True iff ``pred(i, state_i)`` holds for every actor."""
    return FactoredPredicate("forall", pred, "forall_actors")


def exists_actor(pred: Callable) -> FactoredPredicate:
    """True iff ``pred(i, state_i)`` holds for some actor."""
    return FactoredPredicate("exists", pred, "exists_actor")


def forall_actor_pairs(pred: Callable) -> FactoredPredicate:
    """True iff ``pred(i, s_i, j, s_j)`` holds for every pair ``i < j``."""
    return FactoredPredicate("forall_pairs", pred, "forall_actor_pairs")


def exists_actor_pair(pred: Callable) -> FactoredPredicate:
    """True iff ``pred(i, s_i, j, s_j)`` holds for some pair ``i < j``."""
    return FactoredPredicate("exists_pair", pred, "exists_actor_pair")
