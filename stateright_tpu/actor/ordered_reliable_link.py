"""Ordered reliable link: a "perfect link" over a lossy network
(reference ``src/actor/ordered_reliable_link.rs``).

Wraps any actor with sequence numbers, acks, resend-on-timeout, and
at-most-once delivery, so the wrapped actor sees an ordered reliable channel
per source even when the underlying network loses, duplicates, or reorders.
Messages: ``("deliver", seq, msg)`` and ``("ack", seq)``.

Restrictions as in the reference: wrapped actors may not use timers
(``SetTimer``/``CancelTimer`` raise — ``ordered_reliable_link.rs:135-139``),
and actors must not restart (sequencers are not persisted).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from . import Actor, CancelTimer, Id, Out, Send, SetTimer


@dataclass(frozen=True)
class LinkState:
    """ORL bookkeeping around the wrapped actor's state
    (reference ``ordered_reliable_link.rs:48-57``)."""

    next_send_seq: int
    #: frozenset of (seq, dst, msg): sent but not yet acked
    msgs_pending_ack: frozenset
    #: frozenset of (src, last_seq): at-most-once delivery watermark
    last_delivered_seqs: frozenset
    wrapped_state: Any

    def _delivered(self, src: Id) -> int:
        for s, seq in self.last_delivered_seqs:
            if s == src:
                return seq
        return 0


@dataclass
class OrderedReliableLink(Actor):
    """Actor wrapper (reference ``ActorWrapper``,
    ``ordered_reliable_link.rs:30-33``)."""

    wrapped_actor: Actor
    resend_interval: Tuple[float, float] = (1.0, 2.0)

    def on_start(self, id: Id, out: Out):
        out.set_timer(self.resend_interval)
        wrapped_out = Out()
        wrapped_state = self.wrapped_actor.on_start(id, wrapped_out)
        state = LinkState(
            next_send_seq=1,
            msgs_pending_ack=frozenset(),
            last_delivered_seqs=frozenset(),
            wrapped_state=wrapped_state,
        )
        return self._process_output(state, wrapped_out, out)

    def on_msg(self, id: Id, state: LinkState, src: Id, msg, out: Out):
        kind = msg[0]
        if kind == "deliver":
            _, seq, wrapped_msg = msg
            # always ack to stop resends; drop if already delivered
            out.send(src, ("ack", seq))
            if seq <= state._delivered(src):
                return None
            wrapped_out = Out()
            new_wrapped = self.wrapped_actor.on_msg(
                id, state.wrapped_state, src, wrapped_msg, wrapped_out
            )
            if new_wrapped is None and not wrapped_out.commands:
                return None  # inner no-op: don't advance the watermark
            delivered = frozenset(
                p for p in state.last_delivered_seqs if p[0] != src
            ) | {(Id(src), seq)}
            state = LinkState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=state.msgs_pending_ack,
                last_delivered_seqs=delivered,
                wrapped_state=(
                    new_wrapped
                    if new_wrapped is not None
                    else state.wrapped_state
                ),
            )
            return self._process_output(state, wrapped_out, out)
        if kind == "ack":
            _, seq = msg
            pending = frozenset(
                p for p in state.msgs_pending_ack if p[0] != seq
            )
            # reference always registers a state change here, even for an
            # unknown seq (``state.to_mut()`` unconditionally)
            return LinkState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=pending,
                last_delivered_seqs=state.last_delivered_seqs,
                wrapped_state=state.wrapped_state,
            )
        return None

    def on_timeout(self, id: Id, state: LinkState, out: Out):
        out.set_timer(self.resend_interval)
        for seq, dst, msg in sorted(
            state.msgs_pending_ack, key=lambda p: p[0]
        ):
            out.send(dst, ("deliver", seq, msg))
        return None

    def _process_output(
        self, state: LinkState, wrapped_out: Out, out: Out
    ) -> LinkState:
        """Wrap each inner send with a sequencer and track it pending ack
        (reference ``ordered_reliable_link.rs:130-149``)."""
        next_seq = state.next_send_seq
        pending = set(state.msgs_pending_ack)
        for c in wrapped_out.commands:
            if isinstance(c, (SetTimer, CancelTimer)):
                raise NotImplementedError(
                    "timers in ORL-wrapped actors are not supported"
                )
            assert isinstance(c, Send)
            out.send(c.dst, ("deliver", next_seq, c.msg))
            pending.add((next_seq, Id(c.dst), c.msg))
            next_seq += 1
        return LinkState(
            next_send_seq=next_seq,
            msgs_pending_ack=frozenset(pending),
            last_delivered_seqs=state.last_delivered_seqs,
            wrapped_state=state.wrapped_state,
        )
