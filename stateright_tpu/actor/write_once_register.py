"""Write-once register harness (reference ``src/actor/write_once_register.rs``).

Same vocabulary as :mod:`.register` plus a ``("put_fail", req_id)`` reply
mapping to the spec's ``("write_fail",)``; the client additionally treats
``put_fail`` as acknowledging its put.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import Id
from .register import (  # shared vocabulary + recorder
    Get,
    GetOk,
    Internal,
    NULL_VALUE,
    Put,
    PutOk,
    RegisterClient,
    RegisterClientState,
    record_invocations,
    value_chosen,
)
from .register import record_returns as _record_returns


def PutFail(req_id) -> tuple:
    return ("put_fail", req_id)


def record_returns(cfg, history, env):
    """WO variant of :func:`.register.record_returns`: ``put_fail`` completes
    the write with the spec's ``("write_fail",)``, and a null read return is
    translated to ``None`` — the :class:`~stateright_tpu.semantics.WORegister`
    spec models the unset register as ``None`` (the reference models it as
    ``Option``, ``src/semantics/write_once_register.rs``) while the wire
    protocol's null is :data:`~stateright_tpu.actor.register.NULL_VALUE`."""
    if env.msg[0] == "put_fail":
        return history.on_return(env.dst, ("write_fail",))
    if env.msg[0] == "get_ok" and env.msg[2] == NULL_VALUE:
        return history.on_return(env.dst, ("read_ok", None))
    return _record_returns(cfg, history, env)


@dataclass
class WORegisterClient(RegisterClient):
    """Same workload as :class:`RegisterClient`, tolerating ``put_fail``
    (reference ``write_once_register.rs:119-241``)."""

    put_reply_kinds = ("put_ok", "put_fail")


WORegisterClientState = RegisterClientState
