"""Write-once register harness (reference ``src/actor/write_once_register.rs``).

Same vocabulary as :mod:`.register` plus a ``("put_fail", req_id)`` reply
mapping to the spec's ``("write_fail",)``; the client additionally treats
``put_fail`` as acknowledging its put.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import Id
from .register import (  # shared vocabulary + recorder
    Get,
    GetOk,
    Internal,
    NULL_VALUE,
    Put,
    PutOk,
    RegisterClient,
    RegisterClientState,
    record_invocations,
    value_chosen,
)
from .register import record_returns as _record_returns


def PutFail(req_id) -> tuple:
    return ("put_fail", req_id)


def record_returns(cfg, history, env):
    if env.msg[0] == "put_fail":
        return history.on_return(env.dst, ("write_fail",))
    return _record_returns(cfg, history, env)


@dataclass
class WORegisterClient(RegisterClient):
    """Same workload as :class:`RegisterClient`, tolerating ``put_fail``
    (reference ``write_once_register.rs:119-241``)."""

    put_reply_kinds = ("put_ok", "put_fail")


WORegisterClientState = RegisterClientState
