"""Explicit ``Choice`` actor composition (reference ``src/actor.rs:298-426``).

Python's duck typing already lets :class:`~stateright_tpu.actor.ActorModel`
mix actor classes freely, but that leaves one hole the reference's
``Choice`` combinator exists to close: two *different* actor types whose
states happen to compare equal (say an ``int``-state counter and an
``int``-state timer) would collide in fingerprinting and symmetry
reduction.  ``Choice`` wraps each actor with a variant index and tags its
state with :class:`ChoiceState`, so states of different variants are
distinct values no matter what the inner states are — the same guarantee
the reference gets from the nested ``Choice::L``/``Choice::R`` tags.

Mirroring the reference's builder shape (``Choice::new(a)``,
``.or()``)::

    sys = (
        ActorModel()
        .actor(Choice.new(A()))            # variant 0
        .actor(Choice.new(B()).or_())      # variant 1
        .actor(Choice.new(C()).or_().or_())  # variant 2
    )

``ChoiceState`` is a frozen dataclass, so it fingerprints and rewrites
structurally like any other state value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from . import Actor, Id, Out

__all__ = ["Choice", "ChoiceState"]


@dataclass(frozen=True)
class ChoiceState:
    """A wrapped actor's state, tagged with its variant index (the analogue
    of the reference's nested ``Choice<L, R>`` state tags)."""

    index: int
    state: Any


class Choice(Actor):
    """Wraps an actor as one variant of a tagged union of actor types.

    ``Choice.new(actor)`` is variant 0; each ``.or_()`` shifts the wrapper
    one variant deeper, mirroring the reference's ``Choice::new(x).or()``
    chains (``actor.rs:355-370``).  Handlers delegate to the wrapped actor
    and re-tag the resulting state; a ``None`` (no-op) result stays ``None``
    so no-op pruning is preserved.
    """

    def __init__(self, actor: Actor, index: int = 0):
        self.actor = actor
        self.index = index

    @staticmethod
    def new(actor: Actor) -> "Choice":
        return Choice(actor, 0)

    def or_(self) -> "Choice":
        return Choice(self.actor, self.index + 1)

    def __repr__(self) -> str:
        return f"Choice({self.actor!r}, index={self.index})"

    # -- Actor ---------------------------------------------------------------

    def on_start(self, id: Id, out: Out):
        return ChoiceState(self.index, self.actor.on_start(id, out))

    def on_msg(self, id: Id, state: ChoiceState, src: Id, msg, out: Out):
        if state.index != self.index:  # unreachable by construction
            raise AssertionError(
                f"Choice variant mismatch: actor {self.index}, "
                f"state {state.index} (reference actor.rs:400 unreachable!)"
            )
        inner = self.actor.on_msg(id, state.state, src, msg, out)
        return None if inner is None else ChoiceState(self.index, inner)

    def on_timeout(self, id: Id, state: ChoiceState, out: Out):
        if state.index != self.index:  # unreachable by construction
            raise AssertionError(
                f"Choice variant mismatch: actor {self.index}, "
                f"state {state.index}"
            )
        inner = self.actor.on_timeout(id, state.state, out)
        return None if inner is None else ChoiceState(self.index, inner)

    # -- runtime serde delegates (spawn) -------------------------------------

    def serialize(self, msg) -> bytes:
        return self.actor.serialize(msg)

    def deserialize(self, data: bytes):
        return self.actor.deserialize(data)
