"""stateright_tpu — a TPU-native explicit-state model checker.

A brand-new framework with the capabilities of the Stateright model checker
(reference mounted at ``/root/reference``; see ``SURVEY.md``), re-designed
TPU-first: states serialize to fixed-width ``uint64`` rows, frontier expansion
runs as a jit-compiled batched transition function, visited-set deduplication
and property evaluation run on-device, and multi-chip scaling shards the
wavefront over a ``jax.sharding.Mesh`` with fingerprints routed all-to-all
over ICI.

Layers (bottom-up, mirroring the reference's layer map in SURVEY.md §1):

 - :mod:`.fingerprint`, :mod:`.utils` — stable hashing + state containers.
 - :mod:`.core` — ``Model`` / ``Property`` abstraction.
 - :mod:`.checker` — CPU BFS/DFS oracle checkers, paths, visitors.
 - :mod:`.symmetry` — symmetry reduction (``Representative`` / ``RewritePlan``).
 - :mod:`.parallel` — the TPU wavefront engine (``spawn_tpu``).
 - :mod:`.ops` — device kernels: row hashing, dedup, hash tables.
 - :mod:`.actor` — actor DSL, network semantics, actor model, UDP runtime.
 - :mod:`.semantics` — linearizability / sequential consistency testers.
 - :mod:`.models` — example systems (2PC, Paxos, registers, counters).
 - :mod:`.explorer` — web UI for interactive state-space browsing.
 - :mod:`.checkpoint`, :mod:`.supervisor`, :mod:`.testing` — crash-safe
   autosave generations, supervised runs with retry/backoff, and the
   deterministic fault-injection layer (docs/robustness.md).
"""

from .core import Expectation, Model, Property
from .checker import (
    Checker,
    CheckerBuilder,
    Path,
    PathRecorder,
    StateRecorder,
)
from .fingerprint import fingerprint, stable_hash
from .analysis import AuditError, AuditFinding, AuditReport, audit_model
from .supervisor import supervise

__version__ = "0.1.0"

__all__ = [
    "Expectation",
    "Model",
    "Property",
    "Checker",
    "CheckerBuilder",
    "Path",
    "PathRecorder",
    "StateRecorder",
    "fingerprint",
    "stable_hash",
    "AuditError",
    "AuditFinding",
    "AuditReport",
    "audit_model",
    "supervise",
]
