"""Core model abstraction: ``Model``, ``Property``, ``Expectation``.

Mirrors the reference's L1 layer (reference: ``src/lib.rs:155-300``) with a
Python-idiomatic surface.  A :class:`Model` is a nondeterministic state
machine: initial states, enabled actions per state, and a (partial) transition
function.  Properties are named predicates with one of three expectations:

 - ``ALWAYS``   — must hold in every reachable state; a violating state is a
                  *counterexample* discovery.
 - ``SOMETIMES``— must hold in at least one reachable state; a satisfying
                  state is an *example* discovery.
 - ``EVENTUALLY`` — must hold at some point along every maximal path; a
                  terminal path that never satisfied it is a counterexample.
                  (We replicate the reference's path-bit semantics, including
                  its documented cycle false-negative — reference
                  ``src/checker.rs:341-414``.)

Unlike the reference (one trait, one implementation strategy) this framework
has *two coexisting model forms*:

 - the **object form** defined here, used by the CPU oracle checkers, the
   Explorer, and path reconstruction;
 - the **tensor form** (:mod:`stateright_tpu.parallel.tensor_model`), a
   fixed-width ``uint64`` row encoding with a jit-compiled batched transition
   function, executed by the TPU wavefront engine.

Both forms of the same system must agree on fingerprints bit-for-bit; that
equivalence is a test obligation (see ``tests/test_tensor_*``).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Generic, Iterable, Optional, Sequence, TypeVar

from .fingerprint import fingerprint as _fingerprint

State = TypeVar("State")
Action = TypeVar("Action")


class Expectation(Enum):
    """How a property's condition relates to the state space
    (reference ``src/lib.rs:293-300``)."""

    ALWAYS = "always"
    SOMETIMES = "sometimes"
    EVENTUALLY = "eventually"


@dataclass(frozen=True)
class Property(Generic[State]):
    """A named predicate over (model, state) (reference ``src/lib.rs:244-278``)."""

    expectation: Expectation
    name: str
    condition: Callable[[Any, State], bool]

    @staticmethod
    def always(name: str, condition: Callable[[Any, State], bool]) -> "Property":
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[[Any, State], bool]) -> "Property":
        return Property(Expectation.SOMETIMES, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[[Any, State], bool]) -> "Property":
        return Property(Expectation.EVENTUALLY, name, condition)


class Model(Generic[State, Action]):
    """A nondeterministic state machine (reference ``src/lib.rs:155-237``).

    Subclasses implement ``init_states``, ``actions``, ``next_state``; they may
    override ``properties``, ``within_boundary``, display hooks, and
    ``fingerprint_state`` (tensor-form models delegate the latter to the row
    hash so host and device fingerprints coincide).
    """

    # -- transition structure ------------------------------------------------

    def init_states(self) -> Sequence[State]:
        raise NotImplementedError

    def actions(self, state: State) -> Iterable[Action]:
        """Actions enabled in ``state`` (reference ``src/lib.rs:166``)."""
        raise NotImplementedError

    def next_state(self, state: State, action: Action) -> Optional[State]:
        """Apply ``action``; ``None`` means the action is ignored in this state
        (prunes the transition — reference ``src/lib.rs:170``)."""
        raise NotImplementedError

    # -- derived helpers (reference ``src/lib.rs:192-212``) ------------------

    def next_steps(self, state: State) -> list[tuple[Action, State]]:
        out = []
        for action in self.actions(state):
            nxt = self.next_state(state, action)
            if nxt is not None:
                out.append((action, nxt))
        return out

    def next_states(self, state: State) -> list[State]:
        return [s for _, s in self.next_steps(state)]

    # -- properties & bounds -------------------------------------------------

    def properties(self) -> Sequence[Property]:
        return []

    def property_by_name(self, name: str) -> Property:
        for p in self.properties():
            if p.name == name:
                return p
        raise KeyError(name)

    def within_boundary(self, state: State) -> bool:
        """States outside the boundary are not expanded (reference
        ``src/lib.rs:228``)."""
        return True

    # -- identity ------------------------------------------------------------

    def _config_mutated(self) -> None:
        """Hook called by builder-style subclasses when configuration changes
        after construction; tensor-backed models use it to invalidate cached
        eligibility decisions."""

    def fingerprint_state(self, state: State) -> int:
        """Stable nonzero 64-bit state identity.  Tensor-form models override
        this with the device row hash of ``encode_state`` for bit-parity."""
        return _fingerprint(state)

    # -- display hooks (reference ``src/lib.rs:173-189``) --------------------

    def format_action(self, action: Action) -> str:
        return repr(action)

    def format_step(self, last_state: State, action: Action) -> Optional[str]:
        nxt = self.next_state(last_state, action)
        return None if nxt is None else repr(nxt)

    def as_svg(self, path: "Any") -> Optional[str]:
        return None

    # -- entry point ---------------------------------------------------------

    def checker(self) -> "Any":
        """Begin configuring a checker run (reference ``src/lib.rs:231-236``)."""
        from .checker import CheckerBuilder

        return CheckerBuilder(self)
