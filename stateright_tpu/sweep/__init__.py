"""Hyper-batched instance sweeps: one compiled program checks a whole
model family (docs/sweep.md; the ROADMAP "Hyper-batched instance sweeps"
item).

The compiled twins are pure tensor programs over packed rows, so the
model *parameters* can be batched too: a :class:`SweepSpec` enumerates a
family of instances (lossiness flags, bounds, initial values, table
seeds), groups them into **shape cohorts** (instances whose twins trace
to structurally identical kernels — differing constants are lifted out
and gathered per row by an instance *tag*), and the sweep engine
(``sweep/engine.py``) runs each cohort as ONE wavefront over a shared
visited table whose fingerprints are namespaced per instance
(``ops.hashing.ns_hash`` / ``fingerprint.ns_word``), so instances never
collide and every instance's counts, verdicts, and discovery traces
reconcile bit-identically against its own sequential run.

Surfaces: ``CheckerBuilder.sweep(SPEC)`` / the examples' ``sweep`` CLI
verb (``--sweep`` routing) / ``STATERIGHT_TPU_SWEEP=N`` (models that
define ``sweep_family``); one registry record per instance (tagged
``sweep_id``) so ``_cli compare`` and the Explorer dashboard work per
instance.
"""

from .spec import (  # noqa: F401
    ENV_SWEEP,
    SWEEP_V,
    SweepInstance,
    SweepSpec,
    resolve_sweep_spec,
)
