"""The sweep wavefront engine: one device BFS per shape cohort, all
instances concurrently over a shared, namespace-partitioned visited
table (docs/sweep.md).

A purpose-built sibling of ``parallel/wavefront.py``: the same FIFO
queue / bucketized-table / clean-boundary-growth discipline, with three
structural differences —

 - every queue row carries an **instance tag** (a parallel ``q_tag``
   buffer, like ``q_ebits``); successors inherit their parent's tag, and
   the step kernel gathers per-instance constants by it
   (``sweep/cohort.py``);
 - fingerprints are **namespaced** per instance before touching the
   shared table (``ops.hashing.ns_hash``: the instance tag replaces the
   LOW bits of the table sort key ``mix64(fp)``, inverted back through
   ``unmix64``), so instances can never collide — and parent chains stay
   within one instance by construction;
 - every counter is **per instance**: unique/total/max-depth vectors,
   a ``[K, P]`` discovery matrix with per-instance first-hit recording,
   and per-instance done/target early termination — a finished instance
   masks its rows out of expansion without stalling the cohort.

Exactness argument (pinned by tests/test_sweep.py): queue appends are
in TABLE order — sorted by the candidates' sort key — and the
namespacing is deliberately ORDER-PRESERVING within an instance (the
tag lands in the key's low bits; the high bits keep the sequential
run's order), so an instance's rows keep exactly the relative FIFO
order its own sequential run produces, its candidate lanes keep their
relative order (row-major expansion), and novelty is a pure function of
its own namespaced fingerprint set — hence each instance's BFS order,
unique/total counts, per-property first-hit states (discovery traces),
and parent pointers are identical to its own sequential wavefront run.
The one caveat: the sweep's per-instance depth histogram is derived as
an exact bincount at extraction, while the wavefront's live histogram
is the sorted-prefix approximation — two estimators of the same
quantity (docs/sweep.md).

Per-instance targets terminate an instance once its unique count
crosses the target at a batch boundary; because batches interleave
instances, the cut point can differ from a sequential run's (the same
"roughly count" semantics as ``target_states``) — full-enumeration
instances reconcile bit-identically.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..checker.base import Checker, CheckerBuilder
from ..checker.path import Path
from ..core import Expectation
from ..fingerprint import ns_fingerprint
from ..ops.buckets import SLOTS, bucket_insert, host_bucket_rehash
from ..ops.hashing import EMPTY, ns_hash, row_hash
from ..ops.por import candidate_novelty
from ..parallel.prewarm import CompileWatch
from ..parallel.wavefront import _pow2
from .cohort import build_cohorts
from .spec import SWEEP_V

_STATUS_OK = 0
_STATUS_QUEUE_FULL = 1
_STATUS_TABLE_FULL = 2
_STATUS_CAND_FULL = 3
_STATUS_POISON = 4

_STATUS_NAMES = {
    _STATUS_OK: "ok",
    _STATUS_QUEUE_FULL: "queue_full",
    _STATUS_TABLE_FULL: "table_full",
    _STATUS_CAND_FULL: "cand_full",
    _STATUS_POISON: "poison",
}

# carry indices (base tuple; the cartography tail follows when enabled)
(_TFP, _TPL, _QROWS, _QFP, _QTAG, _QEBITS, _QDEPTH, _HEAD, _TAIL,
 _UNIQK, _SCNTK, _DISC, _MAXDK, _STATUS) = range(14)
_CART_START = 14

_SNAPSHOT_KEYS = (
    "table_fp", "table_parent", "q_rows", "q_fp", "q_tag", "q_ebits",
    "q_depth", "head", "tail", "unique_k", "scount_k", "disc",
    "maxdepth_k", "status",
)


def _build_sweep_engine(cohort, cap: int, qcap: int, batch: int,
                        steps: int, cand: Optional[int],
                        cartography: bool = False):
    """``(init_fn, run_fn)`` for one cohort at fixed capacities — the
    sweep analogue of ``wavefront._build_engine`` (no POR/spill/MXU/
    checked tails; the builder rejects those for sweeps)."""
    width, arity, K = cohort.width, cohort.max_actions, cohort.K
    m = batch * arity
    eff_cand = min(cand, m) if cand else m
    qalloc = qcap + m
    props = cohort.props
    n_props = cohort.n_props
    p_dim = max(n_props, 1)
    ev_idx = [
        i for i, p in enumerate(props)
        if p.expectation is Expectation.EVENTUALLY
    ]
    ebit_of = {i: e for e, i in enumerate(ev_idx)}
    if len(ev_idx) > 32:
        raise ValueError("at most 32 eventually properties are supported")
    init_ebits = jnp.uint32((1 << len(ev_idx)) - 1)

    init_rows_np, init_tags_np = cohort.init_data()
    n_init = init_rows_np.shape[0]

    ns_low = jnp.asarray(cohort.ns_low_np)
    ns_xor = jnp.asarray(cohort.ns_xor_np)
    ns_bits = cohort.ns_bits
    # -1 = no target: substitute a sentinel no count reaches
    tg = cohort.targets_np.copy()
    tg[tg < 0] = np.int64(1) << 62
    targets = jnp.asarray(tg)

    twin0 = cohort.twins[0]
    boundary_fn = (
        twin0.boundary_rows
        if getattr(twin0, "has_boundary", False)
        else None
    )
    poison_fn = getattr(twin0, "poison_rows", None)

    def cand_ns(fps, tags):
        """Namespaced candidate fingerprints: the lane's instance tag
        lands in the low sort-key bits (order-preserving; its seed
        scramble, if any, in the high bits) — see ops/hashing.ns_hash."""
        return ns_hash(fps, ns_low[tags], ns_xor[tags], ns_bits)

    def done_of(disc2, uniq_k):
        """bool[K]: all properties discovered, or target reached."""
        tgt = uniq_k >= targets
        if n_props == 0:
            return tgt
        return jnp.all(disc2 != jnp.uint64(0), axis=1) | tgt

    def record_first_k(disc2, i, hit, fps, tags):
        """First-hit-per-instance discovery of property ``i``."""
        b = hit.shape[0]
        order = jnp.where(
            hit, jnp.arange(b, dtype=jnp.int32), jnp.int32(b)
        )
        first = jax.ops.segment_min(order, tags, num_segments=K)
        has = first < b
        fp_first = fps[jnp.clip(first, 0, b - 1)]
        take = has & (disc2[:, i] == jnp.uint64(0))
        return disc2.at[:, i].set(
            jnp.where(take, fp_first, disc2[:, i])
        )

    def eval_props(masks, fps, act, ebits, disc2, tags):
        for i, p in enumerate(props):
            if p.expectation is Expectation.ALWAYS:
                disc2 = record_first_k(
                    disc2, i, act & ~masks[..., i], fps, tags
                )
            elif p.expectation is Expectation.SOMETIMES:
                disc2 = record_first_k(
                    disc2, i, act & masks[..., i], fps, tags
                )
            else:
                clear = jnp.uint32(~(1 << ebit_of[i]) & 0xFFFFFFFF)
                ebits = jnp.where(masks[..., i], ebits & clear, ebits)
        return ebits, disc2

    def flush_terminal(terminal, fps, ebits, disc2, tags):
        for i in ev_idx:
            bit = (ebits >> jnp.uint32(ebit_of[i])) & jnp.uint32(1)
            disc2 = record_first_k(
                disc2, i, terminal & (bit == jnp.uint32(1)), fps, tags
            )
        return disc2

    def step(carry):
        (tfp, tpl, qrows, qfp, qtag, qebits, qdepth, head, tail,
         uniq_k, scnt_k, disc2, maxd_k, status) = carry[:_CART_START]
        cart = carry[_CART_START:]
        n_avail = tail - head
        rows = jax.lax.dynamic_slice(
            qrows, (head, jnp.int32(0)), (batch, width)
        )
        fps = jax.lax.dynamic_slice(qfp, (head,), (batch,))
        tags = jax.lax.dynamic_slice(qtag, (head,), (batch,)).astype(
            jnp.int32
        )
        ebits = jax.lax.dynamic_slice(qebits, (head,), (batch,))
        depths = jax.lax.dynamic_slice(qdepth, (head,), (batch,))
        live = jnp.arange(batch, dtype=jnp.int32) < n_avail

        masks = cohort.property_masks(rows, tags)  # [B, P]
        # per-instance early termination: rows of a done instance are
        # popped but neither evaluated nor expanded (disc is first-wins,
        # so late evaluation could not change verdicts anyway — the mask
        # keeps the evaluated tallies reconciling per instance)
        done_k = done_of(disc2, uniq_k)
        act = live & ~done_k[tags]
        ebits, disc2 = eval_props(masks, fps, act, ebits, disc2, tags)
        d32 = jnp.where(act, depths, 0).astype(jnp.int32)
        maxd_k = jnp.maximum(
            maxd_k,
            jnp.maximum(
                jax.ops.segment_max(d32, tags, num_segments=K), 0
            ),
        )
        done_k = done_of(disc2, uniq_k)
        elive = act & ~done_k[tags]

        succ, valid = cohort.step_rows(rows, tags)  # [B, A, W], [B, A]
        if boundary_fn is not None:
            valid = valid & boundary_fn(succ)
        valid = valid & elive[:, None]
        terminal = elive & ~jnp.any(valid, axis=-1)
        disc2 = flush_terminal(terminal, fps, ebits, disc2, tags)

        tag_la = jnp.broadcast_to(tags[:, None], (batch, arity))
        cand_fp = jnp.where(
            valid, cand_ns(row_hash(succ), tag_la), EMPTY
        ).reshape(m)
        cand_tag = tag_la.reshape(m)
        cand_rows = succ.reshape(m, width)
        cand_par = jnp.broadcast_to(
            fps[:, None], (batch, arity)
        ).reshape(-1)
        cand_ebt = jnp.broadcast_to(
            ebits[:, None], (batch, arity)
        ).reshape(-1)
        cand_dep = jnp.broadcast_to(
            depths[:, None] + jnp.uint32(1), (batch, arity)
        ).reshape(-1)

        tfp, tpl, sel, n_new, toverflow, coverflow = bucket_insert(
            tfp, tpl, cand_fp, cand_par, window=batch,
            compact=eff_cand,
        )
        qrows = jax.lax.dynamic_update_slice(
            qrows, cand_rows[sel], (tail, jnp.int32(0))
        )
        qfp = jax.lax.dynamic_update_slice(qfp, cand_fp[sel], (tail,))
        qtag = jax.lax.dynamic_update_slice(
            qtag, cand_tag[sel].astype(jnp.uint32), (tail,)
        )
        qebits = jax.lax.dynamic_update_slice(
            qebits, cand_ebt[sel], (tail,)
        )
        qdepth = jax.lax.dynamic_update_slice(
            qdepth, cand_dep[sel], (tail,)
        )

        overflow = toverflow | coverflow
        novel = candidate_novelty(m, sel, n_new)
        zero_k = jnp.zeros((K,), jnp.int64)
        d_uniq = jax.ops.segment_sum(
            novel.astype(jnp.int64), cand_tag, num_segments=K
        )
        d_scnt = jax.ops.segment_sum(
            valid.reshape(m).astype(jnp.int64), cand_tag,
            num_segments=K,
        )
        uniq_k = uniq_k + jnp.where(overflow, zero_k, d_uniq)
        scnt_k = scnt_k + jnp.where(overflow, zero_k, d_scnt)
        head = jnp.where(
            overflow, head, head + jnp.minimum(n_avail, batch)
        )
        n_new = jnp.where(overflow, 0, n_new)
        tail = tail + n_new
        unique_tot = jnp.sum(uniq_k)
        status = jnp.where(
            toverflow | (unique_tot * 4 > cap) | (eff_cand * 4 > cap),
            jnp.int32(_STATUS_TABLE_FULL),
            jnp.where(
                coverflow,
                jnp.int32(_STATUS_CAND_FULL),
                jnp.where(
                    tail > qcap,
                    jnp.int32(_STATUS_QUEUE_FULL),
                    status,
                ),
            ),
        )
        if poison_fn is not None:
            status = jnp.where(
                jnp.any(poison_fn(rows) & live),
                jnp.int32(_STATUS_POISON),
                status,
            )
        if cartography:
            act_hist, p_evals, p_hits = cart
            gen = valid.astype(jnp.int64)  # [B, A]
            ev = act.astype(jnp.int64)
            hits = (act[:, None] & masks).astype(jnp.int64)
            zero = jnp.int64(0)
            act_hist = act_hist.at[tags].add(
                jnp.where(overflow, zero, gen)
            )
            p_evals = p_evals.at[tags].add(
                jnp.where(
                    overflow, zero,
                    jnp.broadcast_to(ev[:, None], (batch, p_dim)),
                )
            )
            p_hits = p_hits.at[tags].add(
                jnp.where(overflow, zero, _pad_props(hits, p_dim))
            )
            cart = (act_hist, p_evals, p_hits)
        out = (tfp, tpl, qrows, qfp, qtag, qebits, qdepth, head, tail,
               uniq_k, scnt_k, disc2, maxd_k, status)
        return out + tuple(cart)

    def cond(state):
        k, carry = state
        go = (carry[_STATUS] == jnp.int32(_STATUS_OK)) & (k < steps)
        go = go & (carry[_TAIL] > carry[_HEAD])
        go = go & ~jnp.all(done_of(carry[_DISC], carry[_UNIQK]))
        return go

    def stats_of(carry):
        parts = [
            jnp.stack([
                carry[_HEAD].astype(jnp.uint64),
                carry[_TAIL].astype(jnp.uint64),
                carry[_STATUS].astype(jnp.uint64),
            ]),
            carry[_UNIQK].astype(jnp.uint64),
            carry[_SCNTK].astype(jnp.uint64),
            carry[_MAXDK].astype(jnp.uint64),
            carry[_DISC].reshape(-1),
        ]
        if cartography:
            parts += [
                c.astype(jnp.uint64).reshape(-1)
                for c in carry[_CART_START:]
            ]
        return jnp.concatenate(parts)

    def _run_impl(carry):
        _, carry = jax.lax.while_loop(
            cond, lambda s: (s[0] + 1, step(s[1])), (jnp.int32(0), carry)
        )
        return carry, stats_of(carry)

    run_fn = jax.jit(_run_impl)

    @jax.jit
    def init_fn():
        tfp = jnp.full((cap,), EMPTY, jnp.uint64)
        tpl = jnp.zeros((cap,), jnp.uint64)
        qrows = jnp.zeros((qalloc, width), jnp.uint64)
        qfp = jnp.full((qalloc,), EMPTY, jnp.uint64)
        qtag = jnp.zeros((qalloc,), jnp.uint32)
        qebits = jnp.zeros((qalloc,), jnp.uint32)
        qdepth = jnp.zeros((qalloc,), jnp.uint32)

        irows = jnp.asarray(init_rows_np)
        itags = jnp.asarray(init_tags_np)
        ifp = cand_ns(row_hash(irows), itags)
        tfp, tpl, sel, n_new, overflow, _ = bucket_insert(
            tfp, tpl, ifp,
            jnp.zeros((n_init,), jnp.uint64),
            window=n_init,
        )
        qrows = jax.lax.dynamic_update_slice(
            qrows, irows[sel], (jnp.int32(0), jnp.int32(0))
        )
        qfp = jax.lax.dynamic_update_slice(qfp, ifp[sel], (jnp.int32(0),))
        qtag = jax.lax.dynamic_update_slice(
            qtag, itags[sel].astype(jnp.uint32), (jnp.int32(0),)
        )
        qebits = jax.lax.dynamic_update_slice(
            qebits,
            jnp.full((n_init,), init_ebits, jnp.uint32),
            (jnp.int32(0),),
        )
        novel = candidate_novelty(n_init, sel, n_new)
        uniq_k = jax.ops.segment_sum(
            novel.astype(jnp.int64), itags, num_segments=K
        )
        scnt_k = jax.ops.segment_sum(
            jnp.ones((n_init,), jnp.int64), itags, num_segments=K
        )
        status = jnp.where(
            overflow
            | (n_new.astype(jnp.int64) * 4 > cap)
            | (eff_cand * 4 > cap),
            jnp.int32(_STATUS_TABLE_FULL),
            jnp.where(
                n_new > qcap,
                jnp.int32(_STATUS_QUEUE_FULL),
                jnp.int32(_STATUS_OK),
            ),
        )
        carry = (tfp, tpl, qrows, qfp, qtag, qebits, qdepth,
                 jnp.int32(0), n_new, uniq_k, scnt_k,
                 jnp.zeros((K, p_dim), jnp.uint64),
                 jnp.zeros((K,), jnp.int32),
                 status)
        if cartography:
            carry = carry + (
                jnp.zeros((K, max(arity, 1)), jnp.int64),
                jnp.zeros((K, p_dim), jnp.int64),
                jnp.zeros((K, p_dim), jnp.int64),
            )
        return carry, stats_of(carry)

    return init_fn, run_fn


def _pad_props(hits, p_dim: int):
    """[B, P] -> [B, max(P, 1)] (models with zero properties still carry
    one tally column so the carry shapes stay static)."""
    if hits.shape[-1] == p_dim:
        return hits
    return jnp.zeros(hits.shape[:-1] + (p_dim,), hits.dtype)


class InstanceResult:
    """Per-instance outcome of a joined sweep (JSON-safe scalars + the
    discovery fingerprints; trace chains walked at cohort end)."""

    def __init__(self, instance, global_index, cohort_index):
        self.key = instance.key
        self.params = dict(instance.params)
        self.seed = instance.seed
        self.target = instance.target
        self.global_index = int(global_index)
        self.cohort = int(cohort_index)
        self.unique = 0
        self.states = 0
        self.max_depth = 0
        self.disc = np.zeros(0, np.uint64)
        self.chains: dict = {}  # prop name -> [ns'd fp chain]
        self.cartography: Optional[dict] = None

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "params": self.params,
            "seed": self.seed,
            "cohort": self.cohort,
            "unique": int(self.unique),
            "states": int(self.states),
            "max_depth": int(self.max_depth),
            "discoveries": sorted(self.chains),
        }


class SweepChecker(Checker):
    """One device run checking a whole model family.

    Spawned by ``CheckerBuilder.sweep(SPEC).spawn_tpu(...)``: cohorts
    run back to back on the device (one compiled engine per cohort),
    every instance's counters/verdicts/traces extract independently at
    join, and — when a run registry is configured — one record per
    instance archives under this sweep's ``sweep_id``.
    """

    _engine_tag = "sweep"

    def __init__(
        self,
        options: CheckerBuilder,
        spec,
        capacity: int = 1 << 17,
        batch: Optional[int] = None,
        queue_capacity: Optional[int] = None,
        steps_per_call: int = 64,
        cand: Optional[int] = None,
        sync: bool = False,
        resume: Optional[dict] = None,
    ):
        self.model = options.model
        self.spec = spec
        self._options = options
        self._reject_unsupported(options)
        self._cap = max(_pow2(capacity), 4 * SLOTS)
        self._batch = max(8, batch or (1 << 11))
        self._cand = cand or max(4 * self._batch, 4096)
        self._qcap = queue_capacity or max(self._cap // 2, 4 * self._batch)
        self._steps = steps_per_call
        self._resume = resume
        self._telemetry_opts = options.telemetry_opts or {}
        self._cartography = bool(self._telemetry_opts.get("cartography"))
        self._report_path = getattr(options, "report_path", None)
        self._run_dir = getattr(options, "run_dir", None)
        self._span_parent = getattr(options, "_span_ctx", None)
        self.flight_recorder = options._make_recorder("sweep")
        self.cohorts = build_cohorts(spec)
        self.results: dict = {}
        for ci, cohort in enumerate(self.cohorts):
            for t, inst in enumerate(cohort.instances):
                self.results[inst.key] = InstanceResult(
                    inst, cohort.global_index[t], ci
                )
        self.engine_compiles = 0
        self.growth_events: list = []
        self._instance_run_ids: dict = {}
        self._done = threading.Event()
        self._stop = threading.Event()
        self._ckpt_req: Optional[threading.Event] = None
        self._ckpt_out: Optional[dict] = None
        self._ckpt_ready = threading.Event()
        self._ckpt_lock = threading.Lock()
        self._live = (0, 0)
        self._cohort_idx = 0
        self._timed_out = False
        self._run_error: Optional[BaseException] = None
        if resume is not None:
            self._check_resume_sig(resume)
        if options.timeout_secs is not None:
            timer = threading.Timer(
                options.timeout_secs, self._deadline_stop
            )
            timer.daemon = True
            timer.start()
        self._thread = None
        if sync:
            self._run_guarded()
            if self._run_error is not None:
                err, self._run_error = self._run_error, None
                raise err
            self._maybe_write_report()
        else:
            self._thread = threading.Thread(
                target=self._run_guarded, daemon=True
            )
            self._thread.start()

    # -- configuration fences ------------------------------------------------

    @staticmethod
    def _reject_unsupported(options: CheckerBuilder) -> None:
        from ..parallel.prewarm import (
            ENV_POR,
            ENV_PREDEDUP,
            ENV_SPILL,
            resolve_flag,
        )

        rejects = []
        if options.checked_mode:
            rejects.append("checked()")
        if resolve_flag(getattr(options, "por_mode", None), ENV_POR):
            rejects.append("por()")
        if resolve_flag(getattr(options, "spill_mode", None), ENV_SPILL):
            rejects.append("spill()")
        if resolve_flag(
            getattr(options, "prededup_mode", None), ENV_PREDEDUP
        ):
            rejects.append("prededup()")
        from ..ops.mxu import resolve_mxu

        if resolve_mxu(getattr(options, "mxu_opts", None)) is not None:
            rejects.append("mxu()")
        if options.symmetry_fn is not None:
            rejects.append("symmetry()")
        if options.visitor_obj is not None:
            rejects.append("visitor()")
        if getattr(options, "autosave_opts", None) is not None:
            rejects.append("autosave()")
        if rejects:
            raise NotImplementedError(
                "sweep mode does not compose with "
                + "/".join(rejects)
                + " yet — run those per instance on the plain wavefront "
                "engine (docs/sweep.md)"
            )

    def _deadline_stop(self) -> None:
        if not self._done.is_set():
            self._timed_out = True
        self._stop.set()

    @property
    def timed_out(self) -> bool:
        return self._timed_out

    # -- resume protocol -----------------------------------------------------

    def _sweep_sig(self) -> np.ndarray:
        """Sweep identity for resume: per-instance (ns word, model init
        fingerprints, twin shape) — a different spec (order, seeds,
        members, layouts) must refuse a foreign snapshot."""
        import hashlib
        import json

        src = []
        for ci, cohort in enumerate(self.cohorts):
            for t, inst in enumerate(cohort.instances):
                fps = sorted(
                    int(inst.model.fingerprint_state(s))
                    for s in inst.model.init_states()
                )
                src.append([
                    inst.key, cohort.global_index[t], inst.seed,
                    cohort.ns_bits, fps,
                    cohort.width, cohort.max_actions, cohort.n_props,
                ])
        digest = hashlib.sha256(
            json.dumps(src, sort_keys=True).encode()
        ).digest()[:8]
        return np.frombuffer(digest, np.uint64).copy()

    def _check_resume_sig(self, snap: dict) -> None:
        tag = str(np.asarray(snap.get("engine", "")).item()) if hasattr(
            snap.get("engine", ""), "dtype"
        ) else str(snap.get("engine", ""))
        if tag != "sweep":
            raise ValueError(
                f"resume snapshot was taken by the {tag!r} engine; this "
                "is the sweep engine"
            )
        if not np.array_equal(self._sweep_sig(), snap["model_sig"]):
            raise ValueError(
                "resume snapshot was taken from a different sweep "
                "(instance keys / namespaces / layouts disagree)"
            )
        rid = snap.get("run_id")
        if rid is not None and self.parent_run_id is None:
            self.parent_run_id = (
                str(np.asarray(rid).item())
                if hasattr(rid, "dtype") else str(rid)
            )

    # -- snapshotting --------------------------------------------------------

    def _carry_to_snapshot(self, carry, ci, cap, qcap, cand) -> dict:
        import json

        snap = {
            k: np.asarray(v) for k, v in zip(_SNAPSHOT_KEYS, carry)
        }
        snap["cap"], snap["qcap"] = cap, qcap
        snap["batch"], snap["cand"] = self._batch, cand
        snap["cohort"] = np.int64(ci)
        snap["engine"] = "sweep"
        snap["model_sig"] = self._sweep_sig()
        snap["run_id"] = self.run_id
        # completed cohorts: per-instance results + walked discovery
        # chains travel as a JSON manifest (the tables are gone)
        done = {}
        for ck in range(ci):
            for inst in self.cohorts[ck].instances:
                r = self.results[inst.key]
                done[inst.key] = {
                    "unique": int(r.unique),
                    "states": int(r.states),
                    "max_depth": int(r.max_depth),
                    "disc": [int(x) for x in np.asarray(r.disc)],
                    "chains": {
                        k: [int(f) for f in v]
                        for k, v in r.chains.items()
                    },
                    "cartography": r.cartography,
                }
        snap["sweep_done"] = json.dumps(done)
        if self._cartography and getattr(
            self, "_cart_depth_base", None
        ) is not None:
            # depth lanes banked by growth compactions: without them a
            # resumed per-instance depth histogram forgets every state
            # popped before a pre-snapshot growth (the wavefront
            # engine's cart_depth_base rule).  The per-step
            # action/property tallies restart at resume like the base
            # engine's (documented in docs/sweep.md).
            snap["cart_depth_base"] = self._cart_depth_base.copy()
        return snap

    def checkpoint(self, timeout: Optional[float] = 60.0) -> dict:
        if self._done.is_set() or self._thread is None:
            return dict(self._final_snapshot)
        with self._ckpt_lock:
            self._ckpt_req = self._ckpt_req or threading.Event()
            self._ckpt_ready.clear()
            self._ckpt_req.set()
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            while not self._ckpt_ready.wait(0.2):
                if self._done.is_set():
                    return dict(self._final_snapshot)
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("checkpoint request not served")
            out, self._ckpt_out = self._ckpt_out, None
        if out is None:
            if self._done.is_set():
                return dict(self._final_snapshot)
            raise RuntimeError(
                "checkpoint signalled ready without a snapshot"
            )
        return out

    def stop(self) -> "SweepChecker":
        self._stop.set()
        return self

    # -- run loop ------------------------------------------------------------

    def _run_guarded(self) -> None:
        from ..telemetry.spans import start_span

        rec = self.flight_recorder
        sp = None
        if rec is not None:
            # engine_run span (telemetry/spans.py): parents under the
            # job/attempt span when the fleet/supervisor set
            # builder._span_ctx; roots a fresh trace otherwise
            sp = start_span("engine_run", parent=self._span_parent)
            rec.bind_span(sp.ctx.span_id)
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 - re-raised at join()
            self._run_error = e
        finally:
            if sp is not None:
                sp.end(
                    rec,
                    engine="sweep",
                    error=(
                        type(self._run_error).__name__
                        if self._run_error else None
                    ),
                )
                rec.bind_span(None)
            self._done.set()

    def _restore_done(self, snap: dict) -> None:
        import json

        done = json.loads(str(np.asarray(snap["sweep_done"]).item()))
        for key, d in done.items():
            r = self.results[key]
            r.unique = d["unique"]
            r.states = d["states"]
            r.max_depth = d["max_depth"]
            r.disc = np.asarray(d["disc"], np.uint64)
            r.chains = {k: list(v) for k, v in d["chains"].items()}
            r.cartography = d.get("cartography")

    def _run(self) -> None:
        rec = self.flight_recorder
        start_ci = 0
        resume_carry = None
        if self._resume is not None:
            snap = self._resume
            start_ci = int(np.asarray(snap["cohort"]))
            self._restore_done(snap)
            self._batch = int(snap.get("batch", self._batch))
            resume_carry = snap
        for ci in range(start_ci, len(self.cohorts)):
            self._cohort_idx = ci
            if self._stop.is_set():
                break
            self._run_cohort(
                ci, resume=resume_carry if ci == start_ci else None
            )
            resume_carry = None
        if rec is not None:
            rec.record(
                "sweep", v=SWEEP_V, event="summary",
                instances=len(self.spec), cohorts=len(self.cohorts),
                engine_compiles=int(self.engine_compiles),
            )
            rec.close_run(done=not self._timed_out)

    def _timed_call(self, fn, arg=None):
        rec = self.flight_recorder
        watch = CompileWatch() if rec is not None else None
        t0 = time.monotonic()
        carry, stats = fn() if arg is None else fn(arg)
        carry = list(carry)
        stats = np.asarray(stats)
        if rec is not None:
            dt = time.monotonic() - t0
            d = watch.delta()
            comp = min(max(d["compile_secs"], 0.0), dt)
            rec.add("stage_compile_secs", comp)
            rec.add("stage_device_secs", dt - comp)
            if comp > 0 and self._pending_compile is not None:
                prev = self._pending_compile
                rec.amend(
                    prev,
                    duration=round(
                        float(prev.get("duration", 0.0)) + comp, 6
                    ),
                )
            elif self._pending_compile is not None:
                self._pending_compile = None
        return carry, stats

    def _engine(self, cohort, ci, cap, qcap, batch, cand,
                kind: str = "growth"):
        key = (ci, cap, qcap, batch, cand)
        eng = self._engine_cache.get(key)
        if eng is not None:
            return eng
        rec = self.flight_recorder
        self.engine_compiles += 1
        if rec is not None:
            self._pending_compile = rec.record(
                "compile", cap=cap, qcap=qcap, batch=batch, cand=cand,
                rung=kind, source="fresh", cache_hit=False,
                duration=0.0,
            )
            rec.record(
                "sweep", v=SWEEP_V, event="cohort_compile",
                cohort=ci, instances=cohort.K, width=cohort.width,
                arity=cohort.max_actions,
                unified=bool(cohort.unified and cohort.K > 1),
            )
        eng = _build_sweep_engine(
            cohort, cap, qcap, batch, self._steps, cand,
            cartography=self._cartography,
        )
        self._engine_cache[key] = eng
        return eng

    def _grow(self, carry_np, ci, cap, qcap, batch, status, cand):
        """Clean-boundary growth, the wavefront discipline: rehash the
        table on table-full, reclaim the consumed queue prefix (banking
        its per-instance depth lanes when cartography is on) and double
        the queue while still needed."""
        cohort = self.cohorts[ci]

        def table_small():
            return (
                int(np.sum(carry_np[_UNIQK])) * 4 > cap
                or cand * 4 > cap
            )

        if table_small() or status == _STATUS_TABLE_FULL:
            if table_small():
                while table_small():
                    cap *= 2
            else:
                cap *= 2
            tfp, tpl = host_bucket_rehash(
                carry_np[_TFP], carry_np[_TPL], cap // SLOTS
            )
            carry_np[_TFP], carry_np[_TPL] = tfp, tpl
        head, tail = int(carry_np[_HEAD]), int(carry_np[_TAIL])
        pending = tail - head
        self._bank_depth(
            cohort, carry_np[_QDEPTH], carry_np[_QTAG], head
        )
        for i in (_QROWS, _QFP, _QTAG, _QEBITS, _QDEPTH):
            carry_np[i] = np.asarray(carry_np[i])[head:tail].copy()
        carry_np[_HEAD] = np.int32(0)
        carry_np[_TAIL] = np.int32(pending)
        while pending * 2 > qcap:
            qcap *= 2
        carry_np[_STATUS] = np.int32(_STATUS_OK)
        self._repad(carry_np, qcap + batch * cohort.max_actions)
        return cap, qcap, carry_np

    @staticmethod
    def _repad(carry_np, qalloc: int) -> None:
        for i in (_QROWS, _QFP, _QTAG, _QEBITS, _QDEPTH):
            arr = np.asarray(carry_np[i])
            if arr.shape[0] < qalloc:
                pad = (qalloc - arr.shape[0],) + arr.shape[1:]
                fill = EMPTY if i == _QFP else 0
                arr = np.concatenate(
                    [arr, np.full(pad, fill, arr.dtype)]
                )
            carry_np[i] = (
                arr[:qalloc] if arr.ndim == 1 else arr[:qalloc, :]
            )

    def _bank_depth(self, cohort, qdepth, qtag, n: int) -> None:
        """Bank the consumed queue prefix's per-instance depth lanes
        (cartography only): the final per-instance depth histograms are
        queue-derived, and growth compaction drops the popped prefix."""
        if not self._cartography or n <= 0:
            return
        from ..ops.cartography import DEPTH_BINS

        dep = np.minimum(
            np.asarray(qdepth[:n], np.int64), DEPTH_BINS - 1
        )
        tag = np.asarray(qtag[:n], np.int64)
        np.add.at(self._cart_depth_base, (tag, dep), 1)

    def _run_cohort(self, ci: int, resume: Optional[dict] = None):
        cohort = self.cohorts[ci]
        rec = self.flight_recorder
        cap, qcap, batch = self._cap, self._qcap, self._batch
        arity = cohort.max_actions
        cand = min(self._cand, batch * arity)
        while cand * 4 > cap:
            cap *= 2
        n_init = cohort.init_data()[0].shape[0]
        while n_init > qcap:
            qcap *= 2
        self._engine_cache: dict = {}
        self._pending_compile = None
        if self._cartography:
            from ..ops.cartography import DEPTH_BINS

            self._cart_depth_base = np.zeros(
                (cohort.K, DEPTH_BINS), np.int64
            )
        if resume is not None:
            cap, qcap = int(resume["cap"]), int(resume["qcap"])
            cand = int(resume.get("cand", cand))
            if self._cartography and "cart_depth_base" in resume:
                self._cart_depth_base = np.asarray(
                    resume["cart_depth_base"], np.int64
                ).copy()
            carry = [np.asarray(resume[k]) for k in _SNAPSHOT_KEYS]
            st = int(carry[_STATUS])
            if st != _STATUS_OK:
                if st == _STATUS_CAND_FULL:
                    cand = min(cand * 2, batch * arity)
                cap, qcap, carry = self._grow(
                    carry, ci, cap, qcap, batch, st, cand
                )
            else:
                self._repad(carry, qcap + batch * arity)
            carry = [jnp.asarray(c) for c in carry]
            if self._cartography:
                carry = list(carry) + [
                    jnp.zeros((cohort.K, max(arity, 1)), jnp.int64),
                    jnp.zeros(
                        (cohort.K, max(cohort.n_props, 1)), jnp.int64
                    ),
                    jnp.zeros(
                        (cohort.K, max(cohort.n_props, 1)), jnp.int64
                    ),
                ]
            stats = None
        else:
            while True:
                init_fn, _ = self._engine(
                    cohort, ci, cap, qcap, batch, cand, kind="init"
                )
                carry, stats = self._timed_call(init_fn)
                if int(stats[2]) != _STATUS_TABLE_FULL:
                    break
                prev = cap
                while (n_init * 4 > cap) or (cand * 4 > cap):
                    cap *= 2
                if cap == prev:
                    cap *= 2
        k_dim, p_dim = cohort.K, max(cohort.n_props, 1)
        while True:
            if stats is None:
                stats = self._stats_np(carry, cohort)
            head, tail, status = (
                int(stats[0]), int(stats[1]), int(stats[2]),
            )
            o = 3
            uniq_k = stats[o:o + k_dim].astype(np.int64); o += k_dim
            scnt_k = stats[o:o + k_dim].astype(np.int64); o += k_dim
            maxd_k = stats[o:o + k_dim].astype(np.int64); o += k_dim
            disc2 = stats[o:o + k_dim * p_dim].reshape(k_dim, p_dim)
            o += k_dim * p_dim
            tot_u = int(uniq_k.sum()) + sum(
                r.unique for r in self.results.values()
                if r.cohort != ci
            )
            tot_s = int(scnt_k.sum()) + sum(
                r.states for r in self.results.values()
                if r.cohort != ci
            )
            self._live = (tot_s, tot_u)
            if rec is not None:
                rec.add_bytes(d2h=stats.nbytes)
                rec.step(
                    engine="sweep", states=tot_s, unique=tot_u,
                    status=status, queue=max(tail - head, 0), cap=cap,
                    cand=cand,
                    load_factor=round(int(uniq_k.sum()) / cap, 6),
                )
            if self._ckpt_req is not None and self._ckpt_req.is_set():
                self._ckpt_out = self._carry_to_snapshot(
                    [np.asarray(c) for c in carry[:_CART_START]],
                    ci, cap, qcap, cand,
                )
                self._ckpt_req.clear()
                self._ckpt_ready.set()
            if status == _STATUS_POISON:
                raise RuntimeError(
                    "poisoned rows reached by a sweep instance: a "
                    "compiled transition crossed its compile-time "
                    "state_bound/env_bound; loosen the bounds"
                )
            if status != _STATUS_OK:
                self.growth_events.append((status, tot_u))
                if rec is not None:
                    rec.record(
                        "growth",
                        status=_STATUS_NAMES.get(status, str(status)),
                        unique=tot_u, cap=cap, qcap=qcap, cand=cand,
                    )
                cart_tail = list(carry[_CART_START:])
                carry_np = [
                    np.asarray(c) for c in carry[:_CART_START]
                ]
                if status == _STATUS_CAND_FULL:
                    cand = min(cand * 2, batch * arity)
                    carry_np[_STATUS] = np.int32(_STATUS_OK)
                    while cand * 4 > cap:
                        cap, qcap, carry_np = self._grow(
                            carry_np, ci, cap, qcap, batch,
                            _STATUS_TABLE_FULL, cand,
                        )
                else:
                    cap, qcap, carry_np = self._grow(
                        carry_np, ci, cap, qcap, batch, status, cand
                    )
                carry = [jnp.asarray(c) for c in carry_np] + cart_tail
                stats = None
                continue
            if self._stop.is_set():
                break
            all_done = bool(
                np.all(self._done_k_np(cohort, disc2, uniq_k))
            )
            if tail <= head or all_done:
                break
            _, run_fn = self._engine(cohort, ci, cap, qcap, batch, cand)
            carry, stats = self._timed_call(run_fn, tuple(carry))
        self._extract_cohort(
            ci, carry, uniq_k, scnt_k, maxd_k, disc2, cap, qcap, cand
        )

    @staticmethod
    def _done_k_np(cohort, disc2, uniq_k) -> np.ndarray:
        tgt = cohort.targets_np.copy()
        tgt[tgt < 0] = np.int64(1) << 62
        done = uniq_k >= tgt
        if cohort.n_props:
            done = done | np.all(disc2 != 0, axis=1)
        return done

    def _stats_np(self, carry, cohort) -> np.ndarray:
        k_dim, p_dim = cohort.K, max(cohort.n_props, 1)
        vals = [
            np.asarray(carry[_HEAD]), np.asarray(carry[_TAIL]),
            np.asarray(carry[_STATUS]),
        ]
        out = np.asarray(vals, np.uint64)
        return np.concatenate([
            out,
            np.asarray(carry[_UNIQK]).astype(np.uint64),
            np.asarray(carry[_SCNTK]).astype(np.uint64),
            np.asarray(carry[_MAXDK]).astype(np.uint64),
            np.asarray(carry[_DISC]).reshape(-1),
        ])

    def _extract_cohort(self, ci, carry, uniq_k, scnt_k, maxd_k,
                        disc2, cap, qcap, cand) -> None:
        """Per-instance extraction at cohort end: counters, discovery
        chains (walked now, while the table exists), and — with
        cartography on — the per-instance reconciling counter set."""
        cohort = self.cohorts[ci]
        rec = self.flight_recorder
        tfp = np.asarray(carry[_TFP])
        tpl = np.asarray(carry[_TPL])
        occ = tfp != np.uint64(EMPTY)
        parents = dict(
            zip(tfp[occ].tolist(), tpl[occ].tolist())
        )
        self._last_cohort_carry = carry
        self._last_cohort_caps = (ci, cap, qcap, cand)
        if self._cartography:
            from ..ops.cartography import DEPTH_BINS, snapshot

            tail = int(np.asarray(carry[_TAIL]))
            dep = np.minimum(
                np.asarray(carry[_QDEPTH])[:tail].astype(np.int64),
                DEPTH_BINS - 1,
            )
            tag = np.asarray(carry[_QTAG])[:tail].astype(np.int64)
            dh = self._cart_depth_base.copy()
            np.add.at(dh, (tag, dep), 1)
            act_hist = np.asarray(carry[_CART_START])
            p_evals = np.asarray(carry[_CART_START + 1])
            p_hits = np.asarray(carry[_CART_START + 2])
        for t, inst in enumerate(cohort.instances):
            r = self.results[inst.key]
            r.unique = int(uniq_k[t])
            r.states = int(scnt_k[t])
            r.max_depth = int(maxd_k[t])
            r.disc = disc2[t].astype(np.uint64).copy()
            for i, p in enumerate(cohort.props):
                fp = int(r.disc[i])
                if fp != 0:
                    chain = [fp]
                    while True:
                        par = parents.get(chain[-1], 0)
                        if par == 0:
                            break
                        chain.append(par)
                    chain.reverse()
                    r.chains[p.name] = chain
            if self._cartography:
                r.cartography = snapshot(
                    depth_hist=dh[t], action_hist=act_hist[t],
                    prop_evals=p_evals[t][:max(cohort.n_props, 1)],
                    prop_hits=p_hits[t][:max(cohort.n_props, 1)],
                    prop_names=[p.name for p in cohort.props],
                    states=r.states, unique=r.unique,
                )
            if rec is not None:
                rec.record(
                    "sweep", v=SWEEP_V, event="instance_done",
                    key=inst.key, unique=r.unique, states=r.states,
                    depth=r.max_depth,
                )

    # -- result surface ------------------------------------------------------

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "SweepChecker":
        if self._thread is not None:
            self._thread.join()
        if self._run_error is not None:
            raise self._run_error
        self._maybe_write_report()
        return self

    def state_count(self) -> int:
        if self._done.is_set():
            return sum(r.states for r in self.results.values())
        return self._live[0]

    def unique_state_count(self) -> int:
        if self._done.is_set():
            return sum(r.unique for r in self.results.values())
        return self._live[1]

    def max_depth(self) -> int:
        if not self._done.is_set():
            return 0
        return max(
            (r.max_depth for r in self.results.values()), default=0
        )

    def discoveries(self) -> dict:
        """Aggregate view: ``"<instance key>: <property>"`` -> Path.
        Per-instance access: :meth:`instance_discoveries`."""
        self.join()
        out = {}
        for key in self.results:
            for name, path in self.instance_discoveries(key).items():
                out[f"{key}: {name}"] = path
        return out

    def instance_result(self, key: str) -> InstanceResult:
        self.join()
        return self.results[key]

    def _ns_key(self, key: str):
        """Host fingerprint key matching the instance's namespaced
        device fingerprints (``Path.from_fingerprints(key=...)``)."""
        r = self.results[key]
        inst = self.spec.instances[r.global_index]
        cohort = self.cohorts[r.cohort]
        tag, seed, bits = r.global_index, inst.seed, cohort.ns_bits
        model = inst.model
        return lambda s: ns_fingerprint(
            model.fingerprint_state(s), tag, seed, bits
        )

    def instance_discoveries(self, key: str) -> dict:
        self.join()
        r = self.results[key]
        inst = self.spec.instances[r.global_index]
        out = {}
        for name, chain in r.chains.items():
            out[name] = Path.from_fingerprints(
                inst.model, list(chain), key=self._ns_key(key)
            )
        return out

    def instance_view(self, key: str) -> "SweepInstanceView":
        """A checker-shaped view of one instance: what the report
        builder, the run registry, and the diff engine consume."""
        self.join()
        return SweepInstanceView(self, key)

    @property
    def _final_snapshot(self) -> dict:
        if not hasattr(self, "_last_cohort_carry"):
            if self._run_error is not None:
                raise self._run_error
            raise RuntimeError(
                "sweep has no snapshot: the run failed before "
                "completing a cohort"
            )
        ci, cap, qcap, cand = self._last_cohort_caps
        return self._carry_to_snapshot(
            [
                np.asarray(c)
                for c in self._last_cohort_carry[:_CART_START]
            ],
            ci, cap, qcap, cand,
        )

    def instance_run_id(self, key: str) -> str:
        rid = self._instance_run_ids.get(key)
        if rid is None:
            import uuid

            rid = uuid.uuid4().hex[:16]
            self._instance_run_ids[key] = rid
        return rid

    def _maybe_record_run(self, body=None) -> None:
        """One registry record PER INSTANCE, tagged with this sweep's
        ``sweep_id`` — so ``_cli compare`` and the Explorer dashboard
        work per instance (docs/sweep.md)."""
        if self._run_recorded or self._report_reentry:
            return
        from ..telemetry.registry import resolve_run_dir

        root = resolve_run_dir(self._run_dir)
        if not root:
            return
        self._run_recorded = True
        try:
            from ..telemetry.registry import RunRegistry
            from ..telemetry.report import build_report, identity_doc

            reg = RunRegistry(root)
            for key in self.results:
                view = self.instance_view(key)
                doc = identity_doc(view, build_report(view))
                doc["sweep_id"] = self.run_id
                doc["instance_key"] = key
                # a fleet-packed cohort (stateright_tpu/fleet/) tags
                # its members with the campaign; the instance key IS
                # the tenant's job key there
                cid = getattr(self, "_campaign_id", None)
                if cid:
                    doc["campaign_id"] = str(cid)
                    doc["job_key"] = key
                # checker=None: the headline stays count-derived — the
                # sweep recorder's wall clock is the whole family's, not
                # this instance's
                reg.record_doc(doc)
        except Exception as e:  # noqa: BLE001 - the ledger must never
            import sys

            print(
                "stateright-tpu: sweep registry write failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )


class SweepInstanceView:
    """Checker-shaped per-instance view over a joined sweep.

    Exposes exactly the surface ``telemetry/report.build_report`` /
    ``build_config`` read, so a sweep instance archives (and diffs)
    like a first-class run: ``config.instance.sig`` matches the same
    model's sequential run (engine ``sweep`` vs ``wavefront`` is an
    identical-class delta; the new ``sweep`` flag likewise), and
    ``compare --expect=IDENTICAL`` against the sequential oracle is the
    sweep's one-command parity check."""

    _engine_tag = "sweep"
    _is_sweep_instance = True
    flight_recorder = None
    parent_run_id = None
    timed_out = False

    def __init__(self, sweep: SweepChecker, key: str):
        self._sweep = sweep
        self._result = sweep.results[key]
        inst = sweep.spec.instances[self._result.global_index]
        self.model = inst.model
        self.tensor = inst.model._tensor_cached()
        self._target = inst.target
        self.key = key
        # flag honesty: the archived config says cartography iff the
        # sweep actually carried the per-instance counters
        self._cartography = bool(sweep._cartography)

    @property
    def run_id(self) -> str:
        return self._sweep.instance_run_id(self.key)

    def is_done(self) -> bool:
        return self._sweep.is_done()

    def state_count(self) -> int:
        return self._result.states

    def unique_state_count(self) -> int:
        return self._result.unique

    def max_depth(self) -> int:
        return self._result.max_depth

    def discoveries(self) -> dict:
        return self._sweep.instance_discoveries(self.key)

    def cartography(self) -> Optional[dict]:
        c = self._result.cartography
        return dict(c) if c else None

    def sweep_info(self) -> dict:
        return {
            "sweep_id": self._sweep.run_id,
            "instance_key": self.key,
            "params": dict(self._result.params),
            "seed": self._result.seed,
            "cohort": self._result.cohort,
        }
