"""Shape cohorts: ONE compiled step program per family of compatible
instances (docs/sweep.md).

The sweep engine wants to run many instances of one model family as a
single wavefront.  The twins' kernels are pure functions of the row
words *and their closed-over constants* (bounds, tables, seeds baked at
twin construction), so two instances of the same family trace to
jaxprs that are **structurally identical** and differ only in constant
values.  This module makes that a capability:

 1. every instance's ``step_rows`` / ``property_masks`` is traced at a
    one-row batch (``[1, W]``);
 2. the traced jaxprs are unified: equal constants stay shared, and
    constants (and literals — Python-int bounds trace as jaxpr
    literals) that DIFFER across instances are lifted into arguments
    stacked ``[K, ...]`` across the cohort;
 3. the cohort kernel evaluates the unified jaxpr per row under
    ``jax.vmap``, gathering each row's constants by its instance tag —
    so one XLA program serves every member, and the engine pays ONE
    compile for the cohort instead of K.

Instances whose kernels do not unify (different shapes, different
network semantics, genuinely different code paths) split into separate
cohorts — grouping only affects how many programs compile, never
correctness.  A build-time verification pass backstops the unifier:
the cohort kernel is evaluated on every instance's init rows and
compared against the instance's own kernels; any mismatch demotes the
group to singleton cohorts instead of ever running a wrong program.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..fingerprint import SWEEP_NS_SEED, fold64, mix64, sweep_ns_bits

_KERNELS = ("step_rows", "property_masks")


def shape_signature(instance) -> tuple:
    """The coarse cohort grouping key: twin class + row layout + the
    property list.  Instances that disagree here can never share a
    program (different carry shapes)."""
    tensor = instance.model._tensor_cached()
    props = tuple(
        (p.name, getattr(p.expectation, "name", str(p.expectation)))
        for p in instance.model.properties()
    )
    return (
        type(tensor).__name__,
        int(tensor.width),
        int(tensor.max_actions),
        props,
    )


def _params_eq(a, b) -> bool:
    """Robust eqn-params comparison: dict/tuple recursion, numpy arrays
    by value, nested jaxprs by identity-or-== (the tracing cache makes
    identical inner functions share one jaxpr object; anything else is
    honestly 'different' and the group falls back)."""
    if a is b:
        return True
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _params_eq(a[k], b[k]) for k in a
        )
    if isinstance(a, (tuple, list)) and isinstance(b, (tuple, list)):
        return len(a) == len(b) and all(
            _params_eq(x, y) for x, y in zip(a, b)
        )
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        try:
            return (
                np.asarray(a).dtype == np.asarray(b).dtype
                and np.array_equal(np.asarray(a), np.asarray(b))
            )
        except Exception:  # noqa: BLE001 - exotic params: not equal
            return False
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 - ambiguous/odd __eq__
        return False


def _var_index_maps(jaxprs) -> list:
    """Per-jaxpr ``Var -> ordinal`` maps in definition order (constvars,
    invars, then eqn outvars): two jaxprs are graph-isomorphic in our
    sense iff every eqn reads vars of equal ordinals."""
    maps = []
    for j in jaxprs:
        m = {}
        for v in list(j.constvars) + list(j.invars):
            m[v] = len(m)
        for e in j.eqns:
            for ov in e.outvars:
                m[ov] = len(m)
        maps.append(m)
    return maps


def unify_jaxprs(closed_list):
    """Unify structurally identical ClosedJaxprs into one jaxpr whose
    differing constants/literals are lifted to (stacked) arguments.

    Returns ``(jaxpr, const_spec)`` where ``const_spec`` is an ordered
    list of ``(shared, value)`` pairs matching the unified jaxpr's
    constvars — ``shared=True`` values are identical across instances
    and passed as-is; ``shared=False`` values are stacked ``[K, ...]``
    and gathered by instance tag at evaluation time.  Returns ``None``
    when the jaxprs do not unify (the caller splits the cohort)."""
    from jax._src.core import Literal, Var

    k = len(closed_list)
    j0 = closed_list[0].jaxpr
    jaxprs = [c.jaxpr for c in closed_list]
    for j in jaxprs[1:]:
        if (
            len(j.eqns) != len(j0.eqns)
            or len(j.invars) != len(j0.invars)
            or len(j.constvars) != len(j0.constvars)
            or len(j.outvars) != len(j0.outvars)
        ):
            return None
        if [v.aval for v in j.invars] != [v.aval for v in j0.invars]:
            return None
        if [v.aval for v in j.constvars] != [
            v.aval for v in j0.constvars
        ]:
            return None
    maps = _var_index_maps(jaxprs)

    lifted_vars: list = []
    lifted_vals: list = []
    new_eqns = []
    for ei, eqn in enumerate(j0.eqns):
        eqns_k = [j.eqns[ei] for j in jaxprs]
        if any(e.primitive is not eqn.primitive for e in eqns_k[1:]):
            return None
        if any(
            not _params_eq(e.params, eqn.params) for e in eqns_k[1:]
        ):
            return None
        if any(len(e.invars) != len(eqn.invars) for e in eqns_k[1:]):
            return None
        if any(len(e.outvars) != len(eqn.outvars) for e in eqns_k[1:]):
            return None
        invars = list(eqn.invars)
        changed = False
        for vi, v in enumerate(eqn.invars):
            vs = [e.invars[vi] for e in eqns_k]
            if isinstance(v, Literal):
                if any(not isinstance(x, Literal) for x in vs[1:]):
                    return None
                if any(x.aval != v.aval for x in vs[1:]):
                    return None
                vals = [x.val for x in vs]
                if all(
                    np.array_equal(vals[0], x) for x in vals[1:]
                ):
                    continue
                nv = Var("", v.aval)
                invars[vi] = nv
                changed = True
                lifted_vars.append(nv)
                lifted_vals.append(vals)
            else:
                if any(isinstance(x, Literal) for x in vs[1:]):
                    return None
                if any(
                    maps[i][vs[i]] != maps[0][vs[0]] for i in range(1, k)
                ):
                    return None
        for oi, ov in enumerate(eqn.outvars):
            if any(
                e.outvars[oi].aval != ov.aval for e in eqns_k[1:]
            ):
                return None
        new_eqns.append(
            eqn.replace(invars=invars) if changed else eqn
        )
    for oi, ov in enumerate(j0.outvars):
        ovs = [j.outvars[oi] for j in jaxprs]
        if isinstance(ov, Literal):
            if any(not isinstance(x, Literal) for x in ovs[1:]):
                return None
            if any(
                not np.array_equal(ov.val, x.val) for x in ovs[1:]
            ):
                return None  # differing literal outputs: not worth lifting
        else:
            if any(isinstance(x, Literal) for x in ovs[1:]):
                return None
            if any(
                maps[i][ovs[i]] != maps[0][ovs[0]] for i in range(1, k)
            ):
                return None

    const_spec: list = []
    for ci in range(len(closed_list[0].consts)):
        vals = [np.asarray(c.consts[ci]) for c in closed_list]
        if any(
            v.dtype != vals[0].dtype or v.shape != vals[0].shape
            for v in vals[1:]
        ):
            return None
        if all(np.array_equal(vals[0], v) for v in vals[1:]):
            const_spec.append((True, vals[0]))
        else:
            const_spec.append((False, np.stack(vals)))
    for vals in lifted_vals:
        const_spec.append((False, np.stack([np.asarray(v) for v in vals])))

    new_jaxpr = j0.replace(
        constvars=list(j0.constvars) + lifted_vars, eqns=new_eqns
    )
    return new_jaxpr, const_spec


def _trace_kernel(tensor, name: str):
    """ClosedJaxpr of ``tensor.<name>`` at a one-row batch.  The twin's
    device-const caches are pre-warmed via ``init_rows()`` first (the
    ``run_jaxpr_audit`` discipline: compiled twins materialize lazy
    tables on first use, and tracing must never leak a tracer into
    them)."""
    import jax
    import jax.numpy as jnp

    np.asarray(tensor.init_rows())
    aval = jax.ShapeDtypeStruct((1, int(tensor.width)), jnp.uint64)
    return jax.make_jaxpr(getattr(tensor, name))(aval)


def _unified_kernel(jaxpr, const_spec):
    """The cohort kernel over a unified jaxpr: per-row evaluation under
    ``vmap``, shared constants captured, per-instance constants gathered
    by the row's tag."""
    import jax
    import jax.numpy as jnp
    from jax import core

    shared = [jnp.asarray(v) for s, v in const_spec if s]
    stacked = [jnp.asarray(v) for s, v in const_spec if not s]
    flags = [s for s, _ in const_spec]

    def kernel(rows, tags):
        def one(row, tag):
            consts = []
            si = di = 0
            for s in flags:
                if s:
                    consts.append(shared[si])
                    si += 1
                else:
                    consts.append(stacked[di][tag])
                    di += 1
            outs = core.eval_jaxpr(jaxpr, consts, row[None, :])
            return tuple(o[0] for o in outs)

        return jax.vmap(one)(rows, tags)

    return kernel


class CohortProgram:
    """One shape cohort: the unified kernels + per-instance metadata the
    sweep engine consumes.

    ``instances`` keep their SPEC order; ``tags`` are local (0..K-1)
    row tags, ``global_index[i]`` maps a local tag back to the
    instance's position in the whole sweep (which, with the instance
    seed, derives its namespace word — so cohort grouping never changes
    any instance's fingerprints)."""

    def __init__(self, instances: Sequence, global_index: Sequence[int],
                 ns_bits: int):
        self.instances = list(instances)
        self.global_index = [int(g) for g in global_index]
        self.K = len(self.instances)
        self.twins = [i.model._tensor_cached() for i in self.instances]
        t0 = self.twins[0]
        self.width = int(t0.width)
        self.max_actions = int(t0.max_actions)
        self.props = list(self.instances[0].model.properties())
        self.n_props = len(self.props)
        # namespace parameters (fingerprint.ns_fingerprint): the low
        # ``ns_bits`` key bits carry the GLOBAL tag; a nonzero seed
        # additionally scrambles the high key bits (table-seed fuzzing)
        self.ns_bits = int(ns_bits)
        self.ns_low_np = np.asarray(self.global_index, np.uint64)
        self.ns_xor_np = np.asarray(
            [
                0 if not inst.seed
                else mix64(fold64(SWEEP_NS_SEED, inst.seed))
                for inst in self.instances
            ],
            np.uint64,
        )
        # per-instance target (unique-count early termination); -1 = none
        self.targets_np = np.asarray(
            [
                -1 if inst.target is None else int(inst.target)
                for inst in self.instances
            ],
            np.int64,
        )
        self.unified = True  # False once _build falls back to twin 0
        self._step = None
        self._masks = None
        self._build()

    # -- kernel construction -------------------------------------------------

    def _build(self) -> None:
        if self.K == 1:
            # a singleton cohort runs the twin's own kernels directly —
            # zero unification overhead, exactly the sequential program
            t = self.twins[0]
            self._step = lambda rows, tags: t.step_rows(rows)
            self._masks = lambda rows, tags: t.property_masks(rows)
            return
        traced = {
            name: [_trace_kernel(t, name) for t in self.twins]
            for name in _KERNELS
        }
        unified = {
            name: unify_jaxprs(traced[name]) for name in _KERNELS
        }
        if any(u is None for u in unified.values()):
            raise CohortSplit("kernels do not unify")
        if all(
            not any(not s for s, _ in u[1]) for u in unified.values()
        ):
            # every constant is shared: the twins' programs are
            # literally identical (seed-only sweeps) — run twin 0's own
            # kernels and skip the per-row gather entirely
            t = self.twins[0]
            self._step = lambda rows, tags: t.step_rows(rows)
            self._masks = lambda rows, tags: t.property_masks(rows)
        else:
            sj, sc = unified["step_rows"]
            pj, pc = unified["property_masks"]
            self._step = _unified_kernel(sj, sc)
            mk = _unified_kernel(pj, pc)
            self._masks = lambda rows, tags: mk(rows, tags)[0]
        self._verify()

    def _verify(self) -> None:
        """Build-time backstop: the cohort kernel must reproduce every
        instance's own kernels on that instance's init rows — valid
        masks and property masks exactly, successors exactly on valid
        lanes.  A mismatch raises :class:`CohortSplit` and the group
        demotes to singleton cohorts (correct, just more compiles)."""
        import jax.numpy as jnp

        for tag, twin in enumerate(self.twins):
            rows = jnp.asarray(
                np.asarray(twin.init_rows(), np.uint64)
            )
            tags = jnp.full((rows.shape[0],), tag, jnp.int32)
            succ_c, valid_c = self._step(rows, tags)
            succ_t, valid_t = twin.step_rows(rows)
            if not np.array_equal(
                np.asarray(valid_c), np.asarray(valid_t)
            ):
                raise CohortSplit(
                    f"validity mismatch for {self.instances[tag].key!r}"
                )
            v = np.asarray(valid_t)
            if not np.array_equal(
                np.asarray(succ_c)[v], np.asarray(succ_t)[v]
            ):
                raise CohortSplit(
                    f"successor mismatch for {self.instances[tag].key!r}"
                )
            if not np.array_equal(
                np.asarray(self._masks(rows, tags)),
                np.asarray(twin.property_masks(rows)),
            ):
                raise CohortSplit(
                    f"property mismatch for {self.instances[tag].key!r}"
                )

    # -- engine-facing -------------------------------------------------------

    def step_rows(self, rows, tags):
        return self._step(rows, tags)

    def property_masks(self, rows, tags):
        return self._masks(rows, tags)

    def init_data(self):
        """Concatenated init rows + local tags across the cohort, in
        spec order (the engine inserts them as one batch)."""
        rows, tags = [], []
        for t, twin in enumerate(self.twins):
            r = np.asarray(twin.init_rows(), np.uint64)
            rows.append(r)
            tags.append(np.full((r.shape[0],), t, np.int32))
        return np.concatenate(rows), np.concatenate(tags)


class CohortSplit(Exception):
    """Internal: a candidate group cannot share one program."""


def build_cohorts(spec) -> list:
    """Group the spec's instances into shape cohorts, in order of first
    appearance; groups whose kernels fail to unify (or fail the
    build-time verification) split into singleton cohorts — LOUDLY, so
    a sweep that silently compiles K programs never masquerades as one
    program."""
    import sys

    ns_bits = sweep_ns_bits(len(spec.instances))
    groups: dict = {}
    order: list = []
    for gi, inst in enumerate(spec.instances):
        tensor = inst.model._tensor_cached()
        if tensor is None:
            raise TypeError(
                f"sweep instance {inst.key!r}: "
                f"{type(inst.model).__name__} has no tensor twin — "
                "sweeps run on the device engine only (docs/sweep.md)"
            )
        sig = shape_signature(inst)
        if sig not in groups:
            groups[sig] = []
            order.append(sig)
        groups[sig].append((gi, inst))
    cohorts = []
    for sig in order:
        members = groups[sig]
        insts = [i for _, i in members]
        gidx = [g for g, _ in members]
        try:
            cohorts.append(CohortProgram(insts, gidx, ns_bits))
        except CohortSplit as e:
            print(
                f"stateright-tpu: sweep: {len(insts)} instances of "
                f"{sig[0]} do not share one program ({e}); compiling "
                "separately (docs/sweep.md)",
                file=sys.stderr,
            )
            for g, inst in members:
                cohorts.append(CohortProgram([inst], [g], ns_bits))
    return cohorts
