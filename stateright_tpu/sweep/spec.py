"""Sweep specification: the enumerated model family (docs/sweep.md).

A :class:`SweepSpec` is an ordered list of :class:`SweepInstance`
entries — each a fully configured object-form model plus the sweep
bookkeeping (a unique ``key``, a JSON-safe ``params`` dict for the
registry, an optional per-instance ``target``, and a fingerprint
``seed`` scrambling the instance's table layout).  The instance's
position in the spec is its global **tag**: it lands in the low bits of
the table sort key (``fingerprint.ns_fingerprint``) and keeps instances
apart in the shared visited table, so re-ordering a spec is a different
sweep by construction.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

SWEEP_V = 1
ENV_SWEEP = "STATERIGHT_TPU_SWEEP"


class SweepInstance:
    """One member of a sweep: a configured model + sweep bookkeeping."""

    def __init__(
        self,
        key: str,
        model: Any,
        params: Optional[dict] = None,
        seed: int = 0,
        target: Optional[int] = None,
    ):
        if not key or not isinstance(key, str):
            raise ValueError("SweepInstance needs a non-empty string key")
        self.key = key
        self.model = model
        self.params = dict(params or {})
        self.seed = int(seed)
        self.target = None if target is None else int(target)

    def __repr__(self) -> str:
        return f"SweepInstance({self.key!r})"


class SweepSpec:
    """An ordered family of instances; positions are the instance tags."""

    def __init__(self, instances: Sequence[SweepInstance]):
        self.instances = list(instances)
        if not self.instances:
            raise ValueError("a sweep needs at least one instance")
        keys = [i.key for i in self.instances]
        if len(set(keys)) != len(keys):
            dup = sorted(k for k in set(keys) if keys.count(k) > 1)
            raise ValueError(f"duplicate instance keys: {dup}")

    def __len__(self) -> int:
        return len(self.instances)

    def __iter__(self):
        return iter(self.instances)

    @classmethod
    def family(
        cls,
        factory: Callable[..., Any],
        params_list: Sequence[dict],
        key_fn: Optional[Callable[[dict], str]] = None,
        seeds: Optional[Sequence[int]] = None,
    ) -> "SweepSpec":
        """Build a spec by calling ``factory(**params)`` per entry.

        ``key_fn`` derives the instance key from the params (default:
        ``k=v`` pairs joined by ``,``); ``seeds`` optionally assigns
        per-instance table seeds (default 0 — the instance TAG already
        separates namespaces, and seed 0 keeps discovery-trace parity
        with the sequential oracle; nonzero seeds re-seed the table
        layout for hash-fuzzing sweeps, docs/sweep.md)."""
        insts = []
        for i, params in enumerate(params_list):
            key = (
                key_fn(params)
                if key_fn is not None
                else ",".join(f"{k}={v}" for k, v in sorted(params.items()))
                or f"instance-{i}"
            )
            insts.append(
                SweepInstance(
                    key,
                    factory(**params),
                    params=params,
                    seed=seeds[i] if seeds is not None else 0,
                )
            )
        return cls(insts)


def resolve_sweep_spec(builder_spec, model) -> Optional[SweepSpec]:
    """The effective sweep spec for a spawn: the builder's
    ``sweep(SPEC)`` wins; else the ``STATERIGHT_TPU_SWEEP=N`` env knob
    asks the model for its default family (``model.sweep_family(N)``,
    defined by sweep-capable examples).  Models without the hook print a
    loud ignored-knob one-liner once instead of silently doing nothing
    (the ``--per-channel``-on-a-non-actor-model rule)."""
    import os
    import sys

    if builder_spec is not None:
        return builder_spec
    env = os.environ.get(ENV_SWEEP, "").strip()
    if not env or env == "0":
        return None
    if env.isdigit():
        n = int(env)
    else:
        # a corrupted knob must not silently change the engine: warn
        # and run the plain wavefront (the spill-env malformed rule)
        print(
            f"stateright-tpu: ignoring malformed {ENV_SWEEP}={env!r} "
            "(want the instance count, e.g. 8); running without a "
            "sweep",
            file=sys.stderr,
        )
        return None
    fam = getattr(model, "sweep_family", None)
    if fam is None:
        if not getattr(model, "_sweep_warn_printed", False):
            try:
                object.__setattr__(model, "_sweep_warn_printed", True)
            except Exception:  # noqa: BLE001 - __slots__ models
                pass
            print(
                f"stateright-tpu: {ENV_SWEEP} set but "
                f"{type(model).__name__} defines no sweep_family(); knob "
                "ignored (docs/sweep.md)",
                file=sys.stderr,
            )
        return None
    return fam(n)
