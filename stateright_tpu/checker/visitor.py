"""Per-evaluated-state callbacks (reference ``src/checker/visitor.rs``).

A visitor observes every state the checker evaluates, receiving the full
:class:`~stateright_tpu.checker.path.Path` that led there.  The Explorer's
live snapshot and the visit-order tests are both built on this hook.
"""

from __future__ import annotations

import threading
from typing import Callable

from .path import Path


class CheckerVisitor:
    def visit(self, model, path: Path) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class FnVisitor(CheckerVisitor):
    """Wrap a plain callable (reference ``visitor.rs:23-30``)."""

    def __init__(self, fn: Callable[[object, Path], None]):
        self._fn = fn

    def visit(self, model, path: Path) -> None:
        self._fn(model, path)


class PathRecorder(CheckerVisitor):
    """Records the set of visited paths (reference ``visitor.rs:46-67``)."""

    def __init__(self):
        self.paths: set[Path] = set()
        self._lock = threading.Lock()

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self.paths.add(path)


class StateRecorder(CheckerVisitor):
    """Records final states of visited paths in visit order
    (reference ``visitor.rs:81-100``)."""

    def __init__(self):
        self.states: list = []
        self._lock = threading.Lock()

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self.states.append(path.final_state())
