"""Process-parallel BFS — the honest multi-core CPU baseline.

The thread pool (``pool.py``) mirrors the reference's work-stealing job
market (``bfs.rs:70-151``) faithfully, but under the CPython GIL its
``threads(N)`` is effectively single-core.  This strategy provides real
multi-core checking: ``fork``-ed worker processes running a bulk-synchronous
wavefront with **fingerprint-ownership sharding** — the same decomposition
the device engines use (``parallel/sharded.py`` routes fingerprints to their
owner shard by ``fp % D`` over ICI; here the "devices" are processes and the
"all-to-all" is a pair of multiprocessing queues per worker).

Per round, each worker:

 1. pops its owned frontier, evaluates properties, expands successors
    (identical per-state semantics to ``bfs.py``: no-op/self-loop pruning,
    boundary filter, terminal ebits flush);
 2. routes each successor to ``owner = fp % N`` (one message per peer per
    round, possibly empty — reception is therefore deterministic and
    deadlock-free; ``mp.Queue`` puts are asynchronous via feeder threads);
 3. dedups arrivals against its owned slice of the visited map
    (``fp -> parent fp``, exactly the BFS parent-pointer scheme of
    ``bfs.rs:26`` — each fingerprint has a single owner, so no cross-process
    races exist by construction);
 4. publishes (frontier size, unique count, state count, discovery mask)
    to a shared array and double-barriers: all workers then reach the same
    termination verdict (empty global frontier / all properties discovered /
    target count reached) from the same snapshot.

Work balance comes from fingerprint uniformity instead of stealing: a 64-bit
mixed hash spreads any frontier near-evenly across owners, which is the same
argument the TPU engine rests on.

**Symmetry reduction** works here (beyond the reference, whose symmetry is
DFS-only — ``dfs.rs:260-285``): the dedup key becomes
``stable_hash(representative(state))`` — a pure function, so no
cross-process state is needed — and successors are routed to
``owner = class_key % N`` so each symmetry class has exactly one owner.
The search continues with the *original* state (the ``dfs.py`` subtlety),
and parent pointers link original fingerprints, so discovery paths are
genuine action sequences needing no class-matching walk.  Per-round
arrival batches are folded in worker order, making the reduced counts
deterministic for a fixed worker count (like the device engines, whose
counts are pinned per mesh width).

**Visitors** work here too (closing the reference's multi-core-or-visitor
tradeoff): callbacks cannot cross process boundaries, so workers record
their per-round visit order (fingerprints only) and the PARENT replays
every visit after the merge — round-major, worker-minor, a deterministic
valid BFS level order — reconstructing each ``Path`` from the complete
parent-pointer map.  Recorders and snapshot visitors observe exactly the
states a thread checker would show them; the one semantic difference is
WHEN (after the run, not during), which only matters to a visitor that
races the live run — none of the reference's do.

Discovery *paths* are reconstructed by the parent from the merged visited
map, same as ``bfs.py``.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import time
import traceback
from typing import Optional

from .base import (
    Checker,
    CheckerBuilder,
    ParentPointerTrace,
    evaluate_properties,
    flush_terminal_ebits,
    init_ebits,
)

# shared-stats columns, per worker
_FRONTIER, _UNIQUE, _COUNT, _DISC, _STOP = range(5)
_NCOL = 5


class MpBfsChecker(ParentPointerTrace, Checker):
    """Checker surface over a completed process-parallel run.

    The run happens synchronously in the constructor (workers fork, explore,
    and report back); ``join()`` is a no-op afterwards.  ``fork`` start
    method is required — the model travels to workers by address-space
    inheritance, so arbitrary (unpicklable) models work, matching the thread
    checkers.
    """

    def __init__(self, options: CheckerBuilder, processes: Optional[int] = None):
        self.model = options.model
        self._props = list(self.model.properties())
        # flight recorder: workers cannot share it across the fork, so
        # worker 0 logs one (wall-time, frontier, unique, states) tuple per
        # round — from the SAME barrier snapshot every worker agrees on —
        # and the parent replays the history as "step" records post-merge
        self.flight_recorder = options._make_recorder("mp")
        self._report_path = options.report_path
        self._run_dir = getattr(options, "run_dir", None)
        # run-identity plumbing (telemetry/report.build_config): the
        # prefix target is part of the instance identity and the device
        # engines expose it as _target — mirror it here so a host run's
        # archived config stays comparable with its device counterpart
        self._target = options.target_state_count
        # an EXPLICIT processes count wins verbatim (processes=1 is a valid
        # single-worker debugging run); only the unset case falls through to
        # threads(N) and then to all cores
        if processes is not None:
            n = max(1, processes)
        elif options.thread_count > 1:
            n = options.thread_count
        else:
            n = os.cpu_count() or 1
        self.worker_count = n
        ctx = mp.get_context("fork")
        queues = [ctx.Queue() for _ in range(n)]
        result_q = ctx.Queue()
        stats = ctx.Array("q", n * _NCOL, lock=False)
        barrier = ctx.Barrier(n)
        deadline = (
            time.monotonic() + options.timeout_secs
            if options.timeout_secs is not None
            else None
        )
        want_visits = options.visitor_obj is not None
        workers = [
            ctx.Process(
                target=_worker_main,
                args=(
                    i, n, self.model, self._props, queues, result_q, stats,
                    barrier, options.target_state_count, deadline,
                    options.symmetry_fn, want_visits,
                ),
                daemon=True,
            )
            for i in range(n)
        ]
        for w in workers:
            w.start()
        # drain results BEFORE joining: the visited maps ride the queue's
        # feeder thread, and a child cannot exit until its queue is drained.
        # The get() is watchdogged — a worker that dies WITHOUT reporting
        # (OOM kill, or a crash that strands its peers on the barrier) must
        # not hang the parent forever: on the first error result, or on any
        # abnormally-exited worker with the queue empty, every worker is
        # terminated and the failure surfaces as an exception.
        import queue as _queue

        self._generated: dict[int, int] = {}
        self._discoveries: dict[str, int] = {}
        self._count = 0

        def _fail(msg: str):
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)
            raise RuntimeError(msg)

        results: dict[int, tuple] = {}
        while len(results) < n:
            try:
                kind, who, payload = result_q.get(timeout=5.0)
            except _queue.Empty:
                crashed = [w for w in workers if w.exitcode not in (None, 0)]
                if crashed:
                    _fail(
                        "mp BFS worker died without reporting "
                        f"(exitcode {crashed[0].exitcode}); "
                        "remaining workers terminated"
                    )
                continue
            if kind == "error":
                # peers may be stranded mid-round (their barrier will never
                # fill) — fail fast rather than waiting for n results
                _fail("mp BFS worker failed:\n" + payload)
            results[who] = payload
        # merge in WORKER order, not report-arrival order: when two workers
        # both discovered a property, the surviving witness fingerprint (and
        # therefore the reconstructed trace) must not depend on OS scheduling
        for who in sorted(results):
            visited, disc, count, _, _ = results[who]
            for fp, pfp in visited.values():
                self._generated[fp] = pfp
            for name, fp in disc.items():
                self._discoveries.setdefault(name, fp)
            self._count += count
        for w in workers:
            w.join()
        if self.flight_recorder is not None and 0 in results:
            rec = self.flight_recorder
            for rnd, (t_abs, frontier, unique, count) in enumerate(
                results[0][4]
            ):
                rec.step(
                    engine="mp", states=count, unique=unique,
                    frontier=frontier, round=rnd, t=rec.rel(t_abs),
                )
            rec.close_run(done=True)
        if want_visits:
            self._replay_visits(options.visitor_obj, results)

    def _replay_visits(self, visitor, results: dict) -> None:
        """Replay every worker's recorded visit order through the parent's
        visitor — round-major, worker-minor (a deterministic, valid BFS
        level order) — with paths reconstructed from the now-complete
        merged parent map (callbacks cannot cross the process boundary)."""
        from .path import Path

        logs = {who: results[who][3] for who in results}
        rounds = max((len(l) for l in logs.values()), default=0)
        for r in range(rounds):
            for who in sorted(logs):
                log = logs[who]
                if r >= len(log):
                    continue
                for fp in log[r]:
                    visitor.visit(
                        self.model,
                        Path.from_fingerprints(self.model, self._trace(fp)),
                    )

    # -- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def join(self) -> "MpBfsChecker":
        self._maybe_write_report()
        return self

    def is_done(self) -> bool:
        return True

    # discoveries()/_trace() via ParentPointerTrace


def _worker_main(
    me, n, model, props, queues, result_q, stats, barrier, target, deadline,
    symmetry=None, want_visits=False,
):
    try:
        _worker_loop(
            me, n, model, props, queues, result_q, stats, barrier, target,
            deadline, symmetry, want_visits,
        )
    except Exception:  # noqa: BLE001 - reported to the parent, peers unblocked
        tb = traceback.format_exc()
        for j in range(n):
            if j != me:
                queues[j].put(("abort", me, tb))
        result_q.put(("error", me, tb))
        queues[me].cancel_join_thread()


def _worker_loop(
    me, n, model, props, queues, result_q, stats, barrier, target, deadline,
    symmetry=None, want_visits=False,
):
    prop_count = len(props)
    full_mask = (1 << prop_count) - 1
    prop_index = {p.name: i for i, p in enumerate(props)}
    ebits0 = init_ebits(props)
    # dedup/ownership key: the state fingerprint, or under symmetry the
    # class key stable_hash(representative(state)) — a pure function, so
    # every worker computes it identically with no shared state (the
    # dfs.py::_dedup_key scheme; search continues with ORIGINAL states so
    # parent pointers chain real, re-executable fingerprints)
    if symmetry is not None:
        from ..fingerprint import stable_hash

        def dedup_key(state, fp):
            return stable_hash(symmetry(state))
    else:
        def dedup_key(state, fp):
            return fp

    # key -> (original fp, parent fp); for the plain run key == fp
    visited: dict[int, tuple] = {}
    discoveries: dict[str, int] = {}
    local_count = 0

    # init states: every worker enumerates them (deterministic model
    # obligation, as everywhere in the framework), keeps its owned slice;
    # worker 0 accounts the init contribution to state_count (bfs.py parity)
    frontier = []
    for s in model.init_states():
        if not model.within_boundary(s):
            continue
        if me == 0:
            local_count += 1
        fp = model.fingerprint_state(s)
        key = dedup_key(s, fp)
        if key % n == me and key not in visited:
            visited[key] = (fp, 0)
            frontier.append((s, fp, ebits0))

    # per-round visit order (fps only — the parent replays them through
    # the visitor after the merge; see MpBfsChecker._replay_visits)
    visit_log: list[list[int]] = []
    # per-round (wall, frontier, unique, states) history for the parent's
    # flight recorder; worker 0 only (every worker computes the same
    # barrier snapshot, so one copy suffices)
    round_log: list[tuple] = []

    rnd = 0
    while True:
        if want_visits:
            visit_log.append([fp for _, fp, _ in frontier])
        buckets: list[list] = [[] for _ in range(n)]
        for state, fp, ebits in frontier:
            ebits = evaluate_properties(
                model, props, discoveries, state, ebits, fp
            )
            is_terminal = True
            seen_children = set()
            for action in model.actions(state):
                nxt = model.next_state(state, action)
                if nxt is None:
                    continue
                if not model.within_boundary(nxt):
                    continue
                local_count += 1
                is_terminal = False
                nfp = model.fingerprint_state(nxt)
                key = dedup_key(nxt, nfp)
                if key in seen_children or nfp == fp:
                    continue
                seen_children.add(key)
                buckets[key % n].append((nxt, nfp, fp, ebits, key))
            if is_terminal and ebits:
                flush_terminal_ebits(props, discoveries, ebits, fp)

        # all-to-all: exactly one (possibly empty) message per peer per round
        for j in range(n):
            if j != me:
                queues[j].put((rnd, me, buckets[j]))
        batches = {me: buckets[me]}
        for _ in range(n - 1):
            tag, src, batch = queues[me].get()
            if tag == "abort":
                raise RuntimeError(f"peer worker {src} failed:\n{batch}")
            assert tag == rnd, f"round skew: got {tag}, at {rnd}"
            batches[src] = batch

        frontier = []
        # fold arrivals in worker order, not queue-arrival order: first
        # insertion decides which ORIGINAL state represents a symmetry
        # class (and its parent pointer), so a deterministic fold makes
        # counts and traces reproducible for a fixed worker count
        for j in sorted(batches):
            for state, nfp, pfp, ebits, key in batches[j]:
                if key not in visited:
                    visited[key] = (nfp, pfp)
                    frontier.append((state, nfp, ebits))

        disc_mask = 0
        for name in discoveries:
            disc_mask |= 1 << prop_index[name]
        base = me * _NCOL
        stats[base + _FRONTIER] = len(frontier)
        stats[base + _UNIQUE] = len(visited)
        stats[base + _COUNT] = local_count
        stats[base + _DISC] = disc_mask
        stats[base + _STOP] = int(
            deadline is not None and time.monotonic() > deadline
        )
        barrier.wait()
        tot_frontier = sum(stats[j * _NCOL + _FRONTIER] for j in range(n))
        tot_unique = sum(stats[j * _NCOL + _UNIQUE] for j in range(n))
        if me == 0:
            tot_count = sum(stats[j * _NCOL + _COUNT] for j in range(n))
            round_log.append(
                (time.monotonic(), tot_frontier, tot_unique, tot_count)
            )
        or_mask = 0
        stop = False
        for j in range(n):
            or_mask |= stats[j * _NCOL + _DISC]
            stop = stop or bool(stats[j * _NCOL + _STOP])
        stop = (
            stop
            or tot_frontier == 0
            or (prop_count and or_mask == full_mask)
            or (target is not None and tot_unique >= target)
        )
        # second barrier: nobody may overwrite stats for round r+1 until
        # every worker has read the round-r snapshot and agreed on ``stop``
        barrier.wait()
        if stop:
            break
        rnd += 1

    result_q.put(
        ("done", me, (visited, discoveries, local_count, visit_log,
                      round_log))
    )


def spawn_mp_bfs(model, workers: Optional[int] = None, target_states=None):
    """Convenience: process-parallel BFS over ``model`` (see module doc)."""
    b = model.checker()
    if target_states:
        b = b.target_states(target_states)
    return b.spawn_mp_bfs(processes=workers)
