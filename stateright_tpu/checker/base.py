"""Checker result surface + builder (reference ``src/checker.rs``).

``CheckerBuilder`` is the fluent entry point (``model.checker()...``); the
``Checker`` base class is the uniform result surface shared by every strategy
(CPU BFS, CPU DFS, and the TPU wavefront engine), mirroring reference
``checker.rs:185-338``.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional, Sequence

from ..core import Expectation, Model, Property
from .path import Path
from .visitor import CheckerVisitor, FnVisitor

# States processed per lock round, as in the reference's job market
# (reference ``bfs.rs:120``, ``dfs.rs:126``).
JOB_BLOCK_SIZE = 1500


class CheckerBuilder:
    """Fluent checker configuration (reference ``checker.rs:35-179``)."""

    def __init__(self, model: Model):
        self.model = model
        self.symmetry_fn: Optional[Callable] = None
        self.symmetry_is_default = False
        self.target_state_count: Optional[int] = None
        self.thread_count: int = 1
        self.visitor_obj: Optional[CheckerVisitor] = None
        self.timeout_secs: Optional[float] = None
        self._audit_skip = False
        self.telemetry_opts: Optional[dict] = None
        self.report_path: Optional[str] = None
        # persistent run registry (telemetry/registry.py); None = env
        # default (STATERIGHT_TPU_RUN_DIR, off when unset)
        self.run_dir: Optional[str] = None
        self.checked_mode = False
        # wavefront-throughput knobs (docs/perf.md); None = env default
        self.prewarm_mode: Optional[bool] = None
        self.prededup_mode: Optional[bool] = None
        self.compile_cache_dir: Optional[str] = None
        # partial-order reduction (docs/analysis.md); None = env default
        self.por_mode: Optional[bool] = None
        # billion-state spill tier (docs/spill.md); None = env default
        self.spill_mode: Optional[bool] = None
        # MXU recast round (ops/mxu.py, docs/roofline.md); None = env
        # default (STATERIGHT_TPU_MXU, off when unset)
        self.mxu_opts: Optional[dict] = None
        # periodic crash-safe autosave (stateright_tpu/checkpoint.py,
        # docs/robustness.md); None = env default (STATERIGHT_TPU_AUTOSAVE)
        self.autosave_opts: Optional[dict] = None
        # hyper-batched instance sweep (stateright_tpu/sweep/,
        # docs/sweep.md); None = env default (STATERIGHT_TPU_SWEEP on
        # models that define sweep_family)
        self.sweep_spec = None
        # mesh-native sharded engine (parallel/mesh.py, docs/mesh.md);
        # None = env default (STATERIGHT_TPU_MESH, off when unset)
        self.mesh_mode: Optional[bool] = None
        self.mesh_devices: Optional[int] = None
        # span-trace context (telemetry/spans.py): set by the fleet
        # scheduler / supervisor so spawned engines parent their
        # engine_run spans under the job/attempt span; None = the engine
        # roots a fresh trace (standalone check)
        self._span_ctx = None

    # -- configuration -------------------------------------------------------

    def symmetry(self) -> "CheckerBuilder":
        """Dedupe on symmetry-class representatives; states must define
        ``representative()`` (reference ``checker.rs:150-154``)."""
        self.symmetry_fn = lambda s: s.representative()
        self.symmetry_is_default = True
        return self

    def symmetry_with(self, fn: Callable) -> "CheckerBuilder":
        self.symmetry_fn = fn
        self.symmetry_is_default = False
        return self

    def target_states(self, count: int) -> "CheckerBuilder":
        """Stop after roughly ``count`` unique states
        (reference ``checker.rs:163-167``)."""
        self.target_state_count = count
        return self

    def threads(self, count: int) -> "CheckerBuilder":
        self.thread_count = max(1, count)
        return self

    def visitor(self, v) -> "CheckerBuilder":
        self.visitor_obj = v if isinstance(v, CheckerVisitor) else FnVisitor(v)
        return self

    def timeout(self, secs: float) -> "CheckerBuilder":
        self.timeout_secs = secs
        return self

    def telemetry(
        self,
        enabled: bool = True,
        *,
        capacity: int = 4096,
        occupancy_every: int = 0,
        profile_steps: int = 0,
        profile_dir: Optional[str] = None,
        cartography: bool = False,
        memory: bool = False,
        memory_every: int = 32,
        roofline: bool = False,
        metrics: bool = False,
    ) -> "CheckerBuilder":
        """Attach a flight recorder to the spawned checker
        (``stateright_tpu/telemetry/``; schema in ``docs/telemetry.md``).

        Every strategy then streams one structured record per step — device
        engines per host sync, host engines per job block / mp round — into
        a bounded ring (``capacity`` records) exposed as
        ``checker.flight_recorder`` (JSONL/Chrome-trace export, the
        Explorer's ``/.metrics``, ``bench.py`` summaries).

        ``occupancy_every=N`` additionally samples the visited table's
        bucket-occupancy distribution every N host syncs on the device
        engines, plus a closing ``final`` sample — each a D2H table pull,
        priced in the recorder's transfer counters.  Growth boundaries are
        always sampled for free (the table is host-side there anyway), as
        is the sharded engine's run end (it materializes the table
        host-side regardless); the single-device engine keeps its final
        table on device, so its run-end sample happens only under
        ``occupancy_every``.

        ``profile_steps=N`` arms a scoped ``jax.profiler`` trace of the
        first N hot steps into ``profile_dir`` (device engines only).

        ``memory=True`` attaches the HBM memory ledger
        (``telemetry/memory.py``, docs/telemetry.md "Memory ledger"):
        per-buffer analytic byte accounting for the device-resident
        carry, a growth-transient forecast feeding the health model's
        ``growth_oom_risk`` condition, live ``device.memory_stats()``
        readings where the backend has them, and ``memory`` ring records
        at growth boundaries plus a watermark sample every
        ``memory_every`` host syncs.  Pure host arithmetic over shapes
        the engines already know — zero ops added to the step jaxpr
        either way (pinned by test, the strongest form of the contract
        below).  ``report()`` implies it.

        ``roofline=True`` attaches the roofline cost ledger
        (``telemetry/roofline.py`` + ``analysis/costmodel.py``,
        docs/roofline.md): per-stage/per-op FLOPs-and-bytes attribution
        of the engine pipeline, reconciled against XLA's own
        ``cost_analysis()``, with memory-bound-vs-compute-bound stage
        verdicts where a device spec is known
        (``STATERIGHT_TPU_DEVICE_SPEC`` override) and the JX4xx
        MXU-candidate ranking.  Pure host-side analysis over re-traced
        kernels — the engine's step jaxpr stays bit-identical and the
        engine cache unkeyed either way (the memory ledger's contract,
        pinned by test).  Surfaces as ``checker.roofline()``, the run
        report's ``roofline`` block, ``/.metrics``, and the
        ``costmodel`` CLI verb.

        ``metrics=True`` attaches the process-wide live metrics bus
        (``telemetry/metrics.py``, docs/observability.md): the recorder
        publishes the engine metric families (states/s, frontier size,
        table load, dedup rate, step-time histogram) at host syncs that
        already happen, and the Explorer serves them as Prometheus text
        on ``GET /metrics``.  ``STATERIGHT_TPU_METRICS=1`` is the env
        form.  Pure host-side aggregation of values already in hand —
        zero extra device round-trips, and with the bus detached the
        step-record stream is bit-identical (parity pinned by test).

        ``cartography=True`` additionally folds the search-cartography
        counters into the device step (``ops/cartography.py``,
        docs/telemetry.md): per-depth frontier sizes, the per-action
        successor histogram, per-property evaluation tallies — and on the
        sharded engine per-shard table loads plus the routed-candidate
        matrix.  This is the one telemetry option that changes the step
        program (small integer reductions riding the existing packed
        stats vector; measured ≤5% on 2pc-7, pinned in the slow tier);
        off, the step jaxpr stays bit-identical.  The counters surface as
        ``checker.cartography()``, the recorder's ``cartography`` block,
        the Explorer's ``/.metrics``, and the run report.

        Telemetry off (the default) is exactly the pre-telemetry engine:
        zero ops added to the step jaxpr, no recorder allocated."""
        if not enabled:
            self.telemetry_opts = None
            return self
        # Flags implied earlier (``.report()``/``.cartography()``/
        # ``.memory_ledger()``) are sticky: reconfiguring the recorder
        # must not silently drop the counters/ledger the report contract
        # depends on.
        implied_cart = bool(self.telemetry_opts) and bool(
            self.telemetry_opts.get("cartography")
        )
        implied_mem = bool(self.telemetry_opts) and bool(
            self.telemetry_opts.get("memory")
        )
        implied_roof = bool(self.telemetry_opts) and bool(
            self.telemetry_opts.get("roofline")
        )
        implied_metrics = bool(self.telemetry_opts) and bool(
            self.telemetry_opts.get("metrics")
        )
        # a previously configured cadence is part of the sticky ledger
        # config: keep it unless this call sets one explicitly
        prev_every = (
            self.telemetry_opts.get("memory_every")
            if implied_mem and memory_every == 32
            else None
        )
        self.telemetry_opts = {
            "capacity": capacity,
            "occupancy_every": occupancy_every,
            "profile_steps": profile_steps,
            "profile_dir": profile_dir,
            "cartography": bool(cartography) or implied_cart,
            "memory": bool(memory) or implied_mem,
            "memory_every": int(
                prev_every if prev_every is not None else memory_every
            ),
            "roofline": bool(roofline) or implied_roof,
            "metrics": bool(metrics) or implied_metrics,
        }
        return self

    def cartography(self, enabled: bool = True) -> "CheckerBuilder":
        """Fold the search-cartography counters into the run — a
        ``.telemetry(cartography=True)`` shorthand that composes with an
        existing telemetry config instead of replacing it.  ``report()``
        and the CLI ``--watch`` flag imply it; this method is the one
        place the imply-rule mutates the telemetry options."""
        if not enabled:
            return self
        if self.telemetry_opts is None:
            self.telemetry()
        self.telemetry_opts["cartography"] = True
        return self

    def memory_ledger(self, enabled: bool = True) -> "CheckerBuilder":
        """Attach the HBM memory ledger (``telemetry/memory.py``) — a
        ``.telemetry(memory=True)`` shorthand that composes with an
        existing telemetry config instead of replacing it.  ``report()``
        and the CLI ``--watch`` flag imply it."""
        if not enabled:
            return self
        if self.telemetry_opts is None:
            self.telemetry()
        self.telemetry_opts["memory"] = True
        self.telemetry_opts.setdefault("memory_every", 32)
        return self

    def roofline(self, enabled: bool = True) -> "CheckerBuilder":
        """Attach the roofline cost ledger (``telemetry/roofline.py``) —
        a ``.telemetry(roofline=True)`` shorthand that composes with an
        existing telemetry config instead of replacing it (the
        ``cartography()``/``memory_ledger()`` pattern)."""
        if not enabled:
            return self
        if self.telemetry_opts is None:
            self.telemetry()
        self.telemetry_opts["roofline"] = True
        return self

    def report(self, path: str) -> "CheckerBuilder":
        """Write a post-run report to ``path`` (JSON; a sibling ``.md``
        rendering lands next to it) at the first ``join()`` after the run
        completes — the artifact a human reads after an unattended on-chip
        run (``stateright_tpu/telemetry/report.py``; docs/telemetry.md
        "Reading a run report").  Implies telemetry with cartography AND
        the memory ledger: the report combines the run totals, the
        cartography block, the memory block (analytic — deterministic),
        the health timeline, growth events, and the audit/sanitizer
        status.  The JSON
        body is deterministic for a fixed model/config — wall-clock-
        dependent values live in the markdown rendering only, and the
        volatile fields are exactly the identity header named by
        ``telemetry.report.VOLATILE_KEYS`` (``generated_at``,
        ``run_id``, and ``parent_run_id`` on snapshot-resumed runs)."""
        import os as _os

        if _os.path.splitext(str(path))[1] == ".md":
            raise ValueError(
                f"report path {path!r} ends in .md — pass the JSON path; "
                "the markdown rendering lands next to it as <path-stem>.md"
            )
        self.report_path = str(path)
        return self.cartography().memory_ledger()

    def runs(self, path: str) -> "CheckerBuilder":
        """Archive this run into the persistent run registry rooted at
        ``path`` (``telemetry/registry.py``; docs/telemetry.md "Comparing
        runs"): at the first ``join()`` after completion the
        deterministic report body lands under ``<path>/runs/<run_id>.json``
        and one index record — canonical ``config_key`` + headline
        metrics — appends to ``<path>/index.jsonl``.  Composable with
        ``report()`` (the archived body is the same document).

        Contract (the memory ledger's strongest form, pinned by test):
        the registry is pure host-side post-run I/O — on or off, the
        step jaxpr is bit-identical and the engine cache unkeyed, both
        engines.  Env equivalent: ``STATERIGHT_TPU_RUN_DIR=DIR``
        (archives every run in the process)."""
        self.run_dir = str(path)
        return self

    def prewarm(self, enabled: bool = True) -> "CheckerBuilder":
        """Growth-stall elision for the single-device wavefront engine
        (``docs/perf.md``): the growth ladder's next capacity rungs are
        compiled AHEAD OF TIME on a background thread
        (``jax.jit(...).lower().compile()``), so a growth boundary swaps in
        a ready executable instead of blocking the run on a cold engine
        compile.  Wrong predictions cost one wasted background compile and
        nothing else; the consumed/wasted split and the per-boundary wait
        are recorded in the flight recorder (``compile`` events:
        ``source="prewarm"``, ``duration``).  Default off (env override
        ``STATERIGHT_TPU_PREWARM=1``); search semantics are untouched —
        the prewarmed executable is the SAME program, compiled earlier."""
        self.prewarm_mode = bool(enabled)
        return self

    def prededup(self, enabled: bool = True) -> "CheckerBuilder":
        """Device-side intra-window candidate pre-dedup
        (``ops/buckets.window_unique``; ``docs/perf.md``): duplicate
        fingerprints inside one expansion window are masked to EMPTY before
        the visited-set insert, shrinking the insert pipeline's effective
        width to the window's unique count (the BLEST move: dedup the
        frontier BEFORE the expensive global-memory phase).  Equivalence
        contract, pinned by tests: unique/state counts, discovery traces,
        and the inserted table are bit-identical with the flag on or off —
        the filter keeps exactly the lane ``bucket_insert``'s stable sort
        would have kept.  Default off (env override
        ``STATERIGHT_TPU_PREDEDUP=1``); with the flag off the step jaxpr
        is unchanged (same contract as telemetry/checked)."""
        self.prededup_mode = bool(enabled)
        return self

    def compile_cache(self, path: str) -> "CheckerBuilder":
        """Opt into JAX's persistent compilation cache at ``path``
        (``docs/perf.md``): engine executables are cached on disk keyed on
        their HLO, so repeated CLI/bench/regress invocations skip XLA
        engine compiles entirely (including every growth rung a previous
        run already visited).  Applies process-wide on first engine spawn
        — the cache dir is a global JAX setting.  Env equivalent:
        ``STATERIGHT_TPU_COMPILE_CACHE=DIR``.  Per-rung hits are recorded
        in the flight recorder's ``compile`` events (``cache_hit``)."""
        self.compile_cache_dir = str(path)
        return self

    def por(self, enabled: bool = True) -> "CheckerBuilder":
        """Partial-order reduction on the device engines
        (``docs/analysis.md`` "State-space reduction"): the static
        independence analysis (``analysis/independence.py``) derives a
        per-model action×action conflict matrix from jaxpr footprints at
        BitPacker-field granularity; the engines then mask each state's
        enabled-action set down to a minimal conflict-closed **ample
        subset** (a stubborn-set closure computed on device), with a
        conservative cycle proviso — a state whose ample successors are
        all duplicates is fully expanded, as is the first batch after
        every growth/resume boundary.

        Soundness contract (pinned by tests): property verdicts are
        IDENTICAL to full expansion.  The analysis enforces this by
        falling back to full expansion whenever reduction could be
        unsound — ``eventually``/liveness properties, property-footprint
        conflicts (an ample set may not contain a property-visible
        action), undecidable footprints (conservatively dependent), or a
        boundary-filtered twin.  With the flag OFF (the default) the step
        jaxpr is bit-identical to a pre-POR engine (the
        telemetry/checked/prededup discipline); env override
        ``STATERIGHT_TPU_POR=1``.  Composes with ``symmetry()`` and
        ``prededup()``."""
        self.por_mode = bool(enabled)
        return self

    def mxu(
        self,
        enabled: bool = True,
        *,
        coalesce: bool = True,
        slim_queue: bool = True,
        probe: bool = True,
    ) -> "CheckerBuilder":
        """Arm the MXU recast round on the device engines
        (``stateright_tpu/ops/mxu.py``; docs/roofline.md "Executing the
        hot-spot list"): three flag-gated bytes-moved reductions
        executing PR 11's ranked JX4xx hot spots —

        - ``coalesce``: trace the twin's expand-scatter-coalesced step
          kernel (``step_rows_coalesced``; hand twins + per-channel
          compiled twins) — each action piece's packed-field write-backs
          assemble as one word-stacked block instead of one scatter per
          field (the paxos-3 #1 hot spot: 37 sites, 109 MB/step).
          Twins without a coalesced form silently keep the plain kernel;
        - ``slim_queue``: append novel queue rows in ``batch``-sized
          chunks gated on the novel count instead of one
          candidate-stack-wide ``dynamic_update_slice`` window (queue
          rows 1-3 of the ledger);
        - ``probe``: the BLEST one-hot membership probe — the bucket
          membership/occupancy reductions become one blocked bitmapped
          ``dot_general`` over the candidate x slot comparison tile,
          giving the dedup-insert stage a genuine dot-class op (the
          2pc-7 #1 hot spot).

        Contract, pinned by tests (the prededup/spill discipline): OFF
        (the default) leaves the step jaxpr bit-identical and the engine
        cache unkeyed; ON keeps unique/total counts, property verdicts,
        and discovery traces bit-identical across the fleet — the
        transforms move the same information through cheaper shapes.
        The roofline ledger (``.roofline()``) measures the payoff;
        ``regress.py --mxu`` gates it.  Env override
        ``STATERIGHT_TPU_MXU=1`` (all three components); composes with
        ``symmetry()``/``por()``/``prededup()``/``spill()``."""
        if not enabled:
            # explicit off wins over the env knob (resolve_flag's rule):
            # an all-off component dict resolves to None without ever
            # consulting STATERIGHT_TPU_MXU
            self.mxu_opts = {
                "coalesce": False, "slim_queue": False, "probe": False,
            }
            return self
        self.mxu_opts = {
            "coalesce": bool(coalesce),
            "slim_queue": bool(slim_queue),
            "probe": bool(probe),
        }
        return self

    def mesh(
        self, enabled: bool = True, *, devices: Optional[int] = None
    ) -> "CheckerBuilder":
        """Run ``spawn_tpu`` on the mesh-native sharded engine
        (``stateright_tpu/parallel/mesh.py``; docs/mesh.md): the
        single-device wavefront program partitioned over a named
        ``('host', 'chip')`` device mesh with ``NamedSharding`` rules —
        visited table sharded by bucket owner, queue buffers sharded,
        counters replicated — so the compiler inserts the cross-shard
        collectives instead of a hand-scheduled ``shard_map`` body.

        Parity contract, pinned by tests/test_mesh.py: unique/total
        counts, property verdicts, discovery traces, and kill+resume
        snapshots are bit-identical to the single-device wavefront
        engine (the programs ARE the wavefront engine's; only placement
        differs).  ``devices=N`` bounds the mesh to the first N local
        devices (default: all of them).  Env override
        ``STATERIGHT_TPU_MESH=1`` (or ``=N`` for a device bound).  The
        OLD hand-rolled engine keeps its spelling — the
        ``devices=``/``n_devices=``/``mesh=`` spawn kwargs — and wins
        when both are given explicitly."""
        self.mesh_mode = bool(enabled)
        self.mesh_devices = int(devices) if devices is not None else None
        return self

    def spill(self, enabled: bool = True) -> "CheckerBuilder":
        """Arm the billion-state spill tier on the wavefront engine
        (``stateright_tpu/spill/``; docs/spill.md): the visited set
        becomes a TIERED store — the HBM bucket table as the hot tier,
        backed by a host-RAM append-only fingerprint store (hash-indexed)
        with an mmap'd disk tier behind it.  When PR 7's capacity plan
        says the next growth rung's migration transient will not fit the
        device budget (live ``bytes_limit`` or the
        ``STATERIGHT_TPU_DEVICE_BYTES`` override), the engine EVICTS the
        hot table's contents to the host tier at the growth boundary
        instead of growing; a device-side Bloom filter over the spilled
        set (bit-slices of ``mix64(fp)``) answers "definitely not seen"
        on-device, so only Bloom-positive candidates are resolved against
        the host index at host sync.

        Contracts, pinned by tests/test_spill.py: spill OFF (the
        default) leaves the step jaxpr bit-identical and the engine
        cache unkeyed; spill ON keeps unique/total counts and property
        verdicts bit-identical to an unconstrained run, with the
        cartography block reconciling exactly.  The snapshot manifest
        carries the host/disk tier contents, so kill+resume works
        mid-spill.  Env override ``STATERIGHT_TPU_SPILL=1``; wavefront
        engine only (the sharded engine rejects it with guidance), and
        mutually exclusive with ``por()`` for now.  Spawn knobs:
        ``spill_bloom_bits``, ``spill_dir``, ``spill_host_bytes``
        (host-tier budget before the disk tier takes over; env
        ``STATERIGHT_TPU_HOST_BYTES``)."""
        self.spill_mode = bool(enabled)
        return self

    def autosave(
        self,
        path: str,
        every_secs: float = 60.0,
        keep: int = 3,
    ) -> "CheckerBuilder":
        """Periodically autosave the run to rotating snapshot generations
        under ``path`` (``stateright_tpu/checkpoint.py``;
        docs/robustness.md): at host-sync boundaries, once ``every_secs``
        has elapsed (``0`` = every host sync), the device engines write
        their resume snapshot as ``gen-NNNNNN/snapshot.npz`` + a
        ``MANIFEST.json`` committed LAST — both through the atomic write
        discipline (tmp + fsync + ``os.replace``), so a crash mid-save
        leaves a torn generation that resume detects and skips, never a
        poisoned one.  The newest ``keep`` complete generations are
        retained.

        Resume with ``spawn_tpu(resume=checkpoint.latest_generation(DIR)
        [0])`` — or run under ``supervisor.supervise``, which wires
        autosave + classify + retry/backoff end to end.  Each save emits
        a versioned ``checkpoint`` ring record and a ``stage_checkpoint``
        attribution counter, so the cadence's cost is visible in the
        stage breakdown.  Contract (the registry's form, pinned): on or
        off, the step jaxpr is bit-identical and the engine cache
        unkeyed — autosave is pure host-side I/O at sync boundaries.
        Env equivalent: ``STATERIGHT_TPU_AUTOSAVE=DIR`` (cadence/keep
        via ``STATERIGHT_TPU_AUTOSAVE_SECS``/``_KEEP``)."""
        self.autosave_opts = {
            "dir": str(path),
            "every_secs": float(every_secs),
            "keep": int(keep),
        }
        return self

    def sweep(self, spec) -> "CheckerBuilder":
        """Check a whole model family in one device run
        (``stateright_tpu/sweep/``; docs/sweep.md): ``spec`` is a
        :class:`~stateright_tpu.sweep.SweepSpec` enumerating instances
        (lossiness flags, bounds, initial values, table seeds).
        ``spawn_tpu`` then returns a
        :class:`~stateright_tpu.sweep.engine.SweepChecker`: instances
        group into shape cohorts, each cohort compiles ONE wavefront
        step program (per-instance constants gathered by a row tag),
        and all instances of a cohort explore concurrently over a
        shared visited table whose fingerprints are namespaced per
        instance — so each instance's unique/total counts, property
        verdicts, and discovery traces reconcile bit-identically
        against its own sequential run (pinned by tests).

        Contract (the registry's strongest form, by construction): with
        no sweep requested, ``spawn_tpu`` builds exactly the pre-sweep
        engine — step jaxpr bit-identical, engine cache unkeyed.  Env
        equivalent: ``STATERIGHT_TPU_SWEEP=N`` on models that define
        ``sweep_family(N)``.  A sweep composes with telemetry /
        cartography / report / runs / timeout / target; it rejects
        checked/por/spill/mxu/symmetry/prededup/autosave with guidance.
        """
        self.sweep_spec = spec
        return self

    def checked(self, enabled: bool = True) -> "CheckerBuilder":
        """Checked execution mode: the sanitizer's DYNAMIC guard
        (``docs/analysis.md``).  The device wavefront runs the same
        exploration with the model kernels under
        ``jax.experimental.checkify`` index/nan/div instrumentation and
        fails loudly — a
        :class:`~stateright_tpu.analysis.CheckedExecutionError` naming the
        offending row (index, raw words, decoded state) — instead of
        letting an out-of-bounds gather silently clamp and prune the
        search.  Use it when the static sanitizer reports an *undecided*
        site (JX201/JX202 info) or to confirm a marginal JX203 overflow.

        Contract, mirroring telemetry's: ``checked=False`` (the default)
        leaves the step jaxpr bit-identical to an engine without the
        feature (pinned by test); ``checked=True`` pays the checkify
        instrumentation cost and is a debugging mode, not a bench
        configuration.  Host checkers ignore the flag (Python raises
        eagerly there); the sharded engine rejects it for now."""
        self.checked_mode = bool(enabled)
        return self

    def _make_recorder(self, engine: str):
        """FlightRecorder per the builder's telemetry options (None when
        telemetry is off) — shared by every spawn path."""
        if self.telemetry_opts is None:
            return None
        from ..telemetry import FlightRecorder

        metrics = None
        if self.telemetry_opts.get("metrics"):
            from ..telemetry import default_bus

            metrics = default_bus()
        return FlightRecorder(
            capacity=self.telemetry_opts["capacity"],
            meta={
                "engine": engine,
                "model": type(self.model).__name__,
            },
            metrics=metrics,
        )

    # -- static preflight audit (stateright_tpu/analysis/) -------------------

    def audit(self, *, deep: bool = True) -> "object":
        """Run the static auditor over the model and return the
        :class:`~stateright_tpu.analysis.AuditReport` — jaxpr kernel audit
        of the tensor twin, actor-handler lint, config-drift checks
        (rule catalogue: ``docs/analysis.md``).  ``deep=True`` adds the
        bounded closure-domain probe and the fresh-twin drift re-resolve."""
        from ..analysis import audit_model

        return audit_model(self.model, deep=deep)

    def skip_audit(self) -> "CheckerBuilder":
        """Escape hatch: disable the automatic ``spawn_tpu`` preflight
        audit for this builder (e.g. to reproduce a flagged defect on
        device, or when a rule false-positives on exotic kernels)."""
        self._audit_skip = True
        return self

    def _preflight_audit(self) -> None:
        """Audit before any device launch: errors abort (raising
        :class:`~stateright_tpu.analysis.AuditError`), warnings print once
        per model.  Disabled by :meth:`skip_audit` or the
        ``STATERIGHT_TPU_SKIP_AUDIT=1`` env knob."""
        import os

        if self._audit_skip or os.environ.get("STATERIGHT_TPU_SKIP_AUDIT") == "1":
            return
        from ..analysis import AuditError, Severity, audit_model

        try:
            report = audit_model(self.model, deep=False)
        except Exception:  # noqa: BLE001 - the audit must never mask the
            return  # engine's own (more specific) spawn-time error surface
        if report.errors:
            raise AuditError(
                report, context=f"spawn_tpu({type(self.model).__name__})"
            )
        if report.warnings and not getattr(
            self.model, "_audit_warn_printed", False
        ):
            try:
                object.__setattr__(self.model, "_audit_warn_printed", True)
            except Exception:  # noqa: BLE001 - __slots__ models
                pass
            print(
                report.format(min_severity=Severity.WARNING), file=sys.stderr
            )

    # -- strategies ----------------------------------------------------------

    def spawn_bfs(self) -> "Checker":
        from .bfs import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self) -> "Checker":
        from .dfs import DfsChecker

        return DfsChecker(self)

    def spawn_mp_bfs(self, processes: Optional[int] = None) -> "Checker":
        """Process-parallel BFS: real multi-core checking (the thread pool
        above is GIL-bound).  Fingerprint-ownership sharding over forked
        workers — the CPU analogue of the device engines' all-to-all
        routing; see ``checker/mp.py``.  ``processes`` defaults to
        ``threads(N)`` if set above 1, else all cores."""
        from .mp import MpBfsChecker

        return MpBfsChecker(self, processes=processes)

    def spawn_auto(self, probe_secs: float = 2.0, **tpu_kw) -> "Checker":
        """Pick the engine by *measured* space size, fixing the small-space
        footgun: the device engine pays a fixed per-run cost (compile
        cache, tunnel round-trips, table setup) that dominates below ~1e5
        states, where CPU BFS wins by 8-100x (bench r4: lin-reg-2's
        544-state space ran 927 states/s on a v5e vs 7.4k/s on one CPU
        core).

        Strategy: (1) a thread-engine probe runs first, bounded by
        ``probe_secs`` — if the space exhausts within the budget, the
        finished checker IS the result and nothing bigger is ever paid
        for; (2) a space that outlives the probe escalates to the
        heavier engine, having spent only the probe budget (and with the
        probe's wall-clock deducted from any user ``timeout()``).  The
        heavier engine is the device wavefront (``tpu_kw`` passes
        through to :meth:`spawn_tpu`) — except with a visitor, which
        device engines reject, where it is the process-parallel mp-BFS
        (multi-core + visitor via replay), available only where ``fork``
        exists.  Models with no tensor twin or a compile error check on
        the thread engines outright.  With ``symmetry()`` the probe uses
        DFS — the host thread engine that supports representative dedup,
        as in the reference where symmetry is DFS-only."""
        import time as _time

        cpu_spawn = self.spawn_dfs if self.symmetry_fn else self.spawn_bfs

        def probe_then(escalate, small=None):
            """Visitor-free sizing probe on the thread engine, then either
            the ``small`` outcome (default: the finished probe itself) or
            ``escalate``.

            Timeout semantics: without a visitor, the probe's wall-clock
            is deducted from the user ``timeout()`` so total time stays
            within budget.  WITH a visitor the final engine gets the FULL
            user timeout instead (total may overshoot by at most
            ``probe_secs``): callbacks must fire exactly once on a
            fully-budgeted run — deducting would let an internal probe
            starve the visible run into a partial result, or swallow the
            callbacks entirely."""
            if (
                self.timeout_secs is not None
                and self.timeout_secs <= probe_secs
            ):
                return cpu_spawn()  # the whole run fits in the probe budget
            saved = self.timeout_secs
            vis, self.visitor_obj = self.visitor_obj, None
            self.timeout_secs = probe_secs
            t0 = _time.monotonic()
            try:
                probe = cpu_spawn().join()
            finally:
                self.timeout_secs = saved
                self.visitor_obj = vis
            if not probe.timed_out:
                return probe if small is None else small()
            if saved is None or vis is not None:
                return escalate()
            remaining = saved - (_time.monotonic() - t0)
            if remaining <= 0:
                return probe  # budget gone: the partial probe result is it
            self.timeout_secs = remaining
            try:
                return escalate()
            finally:
                self.timeout_secs = saved

        if self.visitor_obj is not None:
            # device engines reject visitors (they never materialize
            # states), so big spaces escalate to the process-parallel
            # BFS instead (visitors via replay, symmetry supported) —
            # when there are cores to win and fork exists (the model
            # travels to workers by address-space inheritance).  The
            # probe runs visitor-FREE (callbacks must fire exactly once,
            # on the final engine only); a small space then re-runs the
            # thread engine with the visitor attached, which the probe
            # just proved cheap.
            import multiprocessing as _mp
            import os as _os

            can_mp = (_os.cpu_count() or 1) > 1 and (
                "fork" in _mp.get_all_start_methods()
            )
            if not can_mp:
                return cpu_spawn()
            return probe_then(self.spawn_mp_bfs, small=cpu_spawn)
        from ..parallel.tensor_model import twin_or_none

        if twin_or_none(self.model) is None:
            return cpu_spawn()
        return probe_then(lambda: self.spawn_tpu(**tpu_kw))

    def spawn_tpu(self, **kw) -> "Checker":
        """The point of this framework: wavefront BFS on TPU (no reference
        counterpart; see ``stateright_tpu/parallel/wavefront.py``).

        Pass ``devices=N`` (or ``mesh=...``) to shard the wavefront over a
        device mesh with all-to-all fingerprint routing
        (``stateright_tpu/parallel/sharded.py``).  The mesh-NATIVE engine
        (``stateright_tpu/parallel/mesh.py``, docs/mesh.md) is spelled
        :meth:`mesh` / ``--mesh`` / ``STATERIGHT_TPU_MESH=1`` instead;
        an explicit ``devices``/``n_devices``/``mesh=`` argument keeps
        selecting the old engine.

        A static preflight audit runs first (``docs/analysis.md``): audit
        errors abort here, before any device work; silence deliberately
        with :meth:`skip_audit`."""
        from ..parallel.partition import resolve_mesh_flag
        from ..sweep import resolve_sweep_spec

        mesh_on, mesh_n = resolve_mesh_flag(
            getattr(self, "mesh_mode", None),
            getattr(self, "mesh_devices", None),
        )
        spec = resolve_sweep_spec(
            getattr(self, "sweep_spec", None), self.model
        )
        if spec is not None:
            if "n_devices" in kw or "mesh" in kw or kw.get("devices"):
                raise NotImplementedError(
                    "sweeps run on the single-device engine for now — "
                    "drop the devices/mesh argument (docs/sweep.md)"
                )
            if mesh_on:
                raise NotImplementedError(
                    "sweep x mesh is a queued unlock (ROADMAP): sweeps "
                    "run on the single-device engine for now — drop "
                    ".mesh()/--mesh/STATERIGHT_TPU_MESH (docs/sweep.md)"
                )
            # audit once per distinct SHAPE of the family (the cohort
            # grouping key: twin class + row layout + properties) —
            # same-shape members share kernels, so auditing each would
            # re-trace the same programs N times, while differently
            # configured same-class members (lossy vs non-lossy paxos)
            # still get their own preflight
            from ..sweep.cohort import shape_signature

            seen = set()
            for inst in spec.instances:
                try:
                    sig = shape_signature(inst)
                except Exception:  # noqa: BLE001 - twin failures surface
                    sig = id(inst)  # in the audit below, per instance
                if sig in seen:
                    continue
                seen.add(sig)
                saved = self.model
                self.model = inst.model
                try:
                    self._preflight_audit()
                finally:
                    self.model = saved
            from ..sweep.engine import SweepChecker

            return SweepChecker(self, spec, **kw)
        self._preflight_audit()
        devices = kw.pop("devices", None)
        if devices is not None and devices != 1:
            kw.setdefault("n_devices", devices)
        if "n_devices" in kw or "mesh" in kw:
            # the old engine's spelling stays the old engine — even with
            # the mesh flag armed, an explicit devices/mesh argument is
            # an explicit choice (the A/B harness relies on this)
            from ..parallel.sharded import ShardedTpuChecker

            return ShardedTpuChecker(self, **kw)
        if mesh_on:
            from ..parallel.mesh import MeshTpuChecker

            return MeshTpuChecker(self, n_devices=mesh_n, **kw)
        from ..parallel.wavefront import TpuChecker

        return TpuChecker(self, **kw)

    def serve(
        self, addr: str = "localhost:3000", strategy: str = "bfs", **spawn_kw
    ):
        """Spawn a check and serve the Explorer web UI over it (reference
        ``checker.rs:108-114``).  ``strategy="tpu"`` browses a device
        wavefront run (beyond the reference, whose Explorer wraps only
        ``BfsChecker``); with it, extra keyword arguments pass through to
        ``spawn_tpu``."""
        try:
            from ..explorer import serve
        except ImportError as e:
            raise NotImplementedError("the Explorer is not available yet") from e
        return serve(self, addr, strategy=strategy, **spawn_kw)


class Checker:
    """Uniform result surface for all strategies
    (reference ``checker.rs:185-338``)."""

    model: Model
    # run telemetry (stateright_tpu/telemetry/): a FlightRecorder when the
    # builder requested .telemetry(), else None on every strategy
    flight_recorder = None
    # post-run report (telemetry/report.py): the builder's .report(PATH),
    # honored at the first join() after completion on EVERY strategy (host
    # runs simply carry no cartography block)
    _report_path: Optional[str] = None
    _report_written = False
    # persistent run registry (telemetry/registry.py): the builder's
    # .runs(DIR) (or STATERIGHT_TPU_RUN_DIR), honored like the report
    _run_dir: Optional[str] = None
    _run_recorded = False
    _report_reentry = False
    # run identity (docs/telemetry.md "Comparing runs"): minted lazily,
    # stamped into the report header, snapshot manifests, and the
    # registry index; parent_run_id set by snapshot resume
    _run_id: Optional[str] = None
    parent_run_id: Optional[str] = None

    @property
    def run_id(self) -> str:
        """Stable unique id of this run (16 hex chars)."""
        if self._run_id is None:
            import uuid

            self._run_id = uuid.uuid4().hex[:16]
        return self._run_id

    def _maybe_write_report(self) -> None:
        """Write the builder-requested run report (and archive into the
        run registry when one is configured) exactly once, at the first
        join() after completion (never from inside a run thread: the
        report reconstructs discovery paths, which joins)."""
        if not self.is_done():
            return
        body = None
        if self._report_path and not self._report_written:
            self._report_written = True  # before write: never retry a crash
            from ..telemetry.report import write_report

            # building the report reconstructs discovery paths, which
            # JOINS and re-enters this method — hold the registry off
            # until the body exists, so the archive reuses it instead of
            # building a second one from the nested call
            self._report_reentry = True
            try:
                body = write_report(self, self._report_path)
            finally:
                self._report_reentry = False
        self._maybe_record_run(body)

    def _maybe_record_run(self, body=None) -> None:
        """Archive the completed run into the persistent registry when
        one is configured (builder ``.runs(DIR)`` or
        ``STATERIGHT_TPU_RUN_DIR``) — pure post-run host I/O, exactly
        once, never fatal to the join.  ``body`` reuses the report body
        ``write_report`` just built (building one reconstructs discovery
        paths; it must not run twice per join)."""
        if self._run_recorded or self._report_reentry:
            return
        from ..telemetry.registry import resolve_run_dir

        root = resolve_run_dir(self._run_dir)
        if not root:
            return
        self._run_recorded = True  # before write: never retry a crash
        try:
            from ..telemetry.registry import RunRegistry

            RunRegistry(root).record(self, body=body)
        except Exception as e:  # noqa: BLE001 - the ledger must never
            # break a join
            print(
                f"stateright-tpu: run-registry write failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )

    # -- strategy-provided ---------------------------------------------------

    def state_count(self) -> int:
        """Total states generated, including duplicates."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        raise NotImplementedError

    def max_depth(self) -> int:
        return 0

    def discoveries(self) -> dict[str, Path]:
        """Property name -> discovered example/counterexample path."""
        raise NotImplementedError

    def join(self) -> "Checker":
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    # -- shared --------------------------------------------------------------

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> str:
        """"example" or "counterexample" (reference ``checker.rs:245-252``)."""
        exp = self.model.property_by_name(name).expectation
        return "example" if exp == Expectation.SOMETIMES else "counterexample"

    def report(self, stream=None) -> "Checker":
        """Block until done, printing 1 Hz progress then a final ``sec=`` line
        and discoveries (reference ``checker.rs:217-242``); the ``sec=`` value
        is the benchmark metric."""
        stream = stream or sys.stdout
        start = time.monotonic()
        last = 0.0
        while not self.is_done():
            now = time.monotonic()
            if now - last >= 1.0:
                print(
                    f"Checking. states={self.state_count()}, "
                    f"unique={self.unique_state_count()}",
                    file=stream,
                )
                last = now
            time.sleep(0.05)
        self.join()
        sec = max(time.monotonic() - start, 1e-9)
        print(
            f"Done. states={self.state_count()}, "
            f"unique={self.unique_state_count()}, sec={sec:.6g}",
            file=stream,
        )
        for name, path in sorted(self.discoveries().items()):
            cls = self.discovery_classification(name)
            print(f'Discovered "{name}" {cls} {path!r}', file=stream)
        return self

    # -- assertions (reference ``checker.rs:256-338``) -----------------------

    def assert_properties(self) -> None:
        for prop in self.model.properties():
            if prop.expectation == Expectation.SOMETIMES:
                self.assert_any_discovery(prop.name)
            else:
                self.assert_no_discovery(prop.name)

    def assert_any_discovery(self, name: str) -> Path:
        path = self.discovery(name)
        assert path is not None, f"Missing discovery for {name!r}."
        return path

    def assert_no_discovery(self, name: str) -> None:
        path = self.discovery(name)
        assert path is None, (
            f"Unexpected \"{name}\" {self.discovery_classification(name)} {path!r}"
        )

    def assert_discovery(self, name: str, actions: Sequence) -> None:
        """Assert a discovery exists and that ``actions`` is one valid witness
        trace, by re-executing the model (reference ``checker.rs:293-338``)."""
        self.assert_any_discovery(name)
        prop = self.model.property_by_name(name)
        model = self.model
        last_err = f"no init state admits the action sequence {list(actions)!r}"
        for init in model.init_states():
            path = Path.from_actions(model, init, actions)
            if path is None:
                continue
            final = path.final_state()
            if prop.expectation == Expectation.ALWAYS:
                assert not prop.condition(model, final), (
                    f"path does not violate always property {name!r}"
                )
                return
            if prop.expectation == Expectation.SOMETIMES:
                assert prop.condition(model, final), (
                    f"path does not satisfy sometimes property {name!r}"
                )
                return
            # EVENTUALLY counterexample: no state along the maximal path
            # satisfies the condition, and the path ends in a terminal state.
            assert not any(prop.condition(model, s) for s in path.states()), (
                f"path satisfies eventually property {name!r}"
            )
            assert not model.next_steps(final), (
                f"path for eventually property {name!r} does not end terminal"
            )
            return
        raise AssertionError(last_err)


class ParentPointerTrace:
    """Path reconstruction shared by checkers whose visited map stores
    ``child_fp -> parent_fp`` with root sentinel 0 (thread BFS and mp BFS;
    reference ``bfs.rs:314-342``).  Requires ``self.model``,
    ``self._generated`` (the parent-pointer map) and ``self._discoveries``
    (property name -> discovery fp)."""

    def _trace(self, fp: int) -> list[int]:
        fps = [fp]
        while True:
            parent = self._generated.get(fps[-1], 0)
            if parent == 0:
                break
            fps.append(parent)
        fps.reverse()
        return fps

    def discoveries(self) -> dict[str, Path]:
        return {
            name: Path.from_fingerprints(self.model, self._trace(fp))
            for name, fp in dict(self._discoveries).items()
        }


def evaluate_properties(
    model, props: Sequence[Property], discoveries: dict, state, ebits, token
):
    """Shared per-state property evaluation (reference ``bfs.rs:192-227``):
    record always-counterexamples / sometimes-examples under ``token``
    (first writer wins), clear satisfied eventually-bits.  Returns updated
    ebits."""
    for i, prop in enumerate(props):
        if prop.expectation is Expectation.ALWAYS:
            if prop.name not in discoveries and not prop.condition(model, state):
                discoveries.setdefault(prop.name, token)
        elif prop.expectation is Expectation.SOMETIMES:
            if prop.name not in discoveries and prop.condition(model, state):
                discoveries.setdefault(prop.name, token)
        elif i in ebits and prop.condition(model, state):
            ebits = ebits - {i}
    return ebits


def flush_terminal_ebits(
    props: Sequence[Property], discoveries: dict, ebits, token
) -> None:
    """Liveness bits still set at a terminal state are counterexamples
    (reference ``bfs.rs:265-272``)."""
    for i in ebits:
        discoveries.setdefault(props[i].name, token)


def init_ebits(properties: Sequence[Property]) -> frozenset[int]:
    """Initial liveness bits: one per ``eventually`` property, set at path
    start, cleared when satisfied; bits still set at a terminal state flush as
    counterexamples (reference ``checker.rs:341-348``).  Like the reference,
    bits are *not* part of the state fingerprint, which can miss
    counterexamples on DAG joins and cycles (``bfs.rs:239-257`` FIXMEs) —
    replicated for parity, pinned by tests."""
    return frozenset(
        i for i, p in enumerate(properties) if p.expectation == Expectation.EVENTUALLY
    )
