"""Traces through a model's state space (reference ``src/checker/path.rs``).

A :class:`Path` is a sequence ``state --action--> state --action--> ... state``.
Checkers store only fingerprints (device-side the TPU engine stores only
``fp -> parent fp``), so materializing a path *re-executes* the model and
matches successor fingerprints (reference ``path.rs:20-86``).  If re-execution
cannot reproduce a recorded fingerprint the model is nondeterministic (e.g.
iteration over an unordered container with randomized order, wall-clock reads,
RNG without fixed seed) and we raise with a detailed diagnostic, as the
reference does (``path.rs:35-49``).
"""

from __future__ import annotations

from typing import Generic, Iterable, Optional, Sequence, TypeVar

State = TypeVar("State")
Action = TypeVar("Action")

_NONDETERMINISM_MSG = """\
Failed to reconstruct a path because the model is not deterministic.
Refusing to continue. This usually happens when a state contains a
container whose iteration order is not stable across identical states
(e.g. iterating a Python set whose insertion order differs), or when
actions/next_state consult randomness or wall-clock time. Make the
model a pure function of its inputs. Missing fingerprint: {fp:#018x}
after {n} matched step(s)."""


class Path(Generic[State, Action]):
    """A pair sequence ``[(state, action), ..., (final_state, None)]``."""

    def __init__(self, pairs: Sequence[tuple[State, Optional[Action]]]):
        if not pairs:
            raise ValueError("empty path")
        self._pairs = list(pairs)

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int]) -> "Path":
        """Re-execute ``model`` along a fingerprint trace
        (reference ``path.rs:20-86``)."""
        if not fingerprints:
            raise ValueError("empty fingerprint path")
        fps = list(fingerprints)
        init_fp = fps[0]
        state = None
        for s in model.init_states():
            if model.fingerprint_state(s) == init_fp:
                state = s
                break
        if state is None:
            raise RuntimeError(_NONDETERMINISM_MSG.format(fp=init_fp, n=0))
        pairs: list[tuple[State, Optional[Action]]] = []
        for i, want in enumerate(fps[1:], start=1):
            found = None
            for action in model.actions(state):
                nxt = model.next_state(state, action)
                if nxt is not None and model.fingerprint_state(nxt) == want:
                    found = (action, nxt)
                    break
            if found is None:
                raise RuntimeError(_NONDETERMINISM_MSG.format(fp=want, n=i - 1))
            pairs.append((state, found[0]))
            state = found[1]
        pairs.append((state, None))
        return Path(pairs)

    @staticmethod
    def from_actions(
        model, init_state: State, actions: Iterable[Action]
    ) -> Optional["Path"]:
        """Follow an action sequence from ``init_state``; ``None`` if any
        action is unavailable (reference ``path.rs:90-112``)."""
        pairs: list[tuple[State, Optional[Action]]] = []
        state = init_state
        for action in actions:
            available = list(model.actions(state))
            if action not in available:
                return None
            nxt = model.next_state(state, action)
            if nxt is None:
                return None
            pairs.append((state, action))
            state = nxt
        pairs.append((state, None))
        return Path(pairs)

    # -- accessors -----------------------------------------------------------

    def last_state(self) -> State:
        return self._pairs[-1][0]

    final_state = last_state

    def states(self) -> list[State]:
        return [s for s, _ in self._pairs]

    def actions(self) -> list[Action]:
        return [a for _, a in self._pairs if a is not None]

    def into_vec(self) -> list[tuple[State, Optional[Action]]]:
        return list(self._pairs)

    def __len__(self) -> int:
        return len(self._pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._pairs == other._pairs

    def __hash__(self) -> int:
        from ..fingerprint import stable_hash

        try:
            return stable_hash(
                tuple(
                    (stable_hash(s), 0 if a is None else stable_hash(a))
                    for s, a in self._pairs
                )
            )
        except TypeError:
            # exotic unhashable actions: degrade to a weak but
            # eq-consistent hash
            return len(self._pairs)

    def encode(self, model) -> str:
        """``/``-joined fingerprints, as used in Explorer URLs
        (reference ``path.rs:160-165``)."""
        return "/".join(str(model.fingerprint_state(s)) for s, _ in self._pairs)

    def __repr__(self) -> str:
        return "Path[" + ", ".join(repr(a) for a in self.actions()) + "]"

    def __str__(self) -> str:
        return "\n".join(str(a) for a in self.actions())
