"""Traces through a model's state space (reference ``src/checker/path.rs``).

A :class:`Path` is a sequence ``state --action--> state --action--> ... state``.
Checkers store only fingerprints (device-side the TPU engine stores only
``fp -> parent fp``), so materializing a path *re-executes* the model and
matches successor fingerprints (reference ``path.rs:20-86``).  If re-execution
cannot reproduce a recorded fingerprint the model is nondeterministic (e.g.
iteration over an unordered container with randomized order, wall-clock reads,
RNG without fixed seed) and we raise with a detailed diagnostic, as the
reference does (``path.rs:35-49``).
"""

from __future__ import annotations

from typing import Generic, Iterable, Optional, Sequence, TypeVar

State = TypeVar("State")
Action = TypeVar("Action")

_NONDETERMINISM_MSG = """\
Failed to reconstruct a path because the model is not deterministic.
Refusing to continue. This usually happens when a state contains a
container whose iteration order is not stable across identical states
(e.g. iterating a Python set whose insertion order differs), or when
actions/next_state consult randomness or wall-clock time. Make the
model a pure function of its inputs. Missing fingerprint: {fp:#018x}
after {n} matched step(s)."""


class Path(Generic[State, Action]):
    """A pair sequence ``[(state, action), ..., (final_state, None)]``."""

    def __init__(self, pairs: Sequence[tuple[State, Optional[Action]]]):
        if not pairs:
            raise ValueError("empty path")
        self._pairs = list(pairs)

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int], key=None) -> "Path":
        """Re-execute ``model`` along a fingerprint trace
        (reference ``path.rs:20-86``).

        ``key`` overrides the per-state fingerprint used for matching —
        symmetry-reduced device runs pass the *canonical* fingerprint
        (``fingerprint_state(representative(s))``), so the walk picks, at
        each step, an actual successor of the previously chosen member whose
        symmetry class matches the trace.  The result is a genuine path of
        the model."""
        if not fingerprints:
            raise ValueError("empty fingerprint path")
        fps = list(fingerprints)
        if key is None:
            # exact fingerprints are injective along the trace: the greedy
            # first-match walk is exhaustive, no backtracking needed
            key = model.fingerprint_state
            greedy = True
        else:
            # a symmetry key maps whole classes to one fingerprint, and the
            # representative need not be class-invariant — committing to the
            # wrong member can dead-end even though the trace is valid, so
            # the walk backtracks over matching members
            greedy = False

        # members proven to dead-end, per depth: whether a member can match
        # the remaining suffix depends only on (member, depth), never on the
        # prefix that reached it — so a dead-end holds across alternatives,
        # and skipping it bounds the backtracking walk at O(members × depth)
        # instead of worst-case exponential re-exploration
        dead: dict[int, set] = {}

        def matches(state, want, depth):
            out = []
            seen_members = set()
            blocked = dead.get(depth, ())
            for action in model.actions(state):
                nxt = model.next_state(state, action)
                if nxt is not None and key(nxt) == want:
                    if greedy:
                        return [(action, nxt)]
                    # distinct actions often produce the identical successor;
                    # keep one per member or backtracking re-explores the
                    # same dead-end subtree per duplicate
                    member = model.fingerprint_state(nxt)
                    if member not in seen_members and member not in blocked:
                        seen_members.add(member)
                        out.append((action, nxt))
            return out

        init_matches = [
            (None, s) for s in model.init_states() if key(s) == fps[0]
        ]
        if not init_matches:
            raise RuntimeError(_NONDETERMINISM_MSG.format(fp=fps[0], n=0))
        # DFS over (depth, chosen member) with explicit alternatives stack
        stack = [(0, init_matches)]  # depth i: candidates matching fps[i]
        chosen: list[tuple[Optional[Action], State]] = []
        deepest = 0  # deepest matched depth, for the failure diagnostic
        while stack:
            depth, cands = stack[-1]
            if not cands:
                stack.pop()
                if chosen:
                    popped = chosen.pop()
                    if not greedy:  # every continuation failed: dead-end
                        dead.setdefault(depth - 1, set()).add(
                            model.fingerprint_state(popped[1])
                        )
                continue
            act_nxt = cands.pop(0)
            chosen.append(act_nxt)
            deepest = max(deepest, depth)
            if depth + 1 == len(fps):
                pairs: list[tuple[State, Optional[Action]]] = []
                for i in range(len(chosen) - 1):
                    pairs.append((chosen[i][1], chosen[i + 1][0]))
                pairs.append((chosen[-1][1], None))
                return Path(pairs)
            nxt_cands = matches(act_nxt[1], fps[depth + 1], depth + 1)
            if nxt_cands:
                stack.append((depth + 1, nxt_cands))
            else:
                chosen.pop()
                if not greedy:  # no viable continuation at all: dead-end
                    dead.setdefault(depth, set()).add(
                        model.fingerprint_state(act_nxt[1])
                    )
        if not greedy:
            raise RuntimeError(
                "Failed to reconstruct a symmetry-reduced path: no sequence "
                "of class members matches the recorded canonical "
                f"fingerprints (failed past step {deepest} of {len(fps)}). "
                "This indicates the model's representative() disagrees with "
                "the device canonicalizer, or the model is nondeterministic."
            )
        raise RuntimeError(
            _NONDETERMINISM_MSG.format(fp=fps[deepest + 1], n=deepest)
        )

    @staticmethod
    def from_actions(
        model, init_state: State, actions: Iterable[Action]
    ) -> Optional["Path"]:
        """Follow an action sequence from ``init_state``; ``None`` if any
        action is unavailable (reference ``path.rs:90-112``)."""
        pairs: list[tuple[State, Optional[Action]]] = []
        state = init_state
        for action in actions:
            available = list(model.actions(state))
            if action not in available:
                return None
            nxt = model.next_state(state, action)
            if nxt is None:
                return None
            pairs.append((state, action))
            state = nxt
        pairs.append((state, None))
        return Path(pairs)

    # -- accessors -----------------------------------------------------------

    def last_state(self) -> State:
        return self._pairs[-1][0]

    final_state = last_state

    def states(self) -> list[State]:
        return [s for s, _ in self._pairs]

    def actions(self) -> list[Action]:
        return [a for _, a in self._pairs if a is not None]

    def into_vec(self) -> list[tuple[State, Optional[Action]]]:
        return list(self._pairs)

    def __len__(self) -> int:
        return len(self._pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._pairs == other._pairs

    def __hash__(self) -> int:
        from ..fingerprint import stable_hash

        try:
            return stable_hash(
                tuple(
                    (stable_hash(s), 0 if a is None else stable_hash(a))
                    for s, a in self._pairs
                )
            )
        except TypeError:
            # exotic unhashable actions: degrade to a weak but
            # eq-consistent hash
            return len(self._pairs)

    def encode(self, model) -> str:
        """``/``-joined fingerprints, as used in Explorer URLs
        (reference ``path.rs:160-165``)."""
        return "/".join(str(model.fingerprint_state(s)) for s, _ in self._pairs)

    def __repr__(self) -> str:
        return "Path[" + ", ".join(repr(a) for a in self.actions()) + "]"

    def __str__(self) -> str:
        return "\n".join(str(a) for a in self.actions())
