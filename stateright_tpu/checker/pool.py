"""Shared work-stealing worker pool for the CPU checkers.

One implementation of the delicate job-market protocol (idle-count termination
detection, surplus splitting, error propagation, deadline enforcement), shared
by BFS and DFS (reference duplicates it per strategy: ``bfs.rs:70-151``,
``dfs.rs:76-158``).  Subclasses provide ``_check_block`` (process up to
``JOB_BLOCK_SIZE`` entries from a job) and ``_split_job`` (carve ``k`` shares
off a job for idle workers).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .base import Checker, CheckerBuilder

log = logging.getLogger(__name__)


class _JobMarket:
    """Shared job queue + idle count (reference ``bfs.rs:29-30,70-74``)."""

    def __init__(self, thread_count: int):
        self.cond = threading.Condition()
        self.thread_count = thread_count
        self.jobs: list = []
        self.closed = False

    def close(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()


class WorkerPoolChecker(Checker):
    """Checker strategy backed by a pool of work-sharing threads."""

    _telemetry_tag = "pool"  # overridden: "bfs" / "dfs"

    def _start_pool(self, options: CheckerBuilder, initial_job) -> None:
        self._options = options
        # flight recorder (stateright_tpu/telemetry/): one "step" record per
        # processed job block, from whichever worker thread ran it
        self.flight_recorder = options._make_recorder(self._telemetry_tag)
        self._report_path = options.report_path
        self._run_dir = getattr(options, "run_dir", None)
        self._count_lock = threading.Lock()
        self._state_count_shared = 0
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._timed_out = False
        self._deadline = (
            time.monotonic() + options.timeout_secs
            if options.timeout_secs is not None
            else None
        )
        self._market = _JobMarket(options.thread_count)
        self._market.jobs.append(initial_job)
        self._waiting = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(options.thread_count)
        ]
        for t in self._threads:
            t.start()

    # -- strategy hooks ------------------------------------------------------

    def _check_block(self, pending) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def _split_job(self, pending, k: int) -> list:  # pragma: no cover
        raise NotImplementedError

    # -- pool protocol -------------------------------------------------------

    def _worker(self):
        # thread-lifecycle instrumentation (reference ``bfs.rs:84,95,101,107``
        # via the log crate); enable with logging.DEBUG on this module
        log.debug("%s started", threading.current_thread().name)
        try:
            self._worker_loop()
            log.debug("%s done", threading.current_thread().name)
        except BaseException as e:  # user model bugs must reach join()
            log.debug("%s failed: %r", threading.current_thread().name, e)
            self._error = e
            self._stop.set()
            self._market.close()

    def _worker_loop(self):
        market = self._market
        pending = None
        while True:
            if not pending:
                with market.cond:
                    while True:
                        if market.jobs:
                            pending = market.jobs.pop()
                            break
                        if market.closed or self._stop.is_set():
                            return
                        self._waiting += 1
                        if self._waiting == market.thread_count:
                            # all workers idle & no jobs: exploration complete
                            market.closed = True
                            self._waiting -= 1
                            market.cond.notify_all()
                            return
                        market.cond.wait()
                        self._waiting -= 1
                if not pending:
                    continue
            self._check_block(pending)
            if self.flight_recorder is not None:
                # queue = REMAINING market blocks (not the block just
                # processed).  busy=False opts out of the zero-novelty
                # stall heuristic: pool job blocks carry un-deduped
                # successors, so an all-duplicates tail block is a normal
                # converging run, not wavefront-style spinning (the
                # wavefront queue holds only unique rows, where zero
                # fresh inserts IS stall-shaped)
                self.flight_recorder.step(
                    engine=self._telemetry_tag,
                    states=self._state_count_shared,
                    unique=self.unique_state_count(),
                    queue=len(self._market.jobs),
                    busy=False,
                )
            if self._deadline is not None and time.monotonic() > self._deadline:
                # "timed out" means CUT SHORT: a run whose last block
                # exhausted the space just past the deadline completed —
                # only flag when work remains here or in the market (a peer
                # still holding work runs this same check itself)
                if pending or self._market.jobs:
                    self._timed_out = True
                    self._stop.set()
            if self._stop.is_set():
                market.close()
                return
            # share surplus work with idle threads
            # (reference ``bfs.rs:138-150``)
            if len(pending) > 1:
                with market.cond:
                    if self._waiting > 0 and not market.jobs:
                        n = min(self._waiting + 1, len(pending))
                        market.jobs.extend(self._split_job(pending, n - 1))
                        market.cond.notify_all()

    def _add_count(self, n: int) -> None:
        with self._count_lock:
            self._state_count_shared += n

    # -- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count_shared

    @property
    def timed_out(self) -> bool:
        """True when the run was cut short by the builder ``timeout()``
        deadline (as opposed to finishing, reaching ``target_states``, or
        discovering every property) — the signal ``spawn_auto()`` uses to
        decide the space outgrew its CPU probe."""
        return self._timed_out

    def join(self) -> "WorkerPoolChecker":
        for t in self._threads:
            t.join()
        if self._error is not None:
            raise self._error
        if self.flight_recorder is not None:
            # close the health timeline (telemetry/health.py): idempotent,
            # so repeated join() calls emit at most one "done" record.
            # A deadline-cut run stopped without finishing — its phase
            # stays where the run actually was.
            self.flight_recorder.close_run(done=not self._timed_out)
        self._maybe_write_report()
        return self

    def is_done(self) -> bool:
        return all(not t.is_alive() for t in self._threads)
