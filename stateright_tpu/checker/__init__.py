"""Checker engine layer (reference L2, ``src/checker.rs`` + ``src/checker/``)."""

from .base import Checker, CheckerBuilder, JOB_BLOCK_SIZE
from .path import Path
from .visitor import CheckerVisitor, FnVisitor, PathRecorder, StateRecorder

__all__ = [
    "Checker",
    "CheckerBuilder",
    "JOB_BLOCK_SIZE",
    "Path",
    "CheckerVisitor",
    "FnVisitor",
    "PathRecorder",
    "StateRecorder",
]
