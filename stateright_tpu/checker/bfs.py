"""Parallel breadth-first checker — the CPU oracle (reference ``src/checker/bfs.rs``).

Work is distributed through the shared job market (``pool.py``).  The visited
map stores ``fp -> parent fp`` so discovery paths are reconstructed by walking
parent pointers and re-executing the model (reference ``bfs.rs:314-342``).

Semantics pinned by tests (and calibrated against the reference's pinned
report shapes, ``checker.rs:459-461``):

 - ``state_count`` counts init states plus every generated within-boundary
   successor, *including duplicates*; ``unique_state_count`` is the visited-map
   size.
 - Properties are evaluated when a state is popped; the run stops as soon as
   every property has a discovery (checked per state, before expansion).
 - ``eventually`` bookkeeping uses per-path bits flushed at terminal states
   (see ``base.init_ebits`` for the replicated reference caveats).

Dedup across threads relies on CPython's atomic ``dict.setdefault``: the
insert either wins (returns our parent fp) or reveals the earlier entry, so a
state is enqueued exactly once — the Python analogue of the reference's
DashMap entry API (``bfs.rs:245-259``).
"""

from __future__ import annotations

from collections import deque

from .base import (
    CheckerBuilder,
    JOB_BLOCK_SIZE,
    ParentPointerTrace,
    evaluate_properties,
    flush_terminal_ebits,
    init_ebits,
)
from .path import Path
from .pool import WorkerPoolChecker


class BfsChecker(ParentPointerTrace, WorkerPoolChecker):
    _telemetry_tag = "bfs"

    def __init__(self, options: CheckerBuilder):
        self.model = options.model
        self._props = list(self.model.properties())
        self._prop_count = len(self._props)
        self._generated: dict[int, int] = {}  # fp -> parent fp (0 for init)
        self._discoveries: dict[str, int] = {}  # property name -> fp

        ebits = init_ebits(self._props)
        job = deque()
        init_count = 0
        for s in self.model.init_states():
            if not self.model.within_boundary(s):
                continue
            init_count += 1
            fp = self.model.fingerprint_state(s)
            if fp not in self._generated:
                self._generated[fp] = 0
                job.append((s, fp, ebits))
        self._start_pool(options, job)
        self._add_count(init_count)

    # -- strategy hooks ------------------------------------------------------

    def _split_job(self, pending: deque, k: int) -> list:
        chunk = len(pending) // (k + 1)
        return [
            deque(pending.popleft() for _ in range(chunk)) for _ in range(k)
        ]

    def _check_block(self, pending: deque):
        model = self.model
        props = self._props
        generated = self._generated
        discoveries = self._discoveries
        visitor = self._options.visitor_obj
        target = self._options.target_state_count
        local_count = 0
        processed = 0
        while pending and processed < JOB_BLOCK_SIZE and not self._stop.is_set():
            state, fp, ebits = pending.popleft()
            processed += 1
            if visitor is not None:
                visitor.visit(model, Path.from_fingerprints(model, self._trace(fp)))
            ebits = evaluate_properties(
                model, props, discoveries, state, ebits, fp
            )
            if self._prop_count and len(discoveries) == self._prop_count:
                self._stop.set()
                break
            # expansion (reference ``bfs.rs:229-264``)
            is_terminal = True
            seen_children = set()  # two actions can yield the same successor
            for action in model.actions(state):
                nxt = model.next_state(state, action)
                if nxt is None:
                    continue
                if not model.within_boundary(nxt):
                    continue
                local_count += 1
                is_terminal = False
                nfp = model.fingerprint_state(nxt)
                if nfp in seen_children or nfp == fp:
                    continue
                # atomic insert-or-reveal: cross-thread races resolve by
                # parent fp; same-parent duplicates are caught above, so a
                # returned parent equal to ours means our insert won
                if generated.setdefault(nfp, fp) == fp:
                    seen_children.add(nfp)
                    pending.append((nxt, nfp, ebits))
            if is_terminal and ebits:
                flush_terminal_ebits(props, discoveries, ebits, fp)
                if self._prop_count and len(discoveries) == self._prop_count:
                    self._stop.set()
                    break
            if target is not None and len(generated) >= target:
                self._stop.set()
                break
        self._add_count(local_count)

    # -- Checker surface (paths via ParentPointerTrace) ----------------------

    def unique_state_count(self) -> int:
        return len(self._generated)
