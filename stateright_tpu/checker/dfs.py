"""Parallel depth-first checker (reference ``src/checker/dfs.rs``).

Far less memory than BFS: the visited set stores bare fingerprints (no parent
pointers) and each pending entry carries its fingerprint path as a structurally
shared cons chain (the reference copies a ``Vec<Fingerprint>`` per entry,
``dfs.rs:25-29``; sharing makes pushes O(1) instead of O(depth)), so discovery
paths need no reconstruction walk.  Paths found are not generally shortest.

Symmetry reduction applies here only, as in the reference (``dfs.rs:260-285``;
BFS ignores it): successors are deduplicated on
``fingerprint(representative(state))`` while the search continues with the
*original* state so recorded paths stay valid (the reference pins a regression
test for exactly that subtlety, ``dfs.rs:394-483``).

Cross-thread dedup uses atomic ``dict.setdefault`` with a per-attempt token
(identity-compared), the Python analogue of DashSet insertion.
"""

from __future__ import annotations

from .base import (
    CheckerBuilder,
    JOB_BLOCK_SIZE,
    evaluate_properties,
    flush_terminal_ebits,
    init_ebits,
)
from .path import Path
from .pool import WorkerPoolChecker


def _fps(node) -> list[int]:
    """Materialize a cons fp-path chain ``(fp, parent_node)`` into a list."""
    out = []
    while node is not None:
        out.append(node[0])
        node = node[1]
    out.reverse()
    return out


class DfsChecker(WorkerPoolChecker):
    _telemetry_tag = "dfs"

    def __init__(self, options: CheckerBuilder):
        self.model = options.model
        self._symmetry = options.symmetry_fn
        self._props = list(self.model.properties())
        self._prop_count = len(self._props)
        self._generated: dict[int, object] = {}  # fp -> insertion token
        self._discoveries: dict[str, tuple] = {}  # name -> cons fp-path node

        ebits = init_ebits(self._props)
        job: list = []
        init_count = 0
        for s in self.model.init_states():
            if not self.model.within_boundary(s):
                continue
            init_count += 1
            if self._insert(self._dedup_key(s)):
                fp = self.model.fingerprint_state(s)
                job.append((s, (fp, None), ebits))
        self._start_pool(options, job)
        self._add_count(init_count)

    def _dedup_key(self, state) -> int:
        if self._symmetry is not None:
            # The symmetry-dedup key is internal to this run (never used for
            # paths, URLs, or device tables), so it uses the structural hash:
            # representatives permute states into configurations a
            # tensor-backed fingerprint bridge may not be able to encode
            # (e.g. outside a compiled twin's reachable closure).
            from ..fingerprint import stable_hash

            return stable_hash(self._symmetry(state))
        return self.model.fingerprint_state(state)

    def _insert(self, key: int) -> bool:
        """Atomically insert ``key``; True iff we were first."""
        token = object()
        return self._generated.setdefault(key, token) is token

    # -- strategy hooks ------------------------------------------------------

    def _split_job(self, pending: list, k: int) -> list:
        # share from the bottom of the stack: oldest (shallowest) entries
        chunk = len(pending) // (k + 1)
        shares = []
        for _ in range(k):
            shares.append(pending[:chunk])
            del pending[:chunk]
        return shares

    def _check_block(self, pending: list):
        model = self.model
        props = self._props
        discoveries = self._discoveries
        visitor = self._options.visitor_obj
        target = self._options.target_state_count
        local_count = 0
        processed = 0
        while pending and processed < JOB_BLOCK_SIZE and not self._stop.is_set():
            state, node, ebits = pending.pop()
            processed += 1
            if visitor is not None:
                visitor.visit(model, Path.from_fingerprints(model, _fps(node)))
            ebits = evaluate_properties(
                model, props, discoveries, state, ebits, node
            )
            if self._prop_count and len(discoveries) == self._prop_count:
                self._stop.set()
                break
            is_terminal = True
            for action in model.actions(state):
                nxt = model.next_state(state, action)
                if nxt is None:
                    continue
                if not model.within_boundary(nxt):
                    continue
                local_count += 1
                is_terminal = False
                if self._insert(self._dedup_key(nxt)):
                    nfp = model.fingerprint_state(nxt)
                    pending.append((nxt, (nfp, node), ebits))
            if is_terminal and ebits:
                flush_terminal_ebits(props, discoveries, ebits, node)
                if self._prop_count and len(discoveries) == self._prop_count:
                    self._stop.set()
                    break
            if target is not None and len(self._generated) >= target:
                self._stop.set()
                break
        self._add_count(local_count)

    # -- Checker surface -----------------------------------------------------

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> dict[str, Path]:
        return {
            name: Path.from_fingerprints(self.model, _fps(node))
            for name, node in dict(self._discoveries).items()
        }
