"""Contract-aware structural diff over two run reports.

Every observability layer before this one explains a *single* run; this
module is the comparison half (docs/telemetry.md "Comparing runs"): a
deterministic, machine-readable diff of two run-report documents
(``telemetry/report.py``) that KNOWS what each configuration delta
promises — and classifies the pair

 - ``IDENTICAL`` — every count-derived field agrees (and the flag delta,
   if any, promised exactly that);
 - ``ISOMORPHIC`` — property verdicts agree while explored counts differ,
   under a flag delta that promises verdict-isomorphism only
   (``--por``, ``--per-channel``, ``symmetry()``);
 - ``PERF-ONLY`` — the delta is pure perf knobs (prewarm, pallas,
   compile cache, device/git drift): counts still must agree, and the
   interesting difference is throughput;
 - ``DIVERGENT`` — a promised contract is broken; the ``violations``
   list names every break (machine-readable: rule + field + both sides).

Flag classes (each promise is pinned by its own feature's tests — this
table is the single place the diff engine encodes them):

 - *observability* (``telemetry``/``cartography``/``memory``/
   ``roofline``): bit-identical counts; blocks may appear/disappear.
 - *identical* (``checked``/``prededup``/``spill``, and an engine
   delta — wavefront/sharded/host parity is pinned): bit-identical
   counts and verdicts.
 - *isomorphic* (``por``/``symmetry``, and an ``encoding`` delta):
   identical verdicts, explored counts may shrink (a reduction that
   GROWS the space is a violation).
 - *perf* (``prewarm``/``pallas``/``compile_cache``, ``device``/
   ``git_rev`` drift): bit-identical counts; only wall-clock may move.
 - *incomparable* (different model or instance): no contract applies —
   the pair diverges with a single named ``incomparable`` violation.

Volatile identity fields (``generated_at``, ``run_id``, ...) are scrubbed
BY SCHEMA — :data:`telemetry.report.VOLATILE_KEYS` is consulted at diff
time, so a new volatile header field is ignored here automatically.

Kill+resume lineage: when ``b`` carries ``parent_run_id == a.run_id``
(snapshot-manifest propagation), the pair is the SAME logical run
continued — the gates become monotonicity (the resumed run must carry at
least the parent's totals and every parent discovery) plus exact-totals
equality when the parent itself completed.  A passing lineage pair
classifies ``IDENTICAL``; lost work is a ``resume_lost_work`` violation
(the PR-8/PR-10 exact-totals pins as one command).
"""

from __future__ import annotations

from typing import Optional

from . import report as _report

DIFF_V = 1

IDENTICAL = "IDENTICAL"
ISOMORPHIC = "ISOMORPHIC"
PERF_ONLY = "PERF-ONLY"
DIVERGENT = "DIVERGENT"

# flag -> contract class (module docstring table)
FLAG_CLASS = {
    "telemetry": "observability",
    "cartography": "observability",
    "memory": "observability",
    "roofline": "observability",
    "checked": "identical",
    "prededup": "identical",
    "spill": "identical",
    # sweep membership (stateright_tpu/sweep/): per-instance counts and
    # verdicts are contractually bit-identical to the sequential run
    "sweep": "identical",
    "por": "isomorphic",
    "symmetry": "isomorphic",
    "prewarm": "perf",
    "pallas": "perf",
    "compile_cache": "perf",
    # the MXU recast knobs (ops/mxu.py): counts bit-identical by
    # contract, program shapes differ — a pure perf delta
    "mxu": "perf",
}

# non-flag config aspects -> contract class
_TOP_CLASS = {
    "model": "incomparable",
    "instance": "incomparable",
    "engine": "identical",
    "encoding": "isomorphic",
    "device": "perf",
    "git_rev": "perf",
}

# weakest-promise ordering: the pair's contract is the least committal
# class present in the delta
_RANK = {
    "same": 0, "observability": 1, "identical": 2, "perf": 3,
    "isomorphic": 4, "unknown": 5, "incomparable": 6,
}

# contracts under which every count-derived field must agree
_COUNT_CONTRACTS = ("same", "observability", "identical", "perf")


def scrub(doc: dict) -> dict:
    """A report document minus its volatile identity header — consulted
    from the report schema (:data:`report.VOLATILE_KEYS`), never
    hand-listed here."""
    return {
        k: v for k, v in doc.items() if k not in _report.VOLATILE_KEYS
    }


def config_delta(a_cfg: Optional[dict], b_cfg: Optional[dict]) -> dict:
    """``{aspect: {a, b, class}}`` for every config aspect that differs
    between the two reports' ``config`` blocks."""
    a_cfg, b_cfg = a_cfg or {}, b_cfg or {}
    out: dict = {}
    fa = a_cfg.get("flags") or {}
    fb = b_cfg.get("flags") or {}
    for k in sorted(set(fa) | set(fb)):
        if bool(fa.get(k)) != bool(fb.get(k)):
            out[f"flags.{k}"] = {
                "a": fa.get(k), "b": fb.get(k),
                "class": FLAG_CLASS.get(k, "unknown"),
            }
    for k, cls in _TOP_CLASS.items():
        if a_cfg.get(k) != b_cfg.get(k):
            out[k] = {"a": a_cfg.get(k), "b": b_cfg.get(k), "class": cls}
    return out


def contract_of(delta: dict) -> str:
    """The pair's contract: the weakest promise among the differing
    aspects (``same`` when the configs agree entirely)."""
    if not delta:
        return "same"
    return max((d["class"] for d in delta.values()), key=_RANK.get)


def _pair(a, b) -> dict:
    out = {"a": a, "b": b, "match": a == b}
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) and \
            not isinstance(a, bool) and not isinstance(b, bool):
        out["delta"] = b - a
    return out


def _violation(rule: str, field: str, a, b, detail: str) -> dict:
    return {"rule": rule, "field": field, "a": a, "b": b, "detail": detail}


_CART_KEYS = ("depth_hist", "action_hist", "fresh_inserts",
              "duplicate_hits")


def _cartography_block(ca: Optional[dict], cb: Optional[dict]) -> dict:
    """Common-key cartography delta (engine-specific extras like shard
    loads are reported by presence only)."""
    out: dict = {"present": {"a": ca is not None, "b": cb is not None}}
    if ca is None or cb is None:
        return out
    for k in ("fresh_inserts", "duplicate_hits"):
        out[k] = _pair(ca.get(k), cb.get(k))
    for k in ("depth_hist", "action_hist"):
        ha, hb = ca.get(k) or [], cb.get(k) or []
        out[k] = {"match": ha == hb, "bins": _pair(len(ha), len(hb))}
        if ha != hb and len(ha) == len(hb):
            out[k]["delta"] = [y - x for x, y in zip(ha, hb)]
    out["match"] = all(
        out[k].get("match") for k in _CART_KEYS if k in out
    )
    return out


def _scalar_block(a: Optional[dict], b: Optional[dict], keys) -> dict:
    out: dict = {"present": {"a": a is not None, "b": b is not None}}
    if a is None or b is None:
        return out
    for k in keys:
        out[k] = _pair(a.get(k), b.get(k))
    out["match"] = a == b
    return out


def _lineage_of(a: dict, b: dict) -> Optional[dict]:
    rid = a.get("run_id")
    if rid and b.get("parent_run_id") == rid:
        return {"parent": rid, "resumed": b.get("run_id")}
    return None


def diff_reports(
    a: dict,
    b: dict,
    a_headline: Optional[dict] = None,
    b_headline: Optional[dict] = None,
) -> dict:
    """Diff two run-report documents (``write_report`` docs, archived
    registry entries, or bare ``build_report`` bodies).

    ``a_headline``/``b_headline`` optionally attach the registry index
    records' wall-clock headline (throughput, per-stage attribution) —
    never part of the deterministic report body, so it rides in as a
    separate ``perf`` block and gates nothing.

    Returns ``{v, verdict, contract, config_delta, lineage?, blocks,
    violations}`` — deterministic for fixed inputs, JSON-safe."""
    lineage = _lineage_of(a, b)
    a_s, b_s = scrub(a), scrub(b)
    known_cfg = bool(a_s.get("config")) and bool(b_s.get("config"))
    delta = config_delta(a_s.get("config"), b_s.get("config"))
    violations: list = []
    blocks: dict = {}

    if lineage is not None:
        # the same logical run continued: config deltas below the
        # isomorphic class (and the parent's target_states prefix) are
        # resume mechanics, not an A/B — but the MODEL must still match
        contract = "lineage"
        am = (a_s.get("config") or {}).get("model")
        bm = (b_s.get("config") or {}).get("model")
        if known_cfg and am != bm:
            violations.append(_violation(
                "incomparable", "model", am, bm,
                "resumed run reports a different model than its parent",
            ))
    elif not known_cfg:
        contract = "unknown"
    else:
        contract = contract_of(delta)
        for k, d in delta.items():
            if d["class"] == "incomparable":
                violations.append(_violation(
                    "incomparable", k, d["a"], d["b"],
                    f"reports describe different {k}s — no cross-run "
                    "contract applies",
                ))

    # -- per-block deltas (always computed; gating depends on contract) --
    ta, tb = a_s.get("totals") or {}, b_s.get("totals") or {}
    blocks["totals"] = {
        k: _pair(ta.get(k), tb.get(k))
        for k in ("states", "unique", "max_depth", "done")
    }
    pa = {p.get("name"): p for p in a_s.get("properties") or []}
    pb = {p.get("name"): p for p in b_s.get("properties") or []}
    props = []
    for name in sorted(set(pa) | set(pb)):
        ea, eb = pa.get(name), pb.get(name)
        props.append({
            "name": name,
            "expectation": (ea or eb or {}).get("expectation"),
            "a": None if ea is None else bool(ea.get("discovery")),
            "b": None if eb is None else bool(eb.get("discovery")),
            "match": (
                ea is not None and eb is not None
                and bool(ea.get("discovery")) == bool(eb.get("discovery"))
            ),
        })
    blocks["properties"] = props
    blocks["cartography"] = _cartography_block(
        a_s.get("cartography"), b_s.get("cartography")
    )
    blocks["memory"] = _scalar_block(
        a_s.get("memory"), b_s.get("memory"),
        ("total_bytes", "capacity"),
    )
    ra, rb = a_s.get("roofline"), b_s.get("roofline")
    blocks["roofline"] = _scalar_block(
        (ra or {}).get("totals") if ra else None,
        (rb or {}).get("totals") if rb else None,
        ("flops", "bytes"),
    )
    blocks["por"] = _scalar_block(
        a_s.get("por"), b_s.get("por"),
        ("enabled", "rows_reduced", "rows_full_proviso",
         "candidates_masked"),
    )
    blocks["spill"] = _scalar_block(
        a_s.get("spill"), b_s.get("spill"),
        ("evictions", "spilled_fps"),
    )
    ga, gb = a_s.get("growth_events"), b_s.get("growth_events")
    blocks["growth_events"] = {
        "present": {"a": ga is not None, "b": gb is not None},
        "count": _pair(
            len(ga) if ga is not None else None,
            len(gb) if gb is not None else None,
        ),
        "match": ga == gb,
    }
    ha = a_s.get("health_timeline")
    hb = b_s.get("health_timeline")
    blocks["health_timeline"] = {
        "present": {"a": ha is not None, "b": hb is not None},
        "phases": _pair(
            _phase_seq(ha) if ha is not None else None,
            _phase_seq(hb) if hb is not None else None,
        ),
        "match": ha == hb,
    }
    if a_headline or b_headline:
        ah, bh = a_headline or {}, b_headline or {}
        perf: dict = {
            k: _pair(ah.get(k), bh.get(k))
            for k in ("states_per_sec", "wall_secs")
        }
        sa, sb = ah.get("stages") or {}, bh.get("stages") or {}
        if sa or sb:
            perf["stages"] = {
                k: _pair(sa.get(k), sb.get(k))
                for k in sorted(set(sa) | set(sb))
            }
        blocks["perf"] = perf

    # -- contract gates ------------------------------------------------------
    if lineage is not None and not violations:
        # monotonicity: the resumed run continues the parent, so it must
        # carry at least the parent's totals and every parent discovery.
        # (A parent's `done: true` only means it STOPPED cleanly — a
        # stop()/target_states cut still reports done — so exact-totals
        # equality is checked by comparing the resumed run against a
        # fresh FULL run of the same config instead: contract `same`.)
        for k in ("states", "unique", "max_depth"):
            va, vb = ta.get(k), tb.get(k)
            if not isinstance(va, int) or not isinstance(vb, int):
                continue
            if vb < va:
                violations.append(_violation(
                    "resume_lost_work", f"totals.{k}", va, vb,
                    "the resumed run carries less than its parent's "
                    "snapshot — work was lost across kill+resume",
                ))
        lost = [
            p["name"] for p in props if p["a"] is True and p["b"] is not True
        ]
        for name in lost:
            violations.append(_violation(
                "resume_lost_discovery", f"properties.{name}", True, False,
                "a discovery recorded before the snapshot vanished in "
                "the resumed run (first-wins fps never change)",
            ))
    elif contract != "incomparable" and not violations:
        # verdict parity holds under EVERY comparable contract
        for p in props:
            if not p["match"]:
                violations.append(_violation(
                    "verdict_parity", f"properties.{p['name']}",
                    p["a"], p["b"],
                    "property verdicts must agree for every comparable "
                    "flag delta",
                ))
        if contract in _COUNT_CONTRACTS:
            # a cross-ENGINE pair gates unique + verdicts only: host
            # checkers count generated states differently and do not
            # track max_depth (the engine-parity pin is the unique
            # count + discoveries, exactly like bench's gates)
            engine_differs = "engine" in delta
            gated = ("unique", "done")
            if not engine_differs:
                gated = ("states", "unique", "max_depth", "done")
            for k in gated:
                if not blocks["totals"][k]["match"]:
                    violations.append(_violation(
                        "counts_must_match", f"totals.{k}",
                        ta.get(k), tb.get(k),
                        "this flag delta promises bit-identical counts",
                    ))
            cart = blocks["cartography"]
            # a sweep-instance side estimates its depth histogram with
            # an exact per-instance bincount, while the wavefront's live
            # histogram is the sorted-prefix approximation
            # (ops/cartography.queue_depth_hist) — two estimators of the
            # same quantity, equal only when append windows never
            # straddle BFS levels, so depth-profile parity is not gated
            # across a sweep pair (docs/sweep.md)
            sweep_pair = "sweep" in (
                (a_s.get("config") or {}).get("engine"),
                (b_s.get("config") or {}).get("engine"),
            )
            cart_drift = (
                cart.get("match") is False
                if not engine_differs
                # same narrowing across engines: the depth histogram and
                # fresh-insert count are unique-derived and comparable;
                # duplicate_hits/action_hist are generated-state-derived
                else (
                    (
                        not sweep_pair
                        and cart.get("depth_hist", {}).get("match")
                        is False
                    )
                    or cart.get("fresh_inserts", {}).get("match") is False
                )
            )
            if cart_drift:
                violations.append(_violation(
                    "counts_must_match", "cartography",
                    None, None,
                    "search-shape counters must agree when counts are "
                    "promised bit-identical",
                ))
        if contract in ("same", "observability"):
            # strongest form: every deterministic block present on BOTH
            # sides must agree verbatim (presence may differ — the
            # observability flags add/remove blocks, nothing else)
            for key in ("memory", "roofline", "por", "spill",
                        "growth_events", "audit", "sanitizer"):
                va, vb = a_s.get(key), b_s.get(key)
                if va is not None and vb is not None and va != vb:
                    violations.append(_violation(
                        "block_must_match", key, None, None,
                        f"the deterministic {key!r} block differs under "
                        "a same-config/observability-only delta",
                    ))
        if contract == "isomorphic":
            # a reduction may only shrink the explored space: when
            # exactly one side runs the reducing flag, it must not
            # explore MORE than the full-expansion side
            for flag in ("flags.por", "flags.symmetry"):
                d = delta.get(flag)
                if d is None:
                    continue
                red, full = (tb, ta) if d["b"] else (ta, tb)
                # generated-state counts are engine-specific (the totals
                # gate's rule): across an engine delta only the unique
                # count carries the reduction-direction promise
                grow_keys = (
                    ("unique",) if "engine" in delta
                    else ("states", "unique")
                )
                for k in grow_keys:
                    if (
                        isinstance(red.get(k), int)
                        and isinstance(full.get(k), int)
                        and red[k] > full[k]
                    ):
                        violations.append(_violation(
                            "reduction_grew", f"totals.{k}",
                            full[k], red[k],
                            f"the {flag.split('.')[1]} side explored MORE "
                            "than full expansion — a reduction can only "
                            "shrink the space",
                        ))
        # (contract "unknown" — pre-registry reports with no config
        # block — adds no gate beyond the verdict-parity loop above)

    counts_equal = all(
        blocks["totals"][k]["match"]
        for k in ("states", "unique", "max_depth")
    )
    if violations:
        verdict = DIVERGENT
    elif lineage is not None:
        verdict = IDENTICAL
    elif contract in ("same", "observability", "identical"):
        verdict = IDENTICAL
    elif contract == "perf":
        verdict = PERF_ONLY
    else:  # isomorphic / unknown
        verdict = IDENTICAL if counts_equal else ISOMORPHIC

    out = {
        "v": DIFF_V,
        "verdict": verdict,
        "contract": contract,
        "config_delta": delta,
        "blocks": blocks,
        "violations": violations,
    }
    if lineage is not None:
        out["lineage"] = lineage
    return out


def _phase_seq(timeline) -> list:
    """Deduplicated phase sequence of a health timeline (the rendering
    the report's markdown uses)."""
    out: list = []
    for e in timeline or []:
        if not out or out[-1] != e.get("phase"):
            out.append(e.get("phase"))
    return out


def render_diff(d: dict, label_a: str = "a", label_b: str = "b") -> str:
    """Human rendering of a :func:`diff_reports` result: verdict first,
    then the deltas a reader acts on."""
    lines = [f"verdict: {d['verdict']} (contract: {d['contract']})"]
    for k, dd in (d.get("config_delta") or {}).items():
        lines.append(
            f"  config {k}: {dd['a']!r} -> {dd['b']!r} [{dd['class']}]"
        )
    lin = d.get("lineage")
    if lin:
        lines.append(
            f"  lineage: {label_b} resumed from {label_a} "
            f"(parent run {lin['parent']})"
        )
    t = d["blocks"]["totals"]
    bits = []
    for k in ("states", "unique", "max_depth"):
        p = t[k]
        if p["match"]:
            bits.append(f"{k}={p['a']}")
        else:
            bits.append(f"{k} {p['a']} -> {p['b']} ({p.get('delta'):+d})"
                        if isinstance(p.get("delta"), int)
                        else f"{k} {p['a']} -> {p['b']}")
    lines.append("  totals: " + ", ".join(bits))
    for p in d["blocks"]["properties"]:
        mark = "parity" if p["match"] else "MISMATCH"
        lines.append(
            f"  property `{p['name']}` ({p['expectation']}): "
            f"a={p['a']} b={p['b']} — {mark}"
        )
    perf = d["blocks"].get("perf")
    if perf:
        sp = perf.get("states_per_sec") or {}
        if sp.get("a") is not None or sp.get("b") is not None:
            lines.append(
                f"  throughput: {sp.get('a')} -> {sp.get('b')} states/s"
            )
    if d["violations"]:
        lines.append(f"  violations ({len(d['violations'])}):")
        for v in d["violations"]:
            lines.append(
                f"    [{v['rule']}] {v['field']}: a={v['a']!r} "
                f"b={v['b']!r} — {v['detail']}"
            )
    else:
        lines.append("  violations: none")
    return "\n".join(lines)
