"""Atomic, crash-safe host persistence — the ONE write discipline.

Every durable artifact this package writes (run reports, registry
archives, the ``index.jsonl`` ledger, autosave snapshot generations,
spill-tier disk segments) goes through these helpers, so the crash
contract lives in one place:

 - **Replace writes** (:func:`atomic_write_bytes` and friends): the
   payload lands in a same-directory temp file, is fsynced, and then
   ``os.replace``s the target — a reader (or a resume after SIGKILL)
   sees either the complete old file or the complete new file, never a
   torn one.  The containing directory is fsynced afterwards so the
   rename itself is durable, not just the data.
 - **Ledger appends** (:func:`durable_append_line`): append-only files
   cannot be replaced wholesale without losing concurrent history, so
   appends write the full line then flush+fsync the fd.  A crash can
   still tear the LAST line — which is why every ledger reader in this
   package (``registry.RunRegistry.index``) skips unparseable tail
   lines instead of failing: prior records are never lost.

Failure injection: the chaos suite (``stateright_tpu/testing/faults.py``)
arms the ``atomic_write`` seam here, so every durable write in the
package is fault-testable through one hook.
"""

from __future__ import annotations

import io
import json
import os
import tempfile


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so a completed
    ``os.replace`` survives power loss; best-effort on filesystems
    without directory fds."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically: same-dir temp file, fsync,
    ``os.replace``.  Raises ``OSError`` on failure with the target
    untouched (old contents, if any, stay intact)."""
    from ..testing import faults

    faults.fire("atomic_write", path=str(path))
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=d
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path)


def atomic_write_stream(path: str, chunks) -> None:
    """Atomic write of an iterable of byte chunks — the large-payload
    form (spill disk segments): same tmp+fsync+replace+dir-fsync
    discipline as :func:`atomic_write_bytes` without materializing one
    contiguous buffer."""
    from ..testing import faults

    faults.fire("atomic_write", path=str(path))
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=d
    )
    try:
        with os.fdopen(fd, "wb") as f:
            for chunk in chunks:
                f.write(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_json(path: str, obj, indent: int = 1) -> None:
    """The package's JSON artifact write (reports, registry archives,
    autosave manifests): ``json.dump`` shape preserved (insertion order,
    trailing newline) but landed atomically."""
    atomic_write_text(path, json.dumps(obj, indent=indent) + "\n")


def atomic_write_npz(path: str, arrays: dict) -> None:
    """Atomic ``np.savez`` — the snapshot-generation write.  The npz is
    assembled in memory first (snapshots are carry-sized, far below host
    RAM by construction) so the on-disk file is all-or-nothing."""
    import numpy as np

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(path, buf.getvalue())


def durable_append_line(path: str, line: str) -> None:
    """Append one newline-terminated record to an append-only ledger,
    flushed + fsynced before returning.  Atomicity here is per-LINE
    best-effort (POSIX appends of small writes), and the crash contract
    is completed by the readers: a torn tail line is skipped, prior
    records survive."""
    from ..testing import faults

    faults.fire("atomic_write", path=str(path))
    if not line.endswith("\n"):
        line += "\n"
    # heal a torn tail first: a writer killed mid-append can leave the
    # ledger without its final newline — appending straight on would
    # glue THIS record onto the torn fragment and lose both (readers
    # skip unparseable lines; a leading newline isolates the damage)
    needs_nl = False
    try:
        with open(path, "rb") as rf:
            rf.seek(-1, os.SEEK_END)
            needs_nl = rf.read(1) != b"\n"
    except OSError:
        pass  # absent or empty file: nothing to heal
    with open(path, "a") as f:
        if needs_nl:
            f.write("\n")
        f.write(line)
        f.flush()
        os.fsync(f.fileno())
