"""Typed metrics bus: counters / gauges / histograms with labeled
families, rendered in Prometheus text exposition for ``GET /metrics``.

The bus is the LIVE aggregation layer over telemetry the engines already
collect at host syncs: :meth:`FlightRecorder.step` publishes the engine
families (states/s, frontier size, table load, dedup rate), the
occupancy/spill/mesh hooks publish theirs, and the fleet scheduler
publishes pool families (queue depth, slot utilization, preemptions,
admission outcomes).  Samples are taken ONLY at host syncs that already
happen — zero extra device round-trips, and with the bus detached
(the default) the recorder adds nothing (parity pinned by test).

Design rules:

 - **Families are typed and registered once.**  ``counter()`` /
   ``gauge()`` / ``histogram()`` return the existing family on
   re-registration with the same type and raise on a type conflict —
   a family cannot silently change meaning mid-run.
 - **Counters are monotone.**  ``inc()`` rejects negative deltas;
   sources with cumulative totals publish their per-step deltas.
 - **Label cardinality is bounded.**  Each family caps its distinct
   label-sets (``max_series``, default 64); crossing the cap raises —
   an unbounded label (a raw run id, a state fingerprint) is a bug in
   the publisher, not a bigger dashboard.
 - **Thread-safe.**  Engines publish from run threads while the HTTP
   handler scrapes; every mutation and render takes the bus lock.

``default_bus()`` is the process-wide registry the Explorer's
``GET /metrics`` serves; ``STATERIGHT_TPU_METRICS=1`` (or
``.telemetry(metrics=True)``) attaches it to a run's recorder.
"""

from __future__ import annotations

import math
import threading
from typing import Optional

METRICS_V = 1

# default per-family distinct label-set cap (the cardinality guard)
MAX_SERIES = 64

# default histogram buckets: seconds-shaped (host-sync blocks run
# milliseconds to minutes)
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
)

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers bare, floats as-is."""
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f.is_integer():
        return str(int(f))
    return repr(f)


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Family:
    """One named family; per-label-set series live under it."""

    kind = "untyped"

    def __init__(self, bus: "MetricsBus", name: str, help: str,
                 labelnames: tuple):
        self.bus = bus
        self.name = _check_name(name)
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        self._series: dict = {}

    def labels(self, **kv):
        """The series for one label-set (created on first use; the
        cardinality guard trips when a family crosses the bus's
        ``max_series`` distinct label-sets)."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self.bus._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.bus.max_series:
                    raise ValueError(
                        f"metric family {self.name!r} crossed the "
                        f"label-cardinality cap ({self.bus.max_series} "
                        "series): an unbounded label value is a "
                        "publisher bug, not a bigger dashboard"
                    )
                s = self._make_series()
                self._series[key] = s
            return s

    def _make_series(self):
        raise NotImplementedError

    def _render(self, lines: list) -> None:
        lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for key, s in sorted(self._series.items()):
            labels = dict(zip(self.labelnames, key))
            s._render(self.name, labels, lines)


class _CounterSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter decrement ({n}): counters are "
                             "monotone; publish a gauge instead")
        self.value += n

    def _render(self, name, labels, lines) -> None:
        lines.append(f"{name}{_label_str(labels)} {_fmt(self.value)}")


class Counter(_Family):
    kind = "counter"

    def _make_series(self):
        return _CounterSeries()

    def inc(self, n: float = 1, **labels) -> None:
        self.labels(**labels).inc(n)


class _GaugeSeries:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def _render(self, name, labels, lines) -> None:
        lines.append(f"{name}{_label_str(labels)} {_fmt(self.value)}")


class Gauge(_Family):
    kind = "gauge"

    def _make_series(self):
        return _GaugeSeries()

    def set(self, v: float, **labels) -> None:
        self.labels(**labels).set(v)


class _HistogramSeries:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.count += 1
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.counts[i] += 1

    def _render(self, name, labels, lines) -> None:
        cum = 0
        for le, c in zip(self.buckets, self.counts):
            cum += c  # counts are per-bucket internally; exposition is
            # cumulative, as the format requires
            lines.append(
                f"{name}_bucket{_label_str({**labels, 'le': _fmt(le)})} "
                f"{cum}"
            )
        lines.append(
            f"{name}_bucket{_label_str({**labels, 'le': '+Inf'})} "
            f"{self.count}"
        )
        lines.append(f"{name}_sum{_label_str(labels)} {_fmt(self.sum)}")
        lines.append(f"{name}_count{_label_str(labels)} {self.count}")


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, bus, name, help, labelnames,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(bus, name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")

    def _make_series(self):
        return _HistogramSeries(self.buckets)

    def observe(self, v: float, **labels) -> None:
        self.labels(**labels).observe(v)


class MetricsBus:
    """The typed family registry + Prometheus renderer."""

    def __init__(self, max_series: int = MAX_SERIES):
        self.max_series = int(max_series)
        self._lock = threading.RLock()
        self._families: dict = {}

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls:
                    raise ValueError(
                        f"metric family {name!r} already registered as "
                        f"{fam.kind}, not {cls.kind}"
                    )
                return fam
            fam = cls(self, name, help, tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def families(self) -> list:
        with self._lock:
            return sorted(self._families)

    def expose(self) -> str:
        """The whole bus in Prometheus text exposition format (the body
        of ``GET /metrics``)."""
        lines: list = []
        with self._lock:
            for name in sorted(self._families):
                self._families[name]._render(lines)
        return "\n".join(lines) + ("\n" if lines else "")


# -- the process-wide bus (what GET /metrics scrapes) ------------------------

_DEFAULT: Optional[MetricsBus] = None
_DEFAULT_LOCK = threading.Lock()


def default_bus() -> MetricsBus:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsBus()
        return _DEFAULT


def reset_default_bus() -> None:
    """Testing hook: drop the process bus so family values start clean."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


# -- the standard family catalogue (docs/observability.md) -------------------
# Publishers resolve families through these helpers so every engine and
# the fleet agree on names/labels; the catalogue is pinned by tests and
# the CI /metrics smoke.

ENGINE_LABELS = ("engine", "model")


def engine_families(bus: MetricsBus) -> dict:
    return {
        "states": bus.counter(
            "stateright_states_total",
            "cumulative states generated (per-step deltas)",
            ENGINE_LABELS,
        ),
        "unique": bus.counter(
            "stateright_unique_states_total",
            "cumulative unique states inserted",
            ENGINE_LABELS,
        ),
        "sps": bus.gauge(
            "stateright_states_per_sec",
            "per-sync-step throughput",
            ENGINE_LABELS,
        ),
        "frontier": bus.gauge(
            "stateright_frontier_size",
            "queue/frontier depth at the last host sync",
            ENGINE_LABELS,
        ),
        "load": bus.gauge(
            "stateright_table_load",
            "visited-table load factor",
            ENGINE_LABELS,
        ),
        "dedup": bus.gauge(
            "stateright_dedup_ratio",
            "fraction of generated states already visited",
            ENGINE_LABELS,
        ),
        "step": bus.histogram(
            "stateright_step_seconds",
            "host-sync step-block wall time",
            ENGINE_LABELS,
        ),
        "occupancy": bus.gauge(
            "stateright_table_occupancy",
            "bucket-table occupancy (occupancy_stats load factor)",
            ENGINE_LABELS,
        ),
        "spilled": bus.gauge(
            "stateright_spilled_fps",
            "fingerprints resident in the spill tier",
            ENGINE_LABELS,
        ),
        "imbalance": bus.gauge(
            "stateright_shard_imbalance",
            "mesh per-shard load imbalance (max/mean)",
            ENGINE_LABELS,
        ),
    }


def fleet_families(bus: MetricsBus) -> dict:
    return {
        "queue": bus.gauge(
            "stateright_fleet_queue_depth", "jobs waiting for a slot"
        ),
        "slots": bus.gauge(
            "stateright_fleet_slots", "configured pool slots"
        ),
        "busy": bus.gauge(
            "stateright_fleet_slots_busy", "slots running a job now"
        ),
        "completed": bus.counter(
            "stateright_fleet_jobs_completed_total", "jobs completed"
        ),
        "preemptions": bus.counter(
            "stateright_fleet_preemptions_total", "cooperative preemptions"
        ),
        "admissions": bus.counter(
            "stateright_fleet_admissions_total",
            "admission outcomes by decision",
            ("decision",),
        ),
    }
