"""Roofline view: the static cost ledger against the device's ceilings.

``analysis/costmodel.py`` produces the per-stage FLOPs/bytes ledger;
this module combines it with

 - a small **device-spec table** (peak scalar-op throughput + HBM
   bandwidth per known backend, overridable/simulatable with
   ``STATERIGHT_TPU_DEVICE_SPEC=PEAK_FLOPS:HBM_BYTES_PER_SEC``) to
   classify each pipeline stage **memory-bound vs compute-bound**: a
   stage whose arithmetic intensity (FLOPs per byte moved) sits below
   the ridge point ``peak_flops / hbm_bw`` cannot be compute-limited —
   more FLOPs per byte (the MXU recasts the JX4xx findings name) is the
   only way up;
 - the PR-4 **stage wall-clock attribution**
   (``FlightRecorder.stages()``) to estimate achieved bytes/s and
   FLOPs/s against those ceilings — the "achieved-vs-ceiling fraction"
   that answers VERDICT/ADVICE item 3's "bytes-moved roofline estimate
   per state, or a written proof the current rate is memory-bound".

On CPU (or any backend without a known spec) everything degrades to
arithmetic-intensity-only: intensities and verdict-free stage tables,
never a crash — pinned by test, the ``telemetry/memory.py``
degradation discipline.

Contract (the family's strongest form, pinned): the ledger is pure
host-side analysis over RE-TRACED kernels — roofline on or off leaves
the engine's step jaxpr bit-identical and the engine cache unkeyed.
Enabled via ``.telemetry(roofline=True)``; surfaces as
``checker.roofline()``, the run report's deterministic ``roofline``
block (static costs only — wall-clock ceilings render in the markdown
section), the Explorer's ``/.metrics`` + stage-roofline panel, the
``costmodel`` CLI verb, bench's ``tpu_*_roofline`` keys, and the
``regress.py --roofline`` gate.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

# roofline ring-record / block schema version
ROOFLINE_V = 1

ENV_DEVICE_SPEC = "STATERIGHT_TPU_DEVICE_SPEC"

# peak dense-compute FLOPs (bf16 MXU — the ceiling the JX4xx recasts
# chase) + HBM bytes/s per device kind, matched by substring against
# jax's device_kind (lowercased).  Public datasheet numbers; the env
# override wins for anything unlisted or for what-if planning.
DEVICE_SPECS = (
    ("v6 lite", "tpu-v6e", 918e12, 1640e9),
    ("v6e", "tpu-v6e", 918e12, 1640e9),
    ("v5 lite", "tpu-v5e", 197e12, 819e9),
    ("v5e", "tpu-v5e", 197e12, 819e9),
    ("v5p", "tpu-v5p", 459e12, 2765e9),
    ("v5", "tpu-v5e", 197e12, 819e9),
    ("v4", "tpu-v4", 275e12, 1228e9),
    ("v3", "tpu-v3", 123e12, 900e9),
    ("v2", "tpu-v2", 45e12, 700e9),
)


def device_spec(device=None) -> Optional[dict]:
    """``{name, peak_flops, hbm_bytes_per_sec, ridge, src}`` for the
    first JAX device (or ``device``), the env override winning; None
    when nothing is known (CPU) — consumers degrade to
    arithmetic-intensity-only, never crash."""
    env = os.environ.get(ENV_DEVICE_SPEC, "").strip()
    if env:
        parts = env.split(":")
        try:
            peak, bw = float(parts[0]), float(parts[1])
            if peak > 0 and bw > 0:
                return {
                    "name": parts[2] if len(parts) > 2 else "env-override",
                    "peak_flops": peak,
                    "hbm_bytes_per_sec": bw,
                    "ridge": peak / bw,
                    "src": "env",
                }
        except (IndexError, ValueError):
            pass
        print(
            "stateright-tpu: roofline: ignoring malformed "
            f"{ENV_DEVICE_SPEC}={env!r} (want PEAK_FLOPS:HBM_BYTES_PER_SEC"
            "[:NAME], e.g. 1.97e14:8.19e11:tpu-v5e)",
            file=sys.stderr,
        )
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        platform = str(getattr(dev, "platform", "")).lower()
        kind = str(getattr(dev, "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 - no backend: no spec
        return None
    if platform != "tpu":
        return None
    for needle, name, peak, bw in DEVICE_SPECS:
        if needle in kind:
            return {
                "name": name,
                "peak_flops": peak,
                "hbm_bytes_per_sec": bw,
                "ridge": peak / bw,
                "src": "device",
            }
    return None


def classify_stages(static: dict, spec: Optional[dict]) -> dict:
    """Per-stage roofline verdict from the static block's intensities:
    ``memory-bound`` below the ridge point, ``compute-bound`` above,
    ``unknown`` without a spec (CPU degradation) or without bytes."""
    out = {}
    ridge = spec["ridge"] if spec else None
    for name, s in (static.get("stages") or {}).items():
        ai = s.get("intensity")
        if ai is None:
            verdict = "unknown"
        elif ridge is None:
            verdict = "unknown"
        else:
            verdict = "memory-bound" if ai < ridge else "compute-bound"
        entry = {"intensity": ai, "verdict": verdict}
        if ridge is not None:
            entry["ridge"] = round(ridge, 3)
        out[name] = entry
    return out


def achieved_block(
    static: dict, spec: Optional[dict], stages_secs: Optional[dict],
    unique: int, batch: int,
) -> Optional[dict]:
    """Achieved-vs-ceiling estimate from the PR-4 wall-clock attribution:
    per-step analytic bytes/FLOPs x the estimated device-step count
    over the attributed device seconds.  The static costs price ONE
    device's kernels per lockstep step, so the whole block is the
    PER-CHIP view: a sharded run pops ``batch x devices`` rows per
    lockstep step (``devices`` from the static block; 1 on the
    wavefront engine), and the resulting per-chip bytes/s compares
    against one chip's HBM ceiling.  An estimate by construction
    (growth replays and property-hit early exits shift it a few
    percent), which is why it lives in the live/markdown surfaces,
    never the deterministic report body."""
    if not stages_secs:
        return None
    dev_secs = stages_secs.get("device_secs")
    if not dev_secs or dev_secs <= 0 or batch <= 0 or unique <= 0:
        return None
    rows_per_step = int(batch) * max(int(static.get("devices", 1) or 1), 1)
    steps = max((int(unique) + rows_per_step - 1) // rows_per_step, 1)
    totals = static.get("totals") or {}
    bts, fls = totals.get("bytes"), totals.get("flops")
    if not bts:
        return None
    out = {
        "device_secs": dev_secs,
        "est_device_steps": steps,
        "bytes_per_sec": round(bts * steps / dev_secs, 1),
        "flops_per_sec": round((fls or 0) * steps / dev_secs, 1),
    }
    if spec:
        out["frac_of_hbm_ceiling"] = round(
            out["bytes_per_sec"] / spec["hbm_bytes_per_sec"], 6
        )
        out["frac_of_flops_ceiling"] = round(
            out["flops_per_sec"] / spec["peak_flops"], 6
        )
    return out


class RooflineLedger:
    """Host-side roofline accounting for one engine run.

    ``cost_fn() -> CostReport | None`` is the engine's analytic model
    (``costmodel.wavefront_costs`` / ``sharded_costs`` at the run's
    capacities, cached on the twin).  Built once at spawn — re-tracing
    the pipeline kernels plus one small XLA compile per stage for the
    reconciliation — and pushed into the flight recorder as the
    versioned ``roofline`` ring record + live snapshot.  Zero device
    ops, zero engine-program impact (pinned)."""

    def __init__(self, engine: str, cost_fn, recorder=None) -> None:
        self.engine = engine
        self.recorder = recorder
        self._report = None
        self._static: Optional[dict] = None
        self._recon: Optional[dict] = None
        self._spec = device_spec()
        try:
            self._report = cost_fn()
        except Exception:  # noqa: BLE001 - accounting must never break
            self._report = None  # a run (the memory-ledger discipline)
        if self._report is not None:
            self._static = self._report.static_block()
            self._recon = self._report.recon_block()
            if recorder is not None:
                recorder.set_roofline(self.snapshot())
                recorder.record(
                    "roofline", v=ROOFLINE_V, at="init",
                    engine=self._static["engine"],
                    stages={
                        k: {
                            "flops": v["flops"],
                            "bytes": v["bytes_read"] + v["bytes_written"],
                        }
                        for k, v in self._static["stages"].items()
                    },
                    totals=dict(self._static["totals"]),
                    reconciled=bool(self._recon["ok"]),
                )

    @property
    def ok(self) -> bool:
        return self._static is not None

    def findings(self) -> list:
        """The JX4xx MXU-candidate findings (audit-report machinery)."""
        return list(self._report.findings) if self._report else []

    def static_block(self) -> Optional[dict]:
        """The DETERMINISTIC block for the run report: the analytic walk
        only — no XLA numbers, no device spec, no wall clock."""
        return dict(self._static) if self._static else None

    def snapshot(self) -> Optional[dict]:
        """The live block (Explorer/bench/watch): static + the
        reconciliation verdict + the resolved device spec + per-stage
        verdicts."""
        if self._static is None:
            return None
        out = dict(self._static)
        out["reconciliation"] = (
            dict(self._recon) if self._recon else None
        )
        if self._spec:
            out["device_spec"] = dict(self._spec)
        out["verdicts"] = classify_stages(self._static, self._spec)
        return out

    def live_block(self, stages_secs: Optional[dict], unique: int,
                   batch: Optional[int] = None) -> Optional[dict]:
        """snapshot() + the achieved-vs-ceiling estimate once wall-clock
        attribution exists (``checker.roofline()``'s default view).
        ``batch`` defaults to the static block's own (the engine's
        expansion width — the sharded engine's per-device frontier)."""
        snap = self.snapshot()
        if snap is None:
            return None
        if not batch:
            batch = int(self._static.get("batch", 0) or 0)
        ach = achieved_block(
            self._static, self._spec, stages_secs, unique, batch
        )
        if ach is not None:
            snap["achieved"] = ach
        return snap
