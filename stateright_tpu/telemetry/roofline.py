"""Roofline view: the static cost ledger against the device's ceilings.

``analysis/costmodel.py`` produces the per-stage FLOPs/bytes ledger;
this module combines it with

 - a small **device-spec table** (peak scalar-op throughput + HBM
   bandwidth per known backend, overridable/simulatable with
   ``STATERIGHT_TPU_DEVICE_SPEC=PEAK_FLOPS:HBM_BYTES_PER_SEC``) to
   classify each pipeline stage **memory-bound vs compute-bound**: a
   stage whose arithmetic intensity (FLOPs per byte moved) sits below
   the ridge point ``peak_flops / hbm_bw`` cannot be compute-limited —
   more FLOPs per byte (the MXU recasts the JX4xx findings name) is the
   only way up;
 - the PR-4 **stage wall-clock attribution**
   (``FlightRecorder.stages()``) to estimate achieved bytes/s and
   FLOPs/s against those ceilings — the "achieved-vs-ceiling fraction"
   that answers VERDICT/ADVICE item 3's "bytes-moved roofline estimate
   per state, or a written proof the current rate is memory-bound".

On CPU (or any backend without a known spec) everything degrades to
arithmetic-intensity-only: intensities and verdict-free stage tables,
never a crash — pinned by test, the ``telemetry/memory.py``
degradation discipline.

Contract (the family's strongest form, pinned): the ledger is pure
host-side analysis over RE-TRACED kernels — roofline on or off leaves
the engine's step jaxpr bit-identical and the engine cache unkeyed.
Enabled via ``.telemetry(roofline=True)``; surfaces as
``checker.roofline()``, the run report's deterministic ``roofline``
block (static costs only — wall-clock ceilings render in the markdown
section), the Explorer's ``/.metrics`` + stage-roofline panel, the
``costmodel`` CLI verb, bench's ``tpu_*_roofline`` keys, and the
``regress.py --roofline`` gate.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

# roofline ring-record / block schema version
ROOFLINE_V = 1

ENV_DEVICE_SPEC = "STATERIGHT_TPU_DEVICE_SPEC"

# peak dense-compute FLOPs per device kind — TWO ceilings, because a
# stage is only entitled to the one its op mix can actually reach: the
# bf16 MXU peak (what the JX4xx dot recasts chase) and the scalar/VPU
# peak (what gather/scatter/elementwise pipelines top out at; a
# recast-free stage judged against the MXU ridge would look absurdly
# memory-bound, and a dot-recast stage judged against the VPU ridge
# would claim compute-bound with the MXU still idle — the two-peak
# split exists to stop both wrong verdicts).  MXU + HBM numbers are
# public datasheets; VPU peaks are order-of-magnitude estimates
# (vector lanes x clock), good enough for a ridge-side verdict.  The
# env override wins for anything unlisted or for what-if planning.
#
# (needle, name, mxu_peak_flops, vpu_peak_flops, hbm_bytes_per_sec)
DEVICE_SPECS = (
    ("v6 lite", "tpu-v6e", 918e12, 9.2e12, 1640e9),
    ("v6e", "tpu-v6e", 918e12, 9.2e12, 1640e9),
    ("v5 lite", "tpu-v5e", 197e12, 3.2e12, 819e9),
    ("v5e", "tpu-v5e", 197e12, 3.2e12, 819e9),
    ("v5p", "tpu-v5p", 459e12, 9e12, 2765e9),
    ("v5", "tpu-v5e", 197e12, 3.2e12, 819e9),
    ("v4", "tpu-v4", 275e12, 4.3e12, 1228e9),
    ("v3", "tpu-v3", 123e12, 4e12, 900e9),
    ("v2", "tpu-v2", 45e12, 3e12, 700e9),
)

# a stage "is" dot-class when dot ops carry at least half its FLOPs:
# then the MXU ridge is the honest ceiling, else the VPU's
DOT_DOMINANCE = 0.5


def _spec_dict(name: str, mxu_peak: float, vpu_peak: float, bw: float,
               src: str) -> dict:
    """Normalized spec: both peaks, both ridges.  ``peak_flops``/
    ``ridge`` keep the pre-split meaning (the MXU ceiling) so stored
    artifacts and older consumers read unchanged."""
    return {
        "name": name,
        "peak_flops": mxu_peak,  # back-compat alias of mxu_peak
        "mxu_peak": mxu_peak,
        "vpu_peak": vpu_peak,
        "hbm_bytes_per_sec": bw,
        "ridge": mxu_peak / bw,  # back-compat alias of mxu_ridge
        "mxu_ridge": mxu_peak / bw,
        "vpu_ridge": vpu_peak / bw,
        "src": src,
    }


def device_spec(device=None) -> Optional[dict]:
    """``{name, mxu_peak, vpu_peak, hbm_bytes_per_sec, mxu_ridge,
    vpu_ridge, src}`` (plus the pre-split ``peak_flops``/``ridge``
    aliases of the MXU pair) for the first JAX device (or ``device``),
    the env override winning; None when nothing is known (CPU) —
    consumers degrade to arithmetic-intensity-only, never crash."""
    env = os.environ.get(ENV_DEVICE_SPEC, "").strip()
    if env:
        parts = env.split(":")
        try:
            peak, bw = float(parts[0]), float(parts[1])
            vpu = float(parts[3]) if len(parts) > 3 else peak / 64.0
            if peak > 0 and bw > 0 and vpu > 0:
                return _spec_dict(
                    parts[2] if len(parts) > 2 and parts[2]
                    else "env-override",
                    peak, vpu, bw, "env",
                )
        except (IndexError, ValueError):
            pass
        print(
            "stateright-tpu: roofline: ignoring malformed "
            f"{ENV_DEVICE_SPEC}={env!r} (want PEAK_FLOPS:HBM_BYTES_PER_SEC"
            "[:NAME[:VPU_PEAK_FLOPS]], e.g. 1.97e14:8.19e11:tpu-v5e:"
            "3.2e12; VPU peak defaults to PEAK/64)",
            file=sys.stderr,
        )
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        platform = str(getattr(dev, "platform", "")).lower()
        kind = str(getattr(dev, "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 - no backend: no spec
        return None
    if platform != "tpu":
        return None
    for needle, name, peak, vpu, bw in DEVICE_SPECS:
        if needle in kind:
            return _spec_dict(name, peak, vpu, bw, "device")
    return None


def stage_dot_dominated(stage: dict) -> bool:
    """Does the stage's op mix earn the MXU ridge?  True when dot-class
    ops carry at least :data:`DOT_DOMINANCE` of its FLOPs (from the
    static block's per-class split) — the recast stages the JX4xx round
    produces.  A stage with no FLOPs at all is never dot-dominated."""
    classes = stage.get("classes") or {}
    dot = (classes.get("dot") or {}).get("flops") or 0
    total = stage.get("flops") or 0
    return total > 0 and dot / total >= DOT_DOMINANCE


def classify_stages(static: dict, spec: Optional[dict]) -> dict:
    """Per-stage roofline verdict from the static block's intensities:
    ``memory-bound`` below the stage's ridge point, ``compute-bound``
    above, ``unknown`` without a spec (CPU degradation) or without
    bytes.  Each stage is judged against the ridge its op mix can
    actually reach: the MXU ridge when dot-class ops dominate its FLOPs
    (the ``--mxu`` recasts), else the VPU ridge — one shared peak would
    hand a recast stage the wrong verdict (pinned with a synthetic
    dot-heavy stage in tests)."""
    out = {}
    for name, s in (static.get("stages") or {}).items():
        ai = s.get("intensity")
        dot = stage_dot_dominated(s)
        ridge = None
        if spec:
            ridge = (
                spec.get("mxu_ridge", spec.get("ridge"))
                if dot
                else spec.get("vpu_ridge", spec.get("ridge"))
            )
        if ai is None or ridge is None:
            verdict = "unknown"
        else:
            verdict = "memory-bound" if ai < ridge else "compute-bound"
        entry = {"intensity": ai, "verdict": verdict}
        if ridge is not None:
            entry["ridge"] = round(ridge, 3)
            entry["ridge_kind"] = "mxu" if dot else "vpu"
        out[name] = entry
    return out


def achieved_block(
    static: dict, spec: Optional[dict], stages_secs: Optional[dict],
    unique: int, batch: int,
) -> Optional[dict]:
    """Achieved-vs-ceiling estimate from the PR-4 wall-clock attribution:
    per-step analytic bytes/FLOPs x the estimated device-step count
    over the attributed device seconds.  The static costs price ONE
    device's kernels per lockstep step, so the whole block is the
    PER-CHIP view: a sharded run pops ``batch x devices`` rows per
    lockstep step (``devices`` from the static block; 1 on the
    wavefront engine), and the resulting per-chip bytes/s compares
    against one chip's HBM ceiling.  An estimate by construction
    (growth replays and property-hit early exits shift it a few
    percent), which is why it lives in the live/markdown surfaces,
    never the deterministic report body."""
    if not stages_secs:
        return None
    dev_secs = stages_secs.get("device_secs")
    if not dev_secs or dev_secs <= 0 or batch <= 0 or unique <= 0:
        return None
    rows_per_step = int(batch) * max(int(static.get("devices", 1) or 1), 1)
    steps = max((int(unique) + rows_per_step - 1) // rows_per_step, 1)
    totals = static.get("totals") or {}
    bts, fls = totals.get("bytes"), totals.get("flops")
    if not bts:
        return None
    out = {
        "device_secs": dev_secs,
        "est_device_steps": steps,
        "bytes_per_sec": round(bts * steps / dev_secs, 1),
        "flops_per_sec": round((fls or 0) * steps / dev_secs, 1),
    }
    if spec:
        out["frac_of_hbm_ceiling"] = round(
            out["bytes_per_sec"] / spec["hbm_bytes_per_sec"], 6
        )
        out["frac_of_flops_ceiling"] = round(
            out["flops_per_sec"] / spec["peak_flops"], 6
        )
    return out


class RooflineLedger:
    """Host-side roofline accounting for one engine run.

    ``cost_fn() -> CostReport | None`` is the engine's analytic model
    (``costmodel.wavefront_costs`` / ``sharded_costs`` at the run's
    capacities, cached on the twin).  Built once at spawn — re-tracing
    the pipeline kernels plus one small XLA compile per stage for the
    reconciliation — and pushed into the flight recorder as the
    versioned ``roofline`` ring record + live snapshot.  Zero device
    ops, zero engine-program impact (pinned)."""

    def __init__(self, engine: str, cost_fn, recorder=None) -> None:
        self.engine = engine
        self.recorder = recorder
        self._report = None
        self._static: Optional[dict] = None
        self._recon: Optional[dict] = None
        self._spec = device_spec()
        try:
            self._report = cost_fn()
        except Exception:  # noqa: BLE001 - accounting must never break
            self._report = None  # a run (the memory-ledger discipline)
        if self._report is not None:
            self._static = self._report.static_block()
            self._recon = self._report.recon_block()
            if recorder is not None:
                recorder.set_roofline(self.snapshot())
                recorder.record(
                    "roofline", v=ROOFLINE_V, at="init",
                    engine=self._static["engine"],
                    stages={
                        k: {
                            "flops": v["flops"],
                            "bytes": v["bytes_read"] + v["bytes_written"],
                        }
                        for k, v in self._static["stages"].items()
                    },
                    totals=dict(self._static["totals"]),
                    reconciled=bool(self._recon["ok"]),
                )

    @property
    def ok(self) -> bool:
        return self._static is not None

    def findings(self) -> list:
        """The JX4xx MXU-candidate findings (audit-report machinery)."""
        return list(self._report.findings) if self._report else []

    def static_block(self) -> Optional[dict]:
        """The DETERMINISTIC block for the run report: the analytic walk
        only — no XLA numbers, no device spec, no wall clock."""
        return dict(self._static) if self._static else None

    def snapshot(self) -> Optional[dict]:
        """The live block (Explorer/bench/watch): static + the
        reconciliation verdict + the resolved device spec + per-stage
        verdicts."""
        if self._static is None:
            return None
        out = dict(self._static)
        out["reconciliation"] = (
            dict(self._recon) if self._recon else None
        )
        if self._spec:
            out["device_spec"] = dict(self._spec)
        out["verdicts"] = classify_stages(self._static, self._spec)
        return out

    def live_block(self, stages_secs: Optional[dict], unique: int,
                   batch: Optional[int] = None) -> Optional[dict]:
        """snapshot() + the achieved-vs-ceiling estimate once wall-clock
        attribution exists (``checker.roofline()``'s default view).
        ``batch`` defaults to the static block's own (the engine's
        expansion width — the sharded engine's per-device frontier)."""
        snap = self.snapshot()
        if snap is None:
            return None
        if not batch:
            batch = int(self._static.get("batch", 0) or 0)
        ach = achieved_block(
            self._static, self._spec, stages_secs, unique, batch
        )
        if ach is not None:
            snap["achieved"] = ach
        return snap
