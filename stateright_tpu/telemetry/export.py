"""Flight-recorder export: JSONL (lossless round-trip) and Chrome trace.

JSONL layout: line 1 is a header object ``{"kind": "header", "meta": {...},
"summary": {...}, "capacity": N}``; every following line is one record in
ring order.  ``from_jsonl`` rebuilds a recorder whose ring, meta and
derived summary match the exported one (aggregate counters are restored
from the header's summary scalars), pinned by the round-trip test.

Chrome-trace layout (`chrome://tracing` / Perfetto "JSON object format"):
``step`` records become complete events (``ph: "X"``) whose duration is the
step's ``dt``; point events (growth, occupancy, compile) become instant
events (``ph: "i"``); aggregate counters ride a final metadata event.
Resource pressure rides COUNTER tracks (``ph: "C"`` — the viewer plots
them as stacked series over the timeline): ``throughput``
(states_per_sec + load_factor, per step), ``pressure`` (queue depth +
table load, per step), and ``hbm_bytes`` (the memory ledger's analytic
bytes + live ``bytes_in_use``, one point per ``memory`` record) — so a
growth transient or a queue ramp is visible in the same view as the
steps that caused it.  Timestamps are microseconds, as the format
requires.
"""

from __future__ import annotations

import json

from .recorder import FlightRecorder


# JSONL export schema version: the golden-schema test
# (tests/test_telemetry_schema.py) pins field names/types per record kind
# against this number — bump it when the schema changes shape.
SCHEMA_V = 1


def to_jsonl(rec: FlightRecorder, path, append: bool = False) -> None:
    header = {
        "kind": "header",
        "v": SCHEMA_V,
        "meta": rec.meta_snapshot(),
        "capacity": rec.capacity,
        "summary": rec.summary(),
        "counters": rec.counters(),
        # clock origin (monotonic): lets a merged multi-run export
        # (fleet scheduler + per-job recorders appended to one file)
        # re-align every run's relative ``t`` onto one shared timeline
        "t0": round(rec.t0_monotonic, 6),
    }
    with open(path, "a" if append else "w") as f:
        f.write(json.dumps(header) + "\n")
        for r in rec.records():
            f.write(json.dumps(r) + "\n")


def from_jsonl(path) -> FlightRecorder:
    """Rebuild a recorder from a JSONL export (ring + counters + meta).
    Single-run files round-trip the derived summary exactly even when the
    ring evicted records: totals the replayed window cannot reconstruct
    (seq, step/growth counts, cumulative states/unique, wall time) are
    reconciled from the header's summary.  Multi-run files
    (``append=True``) fold every run's records into one recorder, later
    headers overriding meta — their summaries are window-approximate by
    design."""
    rec = None
    headers = []
    # multi-run alignment: later runs' relative timestamps shift by the
    # difference of their monotonic clock origins against the FIRST
    # run's (headers carry ``t0``; absent — an older export — the shift
    # is zero, the pre-alignment behavior)
    t0_first = None
    t_shift = 0.0

    def replaying(r):
        # exported health events replay verbatim; replayed steps must not
        # REgenerate them (the ring would then carry each event twice)
        r._replaying = True
        return r

    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if obj.get("kind") == "header":
                headers.append(obj)
                h_t0 = obj.get("t0")
                if rec is None:
                    if isinstance(h_t0, (int, float)):
                        t0_first = float(h_t0)
                    rec = replaying(FlightRecorder(
                        capacity=int(obj.get("capacity", 4096)),
                        meta=obj.get("meta") or {},
                    ))
                else:
                    rec.update_meta(**(obj.get("meta") or {}))
                    # run boundary in an appended file: the next run's
                    # cumulative counters restart from zero — reset the
                    # delta baseline so they are not clamped/diffed
                    # against the previous run's totals
                    rec._reset_step_baseline()
                    if (
                        t0_first is not None
                        and isinstance(h_t0, (int, float))
                    ):
                        t_shift = float(h_t0) - t0_first
                for k, v in (obj.get("counters") or {}).items():
                    rec.add(k, v)
                continue
            if rec is None:  # record lines before any header: tolerate
                rec = replaying(FlightRecorder())
            kind = obj.get("kind", "note")
            fields = {
                k: v for k, v in obj.items() if k not in ("seq", "t", "kind")
            }
            t_in = obj.get("t")
            if t_in is not None and t_shift:
                t_in = round(float(t_in) + t_shift, 6)
            if kind == "step":
                stored = rec.step(t=t_in, **fields)
            else:
                stored = rec.record(kind, t=t_in, **fields)
            if "seq" in obj:
                # keep the original sequence numbers (replay renumbers
                # from 1, which would mislabel a ring that had evicted)
                stored["seq"] = obj["seq"]
    if rec is None:
        return FlightRecorder()
    if len(headers) == 1:
        rec._reconcile_totals(headers[0].get("summary") or {})
    rec._replaying = False
    return rec


def _span_lanes(records: list) -> tuple:
    """Lane (``tid``) assignment for span-structured records: every span
    renders on the lane of its ROOT ancestor, so one fleet job and all
    its descendants (supervisor attempts, engine runs, step blocks, host
    seams) share a track and the viewer nests them by time containment —
    while concurrent sibling jobs land on separate tracks and never
    corrupt each other's nesting.  Returns ``(lane_of_span_id, lanes)``
    where lanes start at 100 (the plain step lane stays 1)."""
    by_id = {}
    for r in records:
        if r["kind"] == "span" and r.get("span_id"):
            by_id[r["span_id"]] = r
    roots: dict = {}

    def root_of(sid: str) -> str:
        seen = set()
        while True:
            r = by_id.get(sid)
            if r is None:
                return sid
            parent = r.get("parent_id")
            if not parent or parent not in by_id or parent in seen:
                return sid
            seen.add(sid)
            sid = parent

    lane_of: dict = {}
    for sid in by_id:
        root = root_of(sid)
        if root not in roots:
            roots[root] = 100 + len(roots)
        lane_of[sid] = roots[root]
    return lane_of, roots


def to_chrome_trace(rec: FlightRecorder, path) -> None:
    events = []
    pid = 1
    all_records = rec.records()
    span_lane, _ = _span_lanes(all_records)
    for r in all_records:
        ts_us = r["t"] * 1e6
        args = {
            k: v for k, v in r.items() if k not in ("seq", "t", "kind")
        }
        if r["kind"] == "step":
            dur_us = max(float(r.get("dt", 0.0)) * 1e6, 1.0)
            events.append({
                "name": f"step:{r.get('engine', '?')}",
                "cat": "step",
                "ph": "X",
                # complete events anchor at their START time (clamped:
                # a first step with dt=0 gets dur 1us, which must not
                # push ts below the trace origin)
                "ts": round(max(ts_us - dur_us, 0.0), 3),
                "dur": round(dur_us, 3),
                "pid": pid,
                # a step bound to an engine-run span renders on that
                # span's lane, nesting as its child step-block
                "tid": span_lane.get(r.get("span"), 1),
                "args": args,
            })
            # counter track: throughput + table load, plotted by the viewer
            counters = {}
            if r.get("dt", 0) and r.get("d_states") is not None:
                counters["states_per_sec"] = round(
                    r["d_states"] / r["dt"], 1
                )
            if r.get("load_factor") is not None:
                counters["load_factor"] = r["load_factor"]
            if counters:
                events.append({
                    "name": "throughput",
                    "cat": "step",
                    "ph": "C",
                    "ts": round(ts_us, 3),
                    "pid": pid,
                    "args": counters,
                })
            # resource-pressure counter track: queue depth + table load
            # per step, so the timeline shows WHERE the memory pressure
            # built, not just that it did (docs/telemetry.md)
            pressure = {}
            if r.get("queue") is not None:
                pressure["queue"] = r["queue"]
            if r.get("load_factor") is not None:
                pressure["table_load"] = r["load_factor"]
            if pressure:
                events.append({
                    "name": "pressure",
                    "cat": "step",
                    "ph": "C",
                    "ts": round(ts_us, 3),
                    "pid": pid,
                    "args": pressure,
                })
        elif r["kind"] == "spill":
            # spill-tier events (docs/spill.md): the instant event keeps
            # the record browsable; two counter tracks plot the tier byte
            # series (spill_bytes) and the Bloom/pending traffic
            # (bloom_filter) over the same timeline as the steps
            events.append({
                "name": r["kind"],
                "cat": r["kind"],
                "ph": "i",
                "s": "p",
                "ts": round(ts_us, 3),
                "pid": pid,
                "tid": 1,
                "args": args,
            })
            sb = {}
            for k in ("host_bytes", "disk_bytes"):
                if r.get(k) is not None:
                    sb[k] = r[k]
            if sb:
                events.append({
                    "name": "spill_bytes",
                    "cat": "spill",
                    "ph": "C",
                    "ts": round(ts_us, 3),
                    "pid": pid,
                    "args": sb,
                })
            bf = {}
            for k in ("spilled_fps", "pending", "dups", "novel"):
                if r.get(k) is not None:
                    bf[k] = r[k]
            if bf:
                events.append({
                    "name": "bloom_filter",
                    "cat": "spill",
                    "ph": "C",
                    "ts": round(ts_us, 3),
                    "pid": pid,
                    "args": bf,
                })
        elif r["kind"] == "memory":
            # memory-ledger samples: the instant event keeps the full
            # record browsable, the counter track plots the byte series
            events.append({
                "name": r["kind"],
                "cat": r["kind"],
                "ph": "i",
                "s": "p",
                "ts": round(ts_us, 3),
                "pid": pid,
                "tid": 1,
                "args": args,
            })
            hbm = {}
            if r.get("total_bytes") is not None:
                hbm["analytic_bytes"] = r["total_bytes"]
            live = r.get("device") or {}
            if live.get("bytes_in_use") is not None:
                hbm["bytes_in_use"] = live["bytes_in_use"]
            if hbm:
                events.append({
                    "name": "hbm_bytes",
                    "cat": "memory",
                    "ph": "C",
                    "ts": round(ts_us, 3),
                    "pid": pid,
                    "args": hbm,
                })
        elif r["kind"] == "span":
            # span-structured tracing (telemetry/spans.py): proper
            # nested duration events — the record's ``t`` is the close
            # time, so the event anchors at ``t - dur``; every span in
            # one lineage shares its root's lane, and the viewer nests
            # by time containment
            dur_us = max(float(r.get("dur", 0.0)) * 1e6, 1.0)
            events.append({
                "name": str(r.get("name", "span")),
                "cat": "span",
                "ph": "X",
                "ts": round(max(ts_us - dur_us, 0.0), 3),
                "dur": round(dur_us, 3),
                "pid": pid,
                "tid": span_lane.get(r.get("span_id"), 100),
                "args": args,
            })
        else:
            events.append({
                "name": r["kind"],
                "cat": r["kind"],
                "ph": "i",
                "s": "p",
                "ts": round(ts_us, 3),
                "pid": pid,
                "tid": 1,
                "args": args,
            })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"meta": rec.meta_snapshot(), "summary": rec.summary()},
    }
    with open(path, "w") as f:
        json.dump(doc, f)


def from_chrome_trace(path) -> dict:
    """Parse a Chrome-trace export back into ``{events, meta, summary}`` —
    the round-trip half used by tests (the trace format is lossy by design:
    ``seq`` is dropped, step starts are shifted by ``dt``)."""
    with open(path) as f:
        doc = json.load(f)
    return {
        "events": doc.get("traceEvents", []),
        "meta": doc.get("otherData", {}).get("meta", {}),
        "summary": doc.get("otherData", {}).get("summary", {}),
    }
