"""Span-structured tracing: the live half of the telemetry stack.

Dapper-style hierarchical spans over the work that hops scheduler →
supervisor → engine: a **trace** is one fleet run (or one standalone
check), and each unit of work inside it is a **span** with a fresh
``span_id`` and its parent's ``span_id`` as ``parent_id``:

    fleet ──┬── job (one scheduling episode on a slot)
            │     └── attempt (one supervised spawn+join)
            │           └── engine_run (one engine's whole run)
            │                 ├── step blocks (the existing ``step``
            │                 │   records — the engine binds its run
            │                 │   span to the recorder, so every step
            │                 │   carries ``span=<engine span id>``)
            │                 └── host seams: ``autosave``,
            │                     ``spill_drain``, ``resharding``
            └── job ...

Span ids are minted where the work is minted — the fleet scheduler
roots the trace, ``supervise()`` opens one span per attempt, the
engines one per run — and the context propagates DOWN via the builder
(``builder._span_ctx``), never through globals.  A span closes by
recording one ``span`` record into the flight recorder's ring
(``kind="span"``: name, trace/span/parent ids, ``dur``; the record's
``t`` is the close time, so ``t - dur`` is the start).  The Chrome-trace
exporter (:func:`telemetry.export.to_chrome_trace`) turns the records
into nested duration events — one Perfetto load shows the whole fleet
timeline.

Overhead contract (the telemetry discipline): spans are host-side
bookkeeping at seams that already exist — one ``uuid`` and two
``time.monotonic()`` calls per span, one dict per close.  No recorder →
nothing is recorded; the step jaxpr is untouched either way.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

# span record schema version (tests/test_telemetry_schema.py pins it)
SPAN_V = 1


def new_id() -> str:
    """A fresh 64-bit id (hex) for traces and spans alike."""
    return uuid.uuid4().hex[:16]


class SpanContext:
    """The (trace_id, span_id) pair a child span parents under.  Flows
    down the spawn path as ``builder._span_ctx``; immutable in use."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: Optional[str] = None,
                 span_id: Optional[str] = None):
        self.trace_id = trace_id or new_id()
        self.span_id = span_id or new_id()

    def __repr__(self) -> str:  # debugging/log lines only
        return f"SpanContext(trace={self.trace_id}, span={self.span_id})"


class SpanHandle:
    """An open span: created by :func:`start_span`, closed by
    :meth:`end` (which records the ``span`` record).  ``.ctx`` is what
    children parent under.  ``end`` is idempotent — a double close
    records nothing twice."""

    __slots__ = ("name", "ctx", "parent_id", "_t0", "_closed")

    def __init__(self, name: str, parent: Optional[SpanContext] = None):
        self.name = str(name)
        self.ctx = SpanContext(
            trace_id=parent.trace_id if parent is not None else None
        )
        self.parent_id = parent.span_id if parent is not None else None
        self._t0 = time.monotonic()
        self._closed = False

    def end(self, recorder, **attrs) -> Optional[dict]:
        """Close the span and record it into ``recorder`` (None → the
        span is dropped, by the no-recorder-no-telemetry rule).  Extra
        ``attrs`` ride the record (they must stay within the golden
        schema's optional set).  Returns the stored record (or None)."""
        if self._closed:
            return None
        self._closed = True
        dur = round(time.monotonic() - self._t0, 6)
        if recorder is None:
            return None
        fields = {
            "v": SPAN_V,
            "name": self.name,
            "trace_id": self.ctx.trace_id,
            "span_id": self.ctx.span_id,
            "dur": dur,
        }
        if self.parent_id is not None:
            fields["parent_id"] = self.parent_id
        fields.update({k: v for k, v in attrs.items() if v is not None})
        return recorder.record("span", **fields)


def start_span(name: str, parent: Optional[SpanContext] = None) -> SpanHandle:
    """Open a span (child of ``parent``; a fresh trace root without
    one).  Close it with :meth:`SpanHandle.end`."""
    return SpanHandle(name, parent)


class span:
    """Context-manager form for block-shaped seams::

        with span("autosave", rec, parent=self._span_ctx, gen=3):
            ...write the generation...

    The record lands on exit — exception or not (the seam's duration is
    real either way); the original exception always propagates."""

    def __init__(self, name: str, recorder, *,
                 parent: Optional[SpanContext] = None, **attrs):
        self._handle = SpanHandle(name, parent)
        self._recorder = recorder
        self._attrs = attrs

    @property
    def ctx(self) -> SpanContext:
        return self._handle.ctx

    def __enter__(self) -> "span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        attrs = dict(self._attrs)
        if exc_type is not None:
            attrs.setdefault("error", exc_type.__name__)
        self._handle.end(self._recorder, **attrs)
        return False  # never swallow the block's exception
