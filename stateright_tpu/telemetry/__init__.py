"""Flight recorder: streaming run telemetry for every checker strategy.

The engines were flying blind: the bench headline is a single states/sec
number, ``occupancy_stats`` is a point-in-time probe, and nothing records
*how* a run unfolded — per-step frontier dynamics, dedup ratios, table
occupancy drift, growth/compaction events, transfer volume.  GPUexplore's
scalability study (PAPERS.md) shows hash-table occupancy and per-iteration
frontier dynamics are exactly the signals that explain accelerator
model-checker throughput; this package is the instrumentation layer every
perf claim is measured with.

Pieces:

 - :class:`FlightRecorder` (``recorder.py``) — a bounded ring buffer of
   structured records plus monotone aggregate counters.  Engines append one
   ``step`` record per host sync (device engines: one per
   ``steps_per_call`` block; host engines: one per job block / mp round),
   plus ``growth`` / ``occupancy`` / ``compile`` / ``profile`` events.
 - JSONL + Chrome-trace export (``export.py``) — ``to_jsonl`` /
   ``from_jsonl`` round-trip, and ``to_chrome_trace`` for chrome://tracing
   / Perfetto.
 - :class:`ScopedProfiler` (``profile.py``) — a scoped ``jax.profiler``
   hook that traces the first N hot steps of a device run to a logdir.
 - :class:`RunRegistry` (``registry.py``) — the persistent append-only
   run ledger (``CheckerBuilder.runs(DIR)`` / ``STATERIGHT_TPU_RUN_DIR``):
   archived run reports + a ``config_key``-indexed headline record per
   run.
 - ``diff.py`` — the contract-aware cross-run diff
   (IDENTICAL / ISOMORPHIC / PERF-ONLY / DIVERGENT) behind the
   ``compare`` CLI verb, ``regress.py --diff``, and the Explorer's
   multi-run dashboard (docs/telemetry.md "Comparing runs").
 - ``spans.py`` — span-structured tracing (fleet job → supervisor
   attempt → engine run → step blocks → host seams); span records ride
   the ring and export as nested Chrome duration events
   (docs/observability.md).
 - :class:`MetricsBus` (``metrics.py``) — the live typed-metrics bus
   (counters/gauges/histograms with labeled families) behind the
   Explorer's Prometheus ``GET /metrics``; attach with
   ``.telemetry(metrics=True)`` or ``STATERIGHT_TPU_METRICS=1``.

Enabled per run via ``model.checker().telemetry()``; the recorder then
hangs off the checker as ``checker.flight_recorder``.  **Overhead
contract**: telemetry reads only host-visible state the engines already
sync (the packed stats vector), so disabling it adds zero ops to the step
jaxpr and enabling it costs <3% wall time (asserted in
``tests/test_telemetry.py``).  Optional occupancy sampling
(``occupancy_every=N``) pulls the visited table and is priced separately
(recorded as D2H bytes).
"""

from .recorder import FlightRecorder, STATUS_NAMES
from .profile import ScopedProfiler
from .health import HealthTracker
from .registry import RunRegistry
from .spans import SpanContext, SpanHandle, span, start_span
from .metrics import MetricsBus, default_bus, reset_default_bus

__all__ = [
    "FlightRecorder",
    "HealthTracker",
    "MetricsBus",
    "RunRegistry",
    "ScopedProfiler",
    "SpanContext",
    "SpanHandle",
    "STATUS_NAMES",
    "default_bus",
    "reset_default_bus",
    "span",
    "start_span",
]
