"""Progress & run-health model: is the search converging or silently
stalling?

A host-side model over the flight recorder's step stream — no device ops,
no extra transfers (the same overhead contract as the rest of telemetry).
Every :meth:`FlightRecorder.step` feeds :class:`HealthTracker.update`;
phase/stall *transitions* are emitted back into the ring as ``health``
records (so JSONL/Chrome-trace exports carry the health timeline), and
:meth:`FlightRecorder.health` returns the live snapshot (the Explorer's
``/.metrics`` and the ``--watch`` line read it).

Two kinds of signals, deliberately separated:

 - **Count-derived** (deterministic for a fixed run): the novelty rate
   (fresh inserts / generated states per step), the fresh-insert trend
   against its peak, and the coarse completion phase
   ``expanding | peaking | draining | done``.  These are safe to put in
   the deterministic run report (telemetry/report.py).
 - **Wall-clock-derived** (vary run to run): EWMA throughput and the
   drain-ETA estimate.  Live surfaces only — never in the report body.

Stall detection: ``stall_after`` consecutive steps with zero fresh inserts
while the frontier/queue is non-empty (the engine is spinning without
discovering), or the table load pinned at the growth threshold (≥25%
would have triggered growth; riding just under it for many steps means
the growth policy is thrashing).  A stall is a *flag with a reason*, not
a phase — a stalled run still has a phase.
"""

from __future__ import annotations

from typing import Optional

from .memory import OOM_RISK_LOAD

# health snapshot / event schema version
HEALTH_V = 1

PHASES = ("expanding", "peaking", "draining", "done")

# table load just under the engines' 25% growth trigger counts as "pinned"
_PINNED_LOAD = 0.245


class HealthTracker:
    """Incremental health model over step records.

    ``alpha`` is the EWMA smoothing factor for throughput;
    ``stall_after`` the number of consecutive zero-novelty steps (with a
    non-empty frontier) that flags a stall.  NOT thread-safe on its own —
    the recorder calls it under its lock."""

    def __init__(self, alpha: float = 0.3, stall_after: int = 5):
        self.alpha = alpha
        self.stall_after = stall_after
        self.steps = 0
        self.phase = "expanding"
        self.stalled = False
        self.stall_reason: Optional[str] = None
        # growth-OOM risk (telemetry/memory.py): armed by the memory
        # ledger's forecast — the next growth rung's migration transient
        # vs the device budget; flagged once the table load is close
        # enough to the growth trigger that the migration is imminent
        self.oom_risk = False
        # spill tier armed (docs/spill.md): the same forecast condition
        # is INFORMATIONAL — the run will evict to the host tier at the
        # boundary, not die — so it surfaces as ``spill_forecast``
        # instead of ``growth_oom_risk`` (recorder.set_spill_armed)
        self.spill_armed = False
        # spill disk tier lost (ENOSPC/dead disk; docs/robustness.md):
        # sticky for the run — the tier is pinned in host RAM, so
        # capacity headroom shrank (recorder.set_spill_degraded)
        self.spill_degraded = False
        self._mem_next_transient: Optional[int] = None
        self._mem_budget: Optional[int] = None
        self._zero_novel = 0  # consecutive d_unique == 0 steps
        self._pinned = 0  # consecutive load-at-threshold steps
        self._peak_d_unique = 0
        self._last = None  # last step record fields we care about
        self._ewma_sps: Optional[float] = None
        # smoothed NET queue-drain rate (rows/sec the queue actually
        # shrinks by): the drain ETA divides by this, NOT the fresh-insert
        # rate — the queue empties at the pop rate minus the insert rate,
        # and during draining the fresh rate tends to zero by definition
        # (dividing by it would overestimate the ETA without bound)
        self._ewma_drain: Optional[float] = None
        self._prev_queue: Optional[float] = None

    # -- feeding -------------------------------------------------------------

    def set_memory_forecast(
        self,
        next_transient_bytes: Optional[int],
        budget_bytes: Optional[int],
    ) -> None:
        """Arm the ``growth_oom_risk`` condition with the memory ledger's
        forecast (``telemetry/memory.py``): the next table rung's
        migration transient and the device budget.  Either value absent
        (CPU, ledger off) disarms the condition entirely."""
        self._mem_next_transient = (
            int(next_transient_bytes) if next_transient_bytes else None
        )
        self._mem_budget = int(budget_bytes) if budget_bytes else None

    def update(self, rec: dict) -> list:
        """Fold one step record in; returns the ``health`` EVENTS to emit
        (phase changes and stall transitions — transitions only, so the
        ring stays sparse)."""
        self.steps += 1
        d_states = int(rec.get("d_states") or 0)
        d_unique = int(rec.get("d_unique") or 0)
        dt = float(rec.get("dt") or 0.0)
        queue = rec.get("queue", rec.get("frontier"))
        load = rec.get("load_factor")

        if dt > 0:
            sps = d_states / dt
            self._ewma_sps = (
                sps if self._ewma_sps is None
                else self.alpha * sps + (1 - self.alpha) * self._ewma_sps
            )

        if isinstance(queue, (int, float)):
            if dt > 0 and self._prev_queue is not None:
                obs = max((self._prev_queue - queue) / dt, 0.0)
                self._ewma_drain = (
                    obs if self._ewma_drain is None
                    else self.alpha * obs + (1 - self.alpha) * self._ewma_drain
                )
            self._prev_queue = float(queue)

        self._peak_d_unique = max(self._peak_d_unique, d_unique)
        phase = self._classify(d_states, d_unique)

        # -- stall detection ------------------------------------------------
        # engines without a cheap frontier *count* (sharded: only a
        # replicated keep-going flag crosses to the host) send ``busy``
        # explicitly; otherwise an empty queue is completion-shaped
        flag = rec.get("busy")
        if flag is not None:
            busy = bool(flag)
        else:
            busy = queue is None or (
                isinstance(queue, (int, float)) and queue > 0
            )
        if d_unique == 0 and d_states > 0 and busy:
            self._zero_novel += 1
        else:
            self._zero_novel = 0
        if load is not None and float(load) >= _PINNED_LOAD:
            self._pinned += 1
        else:
            self._pinned = 0
        stalled, reason = False, None
        if self._zero_novel >= self.stall_after:
            stalled, reason = True, "no_fresh_inserts"
        elif self._pinned >= self.stall_after:
            stalled, reason = True, "load_pinned_at_growth_threshold"

        # growth-OOM risk: the table load has crossed half-way to the
        # growth trigger (the migration is imminent, not hypothetical)
        # and the ledger's forecast says the next rung's transient does
        # not fit the device.  A *flag with a forecast*, like the stall:
        # the run keeps going, but the operator should checkpoint or
        # re-plan before the growth boundary hits the wall.
        oom = bool(
            self._mem_next_transient
            and self._mem_budget
            and load is not None
            and float(load) >= OOM_RISK_LOAD
            and self._mem_next_transient > self._mem_budget
        )

        events = []
        if oom != self.oom_risk:
            self.oom_risk = oom
            if self.spill_armed:
                # informational: the next rung spills to the host tier
                name = "spill_forecast" if oom else "spill_forecast_cleared"
            else:
                name = "growth_oom_risk" if oom else "growth_oom_risk_cleared"
            events.append({"event": name, "phase": self.phase})
        if phase != self.phase:
            self.phase = phase
            events.append({"event": "phase", "phase": phase})
        # a reason SWITCH while already stalled (fresh insert clears the
        # novelty counter on a step where the load counter is already
        # over threshold) re-emits ``stall`` with the new reason — the
        # live badge and timeline must name the actual cause; a stall
        # span still closes at the next ``stall_cleared``
        if stalled != self.stalled or (
            stalled and reason != self.stall_reason
        ):
            self.stalled, self.stall_reason = stalled, reason
            events.append({
                "event": "stall" if stalled else "stall_cleared",
                "phase": self.phase,
                **({"reason": reason} if reason else {}),
            })
        self._last = {
            "d_states": d_states, "d_unique": d_unique, "dt": dt,
            "queue": queue, "load": load,
        }
        return [{"v": HEALTH_V, **e} for e in events]

    def force_stall(self, reason: str = "injected") -> list:
        """Manufacture a ``stall`` transition (deterministic preemption
        injection — ``fleet.PreemptionPlan`` via
        ``FlightRecorder.inject_stall``): flips the flag exactly as
        detection would, so everything downstream of the transition (the
        ring record, the live badge, the fleet scheduler's preemption
        monitor) runs the real path.  The next step record with fresh
        inserts recomputes the flag and emits the paired
        ``stall_cleared``, like any detected stall."""
        if self.stalled and self.stall_reason == reason:
            return []
        self.stalled, self.stall_reason = True, str(reason)
        return [{
            "v": HEALTH_V, "event": "stall", "phase": self.phase,
            "reason": str(reason),
        }]

    def mark_spill_degraded(self) -> list:
        """The spill store's disk tier failed (ENOSPC / dead disk): one
        sticky ``spill_degraded`` transition — the run continues with the
        tier pinned in host RAM, and the operator should know the
        capacity headroom shrank."""
        if self.spill_degraded:
            return []
        self.spill_degraded = True
        return [{
            "v": HEALTH_V, "event": "spill_degraded", "phase": self.phase,
        }]

    def mark_done(self) -> list:
        """The run completed: close the phase timeline.  An active stall
        is closed first with its ``stall_cleared`` transition — consumers
        pair stall/stall_cleared events, so a finished run must never
        leave one open."""
        events = []
        if self.stalled:
            self.stalled, self.stall_reason = False, None
            events.append({"event": "stall_cleared", "phase": self.phase})
        if self.oom_risk:
            # a finished run grew no further: the risk span closes with
            # the run, like an open stall
            self.oom_risk = False
            events.append({
                "event": (
                    "spill_forecast_cleared" if self.spill_armed
                    else "growth_oom_risk_cleared"
                ),
                "phase": self.phase,
            })
        if self.phase != "done":
            self.phase = "done"
            events.append({"event": "phase", "phase": "done"})
        return [{"v": HEALTH_V, **e} for e in events]

    # -- classification (count-derived: deterministic per run) ---------------

    def _classify(self, d_states: int, d_unique: int) -> str:
        if self.phase == "done":
            return "done"
        peak = self._peak_d_unique
        if peak == 0:
            return "expanding"
        novelty = (d_unique / d_states) if d_states > 0 else 0.0
        if d_unique >= 0.8 * peak and novelty >= 0.3:
            return "expanding"
        if d_unique <= 0.2 * peak or novelty < 0.1:
            return "draining"
        return "peaking"

    # -- reading -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Live health view (JSON-safe).  ``eta_secs`` is a drain-phase
        heuristic — queue size over the smoothed net queue-drain rate —
        and None whenever it would be a guess."""
        last = self._last or {}
        d_states = last.get("d_states") or 0
        d_unique = last.get("d_unique") or 0
        novelty = round(d_unique / d_states, 6) if d_states > 0 else None
        queue = last.get("queue")
        eta = None
        if (
            self.phase == "draining"
            and isinstance(queue, (int, float))
            and queue
            and self._ewma_drain
        ):
            eta = round(float(queue) / self._ewma_drain, 1)
        trend = "flat"
        if self._peak_d_unique:
            if d_unique >= 0.8 * self._peak_d_unique:
                trend = "growing"
            elif d_unique <= 0.2 * self._peak_d_unique:
                trend = "shrinking"
        return {
            "v": HEALTH_V,
            "phase": self.phase,
            # the raw condition only reads as a RISK when no spill tier
            # will catch the growth; armed, it is the spill forecast
            "oom_risk": self.oom_risk and not self.spill_armed,
            **(
                {"spill_forecast": True}
                if (self.oom_risk and self.spill_armed)
                else {}
            ),
            "stalled": self.stalled,
            **(
                {"spill_degraded": True} if self.spill_degraded else {}
            ),
            **(
                {"stall_reason": self.stall_reason}
                if self.stall_reason
                else {}
            ),
            "steps": self.steps,
            "novelty": novelty,
            "peak_fresh_per_step": self._peak_d_unique,
            "frontier": queue if isinstance(queue, (int, float)) else None,
            "frontier_trend": trend,
            "ewma_states_per_sec": (
                round(self._ewma_sps, 1) if self._ewma_sps else None
            ),
            "eta_secs": eta,
        }


def phase_timeline(step_records: list) -> list:
    """Deterministic per-step phase series for the run report: replays the
    COUNT-derived part of the tracker over exported/ring step records.
    Entries: ``{"step", "unique", "d_unique", "novelty", "phase"}``."""
    tracker = HealthTracker()
    out = []
    for i, r in enumerate(step_records):
        tracker.update(r)
        d_states = int(r.get("d_states") or 0)
        d_unique = int(r.get("d_unique") or 0)
        out.append({
            "step": i,
            "unique": int(r.get("unique") or 0),
            "d_unique": d_unique,
            "novelty": (
                round(d_unique / d_states, 6) if d_states > 0 else None
            ),
            "phase": tracker.phase,
        })
    return out
