"""HBM ledger & capacity planning: where the memory goes, and when it
runs out.

The observability triad's third axis (docs/telemetry.md "Memory ledger"):
the flight recorder answers *where time goes* (PR 2), cartography/health
*how the search is going* (PR 5) — this module answers *where the memory
goes*.  GPUexplore's scalability study (PAPERS.md) shows device memory,
not compute, is the binding constraint for explicit-state checking at
scale; the ROADMAP's billion-state spill tier cannot be built before the
stack can *measure* memory.

Two reconciling views, deliberately separated:

 - **Analytic footprint model** — exact bytes-per-buffer for every
   device-resident carry buffer (visited table fp/parent, queue/frontier
   rows, cartography counters, POR tensors, scalars), derived from the
   engines' dtypes and shapes at the current capacity AND at every future
   growth rung.  Computable and testable on CPU: the wavefront specs are
   derived from the engine's own ``_carry_avals`` (the same signature the
   prewarm AOT path compiles against, so agreement is already pinned),
   and ``tests/test_memory.py`` pins analytic bytes == the live engine
   buffers' ``nbytes`` EXACTLY on both engines.
 - **Live device readings** — ``device.memory_stats()`` bytes/peak where
   the backend supports them (TPU; CPU returns nothing and every
   consumer degrades to the analytic path), and
   ``compiled.memory_analysis()`` temp/argument/output bytes captured at
   compile time for fresh, prewarm, and persistent-cache executables
   (backfilled onto ``compile`` ring records via the existing ``amend()``
   path).

On top of the ledger:

 - a **growth-transient forecast**: growth migration holds the old AND
   new carry live across the swap (the host rehashes into fresh buffers
   while the old ones are still referenced), so the next rung's peak is
   ``total(rung) + total(rung+1)`` — and the max reachable capacity on a
   device is the largest rung whose *transient* fits, not whose steady
   state does;
 - a ``growth_oom_risk`` health condition (``telemetry/health.py``):
   the table load is approaching the growth trigger and the forecast
   says the next rung's transient does not fit;
 - a **preflight capacity guard** in ``spawn_tpu`` (``parallel/_base``):
   warn — flag-gated error via ``STATERIGHT_TPU_CAPACITY_GUARD=error`` —
   when the requested capacity analytically exceeds device memory,
   before any compile is paid.

Contract, mirroring telemetry/checked/prededup/cartography: the ledger
adds ZERO ops to the step jaxpr — it is pure host-side accounting over
shapes the engines already know — so ledger off (and on!) leaves the run
program bit-identical (pinned by test).  Enabled via
``.telemetry(memory=True)`` (implied by ``.report()``); the device
budget can be overridden/simulated with ``STATERIGHT_TPU_DEVICE_BYTES``
(bytes), which is also how CPU tests exercise the guard.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Optional

import numpy as np

# memory snapshot / ring-record schema version
MEMORY_V = 1

# table load at which the growth forecast becomes a live risk: half-way
# to the engines' 25% growth trigger — the run WILL grow soon, and if the
# next rung's transient does not fit, the operator should know before it
# happens (health.py reads this)
OOM_RISK_LOAD = 0.125

ENV_DEVICE_BYTES = "STATERIGHT_TPU_DEVICE_BYTES"
ENV_CAPACITY_GUARD = "STATERIGHT_TPU_CAPACITY_GUARD"

# engines grow the visited table when unique * 4 > capacity, so a rung of
# ``cap`` slots holds at most cap/4 unique states before the NEXT rung's
# transient must fit (ops/buckets.py Poisson tail rationale)
GROWTH_LOAD_DENOM = 4


class BufferSpec:
    """One device-resident buffer: name, shape, dtype, exact bytes."""

    __slots__ = ("name", "shape", "dtype", "nbytes")

    def __init__(self, name: str, shape: tuple, dtype) -> None:
        self.name = name
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        n = 1
        for d in self.shape:
            n *= d
        self.nbytes = int(n * self.dtype.itemsize)

    def __repr__(self) -> str:  # debugging ergonomics only
        return (
            f"BufferSpec({self.name!r}, {self.shape}, "
            f"{self.dtype.name}, {self.nbytes}B)"
        )


def total_bytes(specs: list) -> int:
    return int(sum(s.nbytes for s in specs))


def buffers_dict(specs: list) -> dict:
    """JSON-facing ``{name: nbytes}`` map (insertion = carry order)."""
    return {s.name: s.nbytes for s in specs}


# -- per-engine analytic models ----------------------------------------------

# wavefront carry names, in exact carry order (parallel/wavefront.py
# _SNAPSHOT_KEYS + the optional tails); zipped against _carry_avals so
# shapes/dtypes can never drift from what the engine actually allocates
_WAVEFRONT_NAMES = (
    "table_fp", "table_parent", "q_rows", "q_fp", "q_ebits", "q_depth",
    "head", "tail", "unique", "scount", "disc", "maxdepth", "status",
)


def wavefront_specs(
    tensor, n_props: int, cap: int, qcap: int, batch: int,
    *, checked: bool = False, cartography: bool = False, por: bool = False,
    spill=None,
) -> list:
    """Per-buffer specs of the single-device wavefront carry at these
    capacities — derived from the engine's own abstract carry signature
    (``wavefront._carry_avals``, the prewarm-AOT contract), so the
    analytic bytes reconcile EXACTLY against the live buffers' nbytes.
    ``spill`` is the spill-tier config ``(bloom_bits, pend_cap)`` when
    the tier is armed: the Bloom filter and pending buffers are
    device-resident and count against the budget like any carry buffer
    (the HOST/DISK tier contents deliberately do not — they are what the
    budget is being traded against)."""
    from ..parallel.wavefront import _carry_avals

    avals = _carry_avals(
        tensor, n_props, cap, qcap, batch, checked, cartography, por,
        spill,
    )
    names = list(_WAVEFRONT_NAMES)
    if checked:
        names.append("checked_err")
    if por:
        names += ["por_boost", "por_stats"]
    if spill:
        names += ["spill_bloom", "spill_base", "pend_fp", "pend_rows",
                  "pend_parent", "pend_ebits", "pend_depth", "pend_count",
                  "spill_stats"]
    if cartography:
        names += ["cart_action_hist", "cart_prop_evals", "cart_prop_hits"]
    assert len(names) == len(avals), (len(names), len(avals))
    return [
        BufferSpec(n, a.shape, a.dtype) for n, a in zip(names, avals)
    ]


def sharded_specs(
    width: int, arity: int, n_props: int, ndev: int,
    cap_local: int, fcap_local: int,
    *, cartography: bool = False, por: bool = False,
) -> list:
    """Per-buffer specs of the sharded engine's GLOBAL carry (logical
    array shapes — what ``np.asarray(carry[i]).nbytes`` reports; the
    per-device planning view divides the sharded buffers by ``ndev`` and
    counts replicated ones in full, see :func:`sharded_per_device_bytes`).
    Must mirror ``sharded.device_init``'s output exactly (pinned by the
    exactness test)."""
    p = max(n_props, 1)
    specs = [
        BufferSpec("table_fp", (ndev * cap_local,), np.uint64),
        BufferSpec("table_parent", (ndev * cap_local,), np.uint64),
        BufferSpec("rows", (ndev * fcap_local, width), np.uint64),
        BufferSpec("fps", (ndev * fcap_local,), np.uint64),
        BufferSpec("ebits", (ndev * fcap_local,), np.uint32),
        BufferSpec("unique", (), np.int64),
        BufferSpec("scount", (), np.int64),
        BufferSpec("disc", (p,), np.uint64),
        BufferSpec("depth", (), np.int32),
        BufferSpec("status", (), np.int32),
    ]
    if por:
        specs += [
            BufferSpec("por_boost", (), np.int32),
            BufferSpec("por_stats", (3,), np.int64),
        ]
    if cartography:
        from ..ops.cartography import DEPTH_BINS

        specs += [
            BufferSpec("cart_depth_hist", (DEPTH_BINS,), np.int64),
            BufferSpec("cart_action_hist", (max(arity, 1),), np.int64),
            BufferSpec("cart_prop_evals", (p,), np.int64),
            BufferSpec("cart_prop_hits", (p,), np.int64),
            BufferSpec("cart_shard_load", (ndev,), np.int64),
            BufferSpec("cart_route_matrix", (ndev, ndev), np.int64),
        ]
    return specs


_SHARDED_LOCAL = frozenset(
    {"table_fp", "table_parent", "rows", "fps", "ebits", "cart_shard_load",
     "cart_route_matrix"}
)


def sharded_per_device_bytes(specs: list, ndev: int) -> int:
    """HBM-per-chip view of a sharded footprint: sharded buffers divide
    over the mesh, replicated ones are resident in full on every chip."""
    out = 0
    for s in specs:
        out += s.nbytes // ndev if s.name in _SHARDED_LOCAL else s.nbytes
    return int(out)


# -- live device readings ----------------------------------------------------


def device_memory_stats(device=None) -> Optional[dict]:
    """Live ``memory_stats()`` of ``device`` (default: the first JAX
    device), normalized to JSON-safe ints, or None when the backend does
    not expose them (CPU) — every consumer must degrade to the analytic
    path, never crash."""
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 - absent/unsupported backend
        return None
    if not stats:
        return None
    out = {"platform": str(getattr(dev, "platform", "?"))}
    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_free_block_bytes"):
        v = stats.get(k)
        if v is not None:
            out[k] = int(v)
    return out


def device_budget(device=None) -> tuple:
    """``(bytes, src)`` for capacity planning: the env override
    ``STATERIGHT_TPU_DEVICE_BYTES`` wins (simulated budgets — also how
    CPU tests exercise the guard), then the live ``bytes_limit``; both
    absent ⇒ ``(None, None)`` and planners print the analytic table
    without a verdict."""
    env = os.environ.get(ENV_DEVICE_BYTES, "").strip()
    if env:
        try:
            return int(env), "env"
        except ValueError:
            pass
    stats = device_memory_stats(device)
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"]), "device"
    return None, None


def exec_memory(compiled) -> Optional[dict]:
    """``compiled.memory_analysis()`` normalized to JSON-safe ints —
    the temp/argument/output byte breakdown XLA computed at compile time
    — or None when the backend/executable does not expose it."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - not all runtimes implement it
        return None
    if ma is None:
        return None
    out = {}
    for attr, key in (
        ("temp_size_in_bytes", "temp_bytes"),
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            try:
                out[key] = int(v)
            except (TypeError, ValueError):
                continue
    return out or None


# -- growth-transient forecast + capacity plan -------------------------------


def next_rung_block(spec_fn: Callable, caps: dict) -> dict:
    """The analytic forecast for the NEXT table-doubling rung: steady
    bytes and the migration transient (old + new carry live across the
    growth swap)."""
    cur_total = total_bytes(spec_fn(caps))
    nxt = dict(caps)
    nxt["cap"] = int(caps["cap"]) * 2
    nxt_total = total_bytes(spec_fn(nxt))
    return {
        "capacity": int(nxt["cap"]),
        "total_bytes": nxt_total,
        "transient_bytes": cur_total + nxt_total,
    }


def capacity_plan(
    spec_fn: Callable, caps: dict, *, budget: Optional[int] = None,
    rungs: int = 24, spill: bool = False,
    spill_host_bytes: Optional[int] = None,
) -> dict:
    """The capacity ladder from ``caps`` upward: per rung, steady bytes,
    the migration transient (previous rung + this rung live), and —
    when a ``budget`` is known — whether it fits.  ``max_unique`` is the
    planning headline: the largest rung whose TRANSIENT fits holds at
    most ``capacity / 4`` unique states before the next (unfitting)
    migration, i.e. "on this device the run reaches ~N states before
    spilling".

    ``spill=True`` plans WITH the spill tier armed (docs/spill.md): the
    ladder still caps the HOT tier at the largest affordable rung, but
    ``max_unique`` no longer stops at HBM/4 — it extends by the host
    tier's reach (``spill_host_bytes`` / ``STATERIGHT_TPU_HOST_BYTES`` /
    half of physical RAM, at 16 bytes per spilled state) with the mmap'd
    disk tier unbounded behind it, reported in the ``spill`` block."""
    ladder = []
    cur = dict(caps)
    prev_total = None
    max_unique = None
    for _ in range(rungs):
        total = total_bytes(spec_fn(cur))
        transient = total if prev_total is None else prev_total + total
        fits = None if budget is None else transient <= budget
        ladder.append({
            "capacity": int(cur["cap"]),
            "total_bytes": total,
            "transient_bytes": transient,
            **({} if fits is None else {"fits": fits}),
        })
        if fits:
            max_unique = int(cur["cap"]) // GROWTH_LOAD_DENOM
        if fits is False:
            break
        prev_total = total
        cur = dict(cur)
        cur["cap"] = int(cur["cap"]) * 2
    out = {
        "v": MEMORY_V,
        "rungs": ladder,
        "budget_bytes": budget,
    }
    if max_unique is not None:
        out["max_unique"] = max_unique
    if spill and budget is not None and max_unique is not None:
        from ..spill.store import BYTES_PER_ENTRY, default_host_budget

        hb = (
            int(spill_host_bytes)
            if spill_host_bytes is not None
            else default_host_budget()
        )
        block: dict = {
            "hot_max_unique": max_unique,
            "bytes_per_spilled": BYTES_PER_ENTRY,
            "host_budget_bytes": hb,
            "disk": "unbounded (mmap tier; bounded by disk capacity)",
        }
        if hb:
            block["host_max_unique"] = hb // BYTES_PER_ENTRY
            out["max_unique"] = max_unique + block["host_max_unique"]
        out["spill"] = block
    return out


def fmt_bytes(n: Optional[int]) -> str:
    """Human bytes (``1.5GB``); '-' for unknown."""
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024
    return f"{n:.1f}TB"  # pragma: no cover - unreachable


# -- the ledger --------------------------------------------------------------


class MemoryLedger:
    """Host-side memory accounting for one engine run.

    ``spec_fn(caps) -> [BufferSpec]`` is the engine's analytic model;
    ``caps`` dicts must carry at least ``cap`` (table slots — the
    doubling edge the growth forecast walks).  The ledger recomputes the
    footprint only when the capacity rung changes, pushes every snapshot
    into the flight recorder (``rec.set_memory`` — which also feeds the
    health model's ``growth_oom_risk`` guard), and emits ``memory`` ring
    records at growth boundaries plus periodic watermark samples
    (``every`` host syncs; live ``peak_bytes_in_use`` is the watermark).
    Zero device ops — everything here is host arithmetic over shapes the
    engine already knows."""

    def __init__(
        self,
        engine: str,
        spec_fn: Callable,
        recorder=None,
        *,
        every: int = 0,
        extra: Optional[dict] = None,
    ) -> None:
        self.engine = engine
        self.spec_fn = spec_fn
        self.recorder = recorder
        self.every = int(every)
        # engine-shape annotations for the snapshot (queue_capacity /
        # frontier_capacity / devices), refreshed per observe
        self.extra = dict(extra or {})
        self._caps: Optional[dict] = None
        self._snap: Optional[dict] = None
        self._observes = 0
        self._exec: Optional[dict] = None
        budget, src = device_budget()
        self._budget, self._budget_src = budget, src

    # -- feeding -------------------------------------------------------------

    def attach_exec(self, compiled) -> Optional[dict]:
        """Record the latest executable's compile-time memory analysis
        (folded into the snapshot's ``exec`` block); returns the
        normalized dict for the caller to amend onto its ``compile``
        ring record."""
        mem = exec_memory(compiled)
        if mem is not None:
            self._exec = mem
            if self._snap is not None:
                self._snap = dict(self._snap)
                self._snap["exec"] = mem
                if self.recorder is not None:
                    self.recorder.set_memory(self._snap)
        return mem

    def observe(self, caps: dict, *, at: Optional[str] = None,
                extra: Optional[dict] = None) -> dict:
        """One host-sync observation.  Recomputes the analytic block when
        the capacity rung changed (emitting a ``memory`` ring record
        tagged ``growth`` unless ``at`` overrides), else emits only the
        periodic watermark sample when due.  Returns the live snapshot."""
        caps = dict(caps)
        if extra:
            self.extra.update(extra)
        self._observes += 1
        rung_changed = caps != self._caps
        if rung_changed:
            self._caps = caps
            self._snap = self._build_snapshot(caps)
            if self.recorder is not None:
                self.recorder.set_memory(self._snap)
        due = self.every and self._observes % self.every == 0
        if at is not None or rung_changed or due:
            tag = at
            if tag is None:
                tag = "growth" if self._observes > 1 else "init"
                if not rung_changed:
                    tag = f"sample{self._observes}"
            self._record(tag)
        return self._snap

    def finalize(self) -> Optional[dict]:
        """Close the memory time series with a ``final`` record (fresh
        live stats — the run's peak watermark)."""
        if self._caps is None:
            return None
        self._record("final")
        return self.snapshot()

    # -- reading -------------------------------------------------------------

    def snapshot(self) -> Optional[dict]:
        """The latest full block (analytic + live device fields), or
        None before the first observe."""
        return dict(self._snap) if self._snap else None

    def analytic_block(self) -> Optional[dict]:
        """The DETERMINISTIC subset for the run report: analytic bytes
        only — no live device stats, no machine-local budget (the report
        body must stay byte-stable across runs and machines)."""
        snap = self.snapshot()
        if snap is None:
            return None
        return {
            k: snap[k]
            for k in ("v", "engine", "capacity", "queue_capacity",
                      "frontier_capacity", "devices", "buffers",
                      "total_bytes", "per_device_bytes", "next_rung")
            if k in snap
        }

    # -- internals -----------------------------------------------------------

    def _build_snapshot(self, caps: dict) -> dict:
        specs = self.spec_fn(caps)
        snap: dict = {
            "v": MEMORY_V,
            "engine": self.engine,
            "capacity": int(caps["cap"]),
            **self.extra,
            "buffers": buffers_dict(specs),
            "total_bytes": total_bytes(specs),
            "next_rung": next_rung_block(self.spec_fn, caps),
        }
        ndev = self.extra.get("devices")
        if ndev:
            snap["per_device_bytes"] = sharded_per_device_bytes(specs, ndev)
        if self._budget is not None:
            snap["budget_bytes"] = self._budget
            snap["budget_src"] = self._budget_src
        if self._exec is not None:
            snap["exec"] = self._exec
        return snap

    def _record(self, at: str) -> None:
        if self.recorder is None or self._snap is None:
            return
        rec_fields = {
            k: v for k, v in self._snap.items() if k != "v"
        }
        live = device_memory_stats()
        if live is not None:
            rec_fields["device"] = live
            # refresh the live view consumers poll (watch/Explorer)
            self._snap = dict(self._snap)
            self._snap["device"] = live
            self.recorder.set_memory(self._snap)
        self.recorder.record("memory", v=MEMORY_V, at=at, **rec_fields)


# -- preflight capacity guard ------------------------------------------------


def guard_mode() -> str:
    """``warn`` (default) | ``error`` | ``off`` from
    ``STATERIGHT_TPU_CAPACITY_GUARD``."""
    mode = os.environ.get(ENV_CAPACITY_GUARD, "").strip().lower()
    if mode in ("error", "raise"):
        return "error"
    if mode in ("0", "off", "skip"):
        return "off"
    return "warn"


class CapacityError(RuntimeError):
    """Raised by the preflight guard (``STATERIGHT_TPU_CAPACITY_GUARD=
    error``) when the requested capacities analytically exceed device
    memory — before any compile is paid."""


def preflight_guard(
    context: str, total: int, *, warn_once_obj=None
) -> None:
    """Warn (flag-gated error) when an analytic STEADY footprint exceeds
    the device budget — the requested capacities cannot even sit on the
    device.  (Whether future growth TRANSIENTS fit is a forecast, not a
    precondition — a space that fits the first rung may never grow — so
    that lives in the runtime ``growth_oom_risk`` signal and the
    ``capacity`` plan, not here.)  Silent when no budget is known (CPU)
    or the guard is off; ``warn_once_obj`` suppresses repeated prints
    per model (the audit-warning discipline)."""
    mode = guard_mode()
    if mode == "off":
        return
    budget, src = device_budget()
    if budget is None or total <= budget:
        return
    msg = (
        f"stateright-tpu: capacity guard: {context}: analytic "
        f"steady footprint {fmt_bytes(total)} exceeds the device budget "
        f"{fmt_bytes(budget)} ({src}); shrink capacity=/queue_capacity= "
        "or run the `capacity` verb for a plan (docs/telemetry.md)"
    )
    if mode == "error":
        raise CapacityError(msg)
    if warn_once_obj is not None:
        if getattr(warn_once_obj, "_capacity_warn_printed", False):
            return
        try:
            object.__setattr__(warn_once_obj, "_capacity_warn_printed", True)
        except Exception:  # noqa: BLE001 - __slots__ models
            pass
    print(msg, file=sys.stderr)


def snapshot_fits_guard(snap: dict, context: str) -> None:
    """Resume-time guard (rides ``_check_snapshot_sig``): the snapshot's
    recorded analytic footprint (``footprint_bytes``, written by the
    manifest satellite; summed array bytes for older snapshots) must fit
    the target device — warn/flag-gated-error BEFORE any compile."""
    mode = guard_mode()
    if mode == "off":
        return
    budget, src = device_budget()
    if budget is None:
        return
    total = snap.get("footprint_bytes")
    if total is None:
        # HOT TIER ONLY: spill_* manifest arrays are host-resident tier
        # contents (docs/spill.md) and never compete for device memory
        total = sum(
            int(v.nbytes) for k, v in snap.items()
            if isinstance(v, np.ndarray) and not str(k).startswith("spill_")
        )
    total = int(total)
    if total <= budget:
        return
    msg = (
        f"stateright-tpu: capacity guard: {context}: the resume "
        f"snapshot's footprint {fmt_bytes(total)} exceeds this device's "
        f"budget {fmt_bytes(budget)} ({src}) — the resumed run cannot "
        "hold the snapshot"
    )
    if mode == "error":
        raise CapacityError(msg)
    print(msg, file=sys.stderr)
