"""The flight recorder: bounded ring of structured records + aggregates.

Record schema (every record):

 - ``seq``  — monotone sequence number (never reset; ``seq - len(records)``
   is how many old records the ring evicted)
 - ``t``    — seconds since the recorder was created (monotonic clock)
 - ``kind`` — ``"step"`` | ``"growth"`` | ``"occupancy"`` | ``"compile"``
   | ``"profile"`` | ``"health"`` | ``"cartography"`` | ``"memory"``
   | ``"roofline"`` | ``"checkpoint"`` | ``"fault"`` | ``"restart"``
   | ``"sweep"`` | ``"fleet"`` | ``"job"`` | ``"span"`` | ``"note"``

``step`` records additionally carry the engine tag and cumulative counters
(``states``, ``unique``) plus derived per-step deltas (``d_states``,
``d_unique``, ``dedup``, ``dt``) computed against the previous step record
— so each record is self-contained for streaming consumers (the Explorer's
``/.metrics`` sparkline reads them directly).

Aggregate counters (transfer bytes, compile-cache hits, growth/compaction
events) live OUTSIDE the ring so eviction never loses totals; they fold
into :meth:`FlightRecorder.summary`.

Thread safety: engines record from their run thread while the Explorer
polls from HTTP handler threads — every mutation and snapshot takes the
internal lock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from .health import HealthTracker

# Growth-record status vocabulary across engines.  Each engine maps its
# own numeric status words onto these names NEXT TO its constant
# definitions (``parallel/wavefront.py`` and ``parallel/sharded.py`` number
# their codes differently; the integers are never shared, only the names).
STATUS_NAMES = frozenset({
    "ok", "queue_full", "table_full", "cand_full", "poison",
    "frontier_full", "bucket_full", "spill_sync",
})


class FlightRecorder:
    """Bounded, thread-safe run-telemetry recorder.

    ``capacity`` bounds the ring buffer (oldest records evicted); aggregate
    counters are unbounded scalars.  ``meta`` is carried verbatim into
    :meth:`summary` and the JSONL header (engine tag, model name, run
    configuration).
    """

    def __init__(self, capacity: int = 4096, meta: Optional[dict] = None,
                 metrics=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.meta = dict(meta or {})
        # live metrics bus (telemetry/metrics.py): None (the default)
        # detaches publication entirely — step() adds nothing, the
        # parity pin.  ``metrics=`` attaches a bus explicitly;
        # STATERIGHT_TPU_METRICS=1 attaches the process default bus.
        if metrics is None:
            import os as _os

            if _os.environ.get("STATERIGHT_TPU_METRICS") == "1":
                from .metrics import default_bus

                metrics = default_bus()
        self._bus = metrics
        self._bus_fams: Optional[dict] = None
        self._fleet_fams: Optional[dict] = None
        # monotone-counter baselines for fleet snapshots (set_fleet
        # publishes deltas of cumulative pool tallies)
        self._fleet_pub = {"completed": 0, "preemptions": 0}
        # span-structured tracing (telemetry/spans.py): the engine binds
        # its run span here so step/profile records carry its id
        self._bound_span: Optional[str] = None
        self._records: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._seq = 0
        self._counters: dict[str, float] = {}
        # per-kind totals survive ring eviction (the ring is a window, the
        # counts are the truth)
        self._kind_counts: dict[str, int] = {}
        # last step snapshot for delta derivation: (t, states, unique)
        self._last_step: Optional[tuple] = None
        # the full last step record, for O(1) live readers (--watch polls
        # several times a second; scanning the ring would hold the lock
        # across a list copy of up to ``capacity`` dicts each poll)
        self._last_step_rec: Optional[dict] = None
        # wall-clock origin for summary(): recorder creation (t=0), so
        # work done before the FIRST step record (init + first compiled
        # block) is not silently excluded from the throughput denominator.
        # JSONL replay shifts it to reproduce the exported wall time.
        self._t_offset = 0.0
        # progress/health model (health.py): fed by every step record;
        # phase/stall TRANSITIONS are emitted back into the ring as
        # ``health`` records.  JSONL replay suppresses regeneration (the
        # exported events replay verbatim instead).
        self._health = HealthTracker()
        self._replaying = False
        # latest search-cartography snapshot (ops/cartography.py); lives
        # OUTSIDE the ring like the aggregate counters, so eviction never
        # loses it.  The engines refresh it per host sync.
        self._cartography: Optional[dict] = None
        # latest HBM-ledger snapshot (telemetry/memory.py): same
        # outside-the-ring discipline; setting it also arms the health
        # model's growth_oom_risk forecast
        self._memory: Optional[dict] = None
        # latest spill-tier snapshot (stateright_tpu/spill/): same
        # discipline again; the engines refresh it per eviction /
        # resolution / sync
        self._spill: Optional[dict] = None
        # latest roofline-ledger snapshot (telemetry/roofline.py):
        # static per-stage FLOPs/bytes + reconciliation + verdicts;
        # set once at spawn (the static model cannot change mid-run)
        self._roofline: Optional[dict] = None
        # latest durability snapshot (stateright_tpu/checkpoint.py:
        # autosave cadence/generations + supervised restart count); same
        # outside-the-ring discipline
        self._durability: Optional[dict] = None
        # latest fleet pool/queue snapshot (stateright_tpu/fleet/): slot
        # occupancy, queued/running/terminal job keys; same discipline —
        # the scheduler refreshes it on every placement transition and
        # the Explorer's pool panel reads it off ``/.metrics``
        self._fleet: Optional[dict] = None
        # in-band stall-injection seam (fleet.PreemptionPlan): called with
        # the step ordinal inside step(), so a due injection lands its
        # ``health`` record on the step that crosses the threshold — a
        # polling injector can lose the race against a short run
        self._stall_inject: Optional[Callable[[int], Optional[str]]] = None

    # -- metrics bus (telemetry/metrics.py) ----------------------------------

    @property
    def metrics_bus(self):
        """The attached live-metrics bus, or None (publication off)."""
        return self._bus

    def _engine_fams(self) -> dict:
        if self._bus_fams is None:
            from .metrics import engine_families

            self._bus_fams = engine_families(self._bus)
        return self._bus_fams

    def _engine_labels(self, engine: Optional[str] = None) -> dict:
        return {
            "engine": str(engine or self.meta.get("engine", "?")),
            "model": str(self.meta.get("model", "?")),
        }

    def _bus_drop(self, e: BaseException) -> None:
        """Publication must never break a run: detach the bus and leave
        one note in the ring saying why."""
        self._bus = None
        self._append_unlocked("note", {
            "what": "metrics bus detached",
            "error": f"{type(e).__name__}: {e}",
        })

    def _publish_step_unlocked(self, rec: dict) -> None:
        if self._bus is None:
            return
        try:
            fam = self._engine_fams()
            labels = self._engine_labels(rec.get("engine"))
            fam["states"].inc(int(rec.get("d_states") or 0), **labels)
            fam["unique"].inc(int(rec.get("d_unique") or 0), **labels)
            dt = float(rec.get("dt") or 0.0)
            if dt > 0:
                fam["sps"].set(
                    round((rec.get("d_states") or 0) / dt, 1), **labels
                )
                fam["step"].observe(dt, **labels)
            q = rec.get("queue", rec.get("frontier"))
            if isinstance(q, (int, float)):
                fam["frontier"].set(q, **labels)
            if rec.get("load_factor") is not None:
                fam["load"].set(float(rec["load_factor"]), **labels)
            if rec.get("dedup") is not None:
                fam["dedup"].set(float(rec["dedup"]), **labels)
        except Exception as e:  # noqa: BLE001 - never break the run
            self._bus_drop(e)

    def _publish_record_unlocked(self, kind: str, rec: dict) -> None:
        """Non-step families sampled off ring records that already
        happen: occupancy gauges off ``occupancy`` records, the mesh
        shard-imbalance gauge off ``mesh`` records (docs/mesh.md)."""
        if self._bus is None or kind not in ("occupancy", "mesh"):
            return
        try:
            fam = self._engine_fams()
            labels = self._engine_labels()
            if kind == "occupancy" and rec.get("load_factor") is not None:
                fam["occupancy"].set(float(rec["load_factor"]), **labels)
            elif kind == "mesh":
                imb = rec.get("imbalance") or {}
                v = imb.get("max_over_mean", imb.get("ratio"))
                if v is not None:
                    fam["imbalance"].set(float(v), **labels)
        except Exception as e:  # noqa: BLE001 - never break the run
            self._bus_drop(e)

    # -- span binding (telemetry/spans.py) -----------------------------------

    def bind_span(self, span_id: Optional[str]) -> None:
        """Bind the engine-run span: subsequent step records (and the
        profiler's ``profile`` events) carry ``span=<id>`` so the Chrome
        exporter can nest step blocks under the run span."""
        with self._lock:
            self._bound_span = span_id

    def bound_span(self) -> Optional[str]:
        with self._lock:
            return self._bound_span

    # -- recording -----------------------------------------------------------

    def _append_unlocked(
        self, kind: str, fields: dict, t: Optional[float] = None
    ) -> dict:
        """Append one record; caller holds the lock."""
        self._seq += 1
        self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
        rec = {
            "seq": self._seq,
            "t": round(self._now() if t is None else t, 6),
            "kind": kind,
            **fields,
        }
        self._records.append(rec)
        return rec

    def record(self, kind: str, *, t: Optional[float] = None, **fields) -> dict:
        """Append one record; returns it (the stored dict)."""
        with self._lock:
            rec = self._append_unlocked(kind, fields, t)
            if not self._replaying:
                self._publish_record_unlocked(kind, rec)
            return rec

    def step(self, *, engine: str, states: int, unique: int,
             t: Optional[float] = None, **fields) -> dict:
        """One per-step (per host-sync / per-batch) record.  ``states`` and
        ``unique`` are CUMULATIVE run counters; deltas and the dedup ratio
        (fraction of generated states that were already visited) are
        derived here against the previous step record."""
        with self._lock:
            # rounded BEFORE use so a JSONL round-trip (which stores the
            # rounded value) reproduces the summary bit-for-bit
            now = round(self._now() if t is None else t, 6)
            if self._last_step is None:
                prev_t, prev_states, prev_unique = now, 0, 0
            else:
                prev_t, prev_states, prev_unique = self._last_step
            # cumulative counters are monotone by meaning, but concurrent
            # pool workers read-then-record without a common lock, so a
            # late writer can arrive with a stale (smaller) snapshot —
            # clamp so deltas stay >= 0 and the final summary never
            # under-reports
            states = max(int(states), prev_states)
            unique = max(int(unique), prev_unique)
            d_states = states - prev_states
            d_unique = unique - prev_unique
            if (
                self._bound_span is not None
                and not self._replaying
                and "span" not in fields
            ):
                # the engine-run span's id: the Chrome exporter nests
                # this step block under its lane (telemetry/spans.py)
                fields = {**fields, "span": self._bound_span}
            self._last_step = (now, states, unique)
            self._last_step_rec = rec = self._append_unlocked(
                "step",
                {
                    "engine": engine,
                    "dt": round(max(now - prev_t, 0.0), 6),
                    "states": int(states),
                    "unique": int(unique),
                    "d_states": int(d_states),
                    "d_unique": int(d_unique),
                    "dedup": (
                        round(1.0 - d_unique / d_states, 6)
                        if d_states > 0
                        else 0.0
                    ),
                    **fields,
                },
                t=now,
            )
            if not self._replaying:
                # the health model rides the step stream; transitions
                # (phase change, stall start/end) become ``health`` records
                # so exports carry the timeline.  Replays skip this — the
                # exported events come back verbatim instead.
                for ev in self._health.update(rec):
                    self._append_unlocked("health", ev, t=now)
                # live metrics bus: the per-sync engine families sample
                # the SAME host-synced values this record already holds
                # (zero extra device round-trips; telemetry/metrics.py)
                self._publish_step_unlocked(rec)
                if self._stall_inject is not None:
                    why = self._stall_inject(self._kind_counts["step"])
                    if why:
                        for ev in self._health.force_stall(why):
                            self._append_unlocked("health", ev, t=now)
            return rec

    def add(self, counter: str, n: float = 1) -> None:
        """Bump an aggregate counter (ring-independent; never evicted)."""
        with self._lock:
            self._counters[counter] = self._counters.get(counter, 0) + n

    def amend(self, rec: dict, **fields) -> None:
        """Update a previously returned record in place (under the lock:
        the Explorer may be snapshotting concurrently).  Used for values
        that are only measurable after the record's moment — e.g. a
        ``compile`` event recorded at engine acquisition whose duration is
        the NEXT device call's measured compile time (the lazy-jit path
        pays the compile there, not at acquisition)."""
        with self._lock:
            rec.update(fields)

    def add_bytes(self, *, h2d: int = 0, d2h: int = 0) -> None:
        if h2d:
            self.add("h2d_bytes", int(h2d))
        if d2h:
            self.add("d2h_bytes", int(d2h))

    def set_cartography(self, snap: dict) -> None:
        """Replace the latest search-cartography snapshot (the engines
        call this once per host sync with cumulative counters)."""
        with self._lock:
            self._cartography = dict(snap)

    def cartography(self) -> Optional[dict]:
        """Latest search-cartography snapshot, or None when the run was
        spawned without ``.telemetry(cartography=True)``."""
        with self._lock:
            return dict(self._cartography) if self._cartography else None

    def set_memory(self, snap: dict) -> None:
        """Replace the latest memory-ledger snapshot
        (``telemetry/memory.py``) and feed its growth forecast to the
        health model (the ``growth_oom_risk`` condition evaluates on the
        next step record's table load)."""
        with self._lock:
            self._memory = dict(snap)
            self._health.set_memory_forecast(
                (snap.get("next_rung") or {}).get("transient_bytes"),
                snap.get("budget_bytes"),
            )

    def memory(self) -> Optional[dict]:
        """Latest memory-ledger snapshot, or None when the run was
        spawned without ``.telemetry(memory=True)``."""
        with self._lock:
            return dict(self._memory) if self._memory else None

    def set_spill(self, snap: dict) -> None:
        """Replace the latest spill-tier snapshot (per-tier bytes, Bloom
        load, deferral/resolution tallies — ``docs/spill.md``)."""
        with self._lock:
            self._spill = dict(snap)
            if self._bus is not None and snap.get("spilled_fps") is not None:
                try:
                    self._engine_fams()["spilled"].set(
                        int(snap["spilled_fps"]), **self._engine_labels()
                    )
                except Exception as e:  # noqa: BLE001 - never break a run
                    self._bus_drop(e)

    def spill(self) -> Optional[dict]:
        """Latest spill-tier snapshot, or None when the run was spawned
        without ``CheckerBuilder.spill()``."""
        with self._lock:
            return dict(self._spill) if self._spill else None

    def set_roofline(self, snap: dict) -> None:
        """Replace the roofline-ledger snapshot (``telemetry/roofline.py``:
        per-stage FLOPs/bytes, op classes, MXU-candidate ranking,
        XLA-reconciliation verdict)."""
        with self._lock:
            self._roofline = dict(snap)

    def roofline(self) -> Optional[dict]:
        """Latest roofline snapshot, or None when the run was spawned
        without ``.telemetry(roofline=True)``."""
        with self._lock:
            return dict(self._roofline) if self._roofline else None

    def set_spill_armed(self, armed: bool = True) -> None:
        """Tell the health model the spill tier is armed: the
        ``growth_oom_risk`` condition downgrades to the informational
        ``spill_forecast`` — the run will evict, not die."""
        with self._lock:
            self._health.spill_armed = bool(armed)

    def set_spill_degraded(self) -> None:
        """The spill store's disk tier failed (ENOSPC / dead disk,
        docs/robustness.md): emit the sticky ``spill_degraded`` health
        transition (once) — the tier is pinned in host RAM."""
        with self._lock:
            for ev in self._health.mark_spill_degraded():
                self._append_unlocked("health", ev)

    def set_durability(self, snap: Optional[dict]) -> None:
        """Replace the latest durability snapshot
        (``stateright_tpu/checkpoint.py`` autosave status + supervised
        restart count; docs/robustness.md) — the outside-the-ring
        discipline of the other feature blocks.  ``None`` clears it
        (autosave disarmed after arming, e.g. the sharded engine's
        multi-controller fence)."""
        with self._lock:
            self._durability = dict(snap) if snap else None

    def durability(self) -> Optional[dict]:
        """Latest durability snapshot, or None when the run has neither
        autosave armed nor a supervision trail."""
        with self._lock:
            return dict(self._durability) if self._durability else None

    def set_fleet(self, snap: Optional[dict]) -> None:
        """Replace the latest fleet pool/queue snapshot
        (``stateright_tpu/fleet/``: slot occupancy + queued/terminal job
        keys) — the outside-the-ring discipline of the other feature
        blocks.  ``None`` clears it."""
        with self._lock:
            self._fleet = dict(snap) if snap else None
            if self._bus is None or not snap:
                return
            try:
                if self._fleet_fams is None:
                    # sibling telemetry module, NOT stateright_tpu.fleet
                    # (the import-hygiene guard in tests/test_fleet.py
                    # greps import lines for the subsystem name)
                    from . import metrics as _metrics

                    self._fleet_fams = _metrics.fleet_families(self._bus)
                fam = self._fleet_fams
                fam["queue"].set(len(snap.get("queued") or ()))
                fam["busy"].set(len(snap.get("running") or ()))
                if snap.get("slots") is not None:
                    fam["slots"].set(int(snap["slots"]))
                # cumulative pool tallies publish as monotone deltas
                for key, family in (
                    ("completed", "completed"), ("preemptions", "preemptions")
                ):
                    cur = int(snap.get(key) or 0)
                    prev = self._fleet_pub[key]
                    if cur > prev:
                        fam[family].inc(cur - prev)
                        self._fleet_pub[key] = cur
            except Exception as e:  # noqa: BLE001 - never break the pool
                self._bus_drop(e)

    def fleet(self) -> Optional[dict]:
        """Latest fleet pool/queue snapshot, or None when this recorder
        does not belong to a fleet scheduler."""
        with self._lock:
            return dict(self._fleet) if self._fleet else None

    def health(self) -> dict:
        """Live progress/health snapshot (health.py): phase, stall flag,
        novelty rate, EWMA throughput, drain ETA."""
        with self._lock:
            return self._health.snapshot()

    def inject_stall(self, reason: str = "injected") -> None:
        """Force the health model into a ``stall`` transition
        (deterministic preemption injection — ``fleet.PreemptionPlan``).
        The manufactured event rides the ring exactly like a detected
        stall, so consumers (the fleet scheduler's preemption monitor,
        the Explorer badge) cannot tell injection from detection — the
        whole signal path downstream of detection is what gets
        exercised.  The next step record with fresh inserts emits the
        paired ``stall_cleared``, like any real stall."""
        with self._lock:
            for ev in self._health.force_stall(reason):
                self._append_unlocked("health", ev)

    def arm_stall_injection(
        self, fn: Optional[Callable[[int], Optional[str]]]
    ) -> None:
        """Arm the in-band injection seam: ``fn(step_ordinal)`` runs
        inside every :meth:`step` (under the lock — keep it cheap and
        reentrancy-free) and a truthy return forces that reason's stall
        transition on the SAME step.  A polling injector can lose the
        race against a short run; this one cannot."""
        with self._lock:
            self._stall_inject = fn

    def close_run(self, done: bool = True) -> None:
        """Mark the run finished: the health phase transitions to ``done``
        (emitting the closing ``health`` record)."""
        if not done:
            return
        with self._lock:
            if self._replaying:
                return
            for ev in self._health.mark_done():
                self._append_unlocked("health", ev)

    def update_meta(self, **fields) -> None:
        """Locked meta mutation (engines annotate run config mid-run while
        the Explorer may be snapshotting concurrently)."""
        with self._lock:
            self.meta.update(fields)

    def meta_snapshot(self) -> dict:
        with self._lock:
            return dict(self.meta)

    # -- reading -------------------------------------------------------------

    def _now(self) -> float:
        return time.monotonic() - self._t0

    @property
    def t0_monotonic(self) -> float:
        """The recorder's clock origin (``time.monotonic()`` at
        creation).  The JSONL header carries it so a MERGED multi-run
        export (one fleet: scheduler + jobs + attempts) can re-align
        every run's relative timestamps onto one shared timeline —
        within a process the monotonic clock is common, so the
        alignment is exact (telemetry/export.py)."""
        return self._t0

    def rel(self, monotonic_t: float) -> float:
        """Map an absolute ``time.monotonic()`` stamp onto this recorder's
        clock (used when records are replayed from another process's log,
        e.g. the mp-BFS per-round history)."""
        return monotonic_t - self._t0

    def records(self, kind: Optional[str] = None) -> list[dict]:
        """Snapshot of the ring (oldest first), optionally filtered."""
        with self._lock:
            recs = list(self._records)
        if kind is not None:
            recs = [r for r in recs if r["kind"] == kind]
        return recs

    def kind_count(self, kind: str) -> int:
        """TOTAL records of ``kind`` ever appended — unlike ``records()``,
        this survives ring eviction (the ring is a window, the counts are
        the truth).  Consumers compare it against ``len(records(kind))``
        to detect a truncated window (telemetry/report.py)."""
        with self._lock:
            return int(self._kind_counts.get(kind, 0))

    def last_step(self) -> Optional[dict]:
        """The most recent step record (a copy), without scanning the
        ring — the ``--watch`` line polls this several times a second."""
        with self._lock:
            return dict(self._last_step_rec) if self._last_step_rec else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    @property
    def dropped(self) -> int:
        """Records evicted by the ring bound."""
        with self._lock:
            return self._seq - len(self._records)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def stages(self) -> Optional[dict]:
        """Per-stage wall-time breakdown (docs/perf.md): the ``stage_*_secs``
        aggregate counters the device engines accumulate (compile / device /
        growth), plus the host remainder, against the recorder's wall clock.
        None when no engine recorded stage counters (host checkers, or a
        recorder predating the attribution round).  ``host_secs`` is
        everything not attributed to a named stage — trace reconstruction,
        snapshot service, loop bookkeeping, and clock skew; a large value
        here is itself a finding."""
        with self._lock:
            counters = dict(self._counters)
            last_step = self._last_step
            t_offset = self._t_offset
        names = {
            k[len("stage_"):-len("_secs")]: float(v)
            for k, v in counters.items()
            if k.startswith("stage_") and k.endswith("_secs")
        }
        if not names:
            return None
        wall = None
        if last_step is not None:
            wall = max(last_step[0] - t_offset, 0.0)
        out = {f"{k}_secs": round(v, 6) for k, v in sorted(names.items())}
        if wall is not None:
            out["wall_secs"] = round(wall, 6)
            out["host_secs"] = round(max(wall - sum(names.values()), 0.0), 6)
        return out

    def summary(self) -> dict:
        """Aggregate run summary (JSON-safe scalars + small dicts): totals,
        throughput, dedup ratio, event counts, transfer volume, and the
        first/last occupancy samples when any were taken."""
        with self._lock:
            recs = list(self._records)
            counters = dict(self._counters)
            kind_counts = dict(self._kind_counts)
            seq = self._seq
            last_step = self._last_step
            t_offset = self._t_offset
            meta = dict(self.meta)
            cartography = (
                dict(self._cartography) if self._cartography else None
            )
            memory = dict(self._memory) if self._memory else None
            spill = dict(self._spill) if self._spill else None
            roofline = dict(self._roofline) if self._roofline else None
            durability = (
                dict(self._durability) if self._durability else None
            )
            fleet = dict(self._fleet) if self._fleet else None
        occ = [r for r in recs if r["kind"] == "occupancy"]
        out: dict = {
            **meta,
            "records": seq,
            "ring_len": len(recs),
            "dropped": seq - len(recs),
            "steps": kind_counts.get("step", 0),
        }
        if last_step is not None:
            t_last, states, unique = last_step
            # wall runs from recorder creation (not the first step record):
            # states found before the first host sync must pay their time
            wall = max(t_last - t_offset, 0.0)
            out["states"] = int(states)
            out["unique"] = int(unique)
            out["wall_secs"] = round(wall, 6)
            out["states_per_sec"] = (
                round(states / wall, 1) if wall > 0 else None
            )
            out["dedup_ratio"] = (
                round(1.0 - unique / states, 6) if states > 0 else 0.0
            )
        out["growth_events"] = kind_counts.get("growth", 0)
        for key in ("h2d_bytes", "d2h_bytes", "compile_cache_hits",
                    "compile_cache_misses", "compaction_hits"):
            out[key] = int(counters.get(key, 0))
        for key in ("prewarm_scheduled", "prewarm_consumed"):
            if counters.get(key):
                out[key] = int(counters[key])
        stages = self.stages()
        if stages is not None:
            out["stages"] = stages
        if cartography is not None:
            out["cartography"] = cartography
        if memory is not None:
            out["memory"] = memory
        if spill is not None:
            out["spill"] = spill
        if roofline is not None:
            out["roofline"] = roofline
        if durability is not None:
            out["durability"] = durability
        if fleet is not None:
            out["fleet"] = fleet
        if occ:
            keep = ("occupied", "load_factor", "max_bucket", "full_buckets",
                    "poisson_full_expect", "nbuckets")
            out["occupancy_samples"] = len(occ)
            out["occupancy_first"] = {
                k: occ[0].get(k) for k in keep if k in occ[0]
            }
            out["occupancy_last"] = {
                k: occ[-1].get(k) for k in keep if k in occ[-1]
            }
        return out

    def _reconcile_totals(self, summary: dict) -> None:
        """Restore totals the ring window cannot reconstruct from an
        exported summary (``export.from_jsonl``): sequence/kind counts and
        the cumulative step snapshot, so a round-trip through a file whose
        ring had evicted records still reproduces ``summary()``."""
        with self._lock:
            self._seq = max(self._seq, int(summary.get("records", 0)))
            for kind, key in (("step", "steps"),
                              ("growth", "growth_events")):
                if key in summary:
                    self._kind_counts[kind] = max(
                        self._kind_counts.get(kind, 0), int(summary[key])
                    )
            if summary.get("cartography") and self._cartography is None:
                self._cartography = dict(summary["cartography"])
            if summary.get("memory") and self._memory is None:
                self._memory = dict(summary["memory"])
            if summary.get("spill") and self._spill is None:
                self._spill = dict(summary["spill"])
            if summary.get("roofline") and self._roofline is None:
                self._roofline = dict(summary["roofline"])
            if summary.get("durability") and self._durability is None:
                self._durability = dict(summary["durability"])
            if summary.get("fleet") and self._fleet is None:
                self._fleet = dict(summary["fleet"])
            if summary.get("states") is not None and self._last_step:
                last_t = self._last_step[0]
                self._last_step = (
                    last_t, int(summary["states"]), int(summary["unique"])
                )
                if summary.get("wall_secs") is not None:
                    self._t_offset = last_t - float(summary["wall_secs"])

    def _reset_step_baseline(self) -> None:
        """Start a fresh delta baseline (JSONL replay at a run boundary:
        the next run's cumulative counters restart from zero and must not
        be clamped against the previous run's totals)."""
        with self._lock:
            self._last_step = None

    # -- export (see export.py) ----------------------------------------------

    def to_jsonl(self, path, append: bool = False) -> None:
        from .export import to_jsonl

        to_jsonl(self, path, append=append)

    def to_chrome_trace(self, path) -> None:
        from .export import to_chrome_trace

        to_chrome_trace(self, path)

    @classmethod
    def from_jsonl(cls, path) -> "FlightRecorder":
        from .export import from_jsonl

        return from_jsonl(path)
