"""Post-run report: the artifact a human reads after an unattended run.

``write_report(checker, path)`` renders one completed check into a JSON
document at ``path`` plus a sibling markdown rendering (``path`` with the
extension swapped for ``.md``) — combining the run totals, the search
cartography (``ops/cartography.py``), the deterministic health timeline
(``health.phase_timeline``), growth events, and the model's audit /
sanitizer status.  Wired as ``CheckerBuilder.report(PATH)`` (written at
the first ``join()`` after completion), the per-example ``report`` CLI
verb, and ``bench.py``'s paxos-3 / 2pc-7 legs; gated by
``regress.py --cartography``.

Determinism contract (pinned by ``tests/test_cartography.py``): for a
fixed model/config the JSON body is byte-stable across runs — every field
is count-derived (state totals, histograms, phase transitions at step
granularity, growth capacity ladders), and the single volatile field is
the ``generated_at`` header stamped at write time.  Wall-clock data
(stage attribution, throughput, EWMA series) varies run to run and lives
in the MARKDOWN rendering only, clearly sectioned as non-deterministic.

Schema versioning: ``v`` (:data:`REPORT_V`) at the top level; the
embedded cartography block carries its own ``v``
(``ops.cartography.CARTOGRAPHY_V``).

Run identity (docs/telemetry.md "Comparing runs"): the deterministic
body carries a ``config`` block — the canonical run configuration
(model, instance signature, engine, flag set, encoding, device spec,
git rev) plus its 16-hex ``key`` (:func:`config_key`) — and the written
document additionally carries a ``run_id`` (and, for runs resumed from
a snapshot, the parent's ``parent_run_id``) in the volatile header next
to ``generated_at``.  :data:`VOLATILE_KEYS` is the SCHEMA for what is
volatile: the diff engine (``telemetry/diff.py``) scrubs exactly this
tuple, so a new volatile header field is ignored there automatically
instead of by hand-listing.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .health import phase_timeline

REPORT_V = 1

# volatile identity/header fields stamped at write time — everything a
# cross-run diff must ignore lives HERE (telemetry/diff.py consults this
# tuple at diff time; never hand-list these downstream)
VOLATILE_KEYS = (
    "generated_at", "run_id", "parent_run_id",
    # sweep-instance archives (stateright_tpu/sweep/, docs/sweep.md):
    # the sweep's run id + the member key ride the header so a sweep
    # instance diffs cleanly against its sequential oracle run
    "sweep_id", "instance_key",
    # fleet-campaign archives (stateright_tpu/fleet/, docs/fleet.md):
    # the campaign id + tenant key group a fleet's jobs in the run
    # list, and a fleet job must diff IDENTICAL against its solo run
    "campaign_id", "job_key",
)

# growth-record fields that are count-derived (the record's ``t``/``seq``
# are wall-clock/ordering bookkeeping and stay out of the report body)
_GROWTH_KEYS = ("status", "unique", "cap", "qcap", "cand", "fcap", "bucket")


def _expectation_name(prop) -> str:
    # Expectation is a proper enum; its .name is ALWAYS/SOMETIMES/...
    return getattr(prop.expectation, "name", str(prop.expectation)).lower()


def _git_rev() -> Optional[str]:
    """Short git revision of the checkout this package runs from (walks
    up from the package dir; plain file reads, no subprocess — the
    report writer must never fork).  None outside a git checkout."""
    import pathlib

    try:
        for p in pathlib.Path(__file__).resolve().parents:
            head = p / ".git" / "HEAD"
            if not head.is_file():
                continue
            ref = head.read_text().strip()
            if not ref.startswith("ref:"):
                return ref[:12]  # detached HEAD: the hash itself
            name = ref.split(None, 1)[1]
            ref_path = p / ".git" / name
            if ref_path.is_file():
                return ref_path.read_text().strip()[:12]
            packed = p / ".git" / "packed-refs"
            if packed.is_file():
                for line in packed.read_text().splitlines():
                    if line.endswith(" " + name):
                        return line.split()[0][:12]
            return None
    except OSError:
        return None
    return None


def config_key(config: dict) -> str:
    """Canonical 16-hex key over a ``config`` block (minus the ``key``
    field itself): sorted-key compact JSON, sha256-truncated.  Two runs
    share a ``config_key`` iff they are the same measurement
    configuration — the grouping key for registry trends."""
    import hashlib

    body = {k: v for k, v in config.items() if k != "key"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_config(checker) -> dict:
    """The report's deterministic ``config`` block: the canonical run
    configuration the diff engine classifies flag deltas over
    (``telemetry/diff.py``; docs/telemetry.md "Comparing runs").

    ``instance.sig`` hashes the init-state fingerprints + tensor shape +
    property count, so different instance arguments (paxos-2 vs paxos-3)
    get different keys without per-model plumbing; ``flags`` records the
    feature set the engines actually resolved (builder + env knobs);
    ``device``/``git_rev`` pin where and at what revision the run
    happened (perf-class aspects for the diff)."""
    import hashlib

    model = checker.model
    tag = getattr(checker, "_engine_tag", None)
    if tag == "single":
        tag = "wavefront"
    # instance identity must be ENGINE-INDEPENDENT (a wavefront-vs-BFS
    # pair of the same instance is comparable): host checkers carry no
    # .tensor, so fall back to the model's cached twin — init
    # fingerprints alone can coincide across instance sizes (all-zero
    # init rows; the _model_sig rationale), the tensor shape breaks the
    # tie
    tensor = getattr(checker, "tensor", None)
    if tensor is None:
        try:
            from ..parallel.tensor_model import twin_or_none

            tensor = twin_or_none(model)
        except Exception:  # noqa: BLE001 - identity must never break
            tensor = None
    props = list(model.properties())
    try:
        fps = sorted(
            int(model.fingerprint_state(s)) for s in model.init_states()
        )
    except Exception:  # noqa: BLE001 - identity must never break a report
        fps = []
    sig_src = fps + [
        int(getattr(tensor, "width", 0) or 0),
        int(getattr(tensor, "max_actions", 0) or 0),
        len(props),
    ] + sorted(p.name for p in props)
    sig = hashlib.sha256(json.dumps(sig_src).encode()).hexdigest()[:16]
    flags = {
        "telemetry": getattr(checker, "flight_recorder", None) is not None,
        "cartography": bool(getattr(checker, "_cartography", False)),
        "memory": getattr(checker, "_mem_ledger", None) is not None,
        "roofline": getattr(checker, "_roofline_ledger", None) is not None,
        "checked": bool(getattr(checker, "_checked", False)),
        "prededup": bool(getattr(checker, "_prededup", False)),
        "spill": bool(getattr(checker, "_spill", False)),
        # MXU recast round (ops/mxu.py): a perf-class knob — counts are
        # contractually bit-identical, only the step program's shapes
        # change (the diff engine classifies an on/off pair PERF-ONLY)
        "mxu": getattr(checker, "_mxu", None) is not None,
        # sweep membership (stateright_tpu/sweep/): per-instance counts
        # are contractually bit-identical to the sequential run, so the
        # diff engine classes the flag "identical" (docs/sweep.md)
        "sweep": bool(getattr(checker, "_is_sweep_instance", False)),
        # active reduction only: a por() run that FELL BACK ran full
        # expansion and must diff as such (the fallback reason lives in
        # the por block)
        "por": bool(getattr(checker, "_por", False)),
        "symmetry": getattr(checker, "_symmetry", None) is not None,
        "prewarm": bool(getattr(checker, "_prewarm", False)),
        "pallas": bool(getattr(checker, "_pallas", False)),
        "compile_cache": bool(
            getattr(checker, "_compile_cache_dir", None)
        ),
    }
    try:
        import jax

        d0 = jax.devices()[0]
        device = str(getattr(d0, "device_kind", None) or d0.platform)
    except Exception:  # noqa: BLE001 - identity must never break a report
        device = None
    # the prefix target is instance identity (a 4000-state prefix is a
    # different measurement than the full enumeration): device engines
    # and mp store it as _target, the thread-pool checkers keep only the
    # builder options
    target = getattr(checker, "_target", None)
    if target is None:
        target = getattr(
            getattr(checker, "_options", None), "target_state_count", None
        )
    cfg = {
        "model": type(model).__name__,
        "instance": {
            "sig": sig,
            "target": target,
        },
        "engine": tag or type(checker).__name__,
        "encoding": getattr(tensor, "network_encoding", None),
        "flags": flags,
        "device": device,
        "git_rev": _git_rev(),
    }
    cfg["key"] = config_key(cfg)
    return cfg


def build_report(checker) -> dict:
    """The deterministic report body (no ``generated_at``; JSON-safe).

    Works on any completed checker; sections appear only when their data
    source exists (cartography needs ``.telemetry(cartography=True)``,
    growth/health need a flight recorder, audit needs a preflight run)."""
    model = checker.model
    props = list(model.properties())
    disc = checker.discoveries()
    tag = getattr(checker, "_engine_tag", None)
    if tag == "single":
        tag = "wavefront"  # the recorder's naming (parallel/_base.py)
    # is_done() means STOPPED, not "space exhausted": a deadline-cut run
    # is done-in-that-sense but incomplete, and the report is exactly the
    # artifact that must not claim otherwise
    timed_out = bool(getattr(checker, "timed_out", False))
    done = checker.is_done() and not timed_out
    totals = {
        "states": checker.state_count(),
        "unique": checker.unique_state_count(),
        "max_depth": getattr(checker, "max_depth", lambda: None)(),
        "done": done,
    }
    if timed_out:
        totals["timed_out"] = True
    out: dict = {
        "v": REPORT_V,
        "model": type(model).__name__,
        "engine": tag or type(checker).__name__,
        # canonical run configuration + config_key (deterministic for a
        # fixed model/config/machine/checkout): what the registry indexes
        # and the diff engine classifies flag deltas over
        "config": build_config(checker),
        "totals": totals,
        "properties": [
            {
                "name": p.name,
                "expectation": _expectation_name(p),
                "discovery": p.name in disc,
            }
            for p in props
        ],
    }
    cart = None
    if hasattr(checker, "cartography"):
        cart = checker.cartography()
    if cart is not None:
        out["cartography"] = cart
    # memory ledger (telemetry/memory.py): the DETERMINISTIC analytic
    # block only — per-buffer bytes at the final capacities + the next
    # rung's growth-transient forecast.  Live device stats and the
    # machine-local budget stay OUT of the JSON body (they vary by
    # machine and moment; the markdown rendering carries them instead).
    mem_fn = getattr(checker, "memory", None)
    if callable(mem_fn):
        mem = mem_fn(live=False)
        if mem is not None:
            out["memory"] = mem
    # roofline cost ledger (telemetry/roofline.py, docs/roofline.md):
    # the DETERMINISTIC static block only — per-stage analytic
    # FLOPs/bytes, op classes, per-action attribution, MXU-candidate
    # ranking.  XLA reconciliation numbers (backend-specific) and the
    # device spec / wall-clock ceilings stay OUT of the JSON body; the
    # markdown rendering carries them instead.
    roof_fn = getattr(checker, "roofline", None)
    if callable(roof_fn):
        roof = roof_fn(live=False)
        if roof is not None:
            out["roofline"] = roof
    # partial-order reduction (docs/analysis.md): the network encoding in
    # use, the fallback reason when reduction is off, and the
    # reduced-vs-full tallies — count-derived for a fixed model/config,
    # so the block stays report-deterministic like the cartography
    por_fn = getattr(checker, "por_status", None)
    if callable(por_fn):
        por = por_fn()
        if por is not None:
            out["por"] = por
    # spill tier (stateright_tpu/spill/, docs/spill.md): count-derived
    # for a fixed model/config/budget — evictions fire at deterministic
    # growth boundaries and the Bloom is a pure function of the spilled
    # set, so the block stays report-deterministic like the cartography
    sp_fn = getattr(checker, "spill_status", None)
    if callable(sp_fn):
        sp = sp_fn()
        if sp is not None:
            out["spill"] = sp
    # durability (stateright_tpu/checkpoint.py + supervisor.py,
    # docs/robustness.md): the DETERMINISTIC subset only — the autosave
    # cadence config, the supervised restart count, and the degradation
    # events.  Generation counts / checkpoint ages are wall-clock-shaped
    # and live in the markdown rendering, like throughput.
    dur_fn = getattr(checker, "durability_status", None)
    if callable(dur_fn):
        dur = dur_fn(live=False)
        if dur is not None:
            out["durability"] = dur
    rec = getattr(checker, "flight_recorder", None)
    if rec is not None:
        growth = []
        for r in rec.records("growth"):
            growth.append(
                {k: r[k] for k in _GROWTH_KEYS if k in r}
            )
        out["growth_events"] = growth
        if rec.kind_count("growth") > len(growth):
            out["growth_events_truncated"] = True
        # the COUNT-derived health replay (health.py separates this from
        # the wall-clock EWMA/ETA signals, which never enter the report).
        # The ring is a bounded window: a run with more syncs than the
        # telemetry capacity loses its earliest steps, and a timeline
        # replayed from a mid-run prefix misclassifies phases (the true
        # peak is gone) — flag it instead of silently presenting the
        # window as the whole run.
        steps = rec.records("step")
        out["health_timeline"] = phase_timeline(steps)
        if rec.kind_count("step") > len(steps):
            out["health_timeline_truncated"] = True
        out["final_phase"] = (
            "done" if done else rec.health().get("phase")
        )
    audit = getattr(model, "_audit_report", None)
    if audit is not None:
        out["audit"] = {
            "ok": audit.ok,
            "errors": len(audit.errors),
            "warnings": len(audit.warnings),
            "rules": sorted({f.rule_id for f in audit.findings}),
        }
        sanitizer = (audit.metrics or {}).get("sanitizer")
        if sanitizer is not None:
            out["sanitizer"] = {
                k: sanitizer.get(k)
                for k in ("sites", "proved", "undecided", "rules")
            }
            out["sanitizer"]["checked_run"] = bool(
                getattr(checker, "_checked", False)
            )
    return out


def _bar(n: int, peak: int, width: int = 30) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1 if n else 0, round(width * n / peak))


def _hist_lines(values, label_of) -> list:
    peak = max(values) if values else 0
    return [
        f"  {label_of(i):>12}  {v:>10}  {_bar(v, peak)}"
        for i, v in enumerate(values)
    ]


def render_markdown(report: dict, rec=None, roofline_live=None) -> str:
    """Human rendering of a report body.  ``rec`` (the run's live
    FlightRecorder) adds the WALL-CLOCK section — stage attribution and
    throughput — which is deliberately absent from the JSON body (it
    varies run to run; docs/telemetry.md "Reading a run report").
    ``roofline_live`` (``checker.roofline()``'s default view) adds the
    achieved-vs-ceiling roofline estimate; falls back to the recorder's
    spawn-time snapshot (spec + verdicts, no achieved block)."""
    t = report.get("totals", {})
    lines = [
        f"# Run report — {report.get('model')} ({report.get('engine')})",
        "",
        f"- states generated: **{t.get('states')}**",
        f"- unique states: **{t.get('unique')}**",
        f"- max depth: **{t.get('max_depth')}**",
        f"- completed: **{t.get('done')}**"
        + (" (cut short by the run deadline)" if t.get("timed_out") else ""),
        "",
        "## Properties",
        "",
    ]
    for p in report.get("properties", []):
        verdict = (
            "discovery found" if p["discovery"] else "no discovery"
        )
        lines.append(f"- `{p['name']}` ({p['expectation']}): {verdict}")
    cart = report.get("cartography")
    if cart:
        lines += ["", "## Search cartography", "", "Depth histogram "
                  "(fresh inserts per BFS depth):", "```"]
        lines += _hist_lines(cart.get("depth_hist", []), lambda i: f"d={i}")
        lines += ["```", "", "Action histogram (successors generated per "
                  "action slot):", "```"]
        lines += _hist_lines(
            cart.get("action_hist", []), lambda i: f"a{i}"
        )
        lines += ["```", ""]
        lines.append(
            f"- fresh inserts: {cart.get('fresh_inserts')}  /  "
            f"duplicate hits: {cart.get('duplicate_hits')}"
        )
        for p in cart.get("props", []):
            lines.append(
                f"- property `{p['name']}`: evaluated {p['evaluated']} "
                f"rows, condition held on {p['condition_hits']}"
            )
        imb = cart.get("shard_imbalance")
        if imb:
            lines.append(
                f"- shard imbalance: max={imb['max']} mean={imb['mean']} "
                f"ratio={imb['ratio']} (1.0 = balanced)"
            )
        if cart.get("routed_candidates") is not None:
            lines.append(
                f"- all-to-all routed candidates: "
                f"{cart['routed_candidates']}"
            )
    mem = report.get("memory")
    if mem:
        from .memory import fmt_bytes

        lines += ["", "## Memory (analytic)", ""]
        lines.append(
            f"- device-resident carry: **{fmt_bytes(mem.get('total_bytes'))}**"
            f" at capacity {mem.get('capacity')}"
            + (
                f" over {mem['devices']} device(s) "
                f"({fmt_bytes(mem.get('per_device_bytes'))}/device)"
                if mem.get("devices")
                else ""
            )
        )
        nxt = mem.get("next_rung") or {}
        if nxt:
            lines.append(
                f"- next growth rung (capacity {nxt.get('capacity')}): "
                f"{fmt_bytes(nxt.get('total_bytes'))} steady, "
                f"{fmt_bytes(nxt.get('transient_bytes'))} migration "
                "transient (old + new carry live across the swap)"
            )
        buffers = mem.get("buffers") or {}
        if buffers:
            top = sorted(
                buffers.items(), key=lambda kv: kv[1], reverse=True
            )[:6]
            lines.append(
                "- largest buffers: "
                + ", ".join(f"{k}={fmt_bytes(v)}" for k, v in top)
            )
    roof = report.get("roofline")
    if roof:
        from .memory import fmt_bytes

        lines += ["", "## Roofline (static cost model)", ""]
        lines.append(
            f"- per-step analytic totals: **{roof['totals'].get('flops'):,}"
            f" FLOPs**, **{fmt_bytes(roof['totals'].get('bytes'))} moved**"
            + (
                f" (intensity {roof['totals']['intensity']} FLOPs/byte)"
                if roof["totals"].get("intensity") is not None else ""
            )
        )
        lines += ["", "| stage | FLOPs | bytes | intensity | top class |",
                  "|---|---|---|---|---|"]
        for name, s in (roof.get("stages") or {}).items():
            classes = s.get("classes") or {}
            top = max(
                classes, key=lambda k: classes[k]["bytes"], default="-"
            ) if classes else "-"
            lines.append(
                f"| {name} | {s.get('flops'):,} | "
                f"{fmt_bytes(s.get('bytes_read', 0) + s.get('bytes_written', 0))}"
                f" | {s.get('intensity', '-')} | {top} |"
            )
        for c in (roof.get("mxu_candidates") or [])[:4]:
            lines.append(
                f"- MXU candidate #{c['rank']}: `{c['op']}` in "
                f"`{c['stage']}` moving {fmt_bytes(c['bytes'])}/step "
                f"({c['rule']})"
            )
    por = report.get("por")
    if por:
        lines += ["", "## Partial-order reduction", ""]
        enc = por.get("encoding")
        lines.append(
            f"- network encoding: **{enc or 'model-specific twin'}**"
            + (
                "" if enc != "slot-multiset" else
                " (delivery writes are message DATA here — re-compile "
                "with per_channel_() for real reduction; JX305)"
            )
        )
        if por.get("enabled"):
            lines.append(
                f"- rows expanded with a reduced ample set: "
                f"**{por.get('rows_reduced', 0)}** "
                f"({por.get('candidates_masked', 0)} candidates never "
                f"generated; {por.get('rows_full_proviso', 0)} "
                "proviso-forced full re-expansions)"
            )
        else:
            lines.append(
                f"- reduction fell back to full expansion: "
                f"{por.get('fallback')}"
            )
    sp = report.get("spill")
    if sp:
        from .memory import fmt_bytes

        lines += ["", "## Spill tier", ""]
        lines.append(
            f"- evictions: **{sp.get('evictions')}** — "
            f"{sp.get('spilled_fps')} fingerprints off-device "
            f"(host {fmt_bytes(sp.get('host_bytes'))}, "
            f"disk {fmt_bytes(sp.get('disk_bytes'))}, "
            f"index {fmt_bytes(sp.get('index_bytes'))})"
        )
        lines.append(
            f"- Bloom filter: {sp.get('bloom_bits')} bits, "
            f"k={sp.get('bloom_k')}, est. false-positive rate "
            f"{sp.get('bloom_est_false_pos')}"
        )
        lines.append(
            f"- deferred to host resolution: {sp.get('deferred')} "
            f"candidates ({sp.get('resolved_dups')} true duplicates, "
            f"{sp.get('resolved_novel')} Bloom false positives "
            "re-injected)"
        )
        if sp.get("queue_offloaded"):
            lines.append(
                f"- queue overflow: {sp.get('queue_offloaded')} frontier "
                f"rows offloaded to host, {sp.get('queue_refilled')} "
                "refilled"
            )
    dur = report.get("durability")
    if dur:
        lines += ["", "## Durability", ""]
        auto = dur.get("autosave")
        if auto:
            lines.append(
                f"- autosave: every {auto.get('every_secs')}s, newest "
                f"{auto.get('keep')} generations kept"
                + (
                    f" ({auto.get('generations')} written this run"
                    + (
                        f", last age {auto.get('last_checkpoint_age_secs')}s"
                        if auto.get("last_checkpoint_age_secs") is not None
                        else ""
                    )
                    + ")"
                    if auto.get("generations") is not None
                    else ""
                )
            )
            if auto.get("failures"):
                lines.append(
                    f"- **{auto['failures']} checkpoint write(s) FAILED** "
                    "— durability degraded (docs/robustness.md)"
                )
        lines.append(f"- supervised restarts: **{dur.get('restarts', 0)}**")
        for d in dur.get("degradations", []):
            lines.append(f"- degradation: `{d}`")
    timeline = report.get("health_timeline")
    # a truncated-to-empty window (a tiny ring whose tail slots went to
    # span/health records) still owes the reader the truncation note —
    # hiding the whole section would present the mid-run cut as "no
    # timeline recorded"
    if timeline or report.get("health_timeline_truncated"):
        lines += ["", "## Health timeline (count-derived)", ""]
        if report.get("health_timeline_truncated"):
            lines.append(
                "- **truncated**: the run outlived the telemetry ring; "
                "this timeline starts mid-run (raise "
                "`.telemetry(capacity=...)` for the full series)"
            )
        prev = None
        for e in timeline or []:
            if e["phase"] != prev:
                lines.append(
                    f"- step {e['step']}: phase `{e['phase']}` "
                    f"(unique={e['unique']}, novelty={e['novelty']})"
                )
                prev = e["phase"]
        lines.append(f"- final phase: `{report.get('final_phase')}`")
    growth = report.get("growth_events")
    if growth is not None:
        lines += ["", "## Growth events", ""]
        if report.get("growth_events_truncated"):
            lines.append("- **truncated**: earliest growths evicted "
                         "from the telemetry ring")
        if not growth:
            lines.append("- none (buffers pre-sized for the space)")
        for g in growth:
            caps = ", ".join(
                f"{k}={v}" for k, v in g.items()
                if k not in ("status", "unique")
            )
            lines.append(
                f"- `{g.get('status')}` at unique={g.get('unique')} "
                f"({caps})"
            )
    audit = report.get("audit")
    if audit:
        lines += ["", "## Audit / sanitizer", "",
                  f"- audit: {'CLEAN' if audit['ok'] else 'ERRORS'} "
                  f"({audit['errors']} error(s), {audit['warnings']} "
                  f"warning(s); rules: "
                  f"{', '.join(audit['rules']) or 'none'})"]
        san = report.get("sanitizer")
        if san:
            lines.append(
                f"- sanitizer: {san.get('sites')} indexed site(s), "
                f"{san.get('proved')} proved in range, "
                f"{san.get('undecided')} undecided; checked run: "
                f"{san.get('checked_run')}"
            )
    if rec is not None:
        # everything below varies run to run — markdown only, never JSON
        lines += ["", "## Wall clock (non-deterministic)", ""]
        summary = rec.summary()
        if summary.get("wall_secs") is not None:
            lines.append(f"- wall: {summary['wall_secs']}s")
        if summary.get("states_per_sec") is not None:
            lines.append(
                f"- throughput: {summary['states_per_sec']} states/s"
            )
        stages = rec.stages()
        if stages:
            for k, v in stages.items():
                lines.append(f"- {k}: {v}")
        # the roofline's wall-clock half (telemetry/roofline.py):
        # achieved-vs-ceiling estimates + per-stage bound verdicts —
        # device-spec- and machine-dependent, so markdown only, never
        # the deterministic JSON body
        roofl = roofline_live or (
            rec.roofline() if hasattr(rec, "roofline") else None
        )
        if roofl:
            spec = roofl.get("device_spec")
            if spec:
                lines.append(
                    f"- roofline device spec: {spec.get('name')} "
                    f"(peak {spec.get('peak_flops'):.3g} FLOP/s, HBM "
                    f"{spec.get('hbm_bytes_per_sec'):.3g} B/s, ridge "
                    f"{spec.get('ridge'):.2f} FLOPs/byte; "
                    f"{spec.get('src')})"
                )
            verdicts = roofl.get("verdicts") or {}
            bound = [
                f"{k}={v['verdict']}" for k, v in verdicts.items()
                if v.get("verdict") != "unknown"
            ]
            if bound:
                lines.append("- stage roofline verdicts: " + ", ".join(bound))
            ach = roofl.get("achieved")
            if ach:
                bits = [
                    f"{ach['bytes_per_sec']:.3g} B/s",
                    f"{ach['flops_per_sec']:.3g} FLOP/s",
                ]
                if ach.get("frac_of_hbm_ceiling") is not None:
                    bits.append(
                        f"{100 * ach['frac_of_hbm_ceiling']:.2f}% of the "
                        "HBM ceiling"
                    )
                lines.append(
                    "- achieved (est., device time): " + ", ".join(bits)
                )
        live = rec.memory() if hasattr(rec, "memory") else None
        if live and (live.get("device") or live.get("budget_bytes")):
            from .memory import fmt_bytes

            dev = live.get("device") or {}
            bits = []
            if dev.get("bytes_in_use") is not None:
                bits.append(f"in use {fmt_bytes(dev['bytes_in_use'])}")
            if dev.get("peak_bytes_in_use") is not None:
                bits.append(f"peak {fmt_bytes(dev['peak_bytes_in_use'])}")
            if live.get("budget_bytes"):
                bits.append(
                    f"budget {fmt_bytes(live['budget_bytes'])} "
                    f"({live.get('budget_src')})"
                )
            if bits:
                lines.append("- device memory: " + ", ".join(bits))
    lines.append("")
    return "\n".join(lines)


def identity_doc(checker, body: dict) -> dict:
    """The written run-report document: the volatile identity header
    (exactly :data:`VOLATILE_KEYS` — the stamp, the run id, and for
    snapshot-resumed runs the parent's id, so the registry links
    kill+resume chains) ahead of the deterministic ``body``.  The ONE
    header assembly, shared by :func:`write_report` and the run
    registry — a new volatile field lands here and in
    :data:`VOLATILE_KEYS` together."""
    import datetime

    doc = {
        "generated_at": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "run_id": getattr(checker, "run_id", None),
    }
    parent = getattr(checker, "parent_run_id", None)
    if parent:
        doc["parent_run_id"] = parent
    doc.update(body)
    return doc


def write_report(checker, path: str) -> dict:
    """Render ``checker`` into ``path`` (JSON) + the sibling markdown.

    Returns the deterministic body (without the ``generated_at`` header
    stamped into the file).  The JSON is written with sorted keys OFF —
    insertion order is part of the pinned byte layout — and a trailing
    newline."""
    if os.path.splitext(path)[1] == ".md":
        # The markdown sibling is derived by swapping the extension; a .md
        # target would collapse both renderings onto one file and the JSON
        # body would be silently overwritten.
        raise ValueError(
            f"report path {path!r} ends in .md — pass the JSON path; the "
            "markdown rendering lands next to it as <path-stem>.md"
        )
    from ._atomic import atomic_write_json, atomic_write_text

    body = build_report(checker)
    doc = identity_doc(checker, body)
    # atomic (docs/robustness.md): a crash mid-write leaves the previous
    # report intact, never a torn JSON a later diff/regress gate chokes on
    atomic_write_json(path, doc)
    md_path = os.path.splitext(path)[0] + ".md"
    rec = getattr(checker, "flight_recorder", None)
    roof_fn = getattr(checker, "roofline", None)
    roofline_live = roof_fn() if callable(roof_fn) else None
    # the live durability view (generation counts, checkpoint age) rides
    # the markdown like the rest of the wall-clock data
    md_body = dict(body)
    dur_fn = getattr(checker, "durability_status", None)
    if callable(dur_fn):
        live_dur = dur_fn(live=True)
        if live_dur is not None:
            md_body["durability"] = live_dur
    atomic_write_text(
        md_path,
        render_markdown(md_body, rec=rec, roofline_live=roofline_live),
    )
    return body
