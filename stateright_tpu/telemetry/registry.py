"""Persistent run registry: the append-only ledger behind cross-run
observability (docs/telemetry.md "Comparing runs").

Layout under the registry root (``CheckerBuilder.runs(DIR)`` /
``STATERIGHT_TPU_RUN_DIR``):

 - ``runs/<run_id>.json`` — the archived run-report document: the same
   deterministic body ``telemetry/report.py`` writes, plus the volatile
   identity header (``generated_at``, ``run_id``, ``parent_run_id``).
 - ``index.jsonl`` — one append-only, versioned index record per run
   (``v`` = :data:`REGISTRY_V`): the canonical ``config_key`` (model,
   instance signature, engine, flag set, encoding, device spec, git rev
   — ``report.build_config``) plus the headline metrics
   (states/unique/depth/done/discoveries, and wall-clock throughput +
   per-stage attribution when a flight recorder was attached).

Contract (pinned by ``tests/test_run_ledger.py``, the memory ledger's
strongest form): the registry is pure host-side post-run I/O — on or
off, the step jaxpr is bit-identical and the engine cache unkeyed, both
engines.

Consumers: the diff engine (``telemetry/diff.py``), the ``compare`` /
``runs`` CLI verbs (``models/_cli.py``), the Explorer's ``/.runs``
endpoints + multi-run dashboard, and ``bench.py``'s per-leg
registration.
"""

from __future__ import annotations

import json
import os
from typing import Optional

REGISTRY_V = 1
ENV_RUN_DIR = "STATERIGHT_TPU_RUN_DIR"


def resolve_run_dir(builder_dir: Optional[str] = None) -> Optional[str]:
    """The effective registry root: the builder's ``runs(DIR)`` wins,
    else the ``STATERIGHT_TPU_RUN_DIR`` env knob; None = registry off."""
    return builder_dir or os.environ.get(ENV_RUN_DIR) or None


def index_record(doc: dict, checker=None, leg: Optional[str] = None) -> dict:
    """One versioned index line for an archived report document.

    The headline carries the count-derived totals plus — when the run had
    a flight recorder — the wall-clock throughput and per-stage
    attribution, so trend views and perf diffs read the index alone."""
    totals = doc.get("totals") or {}
    headline = {
        "states": totals.get("states"),
        "unique": totals.get("unique"),
        "max_depth": totals.get("max_depth"),
        "done": totals.get("done"),
        "discoveries": sorted(
            p["name"] for p in doc.get("properties") or []
            if p.get("discovery")
        ),
    }
    if checker is not None:
        rec_ = getattr(checker, "flight_recorder", None)
        if rec_ is not None:
            summ = rec_.summary()
            if summ.get("states_per_sec") is not None:
                headline["states_per_sec"] = summ["states_per_sec"]
            if summ.get("wall_secs") is not None:
                headline["wall_secs"] = summ["wall_secs"]
            stages = rec_.stages()
            if stages:
                headline["stages"] = stages
    cfg = doc.get("config") or {}
    rec = {
        "v": REGISTRY_V,
        "run_id": doc.get("run_id"),
        "config_key": cfg.get("key"),
        "model": doc.get("model"),
        "engine": doc.get("engine"),
        "generated_at": doc.get("generated_at"),
        "path": f"runs/{doc.get('run_id')}.json",
        "headline": headline,
    }
    if doc.get("parent_run_id"):
        rec["parent_run_id"] = doc["parent_run_id"]
    if doc.get("sweep_id"):
        # sweep-instance archive (stateright_tpu/sweep/, docs/sweep.md):
        # the sweep id groups the family's members in `_cli runs` and
        # the Explorer run list; instance_key names this member
        rec["sweep_id"] = doc["sweep_id"]
        if doc.get("instance_key"):
            rec["instance_key"] = doc["instance_key"]
    if doc.get("campaign_id"):
        # fleet-campaign archive (stateright_tpu/fleet/, docs/fleet.md):
        # the campaign id groups a fleet's jobs under one expandable
        # row in `_cli runs` and the Explorer run list (the sweep
        # pattern); job_key names the tenant
        rec["campaign_id"] = doc["campaign_id"]
        if doc.get("job_key"):
            rec["job_key"] = doc["job_key"]
    if leg:
        rec["leg"] = leg
    return rec


class RunRegistry:
    """Append-only run ledger rooted at ``root`` (created on demand)."""

    def __init__(self, root: str):
        self.root = str(root)
        self.runs_dir = os.path.join(self.root, "runs")
        self.index_path = os.path.join(self.root, "index.jsonl")

    # -- writing -------------------------------------------------------------

    def record(
        self,
        checker,
        *,
        leg: Optional[str] = None,
        body: Optional[dict] = None,
    ) -> dict:
        """Archive one completed run; returns the appended index record.

        ``body`` reuses a report the caller already built (``report()``'s
        write, bench's embeds) — else :func:`report.build_report` runs on
        the checker (it reconstructs discovery paths, so callers holding
        a body should pass it); ``leg`` tags the record (bench legs)."""
        from .report import build_report, identity_doc

        if body is None:
            body = build_report(checker)
        doc = identity_doc(checker, body)
        # fleet-campaign tags ride the checker (set by the scheduler's
        # spawn wrapper) into the doc + index — volatile identity, like
        # run_id/sweep_id (report.VOLATILE_KEYS)
        cid = getattr(checker, "_campaign_id", None)
        if cid:
            doc["campaign_id"] = str(cid)
            jk = getattr(checker, "_job_key", None)
            if jk:
                doc["job_key"] = str(jk)
        return self.record_doc(doc, checker=checker, leg=leg)

    def record_doc(
        self,
        doc: dict,
        *,
        checker=None,
        leg: Optional[str] = None,
    ) -> dict:
        """Archive an already-assembled report document (a ``run_id``-
        bearing ``identity_doc``, or a checkpoint-derived stub for a run
        killed before its own join — ``checkpoint.stub_report_doc``).

        Crash-safe (docs/robustness.md): the archive lands via the
        atomic replace write and the index line via the durable append
        (``telemetry/_atomic.py``) — a killed writer can tear at most
        the ledger's LAST line, which :meth:`index` skips on read, so
        prior records are never lost and resume is never poisoned."""
        from ._atomic import atomic_write_json, durable_append_line

        run_id = doc.get("run_id")
        if not run_id:
            raise ValueError("report document carries no run_id")
        os.makedirs(self.runs_dir, exist_ok=True)
        atomic_write_json(
            os.path.join(self.runs_dir, f"{run_id}.json"), doc
        )
        rec = index_record(doc, checker=checker, leg=leg)
        durable_append_line(self.index_path, json.dumps(rec))
        return rec

    # -- reading -------------------------------------------------------------

    def index(self) -> list:
        """Every parseable index record, in append order.  Malformed
        lines are skipped: the ledger is append-only, and a torn tail
        line (killed writer) must not hide the rest of the history."""
        try:
            with open(self.index_path) as f:
                lines = f.read().splitlines()
        except OSError:
            return []
        out = []
        for ln in lines:
            if not ln.strip():
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("run_id"):
                out.append(rec)
        return out

    def load(self, run_id: str) -> dict:
        """The archived report document for ``run_id`` (raises on a
        missing/corrupt archive; use :meth:`find` for the soft form)."""
        with open(os.path.join(self.runs_dir, f"{run_id}.json")) as f:
            return json.load(f)

    def find(self, run_id: str) -> Optional[dict]:
        try:
            return self.load(run_id)
        except (OSError, json.JSONDecodeError):
            return None

    def headline(
        self, run_id: str, records: Optional[list] = None
    ) -> Optional[dict]:
        """The index headline for ``run_id`` (wall-clock metrics the
        archived body deliberately excludes), or None.  ``records``
        reuses an already-loaded :meth:`index` list instead of
        re-parsing the ledger."""
        for rec in records if records is not None else self.index():
            if rec.get("run_id") == run_id:
                return rec.get("headline")
        return None

    def chain(self, run_id: str) -> list:
        """The kill+resume lineage ending at ``run_id``, oldest first:
        ``parent_run_id`` links walked through the index."""
        by_id = {r["run_id"]: r for r in self.index()}
        out: list = []
        seen: set = set()
        cur = by_id.get(run_id)
        while cur is not None and cur["run_id"] not in seen:
            seen.add(cur["run_id"])
            out.append(cur)
            cur = by_id.get(cur.get("parent_run_id"))
        out.reverse()
        return out

    def trends(self, records: Optional[list] = None) -> dict:
        """``config_key -> chronological [{run_id, generated_at, leg,
        unique, states, states_per_sec}]`` — the per-configuration trend
        series the Explorer's sparklines and the ``runs`` verb read.
        ``records`` reuses an already-loaded :meth:`index` list instead
        of re-parsing the ledger."""
        out: dict = {}
        for r in records if records is not None else self.index():
            key = r.get("config_key")
            if not key:
                continue
            h = r.get("headline") or {}
            entry = {
                "run_id": r["run_id"],
                "generated_at": r.get("generated_at"),
                "unique": h.get("unique"),
                "states": h.get("states"),
                "states_per_sec": h.get("states_per_sec"),
            }
            if r.get("leg"):
                entry["leg"] = r["leg"]
            out.setdefault(key, []).append(entry)
        return out
