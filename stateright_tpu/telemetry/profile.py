"""Scoped ``jax.profiler`` hook: trace the first N hot steps on device.

A full-run ``jax.profiler`` trace of a long check is unusable (gigabytes,
and the interesting steady state is identical step after step), so the
engines instead arm a :class:`ScopedProfiler` that starts the device trace
at the first engine call and stops it after ``steps`` host syncs — N
representative hot blocks, bounded output.

Failure policy: profiling must never break a run.  A missing/broken
profiler backend (jax built without it, an unwritable logdir) downgrades to
a recorded ``profile`` event with ``error`` set; the check proceeds.
"""

from __future__ import annotations

import os
from typing import Optional

from .recorder import FlightRecorder


class ScopedProfiler:
    """Traces the first ``steps`` host-sync blocks to ``logdir``.

    Engines call :meth:`maybe_start` right before their first device call
    and :meth:`tick` once per host sync; the profiler stops itself after
    ``steps`` ticks (and is closed defensively by :meth:`stop` at run end
    either way).  Events land in the flight recorder.
    """

    def __init__(self, logdir: str, steps: int,
                 recorder: Optional[FlightRecorder] = None):
        self.logdir = str(logdir)
        self.steps = int(steps)
        self.recorder = recorder
        self._ticks = 0
        self._active = False
        self._failed = False

    def _record(self, **fields) -> None:
        if self.recorder is None:
            return
        # profile events carry the engine-run span id when one is bound,
        # so the Chrome trace nests the profiled window under the run
        # span it actually traced (telemetry/spans.py)
        sid = self.recorder.bound_span()
        if sid is not None:
            fields.setdefault("span", sid)
        self.recorder.record("profile", **fields)

    def maybe_start(self) -> None:
        if self._active or self._failed or self.steps <= 0:
            return
        try:
            import jax

            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._record(
                event="start", logdir=self.logdir, steps=self.steps,
            )
        except Exception as e:  # noqa: BLE001 - profiling never breaks a run
            self._failed = True
            if self.recorder is not None:
                self._record(
                    event="unavailable",
                    error=f"{type(e).__name__}: {e}",
                )

    def tick(self) -> None:
        """One host sync passed; stop the trace once N were profiled."""
        if not self._active:
            return
        self._ticks += 1
        if self._ticks >= self.steps:
            self.stop()

    def stop(self) -> None:
        """Close the trace.  Idempotent — the flag flips BEFORE the
        backend call, so the run wrapper's ``finally`` (which stops the
        profiler on the exception path too) can race or repeat a
        happy-path stop without double-stopping; and every backend error
        is swallowed into a ``stop-failed`` event, so calling this while
        an engine exception is in flight never masks the original
        error."""
        if not self._active:
            return
        self._active = False
        try:
            import jax

            jax.profiler.stop_trace()
            self._record(
                event="stop", logdir=self.logdir,
                profiled_steps=self._ticks,
            )
        except Exception as e:  # noqa: BLE001
            self._failed = True
            if self.recorder is not None:
                self._record(
                    event="stop-failed",
                    error=f"{type(e).__name__}: {e}",
                )
