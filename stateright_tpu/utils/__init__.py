"""L0 utilities (reference ``src/util.rs``, ``src/util/``)."""

from .densenatmap import DenseNatMap
from .vector_clock import VectorClock

__all__ = ["DenseNatMap", "VectorClock"]
