"""Vector clocks: a partial causal order (reference ``src/util/vector_clock.rs``).

Equality/hash/ordering ignore trailing zeros so clocks over different actor
counts compare sensibly (reference ``vector_clock.rs:54-106``).
"""

from __future__ import annotations

from typing import Iterable, Optional


class VectorClock:
    __slots__ = ("_v",)

    def __init__(self, values: Iterable[int] = ()):
        self._v = list(values)

    def _trimmed(self) -> tuple[int, ...]:
        v = self._v
        n = len(v)
        while n and v[n - 1] == 0:
            n -= 1
        return tuple(v[:n])

    def get(self, i: int) -> int:
        return self._v[i] if i < len(self._v) else 0

    def incremented(self, i: int) -> "VectorClock":
        """Copy with index ``i`` bumped (reference ``vector_clock.rs:34-40``)."""
        v = self._v + [0] * (i + 1 - len(self._v))
        v[i] += 1
        return VectorClock(v)

    def merge_max(self, other: "VectorClock") -> "VectorClock":
        """Element-wise max (reference ``vector_clock.rs:21-31``)."""
        n = max(len(self._v), len(other._v))
        return VectorClock(max(self.get(i), other.get(i)) for i in range(n))

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1/0/1 if comparable under the causal order, else ``None``."""
        n = max(len(self._v), len(other._v))
        lt = gt = False
        for i in range(n):
            a, b = self.get(i), other.get(i)
            if a < b:
                lt = True
            elif a > b:
                gt = True
        if lt and gt:
            return None
        return (-1 if lt else 0) if not gt else 1

    def __lt__(self, other) -> bool:
        return self.partial_cmp(other) == -1

    def __eq__(self, other) -> bool:
        return isinstance(other, VectorClock) and self._trimmed() == other._trimmed()

    def __hash__(self) -> int:
        return hash(self._trimmed())

    def stable_words(self, out: list[int]) -> None:
        from ..fingerprint import stable_words

        stable_words(self._trimmed(), out)

    def __repr__(self) -> str:
        return f"VectorClock({self._v!r})"
