"""Type-safe map keyed by densely packed nat-like keys
(reference ``src/util/densenatmap.rs``).

Values are stored in a list indexed by ``int(key)``; inserting past the end
with a gap is an error, which catches off-by-one actor-Id bugs early.  Keys
are anything convertible with ``int()`` (e.g. actor ``Id``).
"""

from __future__ import annotations

from typing import Generic, Iterable, Iterator, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class DenseNatMap(Generic[K, V]):
    def __init__(self, values: Iterable[V] = ()):
        self._values: list[V] = list(values)

    @staticmethod
    def from_iter(values: Iterable[V]) -> "DenseNatMap":
        return DenseNatMap(values)

    def insert(self, key: K, value: V) -> None:
        """Insert at ``key``; the key must be in-bounds or exactly one past the
        end (reference ``densenatmap.rs:95-109`` panics on gaps)."""
        i = int(key)
        if i < len(self._values):
            self._values[i] = value
        elif i == len(self._values):
            self._values.append(value)
        else:
            raise IndexError(
                f"DenseNatMap gap insert: key {i} with len {len(self._values)}"
            )

    def __getitem__(self, key: K) -> V:
        return self._values[int(key)]

    def __setitem__(self, key: K, value: V) -> None:
        self.insert(key, value)

    def get(self, key: K):
        i = int(key)
        return self._values[i] if 0 <= i < len(self._values) else None

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self) -> Iterator[V]:
        return iter(self._values)

    def values(self) -> list[V]:
        return list(self._values)

    def items(self):
        return list(enumerate(self._values))

    def __eq__(self, other) -> bool:
        return isinstance(other, DenseNatMap) and self._values == other._values

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"

    def stable_words(self, out: list[int]) -> None:
        from ..fingerprint import stable_words

        stable_words(tuple(self._values), out)

    def rewrite(self, plan) -> "DenseNatMap":
        """Reindex + rewrite values under a symmetry permutation
        (reference ``densenatmap.rs:209-223``)."""
        from ..symmetry import rewrite_value

        reindexed = plan.reindex(self._values)
        return DenseNatMap(rewrite_value(v, plan) for v in reindexed)
