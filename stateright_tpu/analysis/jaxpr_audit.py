"""Jaxpr kernel audit: statically verify a ``TensorModel``'s device kernels.

Every device-engine defect this repo has hit (empty-envelope crashes,
poison-row surprises, mixed fingerprint schemes, divergent closures) was
found minutes into a wavefront run.  The accelerator-checker literature
(GPUexplore's scalability work, the tensor-core BFS line) says the same
thing from the perf side: these engines live or die on kernels staying
statically shaped, pure, and integer-typed.  This pass verifies those
invariants *before launch* by abstractly tracing ``step_rows`` /
``property_masks`` once (``jax.make_jaxpr`` — no XLA compile, no device)
and walking the resulting ``ClosedJaxpr``:

 - ``JX000`` error — the kernel does not trace at all (the exception the
   engine would hit at launch, surfaced preflight with the same message);
 - ``JX101`` error — side-effecting / host-callback primitives (``jax.debug``
   prints, ``pure_callback``/``io_callback``): the wavefront engine runs
   kernels inside ``lax.while_loop`` where callbacks reorder or deadlock,
   and any host round-trip destroys MXU pipelining;
 - ``JX102`` warning — floating-point dataflow inside ``step_rows``: rows
   are u64 fingerprint words; a float round-trip silently truncates to 53
   bits of mantissa and corrupts fingerprints;
 - ``JX103`` error — output contract violation: ``step_rows`` must produce
   ``uint64[B, A, W]`` successors + ``bool[B, A]`` validity for the declared
   ``max_actions``/``width`` (the static shape XLA tiles onto the MXU), and
   ``property_masks`` must produce ``bool[B, P]``;
 - ``JX104`` error — retrace instability: tracing twice yields different
   jaxprs or different embedded constants, i.e. the kernel closes over
   mutable host state.  The engine retraces on every growth event (new
   capacities = new shapes), so an unstable kernel silently forks the
   transition relation mid-run;
 - ``JX105`` info — data-dependent gathers/scatters (indices that are traced
   values, not constants): correct, but each one is a random-access HBM
   fetch the MXU cannot tile — the measured latency bottleneck on hardware
   (see ``ops/buckets.py``);
 - ``JX106`` info — per-row FLOPs/bytes estimate from the jaxpr, so the
   report doubles as a perf preflight (also in ``report.metrics``).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from .report import AuditFinding, Severity

# Host-callback primitives (flagged even when jax reports no effect).
_CALLBACK_PRIMS = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "debug_print",
        "outside_call",
        "host_callback_call",
    }
)

# Elementwise primitives: 1 flop per output element.
_ELEMENTWISE = frozenset(
    {
        "add", "sub", "mul", "div", "rem", "pow", "integer_pow",
        "max", "min", "and", "or", "xor", "not", "neg", "sign", "abs",
        "shift_left", "shift_right_logical", "shift_right_arithmetic",
        "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp",
        "exp", "log", "tanh", "sqrt", "rsqrt", "floor", "ceil", "round",
        "nextafter", "cumsum", "cummax", "cummin", "cumprod",
    }
)

_REDUCE = frozenset(
    {
        "reduce_sum", "reduce_max", "reduce_min", "reduce_and",
        "reduce_or", "reduce_prod", "argmax", "argmin", "reduce_precision",
    }
)


def _aval_elems(v) -> int:
    shape = getattr(getattr(v, "aval", None), "shape", ())
    return int(np.prod(shape)) if shape else 1


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", 8) if dtype is not None else 8
    return _aval_elems(v) * itemsize


def _walk_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params
    (pjit bodies, cond branches, while cond/body, scan, custom calls)."""
    seen = []
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        inner = getattr(j, "jaxpr", j)  # ClosedJaxpr -> Jaxpr
        if any(inner is s for s in seen):
            continue
        seen.append(inner)
        yield inner
        for eqn in inner.eqns:
            for p in eqn.params.values():
                cands = p if isinstance(p, (list, tuple)) else (p,)
                for c in cands:
                    if hasattr(c, "eqns") or hasattr(c, "jaxpr"):
                        stack.append(c)


def _iter_eqns(closed):
    for j in _walk_jaxprs(closed):
        for eqn in j.eqns:
            yield eqn


def _is_var(x) -> bool:
    """A traced value (not a compile-time literal)."""
    return not hasattr(x, "val")


# Shape-only ops a value passes through unchanged: walking back through
# these from a narrowing cast, reaching the raw kernel input means the
# cast truncates full-width row words.
_TRANSPARENT_PRIMS = frozenset(
    {"slice", "squeeze", "reshape", "broadcast_in_dim", "transpose", "copy",
     "rev", "concatenate", "expand_dims"}
)


def _narrow_escape_count(closed) -> int:
    """JX107: count ``uint64 -> <=32-bit integer`` casts whose input is a
    raw row word (the kernel input reached through shape-only ops).  A
    masked/shifted field extraction (``(rows >> off) & mask``) narrows
    provably-small values and stays quiet; casting the word itself zeroes
    its top bits and corrupts fingerprints."""
    count = 0
    for j in _walk_jaxprs(closed):
        producers = {}
        for eqn in j.eqns:
            for ov in eqn.outvars:
                producers[ov] = eqn
        invars = set(j.invars)
        for eqn in j.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            src = eqn.invars[0]
            src_dt = getattr(getattr(src, "aval", None), "dtype", None)
            new_dt = np.dtype(eqn.params.get("new_dtype", np.int64))
            if (
                src_dt is None
                or np.dtype(src_dt) != np.dtype(np.uint64)
                or np.issubdtype(new_dt, np.floating)  # JX102's territory
                or new_dt.itemsize > 4
            ):
                continue
            v, depth = src, 0
            while depth < 8:
                if v in invars:
                    count += 1
                    break
                p = producers.get(v)
                if p is None or p.primitive.name not in _TRANSPARENT_PRIMS:
                    break  # computed/masked value: not provably full-width
                v = p.invars[0]
                depth += 1
    return count


def _index_operands(eqn):
    """The index operands of a gather/scatter-family eqn (the invars whose
    tracedness makes the access data-dependent), per primitive signature:
    ``gather(operand, indices)``, ``scatter*(operand, indices, updates)``,
    ``dynamic_slice(operand, *starts)``,
    ``dynamic_update_slice(operand, update, *starts)``."""
    name = eqn.primitive.name
    if name == "gather" or name.startswith("scatter"):
        return eqn.invars[1:2]
    if name == "dynamic_slice":
        return eqn.invars[1:]
    if name == "dynamic_update_slice":
        return eqn.invars[2:]
    return ()


def _consts_equal(c1, c2) -> bool:
    if len(c1) != len(c2):
        return False
    for a, b in zip(c1, c2):
        if a is b:
            continue
        try:
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                return False
        except Exception:  # noqa: BLE001 - non-array consts: fall back to ==
            if a != b:
                return False
    return True


def _flops_bytes(closed) -> dict:
    """Rough per-trace cost model: flops from primitive arithmetic, bytes
    as the sum of all intermediate outputs written (a memory-traffic
    proxy; gathers/scatters additionally pay random-access latency)."""
    flops = 0
    out_bytes = 0
    eqns = 0
    for eqn in _iter_eqns(closed):
        eqns += 1
        out_elems = sum(_aval_elems(v) for v in eqn.outvars)
        out_bytes += sum(_aval_bytes(v) for v in eqn.outvars)
        name = eqn.primitive.name
        if name in _ELEMENTWISE:
            flops += out_elems
        elif name in _REDUCE:
            flops += sum(_aval_elems(v) for v in eqn.invars)
        elif name == "dot_general":
            dims = eqn.params.get("dimension_numbers", (((), ()), ((), ())))
            contract = dims[0][0] if dims and dims[0] else ()
            k = 1
            for axis in contract:
                shape = getattr(eqn.invars[0].aval, "shape", ())
                if axis < len(shape):
                    k *= shape[axis]
            flops += 2 * out_elems * k
        elif name in ("sort", "argsort"):
            n = max(out_elems, 2)
            flops += int(n * math.log2(n))
        elif name == "convert_element_type":
            flops += out_elems
    return {"flops": flops, "bytes": out_bytes, "eqns": eqns}


def _trace(fn, avals):
    import jax

    jax.config.update("jax_enable_x64", True)
    # Fresh wrapper identity per call: jax memoizes traces on function
    # identity, and a cache hit would return the FIRST jaxpr without
    # re-running the Python body — silently defeating the retrace diff
    # (JX104) that exists to catch impure kernels.
    return jax.make_jaxpr(lambda *args: fn(*args))(*avals)


def _audit_one_kernel(
    fn,
    avals,
    name: str,
    findings: list,
    *,
    retrace: bool,
    flag_floats: bool,
) -> Optional[object]:
    """Trace ``fn`` (twice when ``retrace``), run the structural rules,
    and return the ClosedJaxpr (None when tracing failed)."""
    try:
        closed = _trace(fn, avals)
    except Exception as e:  # noqa: BLE001 - surfaced as a finding
        findings.append(
            AuditFinding(
                "JX000",
                Severity.ERROR,
                name,
                f"kernel does not trace: {type(e).__name__}: {e}",
            )
        )
        return None

    # JX104 retrace instability: same inputs, second trace must be
    # bit-identical (structure AND embedded constants).
    if retrace:
        try:
            closed2 = _trace(fn, avals)
        except Exception as e:  # noqa: BLE001
            findings.append(
                AuditFinding(
                    "JX104",
                    Severity.ERROR,
                    name,
                    f"kernel traced once but not twice ({type(e).__name__}: "
                    f"{e}); it mutates host state while tracing",
                )
            )
            closed2 = None
        if closed2 is not None:
            if str(closed.jaxpr) != str(closed2.jaxpr):
                findings.append(
                    AuditFinding(
                        "JX104",
                        Severity.ERROR,
                        name,
                        "retrace instability: two traces produced different "
                        "jaxprs — the kernel closes over mutable host state "
                        "(the engine retraces on every growth event, forking "
                        "the transition relation mid-run)",
                    )
                )
            elif not _consts_equal(closed.consts, closed2.consts):
                findings.append(
                    AuditFinding(
                        "JX104",
                        Severity.ERROR,
                        name,
                        "retrace instability: identical jaxpr structure but "
                        "different embedded constants — the kernel closes "
                        "over a mutated host container",
                    )
                )

    # JX101 side effects / callbacks.
    effects = set(map(str, getattr(closed, "effects", ()) or ()))
    callback_prims = sorted(
        {
            e.primitive.name
            for e in _iter_eqns(closed)
            if e.primitive.name in _CALLBACK_PRIMS
            or getattr(e, "effects", None)
        }
    )
    if effects or callback_prims:
        detail = ", ".join(callback_prims) or ", ".join(sorted(effects))
        findings.append(
            AuditFinding(
                "JX101",
                Severity.ERROR,
                name,
                f"side-effecting/callback primitives in the kernel ({detail}); "
                "device kernels must be pure — callbacks reorder or deadlock "
                "inside the engine's while_loop and stall the MXU pipeline",
            )
        )

    # JX102 float dataflow (fingerprint-corrupting in step_rows).
    if flag_floats:
        float_prims = sorted(
            {
                e.primitive.name
                for e in _iter_eqns(closed)
                if any(
                    np.issubdtype(
                        getattr(getattr(v, "aval", None), "dtype", np.int32),
                        np.floating,
                    )
                    for v in e.outvars
                )
            }
        )
        if float_prims:
            findings.append(
                AuditFinding(
                    "JX102",
                    Severity.WARNING,
                    name,
                    "floating-point dataflow in a u64 row kernel "
                    f"({', '.join(float_prims)}): floats silently truncate "
                    "row words past 53 bits and corrupt fingerprints",
                )
            )

    # JX107 integer-narrowing escape (the other fingerprint-corrupting
    # dtype class from the float rule above): u64 row words cast to a
    # 32-bit integer lose their top bits.
    if flag_floats:
        narrows = _narrow_escape_count(closed)
        if narrows:
            findings.append(
                AuditFinding(
                    "JX107",
                    Severity.WARNING,
                    name,
                    f"{narrows} uint64->int32/uint32 cast(s) of raw row "
                    "words: the top 32 bits are silently zeroed, corrupting "
                    "fingerprints (mask or shift the field out first — "
                    "BitPacker.get — instead of casting whole words)",
                )
            )

    # JX105 data-dependent gathers/scatters (perf note).  Only the INDEX
    # operands count: update/operand arrays are always traced, and
    # classifying them would flag every static-offset slice update.
    dyn = 0
    for e in _iter_eqns(closed):
        if any(_is_var(v) for v in _index_operands(e)):
            dyn += 1
    if dyn:
        findings.append(
            AuditFinding(
                "JX105",
                Severity.INFO,
                name,
                f"{dyn} data-dependent gather/scatter site(s): random-access "
                "HBM fetches the MXU cannot tile (the measured latency "
                "bottleneck class on hardware; fine if intended)",
            )
        )
    return closed


def run_jaxpr_audit(
    tensor,
    report,
    model=None,
    *,
    deep: bool = False,
    batch: int = 4,
) -> None:
    """Audit ``tensor``'s device kernels into ``report`` (findings +
    ``metrics['step_rows'|'property_masks']``).  Results are cached on the
    tensor instance: respawns and engine growth events re-enter the
    preflight, and the kernels cannot change under a fixed twin."""
    cache = getattr(tensor, "_jaxpr_audit_cache", None)
    if cache is not None and cache[0] >= bool(deep):
        report.extend(cache[1])
        report.metrics.update(cache[2])
        return
    findings: list = []
    metrics: dict = {}
    _run_jaxpr_audit_uncached(
        tensor, findings, metrics, model=model, deep=deep, batch=batch
    )
    try:
        tensor._jaxpr_audit_cache = (bool(deep), tuple(findings), metrics)
    except Exception:  # noqa: BLE001 - __slots__ twins: just skip caching
        pass
    report.extend(findings)
    report.metrics.update(metrics)


def _run_jaxpr_audit_uncached(
    tensor, findings, metrics, *, model, deep, batch
) -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    width = getattr(tensor, "width", None)
    arity = getattr(tensor, "max_actions", None)
    if not isinstance(width, int) or not isinstance(arity, int):
        findings.append(
            AuditFinding(
                "JX103",
                Severity.ERROR,
                type(tensor).__name__,
                "tensor model must declare integer width/max_actions "
                f"(got width={width!r}, max_actions={arity!r})",
            )
        )
        return

    # init_rows first: it is the documented outside-any-trace moment where
    # compiled twins populate their device-constant caches (see
    # CompiledActorTensor.init_rows) — and its output is part of the
    # contract too.
    try:
        init = np.asarray(tensor.init_rows())
        if init.dtype != np.uint64 or init.ndim != 2 or init.shape[1] != width:
            findings.append(
                AuditFinding(
                    "JX103",
                    Severity.ERROR,
                    "init_rows",
                    f"init_rows must return uint64[I, {width}], got "
                    f"{init.dtype}{list(init.shape)}",
                )
            )
    except Exception as e:  # noqa: BLE001 - surfaced as a finding
        findings.append(
            AuditFinding(
                "JX000",
                Severity.ERROR,
                "init_rows",
                f"init_rows failed: {type(e).__name__}: {e}",
            )
        )
        return

    rows_aval = jax.ShapeDtypeStruct((batch, width), jnp.uint64)

    closed = _audit_one_kernel(
        tensor.step_rows,
        (rows_aval,),
        "step_rows",
        findings,
        retrace=True,
        flag_floats=True,
    )
    if closed is not None:
        out = list(closed.out_avals)
        if len(out) != 2:
            findings.append(
                AuditFinding(
                    "JX103",
                    Severity.ERROR,
                    "step_rows",
                    f"must return (succ, valid); traced {len(out)} outputs",
                )
            )
        else:
            succ, valid = out
            want = (batch, arity, width)
            if tuple(succ.shape) != want or succ.dtype != jnp.uint64:
                findings.append(
                    AuditFinding(
                        "JX103",
                        Severity.ERROR,
                        "step_rows",
                        f"successors must be uint64{list(want)} "
                        f"(B, max_actions, width), got "
                        f"{succ.dtype}{list(succ.shape)} — a non-u64 row "
                        "dtype corrupts fingerprints; a shape mismatch "
                        "breaks the engine's static MXU tiling",
                    )
                )
            if tuple(valid.shape) != (batch, arity) or valid.dtype != jnp.bool_:
                findings.append(
                    AuditFinding(
                        "JX103",
                        Severity.ERROR,
                        "step_rows",
                        f"validity mask must be bool[{batch}, {arity}], got "
                        f"{valid.dtype}{list(valid.shape)}",
                    )
                )
        m = _flops_bytes(closed)
        m["flops_per_row"] = m["flops"] / batch
        m["bytes_per_row"] = m["bytes"] / batch
        metrics["step_rows"] = m
        findings.append(
            AuditFinding(
                "JX106",
                Severity.INFO,
                "step_rows",
                "perf preflight: ~{:.0f} flops/row, ~{:.0f} intermediate "
                "bytes/row over {} eqns".format(
                    m["flops_per_row"], m["bytes_per_row"], m["eqns"]
                ),
            )
        )

    n_props = None
    if model is not None:
        try:
            n_props = len(model.properties())
        except Exception:  # noqa: BLE001 - model may be partially built
            n_props = None
    closed_pm = _audit_one_kernel(
        tensor.property_masks,
        (rows_aval,),
        "property_masks",
        findings,
        retrace=deep,
        flag_floats=False,
    )
    if closed_pm is not None:
        out = list(closed_pm.out_avals)
        bad = (
            len(out) != 1
            or out[0].dtype != jnp.bool_
            or len(out[0].shape) != 2
            or out[0].shape[0] != batch
            or (n_props is not None and out[0].shape[1] != n_props)
        )
        if bad:
            got = (
                f"{out[0].dtype}{list(out[0].shape)}"
                if len(out) == 1
                else f"{len(out)} outputs"
            )
            want_p = n_props if n_props is not None else "P"
            findings.append(
                AuditFinding(
                    "JX103",
                    Severity.ERROR,
                    "property_masks",
                    f"must return bool[{batch}, {want_p}] (one column per "
                    f"property, in properties() order), got {got}",
                )
            )
        m = _flops_bytes(closed_pm)
        m["flops_per_row"] = m["flops"] / batch
        m["bytes_per_row"] = m["bytes"] / batch
        metrics["property_masks"] = m
