"""Soundness sanitizer: interval bounds over transition kernels + the
checkify-instrumented checked execution mode.

Why this pass exists: on TPU an out-of-bounds gather silently CLAMPS and an
out-of-bounds scatter silently DROPS — a buggy ``step_rows`` encoding does
not crash, it silently prunes successors, and the checker reports "no
counterexample" for a space it never explored.  The GPU/accelerator
model-checking literature (GPUexplore's scalability analysis, the
tensor-core BFS line) identifies exactly this silent hash/indexing
corruption as the class that decides whether an accelerator checker's
verdicts can be trusted.  PR 1's auditor lints trace-level structure
(JX000–JX107); this pass proves *value-level* facts: every index stays on
its operand's axis, every packed field stays inside its declared width.

Two halves, one contract:

 - **Static** (:func:`run_sanitizer`): forward interval abstract
   interpretation (``interval.py``) over the traced ``step_rows`` /
   ``property_masks`` jaxprs, seeded from declared domain bounds
   (``RowDomain`` / discovered ``BitPacker`` field widths).  Decidable
   violations are findings (JX201/JX202 errors, JX203/JX204 warnings,
   JX205 info).
 - **Dynamic** (:func:`checkify_kernels` + ``CheckerBuilder.checked()``):
   where the interval domain can't decide, the verdict is *not* a false
   positive — the site is counted ``undecided`` (info) and routed to
   checked mode: a ``jax.experimental.checkify``-instrumented twin of the
   step kernels (index/nan/div checks) that runs the same exploration and
   fails loudly, with :func:`localize_checked_failure` re-running the
   failing batch row-by-row to name the offending row and decoded state.

Rule catalogue (``docs/analysis.md``):

 - ``JX201`` error — gather/dynamic-slice index interval escapes the
   operand axis (silent TPU clamp ⇒ dropped/duplicated successors);
 - ``JX202`` error — scatter/dynamic-update-slice index may exceed the
   target (silent drop — the ``buckets.insert`` failure class);
 - ``JX203`` warning — packed-field arithmetic provably overflows its
   declared bit width before the mask (info when the escape is marginal
   and reachability could bound it: checked mode decides);
 - ``JX204`` warning — a gather may read an ``EMPTY``-sentinel slot and
   feed it into arithmetic unguarded (uninitialized-read class);
 - ``JX205`` info — the interval proves a branch dead (model smell; jnp's
   machine-generated negative-index normalization is exempted).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .interval import (
    Interp,
    IVal,
    aval_of,
    dtype_hull,
    is_literal,
)
from .report import AuditFinding, Severity

EMPTY_SENTINEL = (1 << 64) - 1

_ARITH_PRIMS = frozenset(
    {"add", "sub", "mul", "div", "rem", "integer_pow", "cumsum",
     "reduce_sum", "shift_left", "neg"}
)

_TRANSPARENT = ("reshape", "broadcast_in_dim", "squeeze",
                "convert_element_type", "copy", "expand_dims")


# ---------------------------------------------------------------------------
# the hooks object interval.Interp calls back into
# ---------------------------------------------------------------------------


class _Hooks:
    """Collects site verdicts for one kernel trace."""

    def __init__(self, kernel: str, domain=None):
        self.kernel = kernel
        self.findings: list = []
        self.sites = 0
        self.proved = 0
        self.undecided = 0
        self.dead_branches = 0
        self._site_no = 0
        self._empty_consts: set = set()  # vars of consts containing EMPTY
        self._jx204_candidates: list = []  # (out_var, loc)
        self._jaxprs: list = []

    # -- wiring ---------------------------------------------------------------

    def note_const(self, var, c) -> None:
        try:
            a = np.asarray(c)
        except Exception:  # noqa: BLE001
            return
        if (a.dtype == np.uint64 and a.size > 1
                and bool((a == np.uint64(EMPTY_SENTINEL)).any())
                and bool((a != np.uint64(EMPTY_SENTINEL)).any())):
            self._empty_consts.add(var)

    def _loc(self, prim: str) -> str:
        self._site_no += 1
        return f"{self.kernel}:{prim}#{self._site_no}"

    # -- site checks ----------------------------------------------------------

    def site(self, itp: Interp, eqn, ins) -> None:
        name = eqn.primitive.name
        jaxpr = getattr(itp, "_cur_jaxpr", None)
        if jaxpr is not None and jaxpr not in self._jaxprs:
            self._jaxprs.append(jaxpr)
        if name == "gather":
            self._check_gather(itp, eqn, ins)
        elif name == "dynamic_slice":
            self._check_dynamic(itp, eqn, ins, rule="JX201",
                                what="dynamic-slice start")
        elif name.startswith("scatter"):
            self._check_scatter(itp, eqn, ins)
        elif name == "dynamic_update_slice":
            self._check_dynamic(itp, eqn, ins, rule="JX202",
                                what="dynamic-update start", skip=2)

    # gather ------------------------------------------------------------------

    def _index_ivals(self, itp: Interp, eqn, n_dims: int):
        """Per-mapped-dim index intervals: a single mapped dim uses the
        whole index array's interval; multiple dims walk the indices back
        to a last-axis ``concatenate`` whose pieces partition the dims
        (jnp advanced indexing / take_along_axis build exactly that)."""
        idx_var = eqn.invars[1]
        whole = itp.read(idx_var)
        if n_dims == 1:
            return [whole]
        src = itp.walk_back(idx_var, _TRANSPARENT)
        prod = itp._producers.get(src)
        if prod is not None and prod.primitive.name == "concatenate":
            pieces = []
            for pv in prod.invars:
                width = getattr(aval_of(pv), "shape", (1,))[-1] or 1
                val = itp.read(pv)
                pieces.extend([val] * int(width))
            if len(pieces) == n_dims:
                return pieces
        return [whole] * n_dims

    def _verdict(self, idx: IVal, bound: int, dtype) -> str:
        """'proved' | 'escape' | 'undecided' for an index vs [0, bound].

        An escape verdict (-> JX201/JX202 ERROR) requires a *learned*
        bound: an interval still covering half its dtype's range is the
        domain saying "I know nothing" (e.g. an int32 wrap join), and per
        the sanitizer contract an undecidable site routes to checked mode
        instead of becoming a false positive."""
        if not idx.tracked:
            return "undecided"
        lo, hi = idx.hull()
        if 0 <= lo and hi <= bound:
            return "proved"
        dh = dtype_hull(dtype)
        # counting widths inclusively: [0, 2^31-1] — the nonnegative half
        # of int32, i.e. "nothing known beyond the sign" — must land on
        # the undecided side of the threshold
        if dh is None or (hi - lo + 1) * 2 >= (dh[1] - dh[0] + 1):
            return "undecided"
        return "escape"

    def _check_gather(self, itp: Interp, eqn, ins) -> None:
        dnums = eqn.params.get("dimension_numbers")
        slice_sizes = eqn.params.get("slice_sizes", ())
        operand = eqn.invars[0]
        shape = getattr(aval_of(operand), "shape", ())
        smap = tuple(getattr(dnums, "start_index_map", ()) or ())
        if not smap or not shape:
            return
        self.sites += 1
        idx_dtype = getattr(aval_of(eqn.invars[1]), "dtype", np.int64)
        idxs = self._index_ivals(itp, eqn, len(smap))
        verdicts = []
        details = []
        for d, idx in zip(smap, idxs):
            ss = slice_sizes[d] if d < len(slice_sizes) else 1
            bound = int(shape[d]) - int(ss)
            v = self._verdict(idx, bound, idx_dtype)
            verdicts.append(v)
            if v != "proved":
                hull = idx.hull()
                details.append(
                    f"dim {d}: index in "
                    f"{'[%d, %d]' % hull if hull else '<untracked>'} vs "
                    f"valid [0, {bound}] (axis {shape[d]})"
                )
        self._finish_site("JX201", eqn, verdicts, details,
                          "gather index interval escapes the operand axis: "
                          "on TPU the access silently clamps, so successors "
                          "are dropped or duplicated and the space is "
                          "under-explored")
        # JX204: the gather may READ the EMPTY sentinel
        op_val = ins[0]
        may_empty = (op_val.tracked and op_val.may_contain(EMPTY_SENTINEL)
                     and not op_val.is_top_for(
                         getattr(aval_of(operand), "dtype", np.uint64)))
        src = itp.walk_back(operand, _TRANSPARENT)
        if may_empty or src in self._empty_consts:
            self._jx204_candidates.append(
                (eqn.outvars[0], self._loc("gather"))
            )

    def _check_dynamic(self, itp: Interp, eqn, ins, *, rule: str,
                       what: str, skip: int = 1) -> None:
        operand = eqn.invars[0]
        shape = getattr(aval_of(operand), "shape", ())
        starts = eqn.invars[skip:]
        if len(starts) != len(shape):
            return
        if rule == "JX202":
            sizes = getattr(aval_of(eqn.invars[1]), "shape", ())
        else:
            sizes = eqn.params.get("slice_sizes", ())
        self.sites += 1
        verdicts, details = [], []
        for d, sv in enumerate(starts):
            idx = itp.read(sv)
            ss = sizes[d] if d < len(sizes) else 1
            bound = int(shape[d]) - int(ss)
            dt = getattr(aval_of(sv), "dtype", np.int64)
            v = self._verdict(idx, bound, dt)
            verdicts.append(v)
            if v != "proved":
                hull = idx.hull()
                details.append(
                    f"dim {d}: start in "
                    f"{'[%d, %d]' % hull if hull else '<untracked>'} vs "
                    f"valid [0, {bound}]"
                )
        msg = (f"{what} may escape the operand: the device silently clamps, "
               "reading/writing the wrong rows")
        self._finish_site(rule, eqn, verdicts, details, msg)

    def _check_scatter(self, itp: Interp, eqn, ins) -> None:
        dnums = eqn.params.get("dimension_numbers")
        operand = eqn.invars[0]
        updates = eqn.invars[2] if len(eqn.invars) > 2 else None
        shape = getattr(aval_of(operand), "shape", ())
        smap = tuple(getattr(dnums, "scatter_dims_to_operand_dims", ())
                     or ())
        if not smap or not shape:
            return
        self.sites += 1
        inserted = set(getattr(dnums, "inserted_window_dims", ()) or ())
        upd_window = list(getattr(dnums, "update_window_dims", ()) or ())
        upd_shape = getattr(aval_of(updates), "shape", ()) if updates is not None else ()
        # full window extent per operand dim: 1 for inserted dims, the
        # matching update window size otherwise
        window: dict = {}
        wpos = 0
        for d in range(len(shape)):
            batching = set(getattr(dnums, "operand_batching_dims", ()) or ())
            if d in inserted or d in batching:
                window[d] = 1
            else:
                if wpos < len(upd_window) and upd_window[wpos] < len(upd_shape):
                    window[d] = int(upd_shape[upd_window[wpos]])
                else:
                    window[d] = 1
                wpos += 1
        idx_dtype = getattr(aval_of(eqn.invars[1]), "dtype", np.int64)
        idxs = self._index_ivals(itp, eqn, len(smap))
        verdicts, details = [], []
        for d, idx in zip(smap, idxs):
            bound = int(shape[d]) - window.get(d, 1)
            v = self._verdict(idx, bound, idx_dtype)
            verdicts.append(v)
            if v != "proved":
                hull = idx.hull()
                details.append(
                    f"dim {d}: index in "
                    f"{'[%d, %d]' % hull if hull else '<untracked>'} vs "
                    f"valid [0, {bound}] (axis {shape[d]})"
                )
        self._finish_site("JX202", eqn, verdicts, details,
                          "scatter index may exceed the target: on TPU the "
                          "write silently drops (the buckets.insert failure "
                          "class) — table/row updates vanish without a trace")

    def _finish_site(self, rule: str, eqn, verdicts, details, why) -> None:
        prim = eqn.primitive.name
        if all(v == "proved" for v in verdicts):
            self.proved += 1
            return
        loc = self._loc(prim)
        if any(v == "escape" for v in verdicts):
            self.findings.append(AuditFinding(
                rule, Severity.ERROR, loc,
                f"{why} ({'; '.join(details)})",
            ))
        else:
            self.undecided += 1
            self.findings.append(AuditFinding(
                rule, Severity.INFO, loc,
                "interval domain cannot bound this index "
                f"({'; '.join(details)}); not flagged as an error — run "
                "checked mode (CheckerBuilder.checked() / --checked) to "
                "guard it dynamically",
            ))

    # mask / JX203 ------------------------------------------------------------

    def mask_site(self, itp: Interp, eqn, val: IVal, mask: int) -> None:
        if not val.arith or not val.tracked:
            return  # extraction of a raw/packed word, not packing arithmetic
        dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.uint64)
        if val.is_top_for(dt):
            return  # nothing learned: a mask over an unknown word is the
            # extraction idiom, not overflowing arithmetic
        lo, hi = val.hull()
        if hi <= mask and lo >= 0:
            return
        # provable only when even the MINIMUM escapes the field: every
        # input wraps, reachability cannot save it.  A partial escape
        # (lo inside, hi outside) is the reachability-undecidable case —
        # info + the dynamic guard, never a fleet-breaking warning.
        blatant = lo > mask
        self.findings.append(AuditFinding(
            "JX203",
            Severity.WARNING if blatant else Severity.INFO,
            self._loc("and"),
            f"packed-field arithmetic in [{lo}, {hi}] "
            f"{'provably overflows (for every input)' if blatant else 'may overflow'} "
            f"its declared width before the mask 0x{mask:x}: high bits are "
            "silently truncated and the packed field wraps"
            + ("" if blatant else
               " — if reachability bounds it, checked mode "
               "(CheckerBuilder.checked()) can confirm dynamically"),
        ))

    # dead branches / JX205 ---------------------------------------------------

    def dead_branch(self, eqn, pred: IVal) -> None:
        self.dead_branches += 1
        if self.dead_branches > 4:  # cap the noise; count rides the metrics
            return
        self.findings.append(AuditFinding(
            "JX205", Severity.INFO, self._loc(eqn.primitive.name),
            f"interval proves a branch dead (predicate is constantly "
            f"{pred.singleton()}): dead model logic, or a guard made "
            "redundant by the declared domain — worth a look",
        ))

    # JX204 post-pass ---------------------------------------------------------

    def finish(self) -> None:
        """Resolve JX204 candidates: fire when the sentinel-carrying gather
        output reaches arithmetic without an EMPTY-comparison guard."""
        uses: dict = {}
        for jaxpr in self._jaxprs:
            for eqn in jaxpr.eqns:
                for iv in eqn.invars:
                    if not is_literal(iv):
                        uses.setdefault(iv, []).append(eqn)
        for var, loc in self._jx204_candidates:
            frontier, seen, hit, guarded = [var], set(), False, False
            for _ in range(6):
                nxt = []
                for v in frontier:
                    for eqn in uses.get(v, ()):
                        name = eqn.primitive.name
                        if name in ("eq", "ne"):
                            other = [x for x in eqn.invars if x is not v]
                            if other and is_literal(other[0]) and int(
                                np.asarray(other[0].val).reshape(-1)[0]
                            ) == EMPTY_SENTINEL:
                                guarded = True
                                continue
                        if name in _ARITH_PRIMS:
                            hit = True
                        if name in _TRANSPARENT or name in ("slice",
                                                            "select_n"):
                            for ov in eqn.outvars:
                                if ov not in seen:
                                    seen.add(ov)
                                    nxt.append(ov)
                frontier = nxt
                if hit or not frontier:
                    break
            if hit and not guarded:
                self.findings.append(AuditFinding(
                    "JX204", Severity.WARNING, loc,
                    "gather may read an EMPTY-sentinel (uninitialized) "
                    "slot and feed it into arithmetic with no EMPTY "
                    "comparison in sight: the sentinel's bit pattern "
                    "(2^64-1) silently poisons the derived values",
                ))


# ---------------------------------------------------------------------------
# domain discovery + the static driver
# ---------------------------------------------------------------------------


def resolve_row_domain(tensor):
    """The twin's declared :class:`RowDomain` — its ``row_domain()`` hook
    when defined, else synthesized from a discovered ``BitPacker``
    attribute (field widths as bounds), else None (all words top)."""
    from ..parallel.tensor_model import BitPacker, RowDomain

    fn = getattr(tensor, "row_domain", None)
    if callable(fn):
        try:
            dom = fn()
        except Exception:  # noqa: BLE001 - a broken hook must not kill audit
            dom = None
        if dom is not None:
            return dom
    width = getattr(tensor, "width", None)
    packers = [
        v for v in vars(tensor).values()
        if isinstance(v, BitPacker) and v.width <= (width or v.width)
    ]
    if len(packers) != 1 or not isinstance(width, int):
        return None
    dom = RowDomain.from_packer(packers[0])
    if dom.width < width:
        wide = RowDomain(width)
        wide._words[: dom.width] = dom._words
        wide._fields = dom._fields
        return wide
    return dom


def _trace_kernel(fn, avals):
    import jax

    jax.config.update("jax_enable_x64", True)
    return jax.make_jaxpr(lambda *a: fn(*a))(*avals)


def run_sanitizer(tensor, report, model=None, batch: int = 4) -> None:
    """Interval-sanitize ``tensor``'s kernels into ``report`` (findings +
    ``metrics['sanitizer']``).  Cached on the twin instance, like the
    structural jaxpr audit: kernels cannot change under a fixed twin."""
    cache = getattr(tensor, "_sanitizer_cache", None)
    if cache is not None:
        report.extend(cache[0])
        report.metrics["sanitizer"] = dict(cache[1])
        return
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)
    width = getattr(tensor, "width", None)
    if not isinstance(width, int):
        return  # JX103 (structural audit) already reports this
    domain = resolve_row_domain(tensor)
    rows_aval = jax.ShapeDtypeStruct((batch, width), jnp.uint64)
    findings: list = []
    summary = {"sites": 0, "proved": 0, "undecided": 0,
               "dead_branches": 0, "seeded": domain is not None,
               "kernels": {}}
    for kernel in ("step_rows", "property_masks"):
        fn = getattr(tensor, kernel, None)
        if fn is None:
            continue
        try:
            closed = _trace_kernel(fn, (rows_aval,))
        except Exception:  # noqa: BLE001 - JX000 already covers trace fails
            continue
        hooks = _Hooks(kernel)
        itp = Interp(hooks=hooks, row_domain=domain)
        try:
            itp.run(closed)
            hooks.finish()
        except Exception as e:  # noqa: BLE001 - the sanitizer must never
            # take down an audit the structural pass would survive — but a
            # crash may NOT read as a clean verdict either: the kernel went
            # unchecked, and a silent pass here makes the fleet soundness
            # gate vacuous.  JX200 is warning-severity so the fleet-clean
            # tests catch it loudly without aborting spawns.
            findings.append(AuditFinding(
                "JX200", Severity.WARNING, kernel,
                f"sanitizer pass crashed ({type(e).__name__}: {e}); this "
                "kernel's indices are UNCHECKED — treat every site as "
                "undecided and use checked mode; please report the crash",
            ))
            summary.setdefault("crashed", []).append(kernel)
            continue
        findings.extend(hooks.findings)
        summary["sites"] += hooks.sites
        summary["proved"] += hooks.proved
        summary["undecided"] += hooks.undecided
        summary["dead_branches"] += hooks.dead_branches
        summary["kernels"][kernel] = {
            "sites": hooks.sites, "proved": hooks.proved,
            "undecided": hooks.undecided,
        }
    rules = sorted({f.rule_id for f in findings})
    summary["rules"] = rules
    summary["clean"] = not any(
        f.severity == Severity.ERROR for f in findings
    )
    try:
        tensor._sanitizer_cache = (tuple(findings), dict(summary))
    except Exception:  # noqa: BLE001 - __slots__ twins
        pass
    report.extend(findings)
    report.metrics["sanitizer"] = summary


# ---------------------------------------------------------------------------
# checked execution mode (the dynamic guard)
# ---------------------------------------------------------------------------


class CheckedExecutionError(RuntimeError):
    """A checkify-instrumented kernel check failed during a checked run.
    Carries the offending batch row (index, raw words, decoded state when
    the twin can decode it) and the underlying checkify message."""

    def __init__(self, message: str, row_index: Optional[int] = None,
                 row=None, state=None):
        self.row_index = row_index
        self.row = row
        self.state = state
        super().__init__(message)


def checkify_errors():
    from jax.experimental import checkify

    return checkify.index_checks | checkify.float_checks


def checkify_kernels(tensor):
    """``rows -> (err, (masks, succ, valid))``: the model kernels under
    checkify's index/nan/div instrumentation.  Only the MODEL kernels are
    wrapped — the engine's own insert deliberately scatters out-of-range
    with ``mode='drop'`` (the dead-lane discard), which the OOB check
    would (correctly, but uselessly) flag."""
    from jax.experimental import checkify

    def kernels(rows):
        masks = tensor.property_masks(rows)
        succ, valid = tensor.step_rows(rows)
        return masks, succ, valid

    return checkify.checkify(kernels, errors=checkify_errors())


def error_flag(err):
    """Traced scalar bool: does ``err`` record any failed check?  (The
    engine threads only this flag through its loop carry — checkify Error
    pytrees mint fresh error codes per trace, so the full Error cannot
    cross jit boundaries; per-row replay rebuilds the message.)

    Reads checkify's ``Error._pred`` (private but stable on the pinned
    jax).  If a jax upgrade renames it this RAISES at engine build time —
    a checked mode that silently reports all-clear would be worse than no
    checked mode at all."""
    import jax.numpy as jnp

    preds = getattr(err, "_pred", None)
    if preds is None:
        raise RuntimeError(
            "jax.experimental.checkify.Error no longer exposes _pred; "
            "checked mode's failure flag needs porting to this jax "
            "version (stateright_tpu/analysis/sanitizer.py::error_flag)"
        )
    flag = jnp.bool_(False)
    for p in preds.values():
        flag = flag | jnp.any(p)
    return flag


def localize_checked_failure(tensor, rows_np, base_exc=None):
    """Re-run the checkified kernels one batch row at a time to name the
    offending row, then raise :class:`CheckedExecutionError`.  Always
    raises (falls back to the block-level message when per-row replay
    cannot reproduce — e.g. a check that needs batch context)."""
    import jax.numpy as jnp

    checked = checkify_kernels(tensor)
    rows_np = np.asarray(rows_np, np.uint64)
    for i in range(rows_np.shape[0]):
        try:
            err, _ = checked(jnp.asarray(rows_np[i:i + 1]))
            msg = err.get()
        except Exception:  # noqa: BLE001 - replay crash: report this row
            msg = "kernel crashed during per-row replay"
        if msg:
            state = None
            try:
                state = tensor.decode_state(rows_np[i])
            except Exception:  # noqa: BLE001 - decode is best-effort
                pass
            raise CheckedExecutionError(
                "checked mode: a kernel check failed at batch row "
                f"{i} (state={state!r}, row words="
                f"{[hex(int(w)) for w in rows_np[i]]}):\n{msg}",
                row_index=i, row=rows_np[i], state=state,
            ) from base_exc
    raise CheckedExecutionError(
        "checked mode: a kernel check failed inside the device block but "
        "per-row replay did not reproduce it "
        f"(underlying: {base_exc})",
    ) from base_exc
