"""Per-action read/write footprints of a tensor model, at bit granularity.

The independence pass (``independence.py``) needs to know, for every action
family of a compiled tensor model, which packed row bits the action READS
(to compute its successor), which bits its enabledness GUARD reads, and
which bits it WRITES.  Everything else in the row is a pure copy — and pure
copies are exactly what makes two actions commute.  This module extracts
those footprints *statically* from the traced ``step_rows`` /
``property_masks`` jaxprs, reusing the walking conventions of the interval
sanitizer (``interval.py``) with a different abstract domain:

 - every traced value carries ``deps`` — a :class:`FieldSet` (per-word bit
   masks over the input row) of the input bits its VALUE may depend on,
   beyond any identity copy;
 - values derived from a single input word additionally carry an identity
   channel ``(word, shift, eq, supp)``: the value equals
   ``input_word >> shift`` on the ``eq`` bits (value positions), and only
   the ``supp`` bits can be non-zero.  ``BitPacker.get``-style extraction
   (``(rows[..., w] >> off) & mask``) and the ``set`` idiom
   (``(w & ~m) | (v & m)``) stay exact through this channel, which is what
   makes per-field write masks possible at all;
 - arrays whose LAST axis is the row-word axis are tracked per lane, so
   the engine's word-indexed write-back (``rows.at[..., w].set(v)``, a
   constant-index scatter in the jaxpr) replaces exactly one lane.

Per-action decomposition rides the model idiom: ``step_rows`` assembles
``succ`` by stacking per-action row arrays along the action axis (a
``concatenate`` in the jaxpr) and ``valid`` by stacking per-action guard
columns.  Kernels that assemble successors any other way (the compiled
actor twins' data-dependent slot/destination writes) do NOT decompose —
the extraction then reports every action with a ``TOP`` footprint, which
``independence.py`` conservatively treats as dependent-on-everything
(finding ``JX302``).  Undecidable can cost reduction, never soundness.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

import numpy as np

from .interval import aval_of, is_literal, producers_of
from .report import AuditFinding, Severity

ALL64 = (1 << 64) - 1

_TRANSPARENT = ("reshape", "broadcast_in_dim", "squeeze",
                "convert_element_type", "copy", "expand_dims")


# ---------------------------------------------------------------------------
# field sets: per-word bitmasks over the input row
# ---------------------------------------------------------------------------


class FieldSet:
    """A set of input-row bits: ``{word -> bitmask}``, or TOP (unknown)."""

    __slots__ = ("masks", "top")

    def __init__(self, masks: Optional[dict] = None, top: bool = False):
        self.top = bool(top)
        self.masks: dict = {} if top or not masks else {
            w: m & ALL64 for w, m in masks.items() if m
        }

    @classmethod
    def empty(cls) -> "FieldSet":
        return cls()

    @classmethod
    def of(cls, word: int, mask: int = ALL64) -> "FieldSet":
        return cls({int(word): int(mask)})

    @classmethod
    def top_set(cls) -> "FieldSet":
        return cls(top=True)

    @property
    def is_empty(self) -> bool:
        return not self.top and not self.masks

    def union(self, other: "FieldSet") -> "FieldSet":
        if self.top or other.top:
            return FieldSet.top_set()
        out = dict(self.masks)
        for w, m in other.masks.items():
            out[w] = out.get(w, 0) | m
        return FieldSet(out)

    def minus_word_bits(self, word: int, mask: int) -> "FieldSet":
        """Remove ``mask`` bits of ``word`` (TOP stays TOP)."""
        if self.top:
            return self
        out = dict(self.masks)
        if word in out:
            out[word] &= ~mask
        return FieldSet(out)

    def intersects(self, other: "FieldSet") -> bool:
        """Conservative may-intersect: TOP intersects anything non-empty
        (and another TOP)."""
        if self.top:
            return other.top or bool(other.masks)
        if other.top:
            return bool(self.masks)
        return any(
            self.masks.get(w, 0) & m for w, m in other.masks.items()
        )

    def to_json(self) -> object:
        if self.top:
            return "top"
        return {str(w): hex(m) for w, m in sorted(self.masks.items())}

    def __repr__(self) -> str:  # debugging/report ergonomics
        return f"FieldSet({self.to_json()})"


def union_all(sets) -> FieldSet:
    out = FieldSet.empty()
    for s in sets:
        out = out.union(s)
    return out


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Info:
    """Abstract value of one scalar/lane.

    ``deps`` — non-identity input-bit dependencies of the value.
    ``word``/``shift`` — identity provenance: the value is derived from
    ``input[word] >> shift`` (None = no single-word provenance).
    ``eq`` — value-position bits where the value EQUALS
    ``input[word] >> shift`` (meaningful only with provenance).
    ``supp`` — value-position bits that can be non-zero (None = all).
    ``const`` — exact value when statically known (scalar constants).
    ``acc`` — value-position bits where the value is an OR-ACCUMULATE of
    the identity content: ``(input[word] >> shift) | f(deps)``
    (meaningful only with provenance; always disjoint from ``eq``).
    Written back to its own word, such a bit is a *monotone* write — two
    actions' accumulates commute bit-for-bit, which is what lets the
    compiled twins' saturating poison flag stay out of the conflict
    relation (``independence.py``; the per-channel kernel's
    ``_or_field`` idiom).
    """

    deps: FieldSet = field(default_factory=FieldSet.empty)
    word: Optional[int] = None
    shift: int = 0
    eq: int = 0
    supp: Optional[int] = None
    const: Optional[int] = None
    acc: int = 0

    def as_data(self) -> FieldSet:
        """Full read set when the value is consumed AS DATA (identity
        content included): the identity channel's input bits fold in."""
        out = self.deps
        if self.word is not None:
            s = ALL64 if self.supp is None else self.supp
            out = out.union(FieldSet.of(self.word, (s << self.shift) & ALL64))
        return out


TOP_INFO = Info(deps=FieldSet.top_set())


def _join(a: Info, b: Info) -> Info:
    """Join two infos (select/concat): identity survives only where both
    sides carry it, on the intersection of their eq bits.  A bit stays an
    OR-accumulate when BOTH branches keep it ``old | something`` (eq or
    acc) — ``select(p, old, old | f)`` is still ``old | (p ? f : 0)``."""
    deps = a.deps.union(b.deps)
    if (a.word is not None and a.word == b.word and a.shift == b.shift):
        supp = None if (a.supp is None or b.supp is None) else (
            a.supp | b.supp
        )
        eq = a.eq & b.eq
        safe = (a.eq | a.acc) & (b.eq | b.acc)
        return Info(deps=deps, word=a.word, shift=a.shift,
                    eq=eq, supp=supp, acc=safe & ~eq)
    return Info(deps=a.as_data().union(b.as_data()))


def _const_info(v) -> Info:
    arr = np.asarray(v)
    supp = 0
    const = None
    if arr.dtype == np.bool_:
        supp = int(bool(arr.any()))
        if arr.size == 1:
            const = int(bool(arr.reshape(-1)[0]))
    elif np.issubdtype(arr.dtype, np.integer):
        flat = arr.reshape(-1)
        if flat.size == 0:
            supp = 0
        else:
            # the FULL array: an under-approximated support would let
            # genuinely conflicting actions classify independent
            # (soundness), and the reduce is a single vectorized pass
            supp = int(np.bitwise_or.reduce(flat)) & ALL64
        if arr.size == 1:
            const = int(flat[0]) & ALL64
    else:
        return Info(supp=None)
    return Info(supp=supp, const=const)


@dataclass(frozen=True)
class AVal:
    """Abstract value of one traced array: either one collapsed
    :class:`Info`, or per-lane infos along the LAST axis (``lanes``)."""

    info: Optional[Info] = None
    lanes: Optional[tuple] = None

    @property
    def tracked(self) -> bool:
        return self.lanes is not None

    def collapse(self) -> Info:
        if self.lanes is None:
            return self.info if self.info is not None else TOP_INFO
        if not self.lanes:
            return TOP_INFO
        # join keeps the identity channel when every lane agrees on it
        # (e.g. a batch-axis broadcast mistaken for lanes); mismatching
        # lanes fold to their as_data reads inside _join
        out = self.lanes[0]
        for i in self.lanes[1:]:
            out = _join(out, i)
        return out

    def one(self) -> Info:
        return self.info if self.info is not None else self.collapse()


def _scalar(info: Info) -> AVal:
    return AVal(info=info)


TOP_AVAL = AVal(info=TOP_INFO)


# ---------------------------------------------------------------------------
# footprints
# ---------------------------------------------------------------------------


@dataclass
class ActionFootprint:
    """Static footprint of one action family (action slot)."""

    reads: FieldSet  # successor-value reads (pure copies excluded)
    writes: FieldSet  # row bits the successor may change
    guard: FieldSet  # enabledness-condition reads
    decided: bool  # False when any component collapsed to TOP
    # monotone OR-accumulate writes (``new = old | f(reads)``): commute
    # with each other, conflict with plain writes and with reads of the
    # same bits — the compiled twins' saturating poison flag
    accum: FieldSet = field(default_factory=FieldSet.empty)

    def to_json(self) -> dict:
        return {
            "reads": self.reads.to_json(),
            "writes": self.writes.to_json(),
            "guard": self.guard.to_json(),
            "accum": self.accum.to_json(),
            "decided": self.decided,
        }


@dataclass
class ConjunctInfo:
    """Per-action guard CONJUNCT decomposition — what the POR stubborn-set
    closure needs for disabled actions: a false conjunct's writer set is a
    sound *necessary enabling set* (the action cannot become enabled until
    some writer of that conjunct's read footprint fires).

    ``sets[a]`` — one FieldSet per conjunct of action ``a`` (≥ 1; the
    fallback is the whole guard as a single conjunct).
    ``leaf_idx[a]`` — ``(leaf, lane)`` references of ``a``'s conjuncts
    into the kernel's leaf outputs (``lane`` is None for a scalar ``[B]``
    leaf, else the action's lane within a ``[B, cap]`` guard BLOCK — the
    per-channel kernel stacks one guard array per channel, and lane ``k``
    is slot ``k``'s truth), or None: the single-conjunct fallback, whose
    truth is the action's enabled bit itself (a disabled action's whole
    guard is false by definition — no kernel evaluation needed).
    ``n_leaves`` — total distinct evaluable conjunct leaves.
    """

    sets: list
    leaf_idx: list
    n_leaves: int

    @property
    def max_conjuncts(self) -> int:
        return max((len(s) for s in self.sets), default=1)


@dataclass
class ModelFootprints:
    """Footprints of every action plus per-property read sets."""

    width: int
    n_actions: int
    actions: list  # list[ActionFootprint]
    prop_reads: list  # list[FieldSet], properties() order
    decomposed: bool  # per-action successor decomposition succeeded
    findings: list = field(default_factory=list)
    conjuncts: Optional[ConjunctInfo] = None

    @property
    def undecided_actions(self) -> list:
        return [i for i, a in enumerate(self.actions) if not a.decided]


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class _FpInterp:
    """One forward pass over a closed jaxpr with the footprint domain.
    Mirrors ``interval.Interp``'s walking conventions (pjit inlining via
    aliases, producer maps) with conservative TOP for anything unknown."""

    def __init__(self):
        self.env: dict = {}
        self._alias: dict = {}
        self._producers: dict = {}
        self.input_var = None

    # -- env -----------------------------------------------------------------

    def read(self, x) -> AVal:
        if is_literal(x):
            return _scalar(_const_info(x.val))
        v = self.env.get(x)
        return v if v is not None else TOP_AVAL

    def write(self, var, val: AVal) -> None:
        self.env[var] = val

    def resolve(self, var):
        seen = 0
        while not is_literal(var) and var in self._alias and seen < 32:
            var = self._alias[var]
            seen += 1
        return var

    def walk_back(self, var, prims=_TRANSPARENT, depth: int = 8):
        var = self.resolve(var)
        for _ in range(depth):
            if is_literal(var):
                return var
            eqn = self._producers.get(var)
            if eqn is None or eqn.primitive.name not in prims:
                return var
            var = self.resolve(eqn.invars[0])
        return var

    def const_of(self, x) -> Optional[int]:
        if is_literal(x):
            return _const_info(x.val).const
        v = self.env.get(x)
        return v.one().const if v is not None and v.info is not None else None

    # -- entry ---------------------------------------------------------------

    def run(self, closed, rows_var_lanes: int) -> list:
        jaxpr = closed.jaxpr
        for cv, c in zip(jaxpr.constvars, closed.consts):
            self.write(cv, _scalar(_const_info(np.asarray(c))))
        if jaxpr.invars:
            self.input_var = jaxpr.invars[0]
            self.write(
                jaxpr.invars[0],
                AVal(lanes=tuple(
                    Info(word=w, shift=0, eq=ALL64, supp=None)
                    for w in range(rows_var_lanes)
                )),
            )
        for iv in jaxpr.invars[1:]:
            self.write(iv, TOP_AVAL)
        self._run_eqns(jaxpr)
        return [self.read(ov) for ov in jaxpr.outvars]

    def _run_eqns(self, jaxpr) -> None:
        self._producers.update(producers_of(jaxpr))
        for eqn in jaxpr.eqns:
            try:
                self.eqn(eqn)
            except Exception:  # noqa: BLE001 - a rule bug degrades to TOP,
                for ov in eqn.outvars:  # never to a wrong footprint
                    self.write(ov, TOP_AVAL)

    # -- per-eqn transfer ----------------------------------------------------

    def eqn(self, eqn) -> None:
        name = eqn.primitive.name
        rule = _FP_RULES.get(name)
        ins = [self.read(x) for x in eqn.invars]
        if rule is not None:
            out = rule(self, eqn, ins)
            outs = out if isinstance(out, list) else [out]
        elif name in ("pjit", "closed_call", "core_call", "custom_jvp_call",
                      "custom_vjp_call", "remat_call", "checkpoint"):
            outs = self._call(eqn, ins)
        else:
            # unknown primitive: every output depends on every input (as
            # data), lanes lost
            deps = union_all(v.collapse().as_data() for v in ins)
            outs = [_scalar(Info(deps=deps))] * len(eqn.outvars)
        for ov, val in zip(eqn.outvars, outs):
            self.write(ov, val)

    def _call(self, eqn, ins) -> list:
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is None:
            return [TOP_AVAL] * len(eqn.outvars)
        jaxpr = getattr(inner, "jaxpr", inner)
        consts = getattr(inner, "consts", ())
        for cv, c in zip(jaxpr.constvars, consts):
            self.write(cv, _scalar(_const_info(np.asarray(c))))
        for iv, outer, val in zip(jaxpr.invars, eqn.invars, ins):
            self.write(iv, val)
            if not is_literal(outer):
                self._alias[iv] = outer
        self._run_eqns(jaxpr)
        outs = []
        for outer_ov, inner_ov in zip(eqn.outvars, jaxpr.outvars):
            if not is_literal(inner_ov):
                self._alias[outer_ov] = inner_ov
            outs.append(self.read(inner_ov))
        return outs


# -- shape helpers -----------------------------------------------------------


def _shape(x) -> tuple:
    return tuple(getattr(aval_of(x), "shape", ()) or ())


def _last(x) -> int:
    s = _shape(x)
    return int(s[-1]) if s else 1


# -- rules -------------------------------------------------------------------


def _lanewise(fn):
    """Lift a binary Info rule over (AVal, AVal) with last-axis broadcast:
    lanes x lanes (equal length), lanes x scalar, scalar x scalar."""

    def rule(a: AVal, b: AVal) -> AVal:
        if a.tracked and b.tracked and len(a.lanes) == len(b.lanes):
            return AVal(lanes=tuple(
                fn(x, y) for x, y in zip(a.lanes, b.lanes)
            ))
        if a.tracked and not b.tracked:
            bi = b.one()
            return AVal(lanes=tuple(fn(x, bi) for x in a.lanes))
        if b.tracked and not a.tracked:
            ai = a.one()
            return AVal(lanes=tuple(fn(ai, y) for y in b.lanes))
        return _scalar(fn(a.one(), b.one()))

    return rule


def _data_combine(a: Info, b: Info) -> Info:
    return Info(deps=a.as_data().union(b.as_data()))


def _rule_and_info(a: Info, b: Info) -> Info:
    if a.const is not None and b.const is not None:
        v = a.const & b.const
        return Info(supp=v, const=v)
    for x, c in ((a, b.const), (b, a.const)):
        if c is None:
            continue
        supp = (ALL64 if x.supp is None else x.supp) & c
        if x.word is not None:
            # AND-with-const zeroes bits outside c: an accumulate bit
            # masked off is a plain write again, not ``old | f``
            return replace(x, eq=x.eq & c, acc=x.acc & c, supp=supp,
                           const=None)
        return Info(deps=x.deps, supp=supp)
    out = _data_combine(a, b)
    if a.supp is not None or b.supp is not None:
        # no identity survives, but the support still intersects: an AND
        # can only keep bits both operands can carry (what keeps a
        # boolean flag's support at bit 0 through ``occ & poisoned``)
        sa = ALL64 if a.supp is None else a.supp
        sb = ALL64 if b.supp is None else b.supp
        out = replace(out, supp=sa & sb)
    return out


def _rule_or_info(a: Info, b: Info) -> Info:
    if a.const is not None and b.const is not None:
        v = a.const | b.const
        return Info(supp=v, const=v)
    for x, y in ((a, b), (b, a)):
        if x.word is not None and y.word is None and y.supp is not None:
            # value | bounded-support operand: only the operand's support
            # bits stop equalling the input word (the pk.set idiom:
            # cleared | (v & mask) — v's support is the field mask).
            # Those bits become ``old | f`` — OR-accumulates, provided
            # they were still safe (eq or already-acc) before
            eq = x.eq & ~y.supp
            return Info(
                deps=x.deps.union(y.deps),
                word=x.word, shift=x.shift,
                eq=eq,
                supp=None if x.supp is None else (x.supp | y.supp),
                acc=x.acc | (x.eq & y.supp),
            )
    if (a.word is not None and a.word == b.word and a.shift == b.shift):
        sa = ALL64 if a.supp is None else a.supp
        sb = ALL64 if b.supp is None else b.supp
        eq = (a.eq & ~sb) | (b.eq & ~sa) | (a.eq & b.eq)
        # identity bits landing in non-eq output positions become reads
        leak = ((a.eq | b.eq) & ~eq) << a.shift
        deps = a.deps.union(b.deps)
        if leak:
            deps = deps.union(FieldSet.of(a.word, leak & ALL64))
        return Info(deps=deps, word=a.word, shift=a.shift, eq=eq,
                    supp=sa | sb)
    out = _data_combine(a, b)
    if a.supp is not None and b.supp is not None:
        out = replace(out, supp=a.supp | b.supp)
    return out


def _rule_xor_info(a: Info, b: Info) -> Info:
    if a.const is not None and b.const is not None:
        v = a.const ^ b.const
        return Info(supp=v, const=v)
    for x, y in ((a, b), (b, a)):
        if x.word is not None and y.word is None and y.supp is not None:
            # value ^ bounded-support operand: only the support bits flip
            # (a flipped bit is NOT an OR-accumulate — not monotone)
            return Info(
                deps=x.deps.union(y.deps),
                word=x.word, shift=x.shift,
                eq=x.eq & ~y.supp,
                supp=None if x.supp is None else (x.supp | y.supp),
                acc=x.acc & ~y.supp,
            )
    out = _data_combine(a, b)
    if a.supp is not None and b.supp is not None:
        out = replace(out, supp=a.supp | b.supp)
    return out


def _rule_shift_info(left: bool):
    def rule(a: Info, b: Info) -> Info:
        k = b.const
        if k is None or a.deps.top:
            return _data_combine(a, b)
        k = int(k)
        supp = ALL64 if a.supp is None else a.supp
        if a.word is None:
            nsupp = ((supp << k) if left else (supp >> k)) & ALL64
            return Info(deps=a.deps, supp=nsupp,
                        const=None if a.const is None else (
                            ((a.const << k) if left else (a.const >> k))
                            & ALL64))
        if left:
            if k <= a.shift:
                return Info(deps=a.deps, word=a.word, shift=a.shift - k,
                            eq=(a.eq << k) & ALL64, supp=(supp << k) & ALL64,
                            acc=(a.acc << k) & ALL64)
            # over-shift past the origin: identity content moves to higher
            # input positions than it came from — fold to data
            return Info(deps=a.as_data(), supp=(supp << k) & ALL64)
        return Info(deps=a.deps, word=a.word, shift=a.shift + k,
                    eq=a.eq >> k, supp=supp >> k, acc=a.acc >> k)

    return rule


def _rule_cmp_info(a: Info, b: Info) -> Info:
    return Info(deps=a.as_data().union(b.as_data()), supp=1)


def _rule_not_info(a: Info) -> Info:
    if a.const is not None:
        return Info(supp=(~a.const) & ALL64, const=(~a.const) & ALL64)
    return Info(deps=a.as_data())


def _rule_binop(itp, eqn, ins):
    name = eqn.primitive.name
    fn = {
        "and": _rule_and_info,
        "or": _rule_or_info,
        "xor": _rule_xor_info,
        "add": _data_combine,
        "sub": _data_combine,
        "mul": _data_combine,
        "max": _data_combine,
        "min": _data_combine,
        "div": _data_combine,
        "rem": _data_combine,
        "shift_left": _rule_shift_info(True),
        "shift_right_logical": _rule_shift_info(False),
        "shift_right_arithmetic": _rule_shift_info(False),
        "eq": _rule_cmp_info,
        "ne": _rule_cmp_info,
        "lt": _rule_cmp_info,
        "le": _rule_cmp_info,
        "gt": _rule_cmp_info,
        "ge": _rule_cmp_info,
    }[name]
    return _lanewise(fn)(ins[0], ins[1])


def _rule_not(itp, eqn, ins):
    (a,) = ins
    if a.tracked:
        return AVal(lanes=tuple(_rule_not_info(i) for i in a.lanes))
    return _scalar(_rule_not_info(a.one()))


def _rule_select(itp, eqn, ins):
    pred, cases = ins[0], ins[1:]
    out = cases[0]
    for c in cases[1:]:
        out = _lanewise(_join)(out, c)
    pdeps = pred.collapse().as_data()
    if pdeps.is_empty:
        return out
    if out.tracked:
        return AVal(lanes=tuple(
            replace(i, deps=i.deps.union(pdeps)) for i in out.lanes
        ))
    i = out.one()
    return _scalar(replace(i, deps=i.deps.union(pdeps)))


def _rule_slice(itp: _FpInterp, eqn, ins):
    (a,) = ins
    shape = _shape(eqn.invars[0])
    starts = eqn.params.get("start_indices", ())
    limits = eqn.params.get("limit_indices", ())
    strides = eqn.params.get("strides") or (1,) * len(shape)
    if a.tracked and shape and len(starts) == len(shape):
        lo, hi, st = starts[-1], limits[-1], strides[-1]
        lanes = a.lanes[lo:hi:st]
        if len(lanes) == _last(eqn.outvars[0]):
            return AVal(lanes=lanes)
    return _scalar(a.collapse())


def _rule_squeeze(itp, eqn, ins):
    (a,) = ins
    dims = eqn.params.get("dimensions", ())
    in_ndim = len(_shape(eqn.invars[0]))
    if a.tracked and (in_ndim - 1) in dims:
        # the (width-1) lane axis is squeezed away: a single-lane scalar
        if len(a.lanes) == 1:
            return _scalar(a.lanes[0])
        return _scalar(a.collapse())
    if a.tracked and _last(eqn.outvars[0]) == len(a.lanes):
        return a  # lane axis survives
    return _scalar(a.collapse()) if a.tracked else a


def _rule_broadcast(itp, eqn, ins):
    (a,) = ins
    bdims = eqn.params.get("broadcast_dimensions", ())
    out_ndim = len(_shape(eqn.outvars[0]))
    n_out = _last(eqn.outvars[0])
    if a.tracked:
        if bdims and bdims[-1] == out_ndim - 1 and len(a.lanes) == n_out:
            return a  # lane axis preserved
        return _scalar(a.collapse())
    # a scalar broadcast: every output lane carries the same info
    return AVal(lanes=tuple([a.one()] * n_out)) if n_out >= 1 else a


def _rule_reshape(itp, eqn, ins):
    (a,) = ins
    if a.tracked and _last(eqn.outvars[0]) == len(a.lanes):
        in_shape, out_shape = _shape(eqn.invars[0]), _shape(eqn.outvars[0])
        if (int(np.prod(in_shape or (1,))) // max(len(a.lanes), 1)
                == int(np.prod(out_shape or (1,))) // max(len(a.lanes), 1)):
            return a
    return _scalar(a.collapse()) if a.tracked else a


def _rule_convert(itp, eqn, ins):
    return ins[0]


def _rule_concat(itp, eqn, ins):
    dim = eqn.params.get("dimension", 0)
    out_ndim = len(_shape(eqn.outvars[0]))
    if dim == out_ndim - 1:
        lanes = []
        for v, x in zip(ins, eqn.invars):
            n = _last(x)
            if v.tracked and len(v.lanes) == n:
                lanes.extend(v.lanes)
            else:
                lanes.extend([v.collapse()] * n)
        return AVal(lanes=tuple(lanes))
    # non-last-axis concat (the action stack): sound per-lane join; the
    # per-action decomposition walks back through this eqn separately
    out = ins[0]
    for v in ins[1:]:
        out = _lanewise(_join)(out, v)
    return out


def _rule_scatter(itp: _FpInterp, eqn, ins):
    """The word write-back: ``rows.at[..., w].set(v)`` traces as a scatter
    with a constant scatter index onto the last axis.  Recognized form
    replaces exactly one lane; anything else collapses (data-dependent
    writes cannot keep per-field footprints)."""
    operand, updates = ins[0], ins[2] if len(ins) > 2 else TOP_AVAL
    dnums = eqn.params.get("dimension_numbers")
    sdims = tuple(getattr(dnums, "scatter_dims_to_operand_dims", ()) or ())
    op_ndim = len(_shape(eqn.invars[0]))
    idx_src = itp.walk_back(eqn.invars[1])
    idx_const = None
    if is_literal(idx_src):
        idx_const = _const_info(idx_src.val).const
    else:
        prod = itp._producers.get(idx_src)
        if prod is not None and prod.primitive.name == "broadcast_in_dim" \
                and is_literal(prod.invars[0]):
            idx_const = _const_info(prod.invars[0].val).const
    if (operand.tracked and sdims == (op_ndim - 1,)
            and idx_const is not None
            and 0 <= idx_const < len(operand.lanes)):
        lanes = list(operand.lanes)
        lanes[idx_const] = updates.collapse()
        return AVal(lanes=tuple(lanes))
    # unknown target lane: every lane may have been overwritten
    upd = updates.collapse().as_data()
    if operand.tracked:
        return AVal(lanes=tuple(
            Info(deps=i.as_data().union(upd)) for i in operand.lanes
        ))
    return _scalar(Info(deps=operand.collapse().as_data().union(upd)))


def _rule_gather(itp, eqn, ins):
    """Table lookups (``table[idx]``): the output's support is bounded by
    the TABLE's support — every gathered element is one of its entries.
    An all-zero table (a factored predicate that is constant-False for
    this actor) therefore yields a CONSTANT zero with no reads at all,
    which is what lets ``exists_actor(lambda i, s: i == K and ...)``
    read only actor K's field instead of every actor's."""
    operand = ins[0].collapse()
    idx = ins[1].collapse() if len(ins) > 1 else TOP_INFO
    if (operand.supp == 0 and operand.deps.is_empty):
        return _scalar(Info(supp=0, const=0))
    return _scalar(Info(
        deps=operand.as_data().union(idx.as_data()),
        supp=operand.supp,
    ))


def _rule_reduce(itp, eqn, ins):
    return _scalar(Info(deps=ins[0].collapse().as_data()))


def _rule_iota(itp, eqn, ins):
    return _scalar(Info(supp=None))


def _rule_transpose(itp, eqn, ins):
    (a,) = ins
    perm = eqn.params.get("permutation", ())
    in_ndim = len(_shape(eqn.invars[0]))
    if a.tracked and perm and perm[-1] == in_ndim - 1:
        return a
    return _scalar(a.collapse()) if a.tracked else a


_FP_RULES = {
    "and": _rule_binop, "or": _rule_binop, "xor": _rule_binop,
    "add": _rule_binop, "sub": _rule_binop, "mul": _rule_binop,
    "max": _rule_binop, "min": _rule_binop, "div": _rule_binop,
    "rem": _rule_binop,
    "shift_left": _rule_binop,
    "shift_right_logical": _rule_binop,
    "shift_right_arithmetic": _rule_binop,
    "eq": _rule_binop, "ne": _rule_binop, "lt": _rule_binop,
    "le": _rule_binop, "gt": _rule_binop, "ge": _rule_binop,
    "not": _rule_not,
    "select_n": _rule_select,
    "slice": _rule_slice,
    "squeeze": _rule_squeeze,
    "broadcast_in_dim": _rule_broadcast,
    "reshape": _rule_reshape,
    "expand_dims": _rule_reshape,
    "convert_element_type": _rule_convert,
    "copy": _rule_convert,
    "stop_gradient": _rule_convert,
    "concatenate": _rule_concat,
    "gather": _rule_gather,
    "scatter": _rule_scatter,
    "transpose": _rule_transpose,
    "reduce_sum": _rule_reduce, "reduce_max": _rule_reduce,
    "reduce_min": _rule_reduce, "reduce_and": _rule_reduce,
    "reduce_or": _rule_reduce, "argmax": _rule_reduce,
    "argmin": _rule_reduce, "cumsum": _rule_reduce,
    "iota": _rule_iota,
}


# ---------------------------------------------------------------------------
# per-action decomposition + the driver
# ---------------------------------------------------------------------------


def _flatten_stack(itp: _FpInterp, var, axis: int, depth: int = 6) -> list:
    """Flatten nested ``concatenate``s along ``axis`` into per-slot piece
    vars; a piece of axis-size k that is not itself a concat contributes k
    copies of itself.  Returns None when ``var`` is not a concat at all."""
    var = itp.walk_back(var, ("reshape", "copy", "convert_element_type"))
    eqn = itp._producers.get(var)
    if eqn is None or eqn.primitive.name != "concatenate" \
            or eqn.params.get("dimension") != axis:
        return None
    out = []
    for piece in eqn.invars:
        n = _shape(piece)[axis] if axis < len(_shape(piece)) else 1
        sub = (
            _flatten_stack(itp, itp.resolve(piece), axis, depth - 1)
            if depth > 0 and not is_literal(piece)
            else None
        )
        if sub is not None:
            out.extend(sub)
        else:
            out.extend([piece] * int(n))
    return out


def _action_footprint_from_lanes(lanes, guard: FieldSet) -> ActionFootprint:
    """Writes/reads of one action's successor row from its lane infos."""
    writes = FieldSet.empty()
    accum = FieldSet.empty()
    reads = FieldSet.empty()
    decided = not guard.top
    for w, info in enumerate(lanes):
        if info.word == w and info.shift == 0:
            dirty = (~info.eq) & ALL64
            accb = info.acc & dirty
            plain = dirty & ~accb
            if plain:
                writes = writes.union(FieldSet.of(w, plain))
            if accb:
                accum = accum.union(FieldSet.of(w, accb))
            reads = reads.union(info.deps)
            if info.deps.top:
                decided = False
        else:
            # the lane is not a recognizable update of its own word:
            # conservatively a full write fed by everything it touches
            writes = writes.union(FieldSet.of(w, ALL64))
            reads = reads.union(info.as_data())
            if info.as_data().top:
                decided = False
    if writes.top or reads.top:
        decided = False
    return ActionFootprint(reads=reads, writes=writes, guard=guard,
                           decided=decided, accum=accum)


def _trace(fn, avals):
    import jax

    jax.config.update("jax_enable_x64", True)
    return jax.make_jaxpr(lambda *a: fn(*a))(*avals)


# -- guard-conjunct extraction ----------------------------------------------

_MAX_CONJUNCTS = 6  # per action; deeper and-trees fall back to one conjunct


def _flatten_stack_tl(producers_tl: dict, var, axis: int,
                      depth: int = 6) -> Optional[list]:
    """Top-level-only variant of :func:`_flatten_stack`: walks transparent
    prims and nested concatenates through TOP-LEVEL eqns only, so the
    returned piece vars are all evaluable in the top-level jaxpr scope."""
    for _ in range(8):
        if is_literal(var):
            return None
        eqn = producers_tl.get(var)
        if eqn is None or eqn.primitive.name not in (
            "reshape", "copy", "convert_element_type"
        ):
            break
        var = eqn.invars[0]
    eqn = producers_tl.get(var) if not is_literal(var) else None
    if eqn is None or eqn.primitive.name != "concatenate" \
            or eqn.params.get("dimension") != axis:
        return None
    out = []
    for piece in eqn.invars:
        n = _shape(piece)[axis] if axis < len(_shape(piece)) else 1
        sub = (
            _flatten_stack_tl(producers_tl, piece, axis, depth - 1)
            if depth > 0 else None
        )
        if sub is not None:
            out.extend(sub)
        else:
            out.extend([piece] * int(n))
    return out


def _walk_tl(producers_tl: dict, var, depth: int = 8):
    """Walk transparent shape-only prims through top-level eqns."""
    for _ in range(depth):
        if is_literal(var):
            return var
        eqn = producers_tl.get(var)
        if eqn is None or eqn.primitive.name not in _TRANSPARENT:
            return var
        var = eqn.invars[0]
    return var


def _and_leaves(producers_tl: dict, var, depth: int = 16) -> Optional[list]:
    """Leaves of the boolean and-tree rooted at ``var`` (top-level vars
    only); None when the tree is degenerate (literal root)."""
    var = _walk_tl(producers_tl, var)
    if is_literal(var):
        return None
    eqn = producers_tl.get(var)
    if (depth > 0 and eqn is not None and eqn.primitive.name == "and"
            and np.dtype(getattr(aval_of(var), "dtype", np.bool_))
            == np.bool_):
        out = []
        for x in eqn.invars:
            sub = _and_leaves(producers_tl, x, depth - 1)
            if sub is None:
                return None
            out.extend(sub)
        return out
    return [var]


def _guard_vars(closed, producers_tl: dict, arity: int) -> Optional[list]:
    """Per-action ``(guard var, lane)`` pairs from the ``valid`` output's
    action-axis stack (top-level walk); None when it does not decompose.
    A ``[B, cap]`` stack piece covers ``cap`` consecutive actions (the
    per-channel kernel's one-guard-array-per-channel idiom): each gets
    the same var with its lane index within the run."""
    vout = closed.jaxpr.outvars[1]
    ndim = len(_shape(vout))
    pieces = _flatten_stack_tl(producers_tl, vout, ndim - 1)
    if pieces is None or len(pieces) != arity:
        return None
    out = []
    prev, lane = None, 0
    for p in pieces:
        v = _walk_tl(producers_tl, p)
        lane = lane + 1 if (prev is not None and v is prev) else 0
        prev = v
        out.append((v, lane))
    return out


def _conjunct_info(itp: _FpInterp, closed, arity: int,
                   guards: list) -> ConjunctInfo:
    """Assemble :class:`ConjunctInfo` from the traced kernel: the SAME
    leaf selection as :func:`_leaf_vars_of` (one implementation — the
    kernel builder compares its re-derived indices against these, and a
    divergence between two copies of the walk would silently demote
    every run to the imprecise fallback), plus the per-leaf read
    footprints; whole-guard single-conjunct fallback where no and-tree
    extracts.  A laned reference reads the LANE's footprint when the
    leaf is tracked — slot ``k``'s occupancy conjunct reads one region
    word, not the whole region."""

    def conjunct_set(i, ln):
        av = itp.read(leaves[i])
        if ln is not None and av.tracked and ln < len(av.lanes):
            return av.lanes[ln].as_data()
        return av.collapse().as_data()

    leaves, leaf_idx = _leaf_vars_of(closed, arity)
    sets = [
        [guards[a]] if idx is None
        else [conjunct_set(i, ln) for (i, ln) in idx]
        for a, idx in enumerate(leaf_idx)
    ]
    return ConjunctInfo(sets=sets, leaf_idx=leaf_idx,
                        n_leaves=len(leaves))


def _leaf_vars_of(closed, arity: int) -> tuple:
    """(ordered leaf vars, per-action ``(leaf, lane)`` indices) for
    kernel building — re-derivable at any batch size; the derivation is
    deterministic for a deterministic trace (the JX104 retrace-stability
    contract).  A ``[B, cap]`` leaf (the per-channel guard-block idiom)
    carries one lane per action of its block; a ``[B]`` leaf applies to
    the whole block (lane None)."""
    producers_tl = producers_of(closed.jaxpr)
    gvars = _guard_vars(closed, producers_tl, arity)
    leaves: list = []
    leaf_pos: dict = {}
    idx: list = []
    for a in range(arity):
        if gvars is None or is_literal(gvars[a][0]):
            idx.append(None)
            continue
        gv, lane = gvars[a]
        lv = _and_leaves(producers_tl, gv)
        if not lv or len(lv) > _MAX_CONJUNCTS or any(
            is_literal(v) for v in lv
        ):
            idx.append(None)
            continue
        cidx = []
        for v in lv:
            sh = _shape(v)
            if len(sh) == 1:
                ln = None
            elif len(sh) == 2 and lane < sh[-1]:
                ln = lane
            else:  # a shape the kernel cannot index per action
                cidx = None
                break
            if v not in leaf_pos:
                leaf_pos[v] = len(leaves)
                leaves.append(v)
            cidx.append((leaf_pos[v], ln))
        idx.append(cidx)
    return leaves, idx


def conjunct_eval_fn(tensor):
    """A batch-size-polymorphic evaluator of the guard-conjunct leaves:
    ``fn(rows[B, W]) -> [bool[B] | bool[B, cap], ...]`` — the raw leaf
    arrays, indexed by the plan's ``(leaf, lane)`` conjunct references —
    or None when the model has no evaluable leaves.  The step kernel is
    re-traced per batch size and the leaf outputs are exposed as jaxpr
    outputs; under ``jit`` XLA dead-code-eliminates the successor
    computation, so the evaluation costs only the guard bit-ops
    themselves.  Cached per batch size on the twin."""
    import jax
    import jax.numpy as jnp

    fp = extract_footprints(tensor)
    if fp is None or fp.conjuncts is None or fp.conjuncts.n_leaves == 0:
        return None
    expect_idx = fp.conjuncts.leaf_idx
    cache: dict = getattr(tensor, "_conjunct_fn_cache", None)
    if cache is None:
        cache = {}
        try:
            tensor._conjunct_fn_cache = cache
        except Exception:  # noqa: BLE001 - __slots__ twins
            pass
    width, arity = tensor.width, tensor.max_actions

    def fn(rows):
        b = int(rows.shape[0])
        built = cache.get(b)
        if built is None:
            closed = _trace(
                tensor.step_rows,
                (jax.ShapeDtypeStruct((b, width), jnp.uint64),),
            )
            leaves, idx = _leaf_vars_of(closed, arity)
            if idx != expect_idx or not leaves:
                cache[b] = False  # retrace drifted: caller falls back
                return None
            jaxpr = closed.jaxpr
            try:
                sub = jaxpr.replace(outvars=list(leaves))
            except Exception:  # noqa: BLE001 - older jax Jaxpr API
                import jax.core as jcore

                sub = jcore.Jaxpr(
                    jaxpr.constvars, jaxpr.invars, list(leaves),
                    jaxpr.eqns, jaxpr.effects,
                )
            import jax.core as jcore

            closed_sub = jcore.ClosedJaxpr(sub, closed.consts)
            built = jcore.jaxpr_as_fun(closed_sub)
            cache[b] = built
        if built is False:
            return None
        return list(built(rows))

    return fn


def extract_footprints(tensor, batch: int = 4) -> Optional[ModelFootprints]:
    """Extract :class:`ModelFootprints` for ``tensor`` (cached on the twin
    instance — kernels cannot change under a fixed twin).  Returns None when
    the twin has no usable ``width``/``max_actions`` or a kernel does not
    trace (the structural audit already reports those)."""
    cached = getattr(tensor, "_footprint_cache", None)
    if cached is not None:
        return cached
    import jax
    import jax.numpy as jnp

    width = getattr(tensor, "width", None)
    arity = getattr(tensor, "max_actions", None)
    if not isinstance(width, int) or not isinstance(arity, int):
        return None
    rows_aval = jax.ShapeDtypeStruct((batch, width), jnp.uint64)
    findings: list = []
    try:
        # init_rows first — the documented outside-any-trace moment where
        # compiled twins populate their device-constant caches (the same
        # discipline as run_jaxpr_audit: constants materialized inside a
        # make_jaxpr trace would leak tracers into the cache and poison
        # the later engine trace)
        np.asarray(tensor.init_rows())
        closed = _trace(tensor.step_rows, (rows_aval,))
    except Exception:  # noqa: BLE001 - JX000 covers trace failures
        return None

    itp = _FpInterp()
    try:
        succ_v, valid_v = itp.run(closed, width)[:2]
    except Exception as e:  # noqa: BLE001 - degrade to all-TOP, loudly
        findings.append(AuditFinding(
            "JX300", Severity.WARNING, "step_rows",
            f"footprint pass crashed ({type(e).__name__}: {e}); every "
            "action is conservatively dependent on everything",
        ))
        succ_v = valid_v = None

    top_fp = ActionFootprint(
        reads=FieldSet.top_set(), writes=FieldSet.top_set(),
        guard=FieldSet.top_set(), decided=False,
    )
    actions = [top_fp] * arity
    decomposed = False

    # guards: valid [B, A] — the action axis IS the last axis, so the lane
    # machinery already carries per-action guard infos
    guards = [FieldSet.top_set()] * arity
    if valid_v is not None:
        gv = itp.read(closed.jaxpr.outvars[1])
        if gv.tracked and len(gv.lanes) == arity:
            guards = [i.as_data() for i in gv.lanes]
        else:
            guards = [gv.collapse().as_data()] * arity

    # boundary filter participates in enabledness on every action
    if getattr(tensor, "has_boundary", False):
        try:
            b_closed = _trace(
                tensor.boundary_rows,
                (jax.ShapeDtypeStruct((batch, arity, width), jnp.uint64),),
            )
            b_itp = _FpInterp()
            b_out = b_itp.run(b_closed, width)
            b_deps = b_out[0].collapse().as_data() if b_out else (
                FieldSet.top_set()
            )
        except Exception:  # noqa: BLE001
            b_deps = FieldSet.top_set()
        guards = [g.union(b_deps) for g in guards]

    # successors: walk the stacked succ [B, A, W] back to its action-axis
    # concatenate; each piece is one action's row array
    if succ_v is not None:
        out_var = itp.resolve(closed.jaxpr.outvars[0])
        ndim = len(_shape(closed.jaxpr.outvars[0]))
        pieces = _flatten_stack(itp, out_var, ndim - 2) if ndim >= 2 else None
        if pieces is None and arity == 1:
            # a single-action stack emits no concatenate: the whole
            # successor array IS the one action's row array
            pieces = [out_var]
        if pieces is not None and len(pieces) == arity:
            decomposed = True
            actions = []
            for a, piece in enumerate(pieces):
                pv = itp.read(itp.walk_back(piece))
                if pv.tracked and len(pv.lanes) == width:
                    fp = _action_footprint_from_lanes(pv.lanes, guards[a])
                else:
                    info = pv.collapse()
                    fp = ActionFootprint(
                        reads=info.as_data(),
                        writes=FieldSet.top_set(),
                        guard=guards[a], decided=False,
                    )
                actions.append(fp)
        else:
            actions = [
                replace(top_fp, guard=guards[a]) for a in range(arity)
            ]

    # properties: property_masks [B, P] — per-property lane deps
    prop_reads: list = []
    try:
        p_closed = _trace(tensor.property_masks, (rows_aval,))
        p_itp = _FpInterp()
        p_out = p_itp.run(p_closed, width)
        pv = p_out[0] if p_out else TOP_AVAL
        n_props = _last(p_closed.jaxpr.outvars[0])
        if pv.tracked and len(pv.lanes) == n_props:
            prop_reads = [i.as_data() for i in pv.lanes]
        else:
            prop_reads = [pv.collapse().as_data()] * n_props
    except Exception:  # noqa: BLE001 - structural audit reports this
        prop_reads = []

    conjuncts = None
    if succ_v is not None:
        try:
            conjuncts = _conjunct_info(itp, closed, arity, guards)
        except Exception:  # noqa: BLE001 - whole-guard fallback
            conjuncts = ConjunctInfo(
                sets=[[g] for g in guards],
                leaf_idx=[None] * arity, n_leaves=0,
            )

    out = ModelFootprints(
        width=width, n_actions=arity, actions=actions,
        prop_reads=prop_reads, decomposed=decomposed, findings=findings,
        conjuncts=conjuncts,
    )
    try:
        tensor._footprint_cache = out
    except Exception:  # noqa: BLE001 - __slots__ twins
        pass
    return out
