"""Audit driver: resolve a model's device twin, run every pass, cache.

``audit_model(model)`` is the one entry point behind the
``CheckerBuilder.audit()``/preflight surface, the ``audit`` CLI verb, and
the Explorer's ``/.status`` report.  Passes:

 - actor-handler lint (``handler_lint``) when the model is an actor system;
 - jaxpr kernel audit (``jaxpr_audit``) when a device twin resolves;
 - config-lifecycle checks (``CF*``, below).

``deep=True`` adds the expensive passes (the bounded closure-domain probe
and the fresh-twin drift re-resolve); the ``spawn_tpu`` preflight runs the
light tier so launch latency stays bounded, while ``.audit()`` and the CLI
default to deep.

Config rules:

 - ``CF301`` error — the model's configuration changed after its tensor
   twin was resolved (the cached twin no longer matches a fresh resolve,
   or the builder signature drifted).  ``TensorBackedModel`` raises on
   builder mutations only *after the first fingerprint*; this check makes
   the silent window before that — direct attribute writes, bypassed
   builder methods — a preflight failure instead of a mid-run
   mixed-fingerprint-scheme surprise.
 - ``CF302`` info — the model declares ``tensor_model()`` but no twin
   resolves (device engines unavailable; host checkers unaffected).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .handler_lint import run_handler_lint
from .jaxpr_audit import run_jaxpr_audit
from .report import AuditReport, Severity
from .sanitizer import run_sanitizer


_SIMPLE = (int, float, str, bool, bytes, tuple, frozenset, type(None))


def _value_repr(v) -> str:
    """Address-free repr of a config value.  Containers sign by CONTENT
    (via the repo's structural ``stable_hash``) — a length tag would let
    two same-sized-but-different configs share one cached report, and
    would blind CF301 to length-preserving mutations."""
    if isinstance(v, _SIMPLE):
        return repr(v)
    if isinstance(v, (list, set, dict, tuple, frozenset)):
        from ..fingerprint import stable_hash

        try:
            return f"<{type(v).__name__} h={stable_hash(v):x}>"
        except Exception:  # noqa: BLE001 - unhashable exotic content
            return f"<{type(v).__name__} len={len(v)}>"
    return f"<{type(v).__name__}>"


def _code_tag(cls, method_names) -> str:
    """Per-process fingerprint of the methods the audit actually inspects.
    Keys the report cache to the CODE, not just the class name: a
    redefined same-named class (notebook iteration, reload) must not be
    served the old class's findings — the reproduced failure mode was a
    fixed handler still refusing to spawn on a stale AH201 report."""
    h = 0
    for name in method_names:
        code = getattr(getattr(cls, name, None), "__code__", None)
        if code is not None:
            try:
                h = (h * 1000003 + hash((code.co_code, code.co_consts))) & (
                    (1 << 32) - 1
                )
            except TypeError:
                h = (h * 1000003 + hash(code.co_code)) & ((1 << 32) - 1)
    return format(h, "x")


_AUDITED_MODEL_METHODS = (
    "tensor_model", "init_states", "actions", "next_state", "properties",
)
_AUDITED_ACTOR_METHODS = ("on_start", "on_msg", "on_timeout")


def _obj_sig(obj, audited_methods=_AUDITED_MODEL_METHODS) -> str:
    """Value-based signature of a config-carrying object (a model or an
    actor): qualified class name + code tag + dataclass fields or shallow
    simple attributes.  Never the default ``repr`` — that embeds a memory
    address, which (a) misses every attribute mutation and (b) can
    collide after GC reuse."""
    cls = type(obj)
    # module + qualname + __name__ (dynamically generated classes rename
    # themselves via __name__) + a code tag over the audited methods
    parts = [
        f"{cls.__module__}.{cls.__qualname__}/{cls.__name__}"
        f"#{_code_tag(cls, audited_methods)}"
    ]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            try:
                parts.append(f"{f.name}={_value_repr(getattr(obj, f.name))}")
            except Exception:  # noqa: BLE001 - best-effort signature
                pass
    else:
        for k in sorted(getattr(obj, "__dict__", {})):
            if k.startswith("_"):
                continue
            parts.append(f"{k}={_value_repr(obj.__dict__[k])}")
    return ",".join(parts)


def config_signature(model) -> str:
    """Cheap, process-stable fingerprint of a model's *configuration*
    surface: dataclass fields / simple attributes plus the ActorModel
    builder state (each actor signed by value, not by object identity).
    Recorded when the tensor twin resolves; a later mismatch means the
    config mutated underneath a cached twin (rule ``CF301``)."""
    parts = [_obj_sig(model)]
    actors = getattr(model, "actors", None)
    if isinstance(actors, list):
        parts.append(
            "actors="
            + ";".join(_obj_sig(a, _AUDITED_ACTOR_METHODS) for a in actors)
        )
        parts.append(f"lossy={getattr(model, 'lossy', None)!r}")
        parts.append(f"network={type(getattr(model, 'init_network', None)).__name__}")
        try:
            parts.append("props=" + ",".join(p.name for p in model.properties()))
        except Exception:  # noqa: BLE001
            pass
    return "|".join(parts)


def _resolve_twin(model, report: AuditReport, sig=None):
    """Resolve the device twin WITHOUT freezing the fingerprint scheme
    (unlike ``TensorBackedModel._tensor_cached``, which marks the config
    frozen): auditing a model must stay a read-only operation.  The twin
    is still cached on the model the same way, so an audit-then-spawn
    never compiles twice.  Returns ``(twin, fresh)``: a freshly resolved
    twin cannot have drifted yet, so the deep CF301 re-resolve (a second
    full tabulation for compiled models) is skipped for it."""
    if hasattr(model, "_tensor_model_cache"):
        tm = getattr(model, "_tensor_model_cache")
        if tm is None:
            # keep the twin-less explanation on EVERY report, not just the
            # one built when the None twin was first cached
            report.add(
                "CF302",
                Severity.INFO,
                "tensor_model",
                "no device twin for this configuration; spawn_tpu is "
                "unavailable, host checkers unaffected",
            )
        return tm, False
    fn = getattr(model, "tensor_model", None)
    if fn is None:
        return None, False
    try:
        tm = fn()
    except Exception as e:  # noqa: BLE001 - CompileError etc: host fallback
        report.add(
            "CF302",
            Severity.INFO,
            "tensor_model",
            f"no device twin ({type(e).__name__}: {e}); spawn_tpu is "
            "unavailable for this configuration, host checkers unaffected",
        )
        return None, True
    try:
        object.__setattr__(model, "_tensor_model_cache", tm)
        object.__setattr__(
            model,
            "_tensor_config_sig",
            sig if sig is not None else config_signature(model),
        )
    except Exception:  # noqa: BLE001 - __slots__ models: skip caching
        pass
    if tm is None:
        report.add(
            "CF302",
            Severity.INFO,
            "tensor_model",
            "no device twin for this configuration (tensor_model() returned "
            "None); spawn_tpu is unavailable, host checkers unaffected",
        )
    return tm, True


def _check_config_drift(
    model, twin, report: AuditReport, deep: bool, sig=None
) -> None:
    """CF301: a cached twin must still match the live configuration."""
    if twin is None or not hasattr(model, "_tensor_model_cache"):
        return
    recorded = getattr(model, "_tensor_config_sig", None)
    if sig is None:
        sig = config_signature(model)
    if recorded is not None and recorded != sig:
        report.add(
            "CF301",
            Severity.ERROR,
            "builder",
            "configuration mutated after the tensor twin was resolved "
            "(builder signature drifted); the cached twin would fingerprint "
            "with the OLD configuration, silently mixing fingerprint "
            "schemes — re-create the model or configure it fully before "
            "resolving/checking",
        )
        return
    if not deep:
        return
    fn = getattr(model, "tensor_model", None)
    if fn is None:
        return
    try:
        fresh = fn()
    except Exception as e:  # noqa: BLE001 - surfaced as drift
        report.add(
            "CF301",
            Severity.ERROR,
            "builder",
            f"tensor_model() no longer resolves ({type(e).__name__}: {e}) "
            "but a twin is cached: configuration mutated after resolution",
        )
        return
    if fresh is None:
        report.add(
            "CF301",
            Severity.ERROR,
            "builder",
            "tensor_model() now returns None but a twin is cached: "
            "configuration mutated after resolution",
        )
        return
    drift = (
        getattr(fresh, "width", None) != getattr(twin, "width", None)
        or getattr(fresh, "max_actions", None) != getattr(twin, "max_actions", None)
    )
    if not drift:
        try:
            a = np.asarray(twin.init_rows())
            b = np.asarray(fresh.init_rows())
            drift = a.shape != b.shape or not np.array_equal(a, b)
        except Exception:  # noqa: BLE001 - can't compare: leave undecided
            return
    if drift:
        report.add(
            "CF301",
            Severity.ERROR,
            "builder",
            "configuration mutated after the tensor twin was resolved: a "
            "fresh tensor_model() disagrees with the cached twin "
            f"(width {getattr(twin, 'width', '?')} -> "
            f"{getattr(fresh, 'width', '?')}, max_actions "
            f"{getattr(twin, 'max_actions', '?')} -> "
            f"{getattr(fresh, 'max_actions', '?')}); the run would silently "
            "mix fingerprint schemes",
        )


# Process-wide report cache keyed by configuration signature: test suites
# and bench sweeps re-create identical configs by the dozen, and the audit
# of a (class, config) pair is deterministic.  Never consulted for a model
# whose live config drifted from its twin-resolution snapshot (CF301 must
# fire per instance).
_SHARED_REPORTS: dict = {}
_SHARED_REPORTS_MAX = 512


def audit_model(
    model,
    *,
    deep: bool = False,
    batch: int = 4,
    tensor: Optional[object] = None,
    share: bool = True,
) -> AuditReport:
    """Run every static-analysis pass over ``model`` and return the
    :class:`AuditReport`.  Reports are cached on the model and in a
    process-wide config-keyed cache (invalidated by configuration changes
    via :func:`config_signature`), so the spawn-path preflight is free on
    respawns and on same-config re-creations.  ``tensor`` overrides twin
    resolution for auditing a bare :class:`TensorModel`."""
    sig = config_signature(model)
    drifted = (
        hasattr(model, "_tensor_model_cache")
        and getattr(model, "_tensor_config_sig", sig) != sig
    )
    cached = getattr(model, "_audit_report_cache", None)
    if (
        cached is not None
        and cached[0] == sig
        and (cached[1] or not deep)
        and tensor is None
        and not drifted
    ):
        return cached[2]
    if share and tensor is None and not drifted:
        hit = _SHARED_REPORTS.get(sig)
        if hit is not None and (hit[0] or not deep):
            # hand out a COPY: the cached report is pristine, and each
            # model's copy accumulates its own run metrics (table
            # occupancy) without leaking into same-config siblings
            report = hit[1].copy()
            try:
                object.__setattr__(model, "_audit_report_cache", (sig, hit[0], report))
                object.__setattr__(model, "_audit_report", report)
            except Exception:  # noqa: BLE001 - __slots__ models
                pass
            return report

    report = AuditReport(model=type(model).__name__)
    if tensor is not None:
        twin, fresh_twin = tensor, True
    else:
        twin, fresh_twin = _resolve_twin(model, report, sig=sig)

    # actor systems: handler lint (AH*); the AH205 severity depends on
    # whether the compiled twin already declares a state_bound
    if getattr(model, "actors", None):
        run_handler_lint(
            model,
            report,
            deep=deep,
            bounded_twin=bool(getattr(twin, "_has_state_bound", False)),
        )

    if twin is not None:
        run_jaxpr_audit(twin, report, model=model, deep=deep, batch=batch)
        # value-level pass: interval/bounds sanitizer (JX2xx).  Runs in the
        # light tier too — JX201/JX202 are exactly the silent-clamp class
        # the spawn preflight exists to abort on, and the interval walk is
        # a same-order cost as the structural audit's trace.
        run_sanitizer(twin, report, model=model, batch=batch)
        # The static independence analysis (JX3xx, analysis/independence.py)
        # is deliberately NOT part of the audit tiers: its footprint
        # extraction re-traces every kernel, and the audit runs on every
        # spawn and across whole test suites.  It has its own surfaces —
        # the `independence` CLI verb + fleet gate, regress.py
        # --independence, and the engines' lazy por() resolution (cached
        # per twin) — and `independence.fold_into_report` exists for
        # callers that want the findings merged into an AuditReport.
        _check_config_drift(
            model, twin, report, deep and not fresh_twin, sig=sig
        )

    if tensor is not None:
        # override-twin audits are one-off probes: caching them (on the
        # model OR process-wide) would let a later plain audit — including
        # the spawn_tpu preflight — serve the override's findings for the
        # model's REAL twin
        return report
    try:
        object.__setattr__(model, "_audit_report_cache", (sig, deep, report))
        object.__setattr__(model, "_audit_report", report)
    except Exception:  # noqa: BLE001 - __slots__ models: skip caching
        pass
    if share and not drifted:
        if len(_SHARED_REPORTS) >= _SHARED_REPORTS_MAX:
            _SHARED_REPORTS.clear()
        _SHARED_REPORTS[sig] = (deep, report.copy())  # pristine: no run metrics
    return report
