"""Preflight static analysis for device-checked models.

Four passes over a model *before* any device launch — the static
counterpart to the engines' runtime poison/growth diagnostics:

 - :mod:`.jaxpr_audit` — abstractly trace a ``TensorModel``'s
   ``step_rows``/``property_masks`` and walk the jaxpr for purity, dtype,
   shape-contract, and retrace-stability violations (plus a FLOPs/bytes
   perf preflight);
 - :mod:`.sanitizer` (over :mod:`.interval`) — value-level soundness:
   interval abstract interpretation proving gather/scatter indices stay on
   their axes (JX201/JX202) and packed fields inside their widths (JX203),
   with the ``checkify``-instrumented checked execution mode as the
   dynamic guard for what the domain can't decide;
 - :mod:`.handler_lint` — AST-lint actor handlers for nondeterminism and
   in-place mutation, and probe one bounded step of the tabulation
   closure for unbounded (ballot-style) field domains;
 - :mod:`.audit` — the driver: twin resolution, config-drift checks, and
   the per-model report cache;
 - :mod:`.costmodel` — the roofline cost ledger (docs/roofline.md):
   per-op FLOPs/bytes attribution of the engine pipeline, reconciled
   against XLA's ``cost_analysis()``, with the JX4xx MXU-candidate
   ranking (the ``costmodel`` verb and ``.telemetry(roofline=True)``).

Surfaces: ``model.checker().audit()`` (and the automatic ``spawn_tpu``
preflight — errors abort before launch, ``skip_audit()`` overrides),
``python -m stateright_tpu.models._cli audit`` over the example fleet,
and the Explorer's ``/.status``.  Rule catalogue: ``docs/analysis.md``.
"""

from .audit import audit_model, config_signature
from .costmodel import CostReport, sharded_costs, wavefront_costs
from .footprint import extract_footprints
from .independence import (
    IndependenceReport,
    PorPlan,
    por_plan,
    run_independence,
)
from .report import AuditError, AuditFinding, AuditReport, Severity
from .sanitizer import (
    CheckedExecutionError,
    checkify_kernels,
    localize_checked_failure,
    run_sanitizer,
)

__all__ = [
    "AuditError",
    "AuditFinding",
    "AuditReport",
    "CheckedExecutionError",
    "CostReport",
    "IndependenceReport",
    "PorPlan",
    "Severity",
    "audit_model",
    "checkify_kernels",
    "config_signature",
    "extract_footprints",
    "localize_checked_failure",
    "por_plan",
    "run_independence",
    "run_sanitizer",
    "sharded_costs",
    "wavefront_costs",
]
