"""Structured audit results: findings, severities, and the report surface.

Every static-analysis pass (``jaxpr_audit``, ``handler_lint``, the config
drift check) produces :class:`AuditFinding` values; :class:`AuditReport`
aggregates them per model with run-quality metrics (per-row FLOPs/bytes,
visited-table occupancy).  The report is the single artifact shared by the
``CheckerBuilder`` preflight (errors abort before device launch), the
``audit`` CLI verb, and the Explorer's ``/.status`` endpoint.

Rule-id namespaces (full catalogue: ``docs/analysis.md``):

 - ``JX*`` — jaxpr kernel audit (``analysis/jaxpr_audit.py``)
 - ``AH*`` — actor-handler lint (``analysis/handler_lint.py``)
 - ``CF*`` — builder/config lifecycle checks (``analysis/audit.py``)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


class Severity:
    """Ordered severity levels.  ``ERROR`` findings abort ``spawn_tpu``
    preflight; ``WARNING`` findings print once; ``INFO`` findings are
    advisory (perf estimates, downgraded rules)."""

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    _ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

    @classmethod
    def rank(cls, sev: str) -> int:
        return cls._ORDER.get(sev, 3)


@dataclass(frozen=True)
class AuditFinding:
    """One diagnostic: a stable rule id, a severity, where, and why."""

    rule_id: str
    severity: str
    location: str  # e.g. "step_rows", "actor[2].on_msg:14", "builder"
    message: str

    def format(self) -> str:
        return f"{self.severity.upper():7s} {self.rule_id} {self.location}: {self.message}"


@dataclass
class AuditReport:
    """All findings for one model, plus perf/diagnostic metrics.

    ``metrics`` carries non-finding diagnostics: per-kernel FLOPs/bytes
    estimates (``metrics["step_rows"]``) and, once a device run exists,
    the visited-table bucket-occupancy counters (``metrics["table"]``,
    from ``ops/buckets.occupancy_stats``)."""

    model: str = ""
    findings: list = field(default_factory=list)
    metrics: dict = field(default_factory=dict)

    # -- construction --------------------------------------------------------

    def copy(self) -> "AuditReport":
        """Shallow copy with its own findings list and metrics dict.
        Findings are frozen and shared; the metrics dict must be private
        per model — engines fold run diagnostics (table occupancy) into
        it, and a shared dict would leak one run's numbers into every
        same-config model's report."""
        return AuditReport(
            model=self.model,
            findings=list(self.findings),
            metrics=dict(self.metrics),
        )

    def add(self, rule_id: str, severity: str, location: str, message: str) -> None:
        self.findings.append(AuditFinding(rule_id, severity, location, message))

    def extend(self, findings) -> None:
        """Append findings, skipping exact duplicates: multiple passes
        (structural audit + sanitizer) and cache re-extends fold into one
        report without repeating a finding — so the once-per-model warning
        print stays one header + one line per distinct diagnostic."""
        seen = set(self.findings)
        for f in findings:
            if f not in seen:
                self.findings.append(f)
                seen.add(f)

    def merge(self, other: "AuditReport") -> "AuditReport":
        """Fold another pass's report into this one: findings dedupe (see
        :meth:`extend`), metrics merge without overwriting this report's
        entries.  Returns self."""
        self.extend(other.findings)
        for k, v in other.metrics.items():
            self.metrics.setdefault(k, v)
        return self

    # -- queries -------------------------------------------------------------

    @property
    def errors(self) -> list:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def warnings(self) -> list:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    @property
    def infos(self) -> list:
        return [f for f in self.findings if f.severity == Severity.INFO]

    @property
    def ok(self) -> bool:
        """No errors (warnings/infos permitted)."""
        return not self.errors

    def by_rule(self, rule_id: str) -> list:
        return [f for f in self.findings if f.rule_id == rule_id]

    def rule_ids(self) -> set:
        return {f.rule_id for f in self.findings}

    # -- rendering -----------------------------------------------------------

    def format(self, min_severity: str = Severity.INFO) -> str:
        """Human-readable report, most severe first."""
        cut = Severity.rank(min_severity)
        shown = sorted(
            (f for f in self.findings if Severity.rank(f.severity) <= cut),
            key=lambda f: (Severity.rank(f.severity), f.rule_id, f.location),
        )
        head = (
            f"audit {self.model}: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), {len(self.infos)} info"
        )
        lines = [head] + ["  " + f.format() for f in shown]
        if "step_rows" in self.metrics:
            m = self.metrics["step_rows"]
            lines.append(
                "  perf: step_rows ~{flops:.0f} flops/row, "
                "~{bytes:.0f} bytes/row, {eqns} eqns".format(
                    flops=m.get("flops_per_row", 0.0),
                    bytes=m.get("bytes_per_row", 0.0),
                    eqns=m.get("eqns", 0),
                )
            )
        return "\n".join(lines)

    def to_json(self) -> dict:
        """JSON-safe dict for ``/.status`` and tooling."""
        return {
            "model": self.model,
            "ok": self.ok,
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "infos": len(self.infos),
            "findings": [
                {
                    "rule_id": f.rule_id,
                    "severity": f.severity,
                    "location": f.location,
                    "message": f.message,
                }
                for f in self.findings
            ],
            "metrics": self.metrics,
        }


class AuditError(RuntimeError):
    """Preflight audit found errors; raised by ``spawn_tpu`` before any
    device work happens.  Carries the full report plus ``rule_ids`` — the
    error-severity rule ids, machine-readable for CLI exit paths (the
    ``audit``/``sanitize`` verbs print and key on them without parsing the
    rendered message).  Silence deliberately with
    ``CheckerBuilder.skip_audit()``."""

    def __init__(self, report: AuditReport, context: Optional[str] = None):
        self.report = report
        self.rule_ids: tuple = tuple(
            sorted({f.rule_id for f in report.errors})
        )
        prefix = f"{context}: " if context else ""
        super().__init__(
            prefix
            + "preflight audit failed (skip_audit() to override)\n"
            + report.format(min_severity=Severity.WARNING)
        )
