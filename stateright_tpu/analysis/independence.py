"""Static independence analysis → the per-model conflict matrix.

Built on the footprint pass (``footprint.py``): two actions are
**independent** iff each one's write set is disjoint from the other's
read ∪ write set *and* from the other's enabledness-guard footprint —
which gives both halves of the classic independence contract at once:
the updates commute bit-for-bit (each writes bits the other neither
reads nor writes, and the untouched remainder of every word is a pure
copy), and neither action can enable or disable the other (no write
lands in the other's guard).  Everything the footprint pass could not
decide is conservatively **dependent** (rule ``JX301``/``JX302`` below):
undecidability costs reduction, never soundness.

The matrix is a compile-time constant per tensor twin; the device
engines consume it through :func:`por_plan`, which additionally decides
whether partial-order reduction is *usable* for the model at all
(fallback rules below) and which actions are **visible** to the declared
properties (an ample set containing a property-visible action is never a
valid reduction — the C2 invisibility condition).

Rule catalogue (``JX3xx``, ``docs/analysis.md``):

 - ``JX300`` warning — the footprint pass crashed; every action is
   conservatively dependent (inherited from ``footprint.py``).
 - ``JX301`` info — an action's footprint is undecidable (collapsed to
   ⊤): conservatively dependent on every action.
 - ``JX302`` info — the successor stack does not decompose per action
   (data-dependent assembly, e.g. the slot-multiset network twins): the
   whole matrix is conservatively dependent.
 - ``JX303`` warning — a declared property's read footprint contains no
   field any action ever writes: the property is constant over the
   reachable space (dead/vacuous — likely a stale or miswired predicate).
 - ``JX304`` info — ``por()`` would fall back to full expansion for this
   model (an ``eventually`` property makes reduction unsound, or the
   matrix admits no independent pair).
 - ``JX305`` info — the non-decomposition is specifically the
   slot-multiset actor-network packing: names the per-channel encoding
   escape hatch (``ActorModel.per_channel_()`` / ``--per-channel`` /
   ``STATERIGHT_TPU_PER_CHANNEL=1``) that makes the stack decompose.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..core import Expectation
from .footprint import (
    FieldSet,
    ModelFootprints,
    extract_footprints,
    union_all,
)
from .report import AuditFinding, Severity

_MAX_LISTED = 4  # cap per-action JX301 noise; the count rides the metrics


@dataclass
class IndependenceReport:
    """The conflict matrix plus everything the engines and the CLI verb
    surface about it."""

    n_actions: int
    conflict: np.ndarray  # bool [A, A], symmetric, diagonal True
    visible: np.ndarray  # bool [A]: writes intersect any property read
    footprints: Optional[ModelFootprints]
    findings: list = field(default_factory=list)
    #: network packing of the analyzed twin ("slot-multiset" /
    #: "per-channel" for compiled actor twins, None for hand-written ones)
    encoding: Optional[str] = None

    @property
    def independent_pairs(self) -> int:
        a = self.n_actions
        return int((a * a - int(self.conflict.sum())) // 2)

    def summary(self) -> dict:
        fp = self.footprints
        return {
            "actions": self.n_actions,
            "independent_pairs": self.independent_pairs,
            "visible_actions": int(self.visible.sum()),
            "undecided_actions": (
                len(fp.undecided_actions) if fp is not None
                else self.n_actions
            ),
            "decomposed": bool(fp.decomposed) if fp is not None else False,
            "encoding": self.encoding,
            "rules": sorted({f.rule_id for f in self.findings}),
        }


@dataclass
class PorPlan:
    """What a ``por()`` run needs: the conflict matrix, per-action
    visibility, the guard-conjunct enabler tensor for the stubborn-set
    closure, and whether reduction is sound/useful for this model at all.

    ``enablers[i, k, j]`` — action ``j`` writes into conjunct ``k`` of
    action ``i``'s guard (a *necessary enabling set*: while conjunct ``k``
    is false, ``i`` cannot become enabled until some ``j`` fires).  Rows
    past ``i``'s conjunct count are all-False padding (their conjunct
    truth is padded True on device, so they are never selected).
    ``leaf_idx`` — per action, indices into the conjunct kernel's leaf
    outputs (None = single whole-guard conjunct whose truth is the
    enabled bit itself)."""

    conflict: np.ndarray
    visible: np.ndarray
    usable: bool
    fallback_reason: Optional[str] = None
    enablers: Optional[np.ndarray] = None  # bool [A, K, A]
    leaf_idx: Optional[list] = None
    n_leaves: int = 0


def _conflicts(fa, fb) -> bool:
    """May ``a`` and ``b`` interfere?  Independence needs BOTH directions
    write-vs-(read ∪ write ∪ guard) disjoint; undecided is dependent.

    ``accum`` bits (monotone OR-accumulates, ``new = old | f(reads)`` —
    the compiled twins' saturating poison flag) get ONE exemption:
    accum∩accum is commutative bit-for-bit (``old | fa | fb`` either
    way, and each side's ``f`` reads only its own footprint, which the
    plain rules already keep disjoint), so two accumulating actions stay
    independent.  Against everything else an accum bit behaves exactly
    like a write: a plain write could clobber the accumulated bit, and a
    read/guard of it would observe order."""
    if not fa.decided or not fb.decided:
        return True
    return (
        fa.writes.intersects(fb.reads)
        or fa.writes.intersects(fb.writes)
        or fa.writes.intersects(fb.guard)
        or fa.writes.intersects(fb.accum)
        or fb.writes.intersects(fa.reads)
        or fb.writes.intersects(fa.guard)
        or fb.writes.intersects(fa.accum)
        or fa.accum.intersects(fb.reads)
        or fa.accum.intersects(fb.guard)
        or fb.accum.intersects(fa.reads)
        or fb.accum.intersects(fa.guard)
    )


def run_independence(tensor, props, model_name: str = "") -> IndependenceReport:
    """Compute the conflict matrix for ``tensor`` (cached on the twin) —
    ``props`` is the object model's ``properties()`` list (names/kinds for
    visibility and the JX303/JX304 diagnostics)."""
    cached = getattr(tensor, "_independence_cache", None)
    if cached is not None:
        return cached
    arity = int(getattr(tensor, "max_actions", 0) or 0)
    encoding = getattr(tensor, "network_encoding", None)
    fps = extract_footprints(tensor)
    findings: list = []
    if fps is None:
        conflict = np.ones((arity, arity), bool)
        visible = np.ones((arity,), bool)
        findings.append(AuditFinding(
            "JX302", Severity.INFO, "step_rows",
            "no footprints (kernel untraceable or twin contract missing): "
            "every action pair is conservatively dependent",
        ))
        out = IndependenceReport(arity, conflict, visible, None, findings,
                                 encoding=encoding)
        _cache(tensor, out)
        return out

    findings.extend(fps.findings)
    conflict = np.zeros((arity, arity), bool)
    for i in range(arity):
        conflict[i, i] = True
        for j in range(i + 1, arity):
            c = _conflicts(fps.actions[i], fps.actions[j])
            conflict[i, j] = conflict[j, i] = c

    prop_union = union_all(fps.prop_reads) if fps.prop_reads else (
        FieldSet.top_set()
    )
    visible = np.asarray([
        (not a.decided)
        or a.writes.intersects(prop_union)
        or a.accum.intersects(prop_union)
        for a in fps.actions
    ], bool)

    if not fps.decomposed:
        findings.append(AuditFinding(
            "JX302", Severity.INFO, "step_rows",
            "successor assembly does not decompose per action (data-"
            "dependent writes — the slot-multiset network idiom): the "
            "conflict matrix is conservatively all-dependent; por() runs "
            "as full expansion",
        ))
        # JX305 — the actionable escape hatch: when the non-decomposition
        # is the slot-multiset actor packing specifically, the fix is one
        # builder/CLI flag away (pinned firing on the default paxos twin,
        # silent once the model migrates to per-channel)
        if getattr(tensor, "network_encoding", None) == "slot-multiset":
            findings.append(AuditFinding(
                "JX305", Severity.INFO, "step_rows",
                "this is the slot-multiset network packing: a delivery's "
                "destination is message DATA, so its writes cannot be "
                "statically confined.  Re-compile with the per-channel "
                "layout — ActorModel.per_channel_() / --per-channel / "
                "STATERIGHT_TPU_PER_CHANNEL=1 — to make the action stack "
                "decompose and turn por() into real reduction "
                "(docs/analysis.md \"Per-channel encoding\")",
            ))
    else:
        und = fps.undecided_actions
        for a in und[:_MAX_LISTED]:
            findings.append(AuditFinding(
                "JX301", Severity.INFO, f"step_rows:action#{a}",
                "action footprint is undecidable (collapsed to top): "
                "conservatively dependent on every action",
            ))
        if len(und) > _MAX_LISTED:
            findings.append(AuditFinding(
                "JX301", Severity.INFO, "step_rows",
                f"... and {len(und) - _MAX_LISTED} more undecidable "
                "action footprints (count in metrics)",
            ))

    # JX303 — vacuous property: reads only fields no action ever writes.
    # Requires every write footprint decided: an undecided action could
    # write anything, so the lint stays silent (no false fleet noise).
    all_writes_decided = all(a.decided for a in fps.actions)
    if all_writes_decided and props and fps.prop_reads:
        writes_union = union_all(
            a.writes.union(a.accum) for a in fps.actions
        )
        for p, reads in zip(props, fps.prop_reads):
            if reads.top or reads.is_empty:
                continue
            if not reads.intersects(writes_union):
                findings.append(AuditFinding(
                    "JX303", Severity.WARNING,
                    f"property:{getattr(p, 'name', '?')}",
                    "property read footprint contains no field any action "
                    "ever writes: its truth value is frozen at the init "
                    "states — a dead/vacuous (likely miswired) property",
                ))

    out = IndependenceReport(arity, conflict, visible, fps, findings,
                             encoding=encoding)

    # JX304 — por() fallback preview for this model
    plan = _plan_from(out, props, tensor)
    if not plan.usable:
        out.findings.append(AuditFinding(
            "JX304", Severity.INFO, "por",
            f"partial-order reduction falls back to full expansion for "
            f"this model: {plan.fallback_reason}",
        ))
    _cache(tensor, out)
    return out


def _cache(tensor, report: IndependenceReport) -> None:
    try:
        tensor._independence_cache = report
    except Exception:  # noqa: BLE001 - __slots__ twins
        pass


def _plan_from(report: IndependenceReport, props, tensor=None) -> PorPlan:
    """Soundness/usefulness gate for a ``por()`` run (docs/analysis.md
    "POR soundness contract"):

     - any ``eventually`` property disables reduction outright — the
       engines' terminal-state liveness flush is not stutter-closed under
       ample-set exploration, so the liveness verdict could change;
     - a matrix with no independent pair (including every undecidable
       fallback) reduces nothing — run full expansion without paying the
       ample-set selection in the step program.
    """
    has_eventually = any(
        getattr(p, "expectation", None) is Expectation.EVENTUALLY
        for p in (props or [])
    )
    if has_eventually:
        return PorPlan(report.conflict, report.visible, False,
                       "the model declares eventually/liveness properties")
    if tensor is not None and getattr(tensor, "has_boundary", False):
        # the closure classifies actions enabled/disabled by the MODEL
        # guard; a boundary filter disables actions the guard admits, so
        # the classification (and the necessary-enabling logic) would lie
        return PorPlan(report.conflict, report.visible, False,
                       "the twin declares a boundary filter")
    if report.independent_pairs == 0:
        return PorPlan(report.conflict, report.visible, False,
                       "the conflict matrix admits no independent pair")
    if bool(report.visible.all()):
        return PorPlan(report.conflict, report.visible, False,
                       "every action is visible to a property footprint")
    fps = report.footprints
    cj = fps.conjuncts if fps is not None else None
    if cj is None:
        return PorPlan(report.conflict, report.visible, False,
                       "no guard-conjunct decomposition")
    a = report.n_actions
    k = cj.max_conjuncts
    en = np.zeros((a, k, a), bool)
    for i in range(a):
        for ki, cset in enumerate(cj.sets[i]):
            for j in range(a):
                fj = fps.actions[j]
                en[i, ki, j] = (
                    (not fj.decided)
                    or fj.writes.intersects(cset)
                    or fj.accum.intersects(cset)
                )
    return PorPlan(
        report.conflict, report.visible, True,
        enablers=en, leaf_idx=list(cj.leaf_idx), n_leaves=cj.n_leaves,
    )


def por_plan(tensor, props) -> PorPlan:
    """The engines' entry point: conflict matrix + visibility + the
    usable/fallback verdict for this tensor twin."""
    return _plan_from(run_independence(tensor, props), props, tensor)


def fold_into_report(tensor, props, report) -> None:
    """Merge the independence findings + summary into an ``AuditReport``
    (the deep audit tier and the ``independence`` CLI verb)."""
    ind = run_independence(tensor, props)
    report.extend(ind.findings)
    report.metrics["independence"] = ind.summary()
