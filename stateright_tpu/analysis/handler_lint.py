"""Actor-handler lint: AST + one bounded closure step over actor systems.

The model checker's soundness rests on handlers being *pure functions of
(state, message)*: the CPU checkers memoize on state hashes, and the actor
compiler (``parallel/actor_compiler.py``) runs each handler exactly once
per (state, envelope) pair and replays the tabulated effect on device.  A
handler that consults a clock, mutates its input, or iterates a set in
hash order silently forks the transition relation between those replays.

Rules (full catalogue: ``docs/analysis.md``):

 - ``AH201`` error — nondeterminism source in a handler (unseeded
   ``random``, wall-clock ``time``/``datetime``, ``uuid``, ``os.urandom``);
 - ``AH202`` warning — ordering/address nondeterminism: builtin ``id()``
   or iteration over a set literal / ``set()`` call (hash order leaks into
   send order);
 - ``AH203`` error — in-place mutation of the incoming state (assignment
   to, or a mutating method call on, the state parameter): states must be
   immutable values shared structurally across the visited set;
 - ``AH204`` error — unhashable actor start state: the checkers and the
   compiler's interning tables key on ``hash(state)``;
 - ``AH205`` warning — a numeric field (or collection size) grows
   monotonically under a bounded step of the tabulation closure: the
   Paxos-ballot trap — the compile closure diverges without a
   ``state_bound`` (downgraded to info when the model's compiled twin
   already declares one);
 - ``AH206`` info — handler source unavailable; AST rules skipped for
   that actor class.
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
import textwrap
from collections import deque
from typing import Optional

from .report import Severity

_MUTATORS = frozenset(
    {
        "append", "extend", "insert", "remove", "pop", "clear", "add",
        "discard", "update", "setdefault", "popitem", "sort", "reverse",
    }
)

_TIME_FNS = frozenset(
    {"time", "monotonic", "perf_counter", "time_ns", "monotonic_ns"}
)

# (class, method name) -> list[(rule_id, severity, line, message)]
_AST_CACHE: dict = {}


def _root_name(node) -> Optional[str]:
    """Follow ``a.b[c].d`` down to its base ``Name``; None if the chain
    passes through a call or other expression (a copy breaks the chain)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _HandlerVisitor(ast.NodeVisitor):
    def __init__(self, state_param: Optional[str], param_names: set):
        self.state_param = state_param
        self.param_names = param_names
        self.hits: list = []  # (rule_id, severity, lineno, message)

    # -- nondeterminism ------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id not in self.param_names:
                mod, attr = base.id, f.attr
                if mod == "random":
                    self._hit(
                        node, "AH201", Severity.ERROR,
                        f"unseeded random.{attr}() in a handler: every "
                        "closure replay rolls different dice",
                    )
                elif mod == "time" and attr in _TIME_FNS:
                    self._hit(
                        node, "AH201", Severity.ERROR,
                        f"wall-clock time.{attr}() in a handler: transitions "
                        "become time-dependent and unreproducible",
                    )
                elif mod == "uuid" and attr.startswith("uuid"):
                    self._hit(
                        node, "AH201", Severity.ERROR,
                        f"uuid.{attr}() in a handler is a nondeterminism "
                        "source",
                    )
                elif mod == "os" and attr == "urandom":
                    self._hit(
                        node, "AH201", Severity.ERROR,
                        "os.urandom() in a handler is a nondeterminism source",
                    )
            if f.attr in ("now", "utcnow"):
                root = _root_name(f.value)
                if root in ("datetime", "date") and root not in self.param_names:
                    self._hit(
                        node, "AH201", Severity.ERROR,
                        f"{root}.{f.attr}() in a handler: wall-clock "
                        "nondeterminism",
                    )
            # in-place mutation via method call on the state param
            if (
                self.state_param
                and f.attr in _MUTATORS
                and _root_name(f.value) == self.state_param
            ):
                self._hit(
                    node, "AH203", Severity.ERROR,
                    f"in-place mutation of the incoming state "
                    f"({ast.unparse(f)}(...)): handlers must return a new "
                    "state — the old one is shared across the visited set",
                )
        elif isinstance(f, ast.Name):
            if f.id == "id" and "id" not in self.param_names:
                self._hit(
                    node, "AH202", Severity.WARNING,
                    "builtin id() is a memory address: varies across runs "
                    "and processes",
                )
        self.generic_visit(node)

    # -- in-place mutation via assignment ------------------------------------

    def _check_target(self, target):
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            if self.state_param and _root_name(target) == self.state_param:
                self.hits.append(
                    (
                        "AH203",
                        Severity.ERROR,
                        target.lineno,
                        f"assignment into the incoming state "
                        f"({ast.unparse(target)} = ...): handlers must "
                        "build a new state, not mutate the shared one",
                    )
                )
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self._check_target(t)

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_target(node.target)
        self.generic_visit(node)

    # -- set-iteration ordering ----------------------------------------------

    def visit_For(self, node: ast.For):
        it = node.iter
        is_set = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id in ("set", "frozenset")
        )
        if is_set:
            self._hit(
                node, "AH202", Severity.WARNING,
                "iteration over a set in a handler: hash order leaks into "
                "effect order; iterate sorted(...) instead",
            )
        self.generic_visit(node)

    def _hit(self, node, rule, sev, msg):
        self.hits.append((rule, sev, node.lineno, msg))


def _rebinds(fndef, name: str) -> bool:
    """True when the function binds ``name`` itself (plain assignment,
    walrus, for/with target, aug-assign to the bare name)."""
    for node in ast.walk(fndef):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.NamedExpr):
            targets = [node.target]
        elif isinstance(node, ast.For):
            targets = [node.target]
        elif isinstance(node, ast.withitem) and node.optional_vars:
            targets = [node.optional_vars]
        for t in targets:
            stack = [t]
            while stack:
                x = stack.pop()
                if isinstance(x, ast.Name) and x.id == name:
                    return True
                if isinstance(x, (ast.Tuple, ast.List)):
                    stack.extend(x.elts)
    return False


_AST_CACHE_MAX = 2048


def _lint_method(cls, method_name: str) -> Optional[list]:
    """AST-lint one handler; cached per (class, method).  None means the
    source is unavailable (AH206)."""
    key = (cls, method_name)
    if key in _AST_CACHE:
        return _AST_CACHE[key]
    if len(_AST_CACHE) >= _AST_CACHE_MAX:
        _AST_CACHE.clear()  # strong class keys would pin redefined classes
    fn = getattr(cls, method_name, None)
    if fn is None:
        _AST_CACHE[key] = []
        return []
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError):
        _AST_CACHE[key] = None
        return None
    fndef = next(
        (
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ),
        None,
    )
    if fndef is None:
        _AST_CACHE[key] = []
        return []
    params = [a.arg for a in fndef.args.args]
    # on_msg(self, id, state, src, msg, out) / on_timeout(self, id, state, out)
    state_param = (
        params[2] if method_name in ("on_msg", "on_timeout") and len(params) > 2
        else None
    )
    # A handler that REBINDS the state name (`state = dict(state)`) then
    # mutates its own local copy is sound: drop the mutation rule for it
    # rather than abort a correct model (conservative under-reporting).
    if state_param is not None and _rebinds(fndef, state_param):
        state_param = None
    v = _HandlerVisitor(state_param, set(params))
    v.visit(fndef)
    _AST_CACHE[key] = v.hits
    return v.hits


# -- bounded closure probe (AH205) -------------------------------------------


def _leaves(obj, path: str = "", depth: int = 0):
    """Numeric leaves of a state value, plus collection sizes, keyed by a
    stable field path (dataclass fields, tuple indices; set/dict contents
    collapse onto one aggregated path)."""
    if depth > 6:
        return
    if isinstance(obj, bool) or obj is None:
        return
    if isinstance(obj, int):
        yield path or ".", int(obj)
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            yield from _leaves(getattr(obj, f.name), f"{path}.{f.name}", depth + 1)
    elif isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        for name in obj._fields:
            yield from _leaves(getattr(obj, name), f"{path}.{name}", depth + 1)
    elif isinstance(obj, (tuple, list)):
        yield f"{path}.len", len(obj)
        for k, v in enumerate(obj):
            yield from _leaves(v, f"{path}[{k}]", depth + 1)
    elif isinstance(obj, (set, frozenset)):
        yield f"{path}.len", len(obj)
        for v in obj:
            yield from _leaves(v, f"{path}{{}}", depth + 1)
    elif isinstance(obj, dict):
        yield f"{path}.len", len(obj)
        for v in obj.values():
            yield from _leaves(v, f"{path}{{}}", depth + 1)


def _probe_domains(model, max_calls: int = 4000, max_rounds: int = 10):
    """One bounded step of the tabulation closure: pair every discovered
    state with every discovered envelope (exactly what the compiler's
    closure does, over-approximating reachability), bounded by a handler
    call budget.  Returns ``(growing, converged)`` where ``growing`` maps
    ``(actor_index, field_path)`` to its per-round max series."""
    from ..actor import Id, Out, Send
    from ..actor.network import Envelope

    n = len(model.actors)
    state_round: list = [dict() for _ in range(n)]  # state -> round seen
    env_round: dict = {}
    work: deque = deque()  # ("s", i, state, round) | ("e", env, round)
    maxes: dict = {}  # (i, path) -> {round: max}
    calls = 0

    def note(i, s, rnd):
        for path, val in _leaves(s):
            cur = maxes.setdefault((i, path), {})
            cur[rnd] = max(cur.get(rnd, val), val)

    def add_state(i, s, rnd):
        try:
            if s in state_round[i]:
                return
        except TypeError:
            return  # unhashable: AH204 already covers it
        state_round[i][s] = rnd
        note(i, s, rnd)
        work.append(("s", i, s, rnd))

    def add_env(env, rnd):
        if env in env_round:
            return
        env_round[env] = rnd
        work.append(("e", env, rnd))

    try:
        inits = list(model.init_states())
    except Exception:  # noqa: BLE001 - init failure surfaces elsewhere
        return {}, True
    for init in inits:  # seed from EVERY initial system state
        for i, s in enumerate(init.actor_states):
            add_state(i, s, 0)
        for env in init.network.iter_deliverable():
            add_env(env, 0)

    done_pairs: set = set()

    def run_handler(i, s, env, rnd):
        nonlocal calls
        calls += 1
        out = Out()
        try:
            if env is None:
                ret = model.actors[i].on_timeout(Id(i), s, out)
            else:
                ret = model.actors[i].on_msg(Id(i), s, env.src, env.msg, out)
        except Exception:  # noqa: BLE001 - impossible pair: compiler poisons
            return
        if ret is not None:
            add_state(i, ret, rnd + 1)
        for c in out.commands:
            if isinstance(c, Send):
                add_env(Envelope(src=Id(i), dst=c.dst, msg=c.msg), rnd + 1)

    truncated = False
    while work:
        if calls >= max_calls or work[0][-1] >= max_rounds:
            # budget or round cap hit with expansion still pending: the
            # closure did NOT converge (items stay queued so the flag and
            # the queue agree)
            truncated = True
            break
        kind, *rest = work.popleft()
        if kind == "s":
            i, s, rnd = rest
            run_handler(i, s, None, rnd)
            for env in list(env_round):
                if int(env.dst) == i and (i, s, env) not in done_pairs:
                    done_pairs.add((i, s, env))
                    run_handler(i, s, env, rnd)
        else:
            env, rnd = rest
            i = int(env.dst)
            if i < n:
                for s in list(state_round[i]):
                    if (i, s, env) not in done_pairs:
                        done_pairs.add((i, s, env))
                        run_handler(i, s, env, max(rnd, state_round[i][s]))

    converged = not truncated and not work
    growing: dict = {}
    if not converged:
        for (i, path), per_round in maxes.items():
            rounds = sorted(per_round)
            if len(rounds) < 4:
                continue
            series = []
            running = None
            for r in rounds:
                running = per_round[r] if running is None else max(
                    running, per_round[r]
                )
                series.append(running)
            # strictly increasing over the last 3 observed rounds: the
            # field is still growing when the budget ran out
            tail = series[-4:]
            if all(a < b for a, b in zip(tail, tail[1:])):
                growing[(i, path)] = series
    return growing, converged


def run_handler_lint(
    model,
    report,
    *,
    deep: bool = False,
    bounded_twin: bool = False,
) -> None:
    """Lint ``model``'s actors into ``report``.  ``bounded_twin`` downgrades
    AH205 to info (the compiled twin already declares a ``state_bound``,
    so the growth is cut before it reaches the device)."""
    from ..actor import Actor, Id, Out

    actors = getattr(model, "actors", None)
    if not actors:
        return

    seen_classes: set = set()
    for i, actor in enumerate(actors):
        cls = type(actor)
        if cls in seen_classes:
            continue
        seen_classes.add(cls)
        loc_base = f"actor[{i}] {cls.__name__}"
        for method in ("on_start", "on_msg", "on_timeout"):
            fn = getattr(cls, method, None)
            if fn is None or fn is getattr(Actor, method, None):
                continue  # inherited no-op default
            hits = _lint_method(cls, method)
            if hits is None:
                report.add(
                    "AH206",
                    Severity.INFO,
                    f"{loc_base}.{method}",
                    "handler source unavailable; AST lint skipped",
                )
                continue
            for rule, sev, line, msg in hits:
                report.add(rule, sev, f"{loc_base}.{method}:{line}", msg)

    # AH204: start states must be hashable (checker memoization and the
    # compiler's interning tables both key on hash(state)).
    for i, actor in enumerate(actors):
        try:
            s = actor.on_start(Id(i), Out())
        except Exception as e:  # noqa: BLE001 - surfaced as a finding
            report.add(
                "AH206",
                Severity.INFO,
                f"actor[{i}] {type(actor).__name__}.on_start",
                f"on_start failed during preflight: {type(e).__name__}: {e}",
            )
            continue
        try:
            hash(s)
        except TypeError as e:
            report.add(
                "AH204",
                Severity.ERROR,
                f"actor[{i}] {type(actor).__name__}",
                f"start state is unhashable ({e}); states must be immutable "
                "hashable values (frozen dataclasses, tuples, frozensets)",
            )

    if deep:
        growing, _converged = _probe_domains(model)
        sev = Severity.INFO if bounded_twin else Severity.WARNING
        for (i, path), series in sorted(growing.items()):
            suffix = (
                " (the compiled twin's state_bound cuts this tail: ok)"
                if bounded_twin
                else "; compiling to the device needs a state_bound "
                "(the Paxos-ballot trap — see parallel/actor_compiler.py)"
            )
            report.add(
                "AH205",
                sev,
                f"actor[{i}] field {path!r}",
                "monotonically growing domain under the tabulation closure "
                f"(max per round: {series[-4:]}); the compile closure "
                "diverges without a bound" + suffix,
            )
