"""Roofline cost ledger: per-op FLOPs/bytes attribution for the engines.

The observability triad answers *what happened* (flight recorder),
*where the search went* (cartography/health), and *where the memory
goes* (HBM ledger) — this module answers **where the time goes below
stage granularity**: which jaxpr operations in the engine pipeline move
how many bytes and execute how many scalar ops, so the MXU round
(BLEST / "Graph Traversal on Tensor Cores", PAPERS.md) starts from a
ranked, reconciled hot-spot ledger instead of guesses.

The walk reuses the footprint pass's traversal discipline
(``analysis/footprint.py``): materialize the twin's device constants via
``init_rows()`` outside any trace, ``jax.make_jaxpr`` each pipeline
kernel, then one forward pass over the closed jaxpr charging every eqn
with

 - **FLOPs** — one scalar op per output element for elementwise
   primitives, ``n log2 n`` for sorts, the full read for reductions,
   ``2·M·N·K`` for ``dot_general``, zero for pure layout/data movement;
 - **bytes read / bytes written** — the *moved window* for
   data-dependent memory ops (a gather reads the gathered elements, not
   the whole table; a dynamic-update-slice writes the update window, not
   the whole buffer — matching both XLA's charging model and the
   roofline meaning of the number);
 - an **op class** — ``gather`` / ``scatter`` / ``sort`` / ``dot`` /
   ``elementwise`` / ``reduce`` / ``control``.

Costs aggregate per **engine pipeline stage** — ``property`` /
``expand`` / ``hash`` / ``dedup-insert`` / ``queue``, the five phases of
one wavefront step — and per **action** via the footprint pass's
action-axis decomposition (eqns reachable from exactly one action's
successor stack piece charge to it; the rest charge to ``shared``).

Reconciliation (the memory ledger's ``memory_analysis()`` discipline,
``telemetry/memory.py``): every stage kernel is also compiled and its
``compiled.cost_analysis()`` flops / bytes-accessed recorded next to the
analytic totals.  The two models measure different programs — the walk
charges the *unfused* jaxpr, XLA the *optimized* HLO — so the pinned
contract is a tolerance band, not equality: analytic FLOPs within
``FLOPS_BAND``× of XLA's, analytic bytes never below ``BYTES_LO``× of
XLA's (fusion only ever removes traffic the walk charged) and within
``BYTES_HI``× above.  Exact where exact is possible: a purely
elementwise kernel (the ``hash`` stage) charges bit-identical FLOPs to
XLA's count, pinned by test.

MXU-candidate ranking (rule catalogue ``JX4xx``, docs/roofline.md):

 - ``JX400`` info — a gather/scatter-class op whose shape admits a
   blocked one-hot-matmul recast (the BLEST membership-probe move),
   ranked by charged bytes;
 - ``JX401`` info — a sort-class op recastable as blocked
   compare-exchange / bitonic stages on the MXU;
 - ``JX402`` info — the summary line: which stage owns the largest
   MXU-candidate byte volume.

Everything here is host-side analysis over re-traced kernels: the
engines' own step program is never touched (roofline on or off leaves
the run jaxpr bit-identical and the engine cache unkeyed — pinned,
the ``telemetry/memory.py`` contract in its strongest form).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from .interval import is_literal
from .report import AuditFinding, Severity

# cost-model schema version (the ``roofline`` ring-record / report-block
# ``v`` field rides this)
COSTMODEL_V = 1

# reconciliation tolerance bands (analytic / xla ratios), calibrated on
# the bundled twins (docs/roofline.md "Reconciliation contract"):
#  - FLOPs: both models count scalar ops; they differ on fused selects /
#    gather address math, measured within ~3x either way on the fleet.
#  - bytes: the walk charges the unfused jaxpr (every intermediate
#    read+written), XLA the fused HLO (intermediates fused away), so
#    analytic is an upper bound — bounded above by the longest
#    elementwise chain (BYTES_HI).  The lower side is NOT 1.0: the
#    reconciliation compiles each stage kernel standalone, where an
#    un-donated in-place update (the queue stage's
#    dynamic-update-slice) pays a full-buffer copy XLA prices and the
#    walk — correctly, matching the donated engine carry — does not.
#    Fleet calibration (CPU XLA, jax 0.4.37): bytes ratios span ~0.5
#    (the queue stage's un-donated standalone copy) to ~140 (raft's
#    deeply fused elementwise property chain); the bands leave ~2x
#    margin either side.
FLOPS_BAND = 8.0
BYTES_LO = 0.25
BYTES_HI = 256.0

# MXU-candidate threshold: data-movement ops below this per-step byte
# volume are not worth a matmul recast (one MXU pass costs more)
MXU_MIN_BYTES = 4096
_MXU_TOP = 8  # candidates kept in the ranking / emitted as findings

OP_CLASSES = ("gather", "scatter", "sort", "dot", "elementwise",
              "reduce", "control")

# pure layout / data movement: zero FLOPs, bytes only
_LAYOUT = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims", "copy",
    "convert_element_type", "transpose", "slice", "concatenate", "iota",
    "rev", "pad", "stop_gradient", "bitcast_convert_type",
})
_GATHER = frozenset({"gather", "dynamic_slice"})
_SCATTER = frozenset({
    "scatter", "scatter-add", "scatter_add", "scatter_max", "scatter_min",
    "scatter_mul", "dynamic_update_slice",
})
_REDUCE_PREFIX = "reduce_"
_REDUCE = frozenset({
    "argmax", "argmin", "cumsum", "cummax", "cummin", "cumprod",
    "cumlogsumexp",
})
_CALLS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "remat_call", "checkpoint", "remat",
})
_CONTROL = frozenset({"while", "cond", "scan"})


def classify_primitive(name: str) -> str:
    """Op class of one jaxpr primitive (``OP_CLASSES``)."""
    if name in _GATHER:
        return "gather"
    if name in _SCATTER:
        return "scatter"
    if name == "sort":
        return "sort"
    if name in ("dot_general", "conv_general_dilated"):
        return "dot"
    if name.startswith(_REDUCE_PREFIX) or name in _REDUCE:
        return "reduce"
    if name in _CONTROL or name in _CALLS:
        return "control"
    return "elementwise"


def _nelems(v) -> int:
    shape = tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _nbytes(v) -> int:
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    item = np.dtype(dt).itemsize if dt is not None else 8
    return _nelems(v) * item


def _itemsize(v) -> int:
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return np.dtype(dt).itemsize if dt is not None else 8


@dataclass
class EqnCost:
    """Charged cost of one jaxpr eqn (or one aggregated (prim, shape)
    site)."""

    prim: str
    op_class: str
    flops: int
    bytes_read: int
    bytes_written: int
    count: int = 1
    #: shape of the MOVED data (the roofline-relevant window), for the
    #: MXU ranking's recast check
    shape: tuple = ()
    #: shape of the indexed operand (gather/scatter only)
    operand_shape: tuple = ()

    @property
    def bytes_total(self) -> int:
        return self.bytes_read + self.bytes_written


def _charge_eqn(eqn) -> EqnCost:
    """FLOPs/bytes of one non-call eqn, per the module-docstring rules."""
    name = eqn.primitive.name
    cls = classify_primitive(name)
    out_elems = max((_nelems(v) for v in eqn.outvars), default=0)
    out_bytes = sum(_nbytes(v) for v in eqn.outvars)
    in_bytes = sum(
        _nbytes(v) for v in eqn.invars if not is_literal(v)
    )
    shape = tuple(
        getattr(getattr(eqn.outvars[0], "aval", None), "shape", ()) or ()
    ) if eqn.outvars else ()
    operand_shape: tuple = ()
    flops = 0
    if cls == "gather":
        # reads: the gathered window (out-sized elements of the operand)
        # + the index vector; the untouched rest of the operand is free
        operand_shape = tuple(
            getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
            or ()
        )
        idx_bytes = sum(
            _nbytes(v) for v in eqn.invars[1:] if not is_literal(v)
        )
        in_bytes = out_elems * _itemsize(eqn.invars[0]) + idx_bytes
    elif cls == "scatter":
        # moved window = the updates; the operand is updated in place
        # (XLA's aliasing model) — charge the touched region both ways.
        # Operand orders differ: scatter is (operand, indices, updates),
        # dynamic_update_slice is (operand, update, *start_indices).
        operand_shape = tuple(
            getattr(getattr(eqn.invars[0], "aval", None), "shape", ())
            or ()
        )
        if name == "dynamic_update_slice":
            upd = eqn.invars[1]
            idx_vars = eqn.invars[2:]
        else:
            upd = eqn.invars[-1]
            idx_vars = eqn.invars[1:-1]
        upd_bytes = _nbytes(upd)
        idx_bytes = sum(
            _nbytes(v) for v in idx_vars if not is_literal(v)
        )
        in_bytes = upd_bytes + idx_bytes + upd_bytes
        out_bytes = upd_bytes
        shape = tuple(
            getattr(getattr(upd, "aval", None), "shape", ()) or ()
        )
    elif cls == "sort":
        n = max((_nelems(v) for v in eqn.invars if not is_literal(v)),
                default=0)
        flops = int(n * max(math.log2(max(n, 2)), 1.0))
    elif cls == "dot":
        dnums = eqn.params.get("dimension_numbers")
        m_elems = out_elems
        k = 1
        if dnums is not None:
            try:
                (lc, _rc), _ = dnums
                lshape = tuple(
                    getattr(getattr(eqn.invars[0], "aval", None),
                            "shape", ()) or ()
                )
                for d in lc:
                    k *= int(lshape[d])
            except Exception:  # noqa: BLE001 - fall back to out-sized
                k = 1
        flops = 2 * m_elems * k
    elif cls == "reduce":
        flops = sum(
            _nelems(v) for v in eqn.invars if not is_literal(v)
        )
    elif name in _LAYOUT:
        flops = 0
    else:  # elementwise compute
        flops = out_elems
    return EqnCost(
        prim=name, op_class=cls, flops=int(flops),
        bytes_read=int(in_bytes), bytes_written=int(out_bytes),
        shape=shape, operand_shape=operand_shape,
    )


# ---------------------------------------------------------------------------
# jaxpr linearization (call inlining) + the stage walk
# ---------------------------------------------------------------------------


def _iter_eqns(jaxpr):
    """Yield every non-call eqn of ``jaxpr``, recursing into call / control
    primitives (loop and branch bodies charge ONE trip — the static model
    prices one wavefront step, trip counts are runtime data)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CALLS:
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None:
                yield from _iter_eqns(getattr(inner, "jaxpr", inner))
            continue
        if name in _CONTROL:
            bodies = []
            for key in ("jaxpr", "body_jaxpr", "cond_jaxpr"):
                j = eqn.params.get(key)
                if j is not None:
                    bodies.append(j)
            branches = eqn.params.get("branches")
            if branches:
                bodies.extend(branches)
            for b in bodies:
                yield from _iter_eqns(getattr(b, "jaxpr", b))
            continue
        yield eqn


@dataclass
class StageCost:
    """Aggregated cost of one pipeline stage's traced kernel."""

    name: str
    flops: int = 0
    bytes_read: int = 0
    bytes_written: int = 0
    eqns: int = 0
    classes: dict = field(default_factory=dict)  # class -> {flops, bytes}
    #: aggregated data-movement sites for the MXU ranking:
    #: (prim, shape, operand_shape) -> EqnCost (count accumulated)
    movement: dict = field(default_factory=dict)

    @property
    def bytes_total(self) -> int:
        return self.bytes_read + self.bytes_written

    @property
    def intensity(self) -> Optional[float]:
        """Arithmetic intensity (FLOPs per byte moved); None at 0 bytes."""
        if self.bytes_total <= 0:
            return None
        return self.flops / self.bytes_total

    def charge(self, cost: EqnCost) -> None:
        self.flops += cost.flops
        self.bytes_read += cost.bytes_read
        self.bytes_written += cost.bytes_written
        self.eqns += 1
        c = self.classes.setdefault(
            cost.op_class, {"flops": 0, "bytes": 0, "count": 0}
        )
        c["flops"] += cost.flops
        c["bytes"] += cost.bytes_total
        c["count"] += 1
        if cost.op_class in ("gather", "scatter", "sort"):
            key = (cost.prim, cost.shape, cost.operand_shape)
            site = self.movement.get(key)
            if site is None:
                self.movement[key] = EqnCost(
                    prim=cost.prim, op_class=cost.op_class,
                    flops=cost.flops, bytes_read=cost.bytes_read,
                    bytes_written=cost.bytes_written, shape=cost.shape,
                    operand_shape=cost.operand_shape,
                )
            else:
                site.flops += cost.flops
                site.bytes_read += cost.bytes_read
                site.bytes_written += cost.bytes_written
                site.count += 1

    def to_json(self) -> dict:
        out = {
            "flops": int(self.flops),
            "bytes_read": int(self.bytes_read),
            "bytes_written": int(self.bytes_written),
            "eqns": int(self.eqns),
            "classes": {
                k: dict(v) for k, v in sorted(self.classes.items())
            },
        }
        ai = self.intensity
        if ai is not None:
            out["intensity"] = round(ai, 6)
        return out


def walk_jaxpr(closed, name: str = "kernel") -> StageCost:
    """Charge every eqn of a closed jaxpr into one :class:`StageCost`."""
    stage = StageCost(name=name)
    for eqn in _iter_eqns(closed.jaxpr):
        stage.charge(_charge_eqn(eqn))
    return stage


# ---------------------------------------------------------------------------
# per-action attribution (the footprint pass's action-axis decomposition)
# ---------------------------------------------------------------------------


def _flatten_entries(closed):
    """Linearize the jaxpr with calls inlined: returns ``(entries,
    producer, alias)`` where ``entries`` is ``[(eqn, cost), ...]``,
    ``producer`` maps each var to its entry index, and ``alias`` maps
    call-boundary vars onto their outer/inner twins."""
    entries: list = []
    producer: dict = {}
    alias: dict = {}

    def resolve(v):
        seen = 0
        while v in alias and seen < 64:
            v = alias[v]
            seen += 1
        return v

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _CALLS:
                inner = (
                    eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                )
                if inner is None:
                    continue
                ij = getattr(inner, "jaxpr", inner)
                for iv, outer in zip(ij.invars, eqn.invars):
                    if not is_literal(outer):
                        alias[iv] = outer
                walk(ij)
                for outer_ov, inner_ov in zip(eqn.outvars, ij.outvars):
                    if not is_literal(inner_ov):
                        alias[outer_ov] = inner_ov
                continue
            idx = len(entries)
            entries.append((eqn, _charge_eqn(eqn)))
            for ov in eqn.outvars:
                producer[ov] = idx
        return None

    walk(closed.jaxpr)
    return entries, producer, resolve


def _action_pieces(entries, producer, resolve, closed, arity: int):
    """Per-action root vars from the successor stack's action-axis
    concatenate (the footprint pass's decomposition); None when the
    kernel does not decompose (slot-multiset twins)."""
    out_var = resolve(closed.jaxpr.outvars[0])
    ndim = len(
        getattr(getattr(closed.jaxpr.outvars[0], "aval", None), "shape", ())
        or ()
    )
    if ndim < 2:
        return None
    axis = ndim - 2

    def walk_back(v, depth=8):
        for _ in range(depth):
            v = resolve(v)
            idx = producer.get(v)
            if idx is None:
                return v
            eqn = entries[idx][0]
            if eqn.primitive.name not in (
                "reshape", "copy", "convert_element_type",
            ):
                return v
            v = eqn.invars[0]
        return v

    def flatten(v, depth=6):
        v = walk_back(v)
        idx = producer.get(resolve(v))
        if idx is None:
            return None
        eqn = entries[idx][0]
        if eqn.primitive.name != "concatenate" \
                or eqn.params.get("dimension") != axis:
            return None
        pieces = []
        for p in eqn.invars:
            shape = tuple(
                getattr(getattr(p, "aval", None), "shape", ()) or ()
            )
            n = int(shape[axis]) if axis < len(shape) else 1
            sub = flatten(p, depth - 1) if depth > 0 and not is_literal(p) \
                else None
            if sub is not None:
                pieces.extend(sub)
            else:
                pieces.extend([p] * n)
        return pieces

    pieces = flatten(out_var)
    if pieces is None and arity == 1:
        pieces = [out_var]
    if pieces is None or len(pieces) != arity:
        return None
    return pieces


def action_costs(closed, arity: int) -> Optional[list]:
    """Per-action ``{flops, bytes}`` attribution of the expand kernel:
    eqns reachable from exactly one action's successor piece charge to
    it; eqns feeding several actions charge to the trailing ``shared``
    entry (guard-only eqns, reachable from no piece, are out of scope —
    the successor stack is what decomposes).  None when the stack does
    not decompose (JX302 twins)."""
    entries, producer, resolve = _flatten_entries(closed)
    pieces = _action_pieces(entries, producer, resolve, closed, arity)
    if pieces is None:
        return None
    # transitive producer closure per action (memoized per entry)
    reach_memo: dict = {}

    def reach(idx: int) -> frozenset:
        cached = reach_memo.get(idx)
        if cached is not None:
            return cached
        reach_memo[idx] = frozenset()  # cycle guard (none expected)
        eqn = entries[idx][0]
        out = {idx}
        for v in eqn.invars:
            if is_literal(v):
                continue
            p = producer.get(resolve(v))
            if p is not None:
                out |= reach(p)
        result = frozenset(out)
        reach_memo[idx] = result
        return result

    per_action: list = []
    owner: dict = {}
    for a, piece in enumerate(pieces):
        p = producer.get(resolve(piece))
        idxs = reach(p) if p is not None else frozenset()
        per_action.append(idxs)
        for i in idxs:
            owner[i] = a if i not in owner else -1  # -1 = shared
    out = []
    for a in range(arity):
        fl = by = 0
        for i in per_action[a]:
            if owner.get(i) == a:
                c = entries[i][1]
                fl += c.flops
                by += c.bytes_total
        out.append({"action": a, "flops": int(fl), "bytes": int(by)})
    fl = by = 0
    for i, (_, c) in enumerate(entries):
        if owner.get(i) == -1:
            fl += c.flops
            by += c.bytes_total
    out.append({"action": "shared", "flops": int(fl), "bytes": int(by)})
    return out


# ---------------------------------------------------------------------------
# XLA reconciliation (the memory ledger's memory_analysis() discipline)
# ---------------------------------------------------------------------------


def xla_cost(fn: Callable, avals) -> Optional[dict]:
    """``compiled.cost_analysis()`` flops / bytes-accessed for ``fn`` at
    ``avals``, normalized across the list-vs-dict API generations; None
    when the backend does not expose the analysis (never crash — the
    CPU-degradation contract)."""
    import jax

    try:
        ca = jax.jit(fn).lower(*avals).compile().cost_analysis()
    except Exception:  # noqa: BLE001 - absent/unsupported backend
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key, name in (("flops", "flops"), ("bytes accessed", "bytes")):
        v = ca.get(key)
        if v is not None:
            try:
                out[name] = int(v)
            except (TypeError, ValueError):
                continue
    return out or None


def reconcile_stage(stage: StageCost, xla: Optional[dict],
                    bytes_lo: float = BYTES_LO) -> dict:
    """One stage's analytic-vs-XLA verdict under the pinned bands.

    ``bytes_lo=0`` exempts the lower byte bound — the ``queue`` stage's
    documented exemption: XLA's cost model charges a dynamic-update-
    slice at FULL-buffer scale (donated or not — measured on this
    backend), so its number grows with ``qcap/batch`` without bound,
    while the walk charges the moved window — the roofline-correct
    traffic, and what a donated in-place engine carry actually pays."""
    out: dict = {
        "analytic_flops": int(stage.flops),
        "analytic_bytes": int(stage.bytes_total),
    }
    if not xla:
        out["ok"] = True  # no XLA analysis on this backend: nothing to
        out["xla"] = None  # reconcile against (pinned never-crash)
        return out
    problems = []
    xf, xb = xla.get("flops"), xla.get("bytes")
    out["xla_flops"], out["xla_bytes"] = xf, xb
    if xf:
        ratio = stage.flops / xf
        out["flops_ratio"] = round(ratio, 4)
        if not (1.0 / FLOPS_BAND <= ratio <= FLOPS_BAND):
            problems.append(
                f"flops ratio {ratio:.3f} outside [{1 / FLOPS_BAND:.3f}, "
                f"{FLOPS_BAND}]"
            )
    if xb:
        ratio = stage.bytes_total / xb
        out["bytes_ratio"] = round(ratio, 4)
        if not (bytes_lo <= ratio <= BYTES_HI):
            problems.append(
                f"bytes ratio {ratio:.3f} outside [{bytes_lo}, {BYTES_HI}]"
            )
    out["ok"] = not problems
    if problems:
        out["problems"] = problems
    return out


# ---------------------------------------------------------------------------
# MXU-candidate ranking (JX4xx)
# ---------------------------------------------------------------------------

_RECAST = {
    "gather": (
        "JX400",
        "blocked one-hot x table matmul (BLEST membership-probe recast: "
        "a [B, K] one-hot selector against the [K, V] table block)",
    ),
    "scatter": (
        "JX400",
        "blocked scatter-as-matmul accumulate (one-hot^T x updates onto "
        "the table block)",
    ),
    "sort": (
        "JX401",
        "bitonic / blocked compare-exchange stages (the MXU-shaped "
        "dedup-rank move)",
    ),
}


# landed escape hatches, the JX305 pattern: (stage, op_class) whose
# recast SHIPPED as an --mxu component.  Pre-flag, the JX400/JX401
# finding names the hatch; with the component armed, the finding goes
# SILENT (the recast is live — re-advertising it would be noise), both
# pinned by test.  The mxu-config attribute names the component that
# retires the site.
_LANDED_HATCH = {
    ("dedup-insert", "gather"): (
        "probe",
        "--mxu / CheckerBuilder.mxu() (BLEST one-hot probe; "
        "docs/roofline.md)",
    ),
    ("queue", "gather"): (
        "slim_queue",
        "--mxu / CheckerBuilder.mxu() (slim queue traffic; "
        "docs/roofline.md)",
    ),
    ("queue", "scatter"): (
        "slim_queue",
        "--mxu / CheckerBuilder.mxu() (slim queue traffic; "
        "docs/roofline.md)",
    ),
    ("expand", "scatter"): (
        "coalesce",
        "--mxu / CheckerBuilder.mxu() (expand-scatter coalescing; "
        "docs/roofline.md)",
    ),
}


def _landed_hatch(stage: str, op_class: str, mxu=None):
    """``(armed, hatch_text)`` for a ranked site: ``hatch_text`` is the
    landed escape hatch (None when no recast shipped for the site),
    ``armed`` whether the resolving component is ON in ``mxu``."""
    entry = _LANDED_HATCH.get((stage, op_class))
    if entry is None:
        return False, None
    component, text = entry
    armed = bool(mxu is not None and getattr(mxu, component, False))
    return armed, text


def mxu_candidates(stages: dict, mxu=None) -> list:
    """Gather/scatter/sort sites whose shapes admit a blocked-matmul
    recast, ranked by charged bytes (the list docs/roofline.md's
    hot-spot table is generated from).  Sites whose landed recast
    component is armed in ``mxu`` carry ``recast_landed: true`` — the
    findings layer goes silent on them (the JX305 pattern)."""
    out = []
    for sname, stage in stages.items():
        for (prim, shape, op_shape), site in stage.movement.items():
            total = site.bytes_total * 1  # per traced call
            if total < MXU_MIN_BYTES:
                continue
            rule, recast = _RECAST[site.op_class]
            armed, hatch = _landed_hatch(sname, site.op_class, mxu)
            entry = {
                "stage": sname,
                "op": prim,
                "op_class": site.op_class,
                "shape": list(shape),
                "operand_shape": list(op_shape),
                "count": int(site.count),
                "bytes": int(total),
                "flops": int(site.flops),
                "rule": rule,
                "recast": recast,
            }
            if hatch:
                entry["escape_hatch"] = hatch
            if armed:
                entry["recast_landed"] = True
            out.append(entry)
    out.sort(key=lambda c: (-c["bytes"], c["stage"], c["op"]))
    for rank, c in enumerate(out, 1):
        c["rank"] = rank
    return out[:_MXU_TOP]


def mxu_findings(candidates: list, stages: dict) -> list:
    """The ranking as ``JX4xx`` informational audit findings.  A site
    whose recast flag is armed (``recast_landed``) emits NO finding —
    the hatch is taken; pre-flag, the message names it (JX305's
    actionable-pointer pattern, pinned by test)."""
    findings = []
    for c in candidates:
        if c.get("recast_landed"):
            continue
        findings.append(AuditFinding(
            c["rule"], Severity.INFO, f"stage:{c['stage']}",
            f"MXU candidate #{c['rank']}: {c['op']} moving "
            f"{c['bytes']} bytes/step (shape {c['shape']}"
            + (
                f" over operand {c['operand_shape']}"
                if c["operand_shape"] else ""
            )
            + f", x{c['count']}) admits a {c['recast']}"
            + (
                f" — landed escape hatch: {c['escape_hatch']}"
                if c.get("escape_hatch") else ""
            ),
        ))
    if candidates:
        by_stage: dict = {}
        for c in candidates:
            by_stage[c["stage"]] = by_stage.get(c["stage"], 0) + c["bytes"]
        top_stage = max(by_stage, key=by_stage.get)
        total = sum(
            s.bytes_total for s in stages.values()
        ) or 1
        findings.append(AuditFinding(
            "JX402", Severity.INFO, "costmodel",
            f"top MXU-candidate stage is '{top_stage}' with "
            f"{by_stage[top_stage]} candidate bytes/step "
            f"({100.0 * by_stage[top_stage] / total:.1f}% of all charged "
            "bytes) — the tensor-core round's first target "
            "(docs/roofline.md)",
        ))
    return findings


# ---------------------------------------------------------------------------
# the model report + engine entry points
# ---------------------------------------------------------------------------


@dataclass
class CostReport:
    """The full cost ledger of one engine configuration."""

    engine: str
    shapes: dict  # batch/cap/qcap/cand/... (JSON-safe ints)
    stages: dict  # name -> StageCost
    reconciliation: dict  # name -> reconcile_stage verdict (+ "ok")
    actions: Optional[list]  # per-action attribution, or None (JX302)
    candidates: list  # mxu_candidates ranking
    findings: list = field(default_factory=list)

    @property
    def total_flops(self) -> int:
        return sum(s.flops for s in self.stages.values())

    @property
    def total_bytes(self) -> int:
        return sum(s.bytes_total for s in self.stages.values())

    def static_block(self) -> dict:
        """The DETERMINISTIC block (run report / regress contract): the
        analytic walk only — no XLA numbers (backend-specific), no
        device spec (machine-local).  Byte-stable for a fixed
        model/config/jax."""
        totals = {
            "flops": int(self.total_flops),
            "bytes": int(self.total_bytes),
        }
        if totals["bytes"]:
            totals["intensity"] = round(
                totals["flops"] / totals["bytes"], 6
            )
        out = {
            "v": COSTMODEL_V,
            "engine": self.engine,
            **{k: int(v) for k, v in sorted(self.shapes.items())},
            "stages": {
                name: s.to_json() for name, s in self.stages.items()
            },
            "totals": totals,
            "mxu_candidates": [dict(c) for c in self.candidates],
        }
        if self.actions is not None:
            out["actions"] = [dict(a) for a in self.actions]
        return out

    def recon_block(self) -> dict:
        """The reconciliation verdict (live surfaces + the bench/regress
        artifact; XLA's numbers are backend-specific and stay out of the
        deterministic block)."""
        ok = all(
            v.get("ok", False)
            for k, v in self.reconciliation.items()
            if isinstance(v, dict)
        )
        return {
            "ok": ok,
            "bands": {
                "flops": [round(1.0 / FLOPS_BAND, 4), FLOPS_BAND],
                "bytes": [BYTES_LO, BYTES_HI],
            },
            "stages": {
                k: dict(v) for k, v in self.reconciliation.items()
            },
        }


def _trace(fn, avals):
    import jax

    jax.config.update("jax_enable_x64", True)
    return jax.make_jaxpr(lambda *a: fn(*a))(*avals)


def _stage_fns(tensor, cap: int, qcap: int, batch: int, cand: int,
               sym: bool, mxu=None):
    """``name -> (fn, avals)`` for the five wavefront pipeline stages at
    these capacities — the same kernels (and shapes) one engine step
    runs, traced standalone so each stage's costs attribute cleanly.

    The insert/queue wiring here MIRRORS ``wavefront._build_engine``'s
    default path (window=batch, compact=eff_cand, qalloc=qcap+m) by
    hand — the ``telemetry/memory.sharded_specs`` discipline, not the
    ``_carry_avals``-derived one: the engine's step is one fused jaxpr,
    and standalone stage kernels are the whole point of per-stage
    attribution.  The XLA reconciliation checks each stage against its
    OWN compile, so a drift against the engine would NOT trip it —
    when touching ``_build_engine``'s insert or queue-append wiring,
    update this mirror with it.

    ``mxu`` (``ops/mxu.MxuConfig``, None = off) mirrors the engine's
    MXU-recast knobs (docs/roofline.md "Executing the hot-spot list"):
    ``coalesce`` traces the twin's coalesced expand kernel, ``probe``
    passes ``probe_dot`` into the insert mirror, and ``slim_queue``
    swaps the queue mirror's stack-wide append for the engine's
    ``batch``-chunked loop gated on a traced ``n_new`` — so the ledger
    charges exactly what the flagged engine program moves."""
    import jax
    import jax.numpy as jnp

    from ..ops.buckets import bucket_insert
    from ..ops.hashing import row_hash
    from ..ops.mxu import coalesced_step_fn

    width, arity = tensor.width, tensor.max_actions
    m = batch * arity
    eff_cand = min(cand, m) if cand else m
    qalloc = qcap + m
    probe_dot = bool(mxu is not None and mxu.probe)
    # the engine's static slim-queue decision, mirrored (wavefront
    # _build_engine): chunk width min(batch, eff_cand), plain fallback
    # when it does not divide the candidate stack
    qchunk = min(batch, eff_cand)
    slim_queue = bool(
        mxu is not None and mxu.slim_queue and eff_cand % qchunk == 0
    )
    step_rows_fn = coalesced_step_fn(tensor, mxu)
    sds = jax.ShapeDtypeStruct
    rows = sds((batch, width), jnp.uint64)
    succ = sds((batch, arity, width), jnp.uint64)

    def hash_fn(s):
        krows = tensor.representative_rows(s) if sym else s
        return row_hash(krows)

    def insert_fn(tfp, tpl, cfp, cpar):
        return bucket_insert(
            tfp, tpl, cfp, cpar, window=batch, generation_order=sym,
            compact=eff_cand, probe_dot=probe_dot,
        )

    def queue_fn(qrows, qfp, qebits, qdepth, head, tail, crows, cfp,
                 cebt, cdep, sel, n_new=None):
        # the engine's per-step queue traffic: pop one batch window,
        # append the novel-compacted candidate window at the tail
        out_rows = jax.lax.dynamic_slice(
            qrows, (head, jnp.int32(0)), (batch, width)
        )
        out_fp = jax.lax.dynamic_slice(qfp, (head,), (batch,))
        out_eb = jax.lax.dynamic_slice(qebits, (head,), (batch,))
        out_dp = jax.lax.dynamic_slice(qdepth, (head,), (batch,))
        if slim_queue:
            # the engine's append_novel slim path (wavefront.py): one
            # batch-sized chunk per loop trip, gated on n_new — the
            # walk charges the body once, so charged bytes track the
            # chunk window, matching the flagged engine program
            def chunk(state):
                k, qr, qf, qe, qd = state
                off = k * qchunk
                w_idx = jax.lax.dynamic_slice(sel, (off,), (qchunk,))
                qr = jax.lax.dynamic_update_slice(
                    qr, crows[w_idx], (tail + off, jnp.int32(0))
                )
                qf = jax.lax.dynamic_update_slice(
                    qf, cfp[w_idx], (tail + off,)
                )
                qe = jax.lax.dynamic_update_slice(
                    qe, cebt[w_idx], (tail + off,)
                )
                qd = jax.lax.dynamic_update_slice(
                    qd, cdep[w_idx], (tail + off,)
                )
                return k + 1, qr, qf, qe, qd

            _, qrows, qfp, qebits, qdepth = jax.lax.while_loop(
                lambda st: st[0] * qchunk < n_new,
                chunk,
                (jnp.int32(0), qrows, qfp, qebits, qdepth),
            )
        else:
            qrows = jax.lax.dynamic_update_slice(
                qrows, crows[sel], (tail, jnp.int32(0))
            )
            qfp = jax.lax.dynamic_update_slice(qfp, cfp[sel], (tail,))
            qebits = jax.lax.dynamic_update_slice(
                qebits, cebt[sel], (tail,)
            )
            qdepth = jax.lax.dynamic_update_slice(
                qdepth, cdep[sel], (tail,)
            )
        return (out_rows, out_fp, out_eb, out_dp, qrows, qfp, qebits,
                qdepth)

    def expand_fn(r):
        s, valid = step_rows_fn(r)
        if getattr(tensor, "has_boundary", False):
            valid = valid & tensor.boundary_rows(s)
        return s, valid

    queue_avals = (
        sds((qalloc, width), jnp.uint64), sds((qalloc,), jnp.uint64),
        sds((qalloc,), jnp.uint32), sds((qalloc,), jnp.uint32),
        sds((), jnp.int32), sds((), jnp.int32),
        sds((m, width), jnp.uint64), sds((m,), jnp.uint64),
        sds((m,), jnp.uint32), sds((m,), jnp.uint32),
        sds((m,), jnp.int32),
    )
    if slim_queue:
        queue_avals = queue_avals + (sds((), jnp.int32),)
    return {
        "property": (tensor.property_masks, (rows,)),
        "expand": (expand_fn, (rows,)),
        "hash": (hash_fn, (succ,)),
        "dedup-insert": (
            insert_fn,
            (
                sds((cap,), jnp.uint64), sds((cap,), jnp.uint64),
                sds((m,), jnp.uint64), sds((m,), jnp.uint64),
            ),
        ),
        "queue": (queue_fn, queue_avals),
    }


def _cost_cache(tensor) -> Optional[dict]:
    cache = getattr(tensor, "_cost_cache", None)
    if cache is None:
        cache = {}
        try:
            tensor._cost_cache = cache
        except Exception:  # noqa: BLE001 - __slots__ twins
            return None
    return cache


def wavefront_costs(
    tensor, cap: int, qcap: int, batch: int,
    cand: Optional[int] = None, *, sym: bool = False,
    reconcile: bool = True, mxu=None,
) -> Optional[CostReport]:
    """The wavefront engine's full cost ledger at these capacities
    (cached on the twin — kernels cannot change under a fixed twin).
    ``mxu`` mirrors the engine's MXU-recast knobs into the stage
    kernels (see ``_stage_fns``), so a flagged run's ledger prices the
    flagged program — the before/after evidence ``regress.py --mxu``
    gates on.  Returns None when the twin has no usable width/arity or
    a kernel does not trace (the structural audit already reports
    those)."""
    width = getattr(tensor, "width", None)
    arity = getattr(tensor, "max_actions", None)
    if not isinstance(width, int) or not isinstance(arity, int):
        return None
    cand = cand or max(4 * batch, 4096)
    key = ("wavefront", cap, qcap, batch, min(cand, batch * arity),
           bool(sym), bool(reconcile))
    if mxu is not None:
        key = key + (tuple(mxu),)
    cache = _cost_cache(tensor)
    if cache is not None and key in cache:
        return cache[key]
    try:
        # init_rows first: the documented outside-any-trace moment where
        # compiled twins populate their device-constant caches (the
        # footprint/run_jaxpr_audit discipline — constants materialized
        # inside a make_jaxpr trace would leak tracers into the cache)
        np.asarray(tensor.init_rows())
        fns = _stage_fns(tensor, cap, qcap, batch, cand, sym, mxu=mxu)
    except Exception:  # noqa: BLE001 - JX000 covers trace failures
        return None
    stages: dict = {}
    recon: dict = {}
    expand_closed = None
    for name, (fn, avals) in fns.items():
        try:
            closed = _trace(fn, avals)
        except Exception:  # noqa: BLE001 - a kernel that does not trace
            continue  # is the structural audit's finding, not ours
        if name == "expand":
            expand_closed = closed
        stages[name] = walk_jaxpr(closed, name)
        if reconcile:
            recon[name] = reconcile_stage(
                stages[name], xla_cost(fn, avals),
                bytes_lo=0.0 if name == "queue" else BYTES_LO,
            )
    if not stages:
        return None
    actions = None
    if expand_closed is not None:
        try:
            actions = action_costs(expand_closed, arity)
        except Exception:  # noqa: BLE001 - attribution only, never fatal
            actions = None
    # landed-recast bookkeeping prices what actually traced: coalesce
    # downgrades when the twin has no coalesced kernel (effective_mxu),
    # slim_queue when the chunk width does not divide the candidate
    # stack (the _stage_fns/_build_engine static fallback) — a fallen-
    # back component must never silence its JX400 findings
    from ..ops.mxu import effective_mxu

    mxu_eff = effective_mxu(tensor, mxu)
    if mxu_eff is not None and mxu_eff.slim_queue:
        ec = min(cand, batch * arity)
        if ec % min(batch, ec):
            mxu_eff = mxu_eff._replace(slim_queue=False)
    candidates = mxu_candidates(stages, mxu=mxu_eff)
    out = CostReport(
        engine="wavefront",
        shapes={"batch": batch, "capacity": cap, "queue_capacity": qcap,
                "cand": min(cand, batch * arity)},
        stages=stages, reconciliation=recon, actions=actions,
        candidates=candidates,
        findings=mxu_findings(candidates, stages),
    )
    if cache is not None:
        cache[key] = out
    return out


def sharded_costs(
    tensor, cap_local: int, fcap_local: int, ndev: int,
    *, sym: bool = False, reconcile: bool = True, mxu=None,
) -> Optional[CostReport]:
    """The sharded engine's MODEL-kernel ledger (property/expand/hash at
    the per-device frontier width).  The engine-side insert and
    all-to-all are mesh collectives the single-kernel walk cannot price
    honestly — they land with the pod-scale mesh round (ROADMAP); the
    block says so via the ``engine`` tag."""
    width = getattr(tensor, "width", None)
    arity = getattr(tensor, "max_actions", None)
    if not isinstance(width, int) or not isinstance(arity, int):
        return None
    key = ("sharded", cap_local, fcap_local, ndev, bool(sym),
           bool(reconcile))
    if mxu is not None:
        key = key + (tuple(mxu),)
    cache = _cost_cache(tensor)
    if cache is not None and key in cache:
        return cache[key]
    try:
        np.asarray(tensor.init_rows())
        fns = _stage_fns(
            tensor, cap_local, max(cap_local // 2, 1), fcap_local,
            4 * fcap_local, sym, mxu=mxu,
        )
    except Exception:  # noqa: BLE001
        return None
    stages: dict = {}
    recon: dict = {}
    expand_closed = None
    for name in ("property", "expand", "hash"):
        fn, avals = fns[name]
        try:
            closed = _trace(fn, avals)
        except Exception:  # noqa: BLE001
            continue
        if name == "expand":
            expand_closed = closed
        stages[name] = walk_jaxpr(closed, name)
        if reconcile:
            recon[name] = reconcile_stage(
                stages[name], xla_cost(fn, avals)
            )
    if not stages:
        return None
    actions = None
    if expand_closed is not None:
        try:
            actions = action_costs(expand_closed, arity)
        except Exception:  # noqa: BLE001
            actions = None
    from ..ops.mxu import effective_mxu

    candidates = mxu_candidates(stages, mxu=effective_mxu(tensor, mxu))
    out = CostReport(
        engine="sharded",
        shapes={"batch": fcap_local, "capacity": cap_local * ndev,
                "devices": ndev},
        stages=stages, reconciliation=recon, actions=actions,
        candidates=candidates,
        findings=mxu_findings(candidates, stages),
    )
    if cache is not None:
        cache[key] = out
    return out


def fold_into_report(cost: CostReport, report) -> None:
    """Merge the JX4xx findings + the summary metrics into an
    ``AuditReport`` — the ``independence.fold_into_report`` pattern:
    the audit tiers deliberately do NOT run the cost walk (it re-traces
    and compiles every pipeline kernel), so this hook exists for
    callers that want the ledger merged into a model's report (the
    verb prints the findings directly instead)."""
    report.extend(cost.findings)
    report.metrics["costmodel"] = {
        "engine": cost.engine,
        "flops": int(cost.total_flops),
        "bytes": int(cost.total_bytes),
        "stages": sorted(cost.stages),
        "mxu_candidates": len(cost.candidates),
        "reconciled": cost.recon_block()["ok"],
    }
