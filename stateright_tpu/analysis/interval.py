"""Integer interval abstract interpretation over closed jaxprs.

The value-level half of the static auditor (the structural half is
``jaxpr_audit.py``): a forward pass that tracks a ``[lo, hi]`` integer
interval per traced value through the kernel's arithmetic
(add/sub/mul/shift/and/or/mod/select/concat/iota, widening on
``scan``/``while``), seeded from the model's *declared domain bounds*
(:class:`~stateright_tpu.parallel.tensor_model.RowDomain`: per-word packed
bounds, per-field widths, sentinel-carrying words).  The sanitizer
(``sanitizer.py``) drives it and turns site verdicts into JX2xx findings.

Three design points carry the precision the real kernels need:

 - **Sentinel outliers.**  A slot word's domain is ``[0, max_code] ∪
   {EMPTY}`` — a plain interval would collapse to top.  Abstract values
   carry up to two exact *outlier points* beside the interval; unary
   arithmetic maps them exactly (``EMPTY >> 6`` stays one point), and the
   guard refinement below deletes them, which is how
   ``where(slots != EMPTY, f(slots), 0)`` proves ``f``'s gather in range.
 - **Guard refinement.**  ``select_n`` whose predicate is a comparison of a
   traced value against a constant re-evaluates each branch with the
   compared value's interval refined by the branch condition (depth-bounded
   walk of the producing sub-DAG).  This covers both the sentinel idiom and
   jnp's machine-generated negative-index normalization
   (``select_n(x < 0, x, x + N)``) without flagging either.
 - **Field provenance.**  A value sliced from a row word remembers
   ``(word, accumulated right-shift)``; a subsequent ``& mask`` with a
   contiguous mask is a ``BitPacker.get`` field extraction and intersects
   with the field's *declared* bound — tighter than the mask when a field's
   width over-allocates its domain (state codes, queue indices).

Every transfer function is deliberately conservative: unknown primitives
and undecidable cases widen to the dtype hull, never narrower — the
sanitizer treats "top" as *undecided* (route to checked mode), so a missing
rule can cost precision but never soundness of an "in range" verdict.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Optional

import numpy as np

_MAX_OUTLIERS = 2
_REFINE_DEPTH = 48  # guarded re-evaluation walk budget (eqns per branch)


def dtype_hull(dtype) -> Optional[tuple]:
    """``(lo, hi)`` of an integer/bool dtype, None for floats/complex."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return (0, 1)
    if np.issubdtype(dt, np.signedinteger):
        b = dt.itemsize * 8
        return (-(1 << (b - 1)), (1 << (b - 1)) - 1)
    if np.issubdtype(dt, np.unsignedinteger):
        return (0, (1 << (dt.itemsize * 8)) - 1)
    return None


def _wrap(v: int, dtype) -> int:
    """Exact dtype wrap of a python int (what a convert/overflow does)."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return int(bool(v))
    bits = dt.itemsize * 8
    v &= (1 << bits) - 1
    if np.issubdtype(dt, np.signedinteger) and v >= (1 << (bits - 1)):
        v -= 1 << bits
    return v


@dataclass(frozen=True)
class IVal:
    """Abstract value: interval + exact outlier points + provenance flags.

    ``lo``/``hi`` are python ints (None = untracked, e.g. float dataflow).
    ``outliers`` are exact points the value may ALSO take, kept outside the
    interval (the EMPTY-sentinel machinery).  ``arith`` marks derivation
    through real arithmetic (feeds the JX203 overflow-before-mask rule);
    ``word``/``shift`` are the BitPacker field-extraction provenance.
    """

    lo: Optional[int]
    hi: Optional[int]
    outliers: frozenset = frozenset()
    arith: bool = False
    word: Optional[int] = None  # input row word this value derives from
    shift: int = 0  # accumulated logical right-shift since the word

    # -- constructors --------------------------------------------------------

    @staticmethod
    def top(dtype) -> "IVal":
        h = dtype_hull(dtype)
        if h is None:
            return IVal(None, None)
        return IVal(h[0], h[1])

    @staticmethod
    def const(v) -> "IVal":
        a = np.asarray(v)
        if a.dtype == np.bool_:
            vs = {int(bool(x)) for x in a.reshape(-1)[:4097].tolist()} or {0}
            return IVal(min(vs), max(vs))
        if not np.issubdtype(a.dtype, np.integer):
            return IVal(None, None)
        if a.size == 0:
            return IVal(0, 0)
        return IVal(int(a.min()), int(a.max()))

    @staticmethod
    def point(v: int) -> "IVal":
        return IVal(int(v), int(v))

    # -- queries -------------------------------------------------------------

    @property
    def tracked(self) -> bool:
        return self.lo is not None

    def hull(self) -> Optional[tuple]:
        """``(lo, hi)`` including outliers (what a check must assume)."""
        if not self.tracked:
            return None
        pts = [self.lo, self.hi, *self.outliers]
        return (min(pts), max(pts))

    def is_top_for(self, dtype) -> bool:
        """Nothing learned beyond the dtype itself (=> 'undecided')."""
        h = dtype_hull(dtype)
        if h is None or not self.tracked:
            return True
        lo, hi = self.hull()
        return lo <= h[0] and hi >= h[1]

    def may_contain(self, v: int) -> bool:
        if not self.tracked:
            return True
        return (self.lo <= v <= self.hi) or v in self.outliers

    def singleton(self) -> Optional[int]:
        if self.tracked and self.lo == self.hi and not self.outliers:
            return self.lo
        return None

    # -- algebra -------------------------------------------------------------

    def _norm(self) -> "IVal":
        """Fold outliers into the interval when they stop being outliers
        (inside it, or too many to track exactly)."""
        if not self.tracked:
            return IVal(None, None)
        outs = {o for o in self.outliers if not self.lo <= o <= self.hi}
        if len(outs) > _MAX_OUTLIERS:
            pts = [self.lo, self.hi, *outs]
            return replace(self, lo=min(pts), hi=max(pts),
                           outliers=frozenset())
        return replace(self, outliers=frozenset(outs))

    def join(self, other: "IVal") -> "IVal":
        if not self.tracked or not other.tracked:
            return IVal(None, None)
        return IVal(
            min(self.lo, other.lo),
            max(self.hi, other.hi),
            self.outliers | other.outliers,
            self.arith or other.arith,
        )._norm()

    def clip(self, lo: Optional[int], hi: Optional[int]) -> Optional["IVal"]:
        """Meet with ``[lo, hi]`` (None = unbounded side); None if empty."""
        if not self.tracked:
            return self
        nlo = self.lo if lo is None else max(self.lo, lo)
        nhi = self.hi if hi is None else min(self.hi, hi)
        outs = frozenset(
            o for o in self.outliers
            if (lo is None or o >= lo) and (hi is None or o <= hi)
        )
        if nlo > nhi:
            if not outs:
                return None
            vals = sorted(outs)
            return IVal(vals[0], vals[-1], frozenset(vals[1:-1]),
                        self.arith)._norm()
        return IVal(nlo, nhi, outs, self.arith, self.word, self.shift)._norm()

    def drop_point(self, v: int) -> "IVal":
        """Refine under a ``!= v`` guard: exact only for outliers/endpoints."""
        if not self.tracked:
            return self
        if v in self.outliers:
            return replace(self, outliers=self.outliers - {v})
        if self.lo == self.hi == v:
            # contradiction; caller treats as dead, give the empty-ish point
            return self
        if v == self.lo:
            return replace(self, lo=self.lo + 1)
        if v == self.hi:
            return replace(self, hi=self.hi - 1)
        return self

    def map_exact(self, fn: Callable[[int], int],
                  *, arith: Optional[bool] = None) -> "IVal":
        """Apply a MONOTONE exact unary function to the interval endpoints
        and each outlier (shift/and-mask/add-const class).  Drops field
        provenance; callers that preserve it rebuild explicitly."""
        if not self.tracked:
            return IVal(None, None)
        a, b = fn(self.lo), fn(self.hi)
        return IVal(
            min(a, b), max(a, b),
            frozenset(fn(o) for o in self.outliers),
            self.arith if arith is None else arith,
        )._norm()


TOP64 = IVal(0, (1 << 64) - 1)


def _is_contiguous_mask(m: int) -> Optional[tuple]:
    """``m == (2^bits - 1) << off``?  Returns ``(off, bits)`` or None."""
    if m <= 0:
        return None
    off = (m & -m).bit_length() - 1
    run = m >> off
    if run & (run + 1):
        return None
    return off, run.bit_length()


# ---------------------------------------------------------------------------
# jaxpr walking helpers (shared with sanitizer.py)
# ---------------------------------------------------------------------------


def aval_of(x):
    return getattr(x, "aval", None)


def is_literal(x) -> bool:
    return hasattr(x, "val")


def producers_of(jaxpr) -> dict:
    return {ov: eqn for eqn in jaxpr.eqns for ov in eqn.outvars}


def walk_transparent(var, producers, prims=("reshape", "broadcast_in_dim",
                                            "squeeze", "convert_element_type",
                                            "copy", "expand_dims"),
                     depth: int = 8):
    """Follow shape-only/convert producers back from ``var``."""
    for _ in range(depth):
        eqn = producers.get(var)
        if eqn is None or eqn.primitive.name not in prims:
            return var
        var = eqn.invars[0]
    return var


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class Interp:
    """One forward pass over a (sub-)jaxpr with an interval environment.

    ``hooks`` (the sanitizer) receives ``site(eqn, kind, ...)`` callbacks
    at gather/scatter/dynamic-slice/mask/select sites.  ``row_domain``
    seeds last-axis columns of the designated input var.
    """

    def __init__(self, hooks=None, row_domain=None):
        self.hooks = hooks
        self.row_domain = row_domain
        self.env: dict = {}
        self.input_var = None  # the rows var the domain seeds
        # pjit bodies are INLINED into this flat environment; the alias map
        # links an inner jaxpr's invars to the outer vars that feed them
        # (and call outvars to the body's outvars), so guard recognition
        # and refinement walk straight through jnp's where/clip wrappers.
        self._alias: dict = {}
        self._producers: dict = {}
        # False while _refine_eval re-walks a sub-DAG: rules that report
        # through hooks (mask_site, dead_branch) must stay silent there or
        # every guarded re-evaluation duplicates findings under fresh
        # site numbers
        self._checking = True

    # -- env -----------------------------------------------------------------

    def read(self, x) -> IVal:
        if is_literal(x):
            return IVal.const(x.val)
        v = self.env.get(x)
        if v is None:
            v = IVal.top(getattr(aval_of(x), "dtype", np.int64))
            self.env[x] = v
        return v

    def write(self, var, val: IVal) -> None:
        self.env[var] = val._norm() if val.tracked else val

    # -- entry ---------------------------------------------------------------

    def run(self, closed, in_vals=None) -> list:
        """Interpret a ClosedJaxpr; returns output IVals."""
        jaxpr = closed.jaxpr
        for cv, c in zip(jaxpr.constvars, closed.consts):
            self.write(cv, IVal.const(np.asarray(c)))
            self._note_const(cv, c)
        if in_vals is None:
            in_vals = []
            for iv in jaxpr.invars:
                in_vals.append(IVal.top(getattr(aval_of(iv), "dtype",
                                                np.int64)))
        for iv, val in zip(jaxpr.invars, in_vals):
            self.write(iv, val)
        if self.row_domain is not None and jaxpr.invars:
            self.input_var = jaxpr.invars[0]
        self._run_eqns(jaxpr)
        return [self.read(ov) for ov in jaxpr.outvars]

    def _note_const(self, var, c) -> None:
        if self.hooks is not None:
            self.hooks.note_const(var, c)

    def _run_eqns(self, jaxpr) -> None:
        self._producers.update(producers_of(jaxpr))
        self._cur_jaxpr = jaxpr  # hooks (JX204 post-pass) read this
        for eqn in jaxpr.eqns:
            try:
                self.eqn(eqn)
            except Exception:  # noqa: BLE001 - a rule bug must not kill the
                # audit: fall back to top for this eqn's outputs
                for ov in eqn.outvars:
                    self.write(ov, IVal.top(getattr(aval_of(ov), "dtype",
                                                    np.int64)))

    # -- alias-aware structural walks ----------------------------------------

    def resolve(self, var):
        """Follow inlined-call aliases to the canonical var."""
        seen = 0
        while var in self._alias and seen < 32:
            var = self._alias[var]
            seen += 1
        return var

    def walk_back(self, var, prims=("reshape", "broadcast_in_dim",
                                    "squeeze", "convert_element_type",
                                    "copy", "expand_dims"),
                  depth: int = 8):
        """Alias-resolving :func:`walk_transparent`."""
        var = self.resolve(var)
        for _ in range(depth):
            eqn = self._producers.get(var)
            if eqn is None or eqn.primitive.name not in prims:
                return var
            var = self.resolve(eqn.invars[0])
        return var

    # -- guarded re-evaluation ----------------------------------------------

    def _refine_eval(self, var, base_var, refined: IVal,
                     depth: int = _REFINE_DEPTH) -> IVal:
        """Interval of ``var`` re-derived with ``base_var``'s value replaced
        by ``refined`` (memoized, depth-bounded walk of producers)."""
        memo: dict = {}

        base_var = self.resolve(base_var)
        saved_checking, self._checking = self._checking, False

        def go(v, d):
            if is_literal(v):
                return IVal.const(v.val)
            v = self.resolve(v)
            if v is base_var:
                return refined
            if v in memo:
                return memo[v]
            eqn = self._producers.get(v)
            if eqn is None or d <= 0:
                return self.read(v)
            memo[v] = self.read(v)  # cycle/width guard: current value
            ins = [go(x, d - 1) for x in eqn.invars]
            outs = self._transfer(eqn, ins, check=False)
            for ov, o in zip(eqn.outvars, outs):
                if self.resolve(ov) is v:
                    memo[v] = o
            return memo[v]

        try:
            return go(var, depth)
        finally:
            self._checking = saved_checking

    def _side_const(self, x) -> Optional[int]:
        """Exact constant value of one comparison side, if any."""
        if is_literal(x):
            return IVal.const(x.val).singleton()
        v = self.env.get(x)
        return v.singleton() if v is not None else None

    def _guard_of(self, pred_var):
        """``(base_var, op, const)`` when the predicate is a comparison of a
        traced value against a constant (either side), else None."""
        eqn = self._producers.get(self.walk_back(pred_var))
        if eqn is None or eqn.primitive.name not in (
            "eq", "ne", "lt", "le", "gt", "ge"
        ):
            return None
        a, b = eqn.invars
        op = eqn.primitive.name
        cb = self._side_const(b)
        if cb is not None and not is_literal(a):
            return a, op, cb
        ca = self._side_const(a)
        if ca is not None and not is_literal(b):
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                    "eq": "eq", "ne": "ne"}
            return b, flip[op], ca
        return None

    @staticmethod
    def _apply_guard(val: IVal, op: str, c: int, truth: bool):
        """Refine ``val`` under ``val <op> c == truth``; None = dead."""
        eff = {  # (op, truth) -> constraint
            ("lt", True): ("hi", c - 1), ("lt", False): ("lo", c),
            ("le", True): ("hi", c), ("le", False): ("lo", c + 1),
            ("gt", True): ("lo", c + 1), ("gt", False): ("hi", c),
            ("ge", True): ("lo", c), ("ge", False): ("hi", c - 1),
        }
        if (op, truth) in eff:
            side, bound = eff[(op, truth)]
            return val.clip(bound if side == "lo" else None,
                            bound if side == "hi" else None)
        if (op, truth) in (("eq", True), ("ne", False)):
            if not val.may_contain(c):
                return None
            return IVal.point(c)
        # != c: exact for outliers/endpoints, else unchanged
        if val.tracked and val.lo == val.hi == c and not val.outliers:
            return None
        return val.drop_point(c)

    # -- per-eqn transfer -----------------------------------------------------

    def eqn(self, eqn) -> None:
        ins = [self.read(x) for x in eqn.invars]
        outs = self._transfer(eqn, ins, check=True)
        for ov, val in zip(eqn.outvars, outs):
            self.write(ov, val)

    def _transfer(self, eqn, ins, *, check: bool) -> list:
        name = eqn.primitive.name
        rule = _RULES.get(name)
        if check and self.hooks is not None:
            self.hooks.site(self, eqn, ins)
        if rule is not None:
            out = rule(self, eqn, ins)
            return out if isinstance(out, list) else [out]
        if name in ("pjit", "closed_call", "core_call", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr",
                    "remat_call", "checkpoint"):
            return self._call(eqn, ins)
        if name == "cond":
            return self._cond(eqn, ins)
        if name in ("while", "scan"):
            return self._loop(eqn, ins)
        # unknown: top per output dtype
        return [IVal.top(getattr(aval_of(ov), "dtype", np.int64))
                for ov in eqn.outvars]

    # -- HOPs ----------------------------------------------------------------

    def _sub(self, closed, in_vals) -> list:
        sub = Interp(hooks=self.hooks, row_domain=None)
        sub._producers = {}
        out = sub.run(closed, in_vals=in_vals)
        return out

    def _call(self, eqn, ins) -> list:
        """INLINE a pjit/call body into the flat environment (alias-linked),
        so guards recognized outside a ``jnp.where`` wrapper refine values
        inside it and vice versa."""
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is None:
            return [IVal.top(getattr(aval_of(ov), "dtype", np.int64))
                    for ov in eqn.outvars]
        jaxpr = getattr(inner, "jaxpr", inner)
        consts = getattr(inner, "consts", ())
        for cv, c in zip(jaxpr.constvars, consts):
            self.write(cv, IVal.const(np.asarray(c)))
            self._note_const(cv, c)
        for iv, outer, val in zip(jaxpr.invars, eqn.invars, ins):
            self.write(iv, val)
            if not is_literal(outer):
                self._alias[iv] = outer
        saved = getattr(self, "_cur_jaxpr", None)
        self._run_eqns(jaxpr)
        self._cur_jaxpr = saved
        outs = []
        for outer_ov, inner_ov in zip(eqn.outvars, jaxpr.outvars):
            if not is_literal(inner_ov):
                self._alias[outer_ov] = inner_ov
            outs.append(self.read(inner_ov))
        return outs

    def _cond(self, eqn, ins) -> list:
        branches = eqn.params.get("branches", ())
        pred, args = ins[0], ins[1:]
        outs = None
        live = []
        for i, br in enumerate(branches):
            if pred.tracked and not pred.may_contain(i) and len(branches) > 1:
                continue  # interval proves this branch dead
            live.append(i)
            o = self._sub(br, args)
            outs = o if outs is None else [a.join(b) for a, b in zip(outs, o)]
        if self.hooks is not None and len(live) < len(branches):
            self.hooks.dead_branch(eqn, pred)
        if outs is None:  # defensive: evaluate branch 0
            outs = self._sub(branches[0], args)
        return outs

    def _fix_carry(self, body, consts, carry, tail, outvars):
        """Sound widening fixpoint for a loop carry: iterate the body; any
        carry component that keeps moving widens to its dtype hull (top is
        absorbing, so this terminates); 'stable' components are only
        trusted once the WHOLE carry has stabilized — a component stable
        under narrow inputs must be re-checked under the widened ones."""

        def same(a, b):
            return (a.tracked == b.tracked and a.lo == b.lo
                    and a.hi == b.hi and a.outliers == b.outliers)

        for _ in range(6):
            out = self._sub(body, consts + carry + tail)[:len(carry)]
            nxt = []
            moved = False
            for c, o, ov in zip(carry, out, outvars):
                j = c.join(o)
                if same(j, c):
                    nxt.append(c)
                else:
                    moved = True
                    nxt.append(
                        IVal.top(getattr(aval_of(ov), "dtype", np.int64))
                    )
            carry = nxt
            if not moved:
                return carry
        return [IVal.top(getattr(aval_of(ov), "dtype", np.int64))
                for ov in outvars]

    def _loop(self, eqn, ins) -> list:
        """Widening on while/scan (see :meth:`_fix_carry`).  scan's ys are
        evaluated ONCE at the post-fixpoint carries — joining ys from the
        narrow pre-widening iterations would under-approximate them."""
        name = eqn.primitive.name
        if name == "while":
            body = eqn.params["body_jaxpr"]
            b_consts = eqn.params.get("body_nconsts", 0)
            c_consts = eqn.params.get("cond_nconsts", 0)
            consts = ins[c_consts:c_consts + b_consts]
            carry = ins[c_consts + b_consts:]
            return self._fix_carry(body, consts, carry, [], eqn.outvars)
        # scan: [consts..., carry..., xs...] -> [carry..., ys...]
        n_consts = eqn.params.get("num_consts", 0)
        n_carry = eqn.params.get("num_carry", 0)
        body = eqn.params["jaxpr"]
        consts = ins[:n_consts]
        carry = ins[n_consts:n_consts + n_carry]
        xs = ins[n_consts + n_carry:]
        carry = self._fix_carry(body, consts, carry, xs,
                                eqn.outvars[:n_carry])
        ys = self._sub(body, consts + carry + xs)[n_carry:]
        return carry + ys


# ---------------------------------------------------------------------------
# primitive rules
# ---------------------------------------------------------------------------


def _binop(fn_exact, widen_wrap=True):
    """Exact interval combine via ``fn_exact`` on endpoint pairs; wraps to
    the output dtype hull when the result escapes it."""

    def rule(itp: Interp, eqn, ins):
        a, b = ins[0], ins[1]
        dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
        hull = dtype_hull(dt)
        if hull is None or not a.tracked or not b.tracked:
            return IVal(None, None) if hull is None else IVal.top(dt)
        cands = [fn_exact(x, y)
                 for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
        lo, hi = min(cands), max(cands)
        arith = True
        # exact outlier propagation when ONE side is a single point
        outs = frozenset()
        bs, as_ = b.singleton(), a.singleton()
        if bs is not None and a.outliers:
            outs = frozenset(_wrap(fn_exact(o, bs), dt) for o in a.outliers)
        elif as_ is not None and b.outliers:
            outs = frozenset(_wrap(fn_exact(as_, o), dt) for o in b.outliers)
        elif a.outliers or b.outliers:
            pts = ([fn_exact(o, y) for o in a.outliers
                    for y in (b.lo, b.hi)]
                   + [fn_exact(x, o) for o in b.outliers
                      for x in (a.lo, a.hi)])
            lo, hi = min([lo, *pts]), max([hi, *pts])
        if widen_wrap and (lo < hull[0] or hi > hull[1]):
            return IVal(hull[0], hull[1], frozenset(), arith)
        return IVal(lo, hi, outs, arith)._norm()

    return rule


def _rule_add(itp, eqn, ins):
    return _binop(lambda x, y: x + y)(itp, eqn, ins)


def _rule_sub(itp, eqn, ins):
    return _binop(lambda x, y: x - y)(itp, eqn, ins)


def _rule_mul(itp, eqn, ins):
    return _binop(lambda x, y: x * y)(itp, eqn, ins)


def _rule_and(itp: Interp, eqn, ins):
    a, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if np.dtype(dt) == np.bool_:
        if a.singleton() == 0 or b.singleton() == 0:
            return IVal.point(0)
        if a.singleton() == 1 and b.singleton() == 1:
            return IVal.point(1)
        return IVal(0, 1)
    if not a.tracked or not b.tracked:
        return IVal.top(dt)
    # mask-extraction hook: one side a constant contiguous mask
    mask_side, val_side, mval = None, None, 0
    for m, v in ((a, b), (b, a)):
        ms = m.singleton()
        if ms is not None and ms > 0 and _is_contiguous_mask(ms):
            mask_side, val_side, mval = m, v, ms
            break
    if a.lo < 0 or b.lo < 0:
        return IVal.top(dt)
    hi = min(a.hull()[1], b.hull()[1])
    out = IVal(0, hi)
    if mask_side is not None:
        out = IVal(0, min(hi, mval))
        off, bits = _is_contiguous_mask(mval)
        # field-provenance: declared bound for word bits [shift+off, +bits)
        if (itp.row_domain is not None and val_side.word is not None
                and off == 0):
            declared = itp.row_domain.field_hi(val_side.word,
                                               val_side.shift, bits)
            if declared is not None:
                out = IVal(0, min(out.hi, declared))
        if itp.hooks is not None and itp._checking:
            itp.hooks.mask_site(itp, eqn, val_side, mval)
    return replace(out, arith=False)


def _rule_or(itp, eqn, ins):
    a, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if np.dtype(dt) == np.bool_:
        if a.singleton() == 1 or b.singleton() == 1:
            return IVal.point(1)
        if a.singleton() == 0 and b.singleton() == 0:
            return IVal.point(0)
        return IVal(0, 1)
    if not a.tracked or not b.tracked or a.lo < 0 or b.lo < 0:
        return IVal.top(dt)
    ah, bh = a.hull()[1], b.hull()[1]
    hi = (1 << max(ah.bit_length(), bh.bit_length())) - 1
    return IVal(max(a.lo, b.lo), max(hi, ah, bh))


def _rule_xor(itp, eqn, ins):
    a, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if np.dtype(dt) == np.bool_:
        return IVal(0, 1)
    if not a.tracked or not b.tracked or a.lo < 0 or b.lo < 0:
        return IVal.top(dt)
    hi = (1 << max(a.hull()[1].bit_length(), b.hull()[1].bit_length())) - 1
    return IVal(0, hi)


def _rule_shr(itp: Interp, eqn, ins):
    a, s = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if not a.tracked or not s.tracked or a.lo < 0 or s.lo < 0:
        return IVal.top(dt)
    ss = s.singleton()
    if ss is not None:
        out = a.map_exact(lambda v: v >> ss)
        if a.word is not None:  # field provenance survives a const rshift
            out = replace(out, word=a.word, shift=a.shift + ss)
        return out
    return IVal(a.lo >> s.hi, a.hull()[1] >> s.lo)


def _rule_shl(itp, eqn, ins):
    a, s = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    hull = dtype_hull(dt)
    if (hull is None or not a.tracked or not s.tracked or a.lo < 0
            or s.lo < 0):
        return IVal.top(dt)
    ss = s.singleton()
    if ss is not None:
        out = a.map_exact(lambda v: v << ss, arith=a.arith)
    else:
        out = IVal(a.lo << s.lo, a.hull()[1] << s.hi, frozenset(), a.arith)
    oh = out.hull()
    if oh[0] < hull[0] or oh[1] > hull[1]:
        return IVal(hull[0], hull[1], frozenset(), a.arith)
    return out


def _rule_cmp(name):
    def rule(itp, eqn, ins):
        a, b = ins
        out = IVal(0, 1)
        if a.tracked and b.tracked:
            al, ah = a.hull()
            bl, bh = b.hull()
            verdict = None
            if name == "lt":
                verdict = True if ah < bl else (False if al >= bh else None)
            elif name == "le":
                verdict = True if ah <= bl else (False if al > bh else None)
            elif name == "gt":
                verdict = True if al > bh else (False if ah <= bl else None)
            elif name == "ge":
                verdict = True if al >= bh else (False if ah < bl else None)
            elif name == "eq":
                if ah < bl or al > bh:
                    verdict = False
                elif a.singleton() is not None and a.singleton() == b.singleton():
                    verdict = True
            elif name == "ne":
                if ah < bl or al > bh:
                    verdict = True
                elif (a.singleton() is not None
                      and a.singleton() == b.singleton()):
                    verdict = False
            if verdict is not None:
                out = IVal.point(int(verdict))
        return out

    return rule


def _rule_select(itp: Interp, eqn, ins):
    pred, cases = ins[0], ins[1:]
    pred_var = eqn.invars[0]
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    guard = itp._guard_of(pred_var) if len(cases) == 2 else None
    # machine-generated negative-index normalization: never a model smell
    is_norm = bool(guard and guard[1] == "lt" and guard[2] == 0)
    taken = []
    for i, (cvar, cval) in enumerate(zip(eqn.invars[1:], cases)):
        if pred.tracked and not pred.may_contain(i):
            continue  # interval proves this case dead
        if guard is not None:
            base, op, c = guard
            refined = Interp._apply_guard(itp.read(base), op, c,
                                          truth=bool(i))
            if refined is None:
                continue  # guard contradiction: case unreachable
            cval = itp._refine_eval(cvar, base, refined) if not is_literal(
                cvar) else cval
        taken.append(cval)
    if (itp.hooks is not None and itp._checking
            and len(taken) < len(cases) and not is_norm):
        itp.hooks.dead_branch(eqn, pred)
    if not taken:
        return IVal.top(dt)
    out = taken[0]
    for t in taken[1:]:
        out = out.join(t)
    return out


def _rule_convert(itp, eqn, ins):
    (a,) = ins
    dt = np.dtype(eqn.params.get("new_dtype", np.int64))
    hull = dtype_hull(dt)
    if hull is None:
        return IVal(None, None)
    if not a.tracked:
        return IVal.top(dt)
    if hull[0] <= a.lo and a.hi <= hull[1]:
        outs = frozenset(_wrap(o, dt) for o in a.outliers)
        return IVal(a.lo, a.hi, outs, a.arith, a.word, a.shift)._norm()
    return IVal.top(dt)


def _rule_identity(itp, eqn, ins):
    return ins[0]


def _rule_slice(itp: Interp, eqn, ins):
    (a,) = ins
    var = itp.resolve(eqn.invars[0])
    # last-axis column selection on the seeded input row var
    if (itp.row_domain is not None and var is itp.input_var):
        shape = getattr(aval_of(var), "shape", ())
        starts = eqn.params.get("start_indices", ())
        limits = eqn.params.get("limit_indices", ())
        if len(shape) >= 1 and len(starts) == len(shape):
            full_front = all(
                s == 0 and l == d
                for s, l, d in zip(starts[:-1], limits[:-1], shape[:-1])
            )
            if full_front:
                return itp.row_domain.words_ival(starts[-1], limits[-1])
    return a


def _rule_iota(itp, eqn, ins):
    shape = eqn.params.get("shape", ())
    dim = eqn.params.get("dimension", 0)
    n = shape[dim] if shape else 1
    return IVal(0, max(0, int(n) - 1))


def _rule_concat(itp, eqn, ins):
    out = ins[0]
    for v in ins[1:]:
        out = out.join(v)
    return out


def _rule_gather(itp: Interp, eqn, ins):
    # value interval: whatever the operand holds (plus, silently on TPU,
    # clamp artifacts — the hooks' JX201 covers the index side)
    return replace(ins[0], word=None, shift=0)


def _rule_scatter(itp, eqn, ins):
    return ins[0].join(ins[2]) if len(ins) >= 3 else ins[0]


def _rule_dus(itp, eqn, ins):
    return ins[0].join(ins[1])


def _rule_reduce_sum(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    hull = dtype_hull(dt)
    if hull is None or not a.tracked:
        return IVal(None, None) if hull is None else IVal.top(dt)
    n = 1
    in_elems = int(np.prod(getattr(aval_of(eqn.invars[0]), "shape", ()) or
                           (1,)))
    out_elems = int(np.prod(getattr(aval_of(eqn.outvars[0]), "shape", ()) or
                            (1,)))
    n = max(1, in_elems // max(out_elems, 1))
    lo, hi = a.hull()
    lo, hi = min(lo * n, lo), max(hi * n, hi)
    if lo < hull[0] or hi > hull[1]:
        return IVal.top(dt)
    return IVal(lo, hi, frozenset(), True)


def _rule_minmax(fn):
    def rule(itp, eqn, ins):
        a, b = ins
        if not a.tracked or not b.tracked:
            dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
            return IVal.top(dt)
        return IVal(fn(a.lo, b.lo), fn(a.hull()[1], b.hull()[1]))

    return rule


def _rule_clamp(itp, eqn, ins):
    # clamp(a, x, b) = max(a, min(x, b)) elementwise, min/max monotone
    a, x, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if not (a.tracked and x.tracked and b.tracked):
        return IVal.top(dt)
    t_lo = min(x.hull()[0], b.hull()[0])
    t_hi = min(x.hull()[1], b.hull()[1])
    return IVal(max(a.hull()[0], t_lo), max(a.hull()[1], t_hi))


def _rule_rem(itp, eqn, ins):
    a, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if (a.tracked and b.tracked and a.lo >= 0 and b.lo > 0):
        return IVal(0, min(a.hull()[1], b.hull()[1] - 1), frozenset(), True)
    return IVal.top(dt)


def _rule_div(itp, eqn, ins):
    a, b = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if dtype_hull(dt) is None:
        return IVal(None, None)
    if a.tracked and b.tracked and a.lo >= 0 and b.lo > 0:
        return IVal(a.lo // b.hull()[1], a.hull()[1] // b.lo,
                    frozenset(), True)
    return IVal.top(dt)


def _rule_argextreme(itp, eqn, ins):
    axes = eqn.params.get("axes", ())
    shape = getattr(aval_of(eqn.invars[0]), "shape", ())
    n = 1
    for ax in axes:
        if ax < len(shape):
            n *= shape[ax]
    return IVal(0, max(0, n - 1))


def _rule_argsort_like(itp, eqn, ins):
    # sort: per-operand identity intervals (argsort handled via iota operand)
    return [replace(v, word=None, shift=0) for v in ins]


def _rule_neg(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    hull = dtype_hull(dt)
    if hull is None or not a.tracked:
        return IVal(None, None) if hull is None else IVal.top(dt)
    lo, hi = -a.hull()[1], -a.hull()[0]
    if lo < hull[0] or hi > hull[1]:
        return IVal.top(dt)
    return IVal(lo, hi, frozenset(), True)


def _rule_not(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if np.dtype(dt) == np.bool_:
        s = a.singleton()
        return IVal.point(1 - s) if s in (0, 1) else IVal(0, 1)
    if a.tracked:
        return a.map_exact(lambda v: _wrap(~v, dt))
    return IVal.top(dt)


def _rule_cumsum(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    hull = dtype_hull(dt)
    if hull is None or not a.tracked:
        return IVal(None, None) if hull is None else IVal.top(dt)
    shape = getattr(aval_of(eqn.invars[0]), "shape", ())
    ax = eqn.params.get("axis", 0)
    n = int(shape[ax]) if ax < len(shape) else 1
    lo, hi = a.hull()
    lo, hi = min(lo, lo * n), max(hi, hi * n)
    if lo < hull[0] or hi > hull[1]:
        return IVal.top(dt)
    return IVal(lo, hi, frozenset(), True)


def _rule_bool01(itp, eqn, ins):
    return IVal(0, 1)


def _rule_reduce_keep(itp, eqn, ins):
    return replace(ins[0], word=None, shift=0)


def _rule_pad(itp, eqn, ins):
    return ins[0].join(ins[1])


def _rule_abs(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if dtype_hull(dt) is None:
        return IVal(None, None)
    if not a.tracked:
        return IVal.top(dt)
    lo, hi = a.hull()
    if lo >= 0:
        return IVal(lo, hi)
    if hi <= 0:
        return IVal(-hi, -lo)
    return IVal(0, max(-lo, hi))


def _rule_sign(itp, eqn, ins):
    (a,) = ins
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    if dtype_hull(dt) is None:
        return IVal(None, None)
    if not a.tracked:
        return IVal(-1, 1)
    lo, hi = a.hull()
    if lo > 0:
        return IVal.point(1)
    if hi < 0:
        return IVal.point(-1)
    return IVal(-1 if lo < 0 else 0, 1 if hi > 0 else 0)


def _rule_integer_pow(itp, eqn, ins):
    (a,) = ins
    y = eqn.params.get("y", 1)
    dt = getattr(aval_of(eqn.outvars[0]), "dtype", np.int64)
    hull = dtype_hull(dt)
    if hull is None or not a.tracked or a.lo < 0 or y < 0:
        return IVal.top(dt) if hull else IVal(None, None)
    lo, hi = a.lo ** y, a.hull()[1] ** y
    if hi > hull[1]:
        return IVal.top(dt)
    return IVal(lo, hi, frozenset(), True)


_RULES = {
    "add": _rule_add,
    "sub": _rule_sub,
    "mul": _rule_mul,
    "and": _rule_and,
    "or": _rule_or,
    "xor": _rule_xor,
    "not": _rule_not,
    "neg": _rule_neg,
    "shift_right_logical": _rule_shr,
    "shift_right_arithmetic": _rule_shr,
    "shift_left": _rule_shl,
    "eq": _rule_cmp("eq"),
    "ne": _rule_cmp("ne"),
    "lt": _rule_cmp("lt"),
    "le": _rule_cmp("le"),
    "gt": _rule_cmp("gt"),
    "ge": _rule_cmp("ge"),
    "select_n": _rule_select,
    "convert_element_type": _rule_convert,
    "reshape": _rule_identity,
    "broadcast_in_dim": _rule_identity,
    "squeeze": _rule_identity,
    "expand_dims": _rule_identity,
    "transpose": _rule_identity,
    "rev": _rule_identity,
    "copy": _rule_identity,
    "stop_gradient": _rule_identity,
    "slice": _rule_slice,
    "iota": _rule_iota,
    "concatenate": _rule_concat,
    "gather": _rule_gather,
    "scatter": _rule_scatter,
    "scatter-add": _rule_scatter,
    "scatter_add": _rule_scatter,
    "scatter_min": _rule_scatter,
    "scatter_max": _rule_scatter,
    "scatter_mul": _rule_scatter,
    "dynamic_slice": _rule_reduce_keep,
    "dynamic_update_slice": _rule_dus,
    "reduce_sum": _rule_reduce_sum,
    "cumsum": _rule_cumsum,
    "reduce_max": _rule_reduce_keep,
    "reduce_min": _rule_reduce_keep,
    "cummax": _rule_reduce_keep,
    "cummin": _rule_reduce_keep,
    "reduce_and": _rule_bool01,
    "reduce_or": _rule_bool01,
    "argmax": _rule_argextreme,
    "argmin": _rule_argextreme,
    "sort": _rule_argsort_like,
    "max": _rule_minmax(max),
    "min": _rule_minmax(min),
    "clamp": _rule_clamp,
    "rem": _rule_rem,
    "div": _rule_div,
    "pad": _rule_pad,
    "integer_pow": _rule_integer_pow,
    "abs": _rule_abs,
    "sign": _rule_sign,
    "population_count": lambda i, e, ins: IVal(0, 64),
    "clz": lambda i, e, ins: IVal(0, 64),
}
