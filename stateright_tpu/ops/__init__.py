"""Device kernels: vectorized fingerprinting, hash-table dedup, sorting ops.

Everything in this package runs under ``jit`` on TPU (or the CPU backend in
tests).  64-bit integers are required for fingerprint math, so importing this
package enables JAX x64 mode; all kernels use explicit dtypes, so the change
to *default* dtypes does not leak into user code paths.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .hashing import EMPTY, row_hash  # noqa: E402,F401
