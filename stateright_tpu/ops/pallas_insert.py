"""Pallas TPU kernel for the visited-set insert (the north-star hot op).

Drop-in replacement for the fp/payload windowed-scatter ``while_loop`` in
``ops/buckets.bucket_insert`` (reference analogue: the lock-striped
``DashMap`` insert, ``src/checker/bfs.rs:26``).  The XLA path expresses the
insert as chunked ``scatter``s; this kernel instead walks the novel
candidates once, streaming each touched **block** of the table
HBM→VMEM→HBM with explicit DMA:

 - the tables stay in HBM (``pl.ANY``) and are updated **in place** via
   ``input_output_aliases`` — no table-sized copies, no scatter lowering;
 - a block is 8 line groups = 1024 u64 slots (Mosaic tiles 2-D i32 HBM
   memrefs as (8, 128), so DMA slices must cover whole 8-row tiles);
 - per candidate the update is a masked select on the VPU over the
   (8, 256)-lane block;
 - candidate metadata stays in HBM and is streamed into a fixed
   512-candidate SMEM window per DMA, so the kernel's VMEM footprint is
   batch-independent;
 - the trip count is the *dynamic* novel count — padding lanes cost
   nothing, so one compiled kernel serves every batch.

**The DMA walk is pipelined** (round 4; the round-3 serial walk paid ~2
blocking DMA latencies per touched block, which at engine scale — ~5k
distinct blocks per 8k-candidate batch against an 8M-slot table —
dominated the whole step).  The wrapper sorts candidates by target slot,
making touched blocks *ascending and unique*, and derives the
distinct-block sequence ("runs").  The kernel keeps a ring of ``NBUF``
resident block buffers: entering run ``r`` starts an async flush of the
evicted run and an async prefetch of run ``r + NBUF - 1``, so up to
``NBUF-1`` fetches and flushes are in flight while the VPU applies
selects to the resident block.  Re-sorting is safe for every caller:
target slots are distinct, so write order cannot matter, and exploration
order is carried by ``sel``, which is computed in ``bucket_insert``
before the kernel runs.

Measured verdict (v5e, 8M-slot table, 8192 novel/batch): serial walk
54.1 ms/insert → pipelined 37.3 ms/insert → **XLA windowed scatter
0.14 ms/insert**.  The XLA path remains the default and the recommended
one; ``docs/pallas-insert-verdict.md`` explains why tile-granularity DMA
read-modify-write loses to the native scatter by construction at the
engine's ~1-candidate-per-block densities, and what narrower regime the
kernel shape would suit.

``uint64`` is not a native Pallas/TPU dtype, so the wrapper bitcasts the
u64 tables and candidate words to pairs of u32 lanes (little-endian: lane
``2k`` = low word of slot ``k``).

No occupancy metadata exists to maintain: slots fill densely and never
free, so a bucket's occupancy is implicit in its line (``ops/buckets.py``
derives it from the membership gather) — the u64 fp/payload writes this
kernel performs are the whole visited-set update.

Correctness contract (same as the XLA scatters): target slots are distinct
(bucket * SLOTS + per-bucket rank) and candidates are pre-deduplicated and
pre-screened for membership.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .buckets import SLOTS

# one line group = 8 buckets x 16 slots = 128 u64 slots = 256 u32 lanes
GROUP_BUCKETS = 8
GROUP_SLOTS = GROUP_BUCKETS * SLOTS
GROUP_LANES = 2 * GROUP_SLOTS  # u32 lanes per group
# one DMA block = 8 line groups (the (8, 128) i32 HBM tile height)
BLOCK_GROUPS = 8
BLOCK_SLOTS = BLOCK_GROUPS * GROUP_SLOTS
# candidates per meta SMEM window (multiple of the 128-lane tile width)
META_WINDOW = 512
# meta rows: run, row-in-block, lane, fplo, fphi, pllo, plhi, pad
META_ROWS = 8
# resident block buffers (ring): up to NBUF-1 prefetches in flight
NBUF = 8
# distinct-block ids per runs SMEM window (1-D i32 memrefs tile by 1024
# lanes, and DMA slices must cover whole tiles)
RUNW = 1024
# state_ref cells
_R_CUR, _R_PF, _R_WIN = 0, 1, 2


def _insert_kernel(
    scal_ref,  # SMEM (2,) i32: [novel count, run count]
    meta_hbm,  # ANY  [META_ROWS, Mpad] i32 (streamed in windows)
    runs_hbm,  # ANY  [Rpad] i32: ascending distinct block ids
    tfp_hbm,  # ANY  [nblocks * BLOCK_GROUPS, GROUP_LANES] u32 (aliased out 0)
    tpl_hbm,  # ANY  (aliased out 1)
    tfp_out,
    tpl_out,
    meta_win,  # SMEM scratch (META_ROWS, META_WINDOW) i32 — SMEM because the
    #            kernel reads single elements at dynamic lane offsets, which
    #            Mosaic only supports for scalar memory
    runs_win,  # SMEM scratch (RUNW,) i32
    blk_ring,  # SMEM scratch (NBUF,) i32: block id resident in each buffer
    state,  # SMEM scratch (4,) i32: r_cur, r_pf, loaded runs-window id
    fp_buf,  # VMEM scratch (NBUF, BLOCK_GROUPS, GROUP_LANES) u32
    pl_buf,
    fetch_sem,  # DMA semaphores (NBUF, 2): fp / payload fetch per buffer
    flush_sem,  # DMA semaphores (NBUF, 2)
    win_sem,  # DMA semaphores (2,): meta / runs window loads
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = scal_ref[0]
    n_runs = scal_ref[1]
    rows = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK_GROUPS, GROUP_LANES), 0
    )
    lanes = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK_GROUPS, GROUP_LANES), 1
    )
    nbuf = jnp.int32(NBUF)

    def load_runs_window(w):
        cp = pltpu.make_async_copy(
            runs_hbm.at[pl.ds(w * jnp.int32(RUNW), RUNW)],
            runs_win,
            win_sem.at[jnp.int32(1)],
        )
        cp.start()
        cp.wait()
        state[_R_WIN] = w

    def start_fetch(r):
        """Begin streaming run ``r``'s block into its ring buffer.  The
        caller guarantees runs_win holds ``r``'s window and the buffer's
        previous flush (if any) has been waited."""
        b = jax.lax.rem(r, nbuf)
        blk = runs_win[r - state[_R_WIN] * jnp.int32(RUNW)]
        blk_ring[b] = blk
        g0 = blk * jnp.int32(BLOCK_GROUPS)
        pltpu.make_async_copy(
            tfp_out.at[pl.ds(g0, BLOCK_GROUPS)],
            fp_buf.at[b],
            fetch_sem.at[b, jnp.int32(0)],
        ).start()
        pltpu.make_async_copy(
            tpl_out.at[pl.ds(g0, BLOCK_GROUPS)],
            pl_buf.at[b],
            fetch_sem.at[b, jnp.int32(1)],
        ).start()

    def wait_fetch(r):
        b = jax.lax.rem(r, nbuf)
        g0 = blk_ring[b] * jnp.int32(BLOCK_GROUPS)
        pltpu.make_async_copy(
            tfp_out.at[pl.ds(g0, BLOCK_GROUPS)],
            fp_buf.at[b],
            fetch_sem.at[b, jnp.int32(0)],
        ).wait()
        pltpu.make_async_copy(
            tpl_out.at[pl.ds(g0, BLOCK_GROUPS)],
            pl_buf.at[b],
            fetch_sem.at[b, jnp.int32(1)],
        ).wait()

    def start_flush(r):
        b = jax.lax.rem(r, nbuf)
        g0 = blk_ring[b] * jnp.int32(BLOCK_GROUPS)
        pltpu.make_async_copy(
            fp_buf.at[b],
            tfp_out.at[pl.ds(g0, BLOCK_GROUPS)],
            flush_sem.at[b, jnp.int32(0)],
        ).start()
        pltpu.make_async_copy(
            pl_buf.at[b],
            tpl_out.at[pl.ds(g0, BLOCK_GROUPS)],
            flush_sem.at[b, jnp.int32(1)],
        ).start()

    def wait_flush(r):
        b = jax.lax.rem(r, nbuf)
        g0 = blk_ring[b] * jnp.int32(BLOCK_GROUPS)
        pltpu.make_async_copy(
            fp_buf.at[b],
            tfp_out.at[pl.ds(g0, BLOCK_GROUPS)],
            flush_sem.at[b, jnp.int32(0)],
        ).wait()
        pltpu.make_async_copy(
            pl_buf.at[b],
            tpl_out.at[pl.ds(g0, BLOCK_GROUPS)],
            flush_sem.at[b, jnp.int32(1)],
        ).wait()

    def prefetch_next():
        """Issue at most one fetch, keeping ≤ NBUF-2 ahead of r_cur: the
        last slot of slack means run q+NBUF-1's refetch (which waits
        flush(q-1)) is issued one full run AFTER flush(q-1) started, so a
        flush is never waited in the same advance that issued it."""
        r_pf = state[_R_PF]

        @pl.when((r_pf < n_runs) & (r_pf < state[_R_CUR] + nbuf - jnp.int32(1)))
        def _():
            w = r_pf // jnp.int32(RUNW)

            @pl.when(w != state[_R_WIN])
            def _():
                load_runs_window(w)

            # the buffer's previous occupant (run r_pf - NBUF < r_cur) was
            # evicted earlier; its flush must land before the refetch
            @pl.when(r_pf >= nbuf)
            def _():
                wait_flush(r_pf - nbuf)

            start_fetch(r_pf)
            state[_R_PF] = r_pf + jnp.int32(1)

    def body(j, _):
        r = meta_win[0, j]

        @pl.when(r != state[_R_CUR])
        def _():
            # runs advance one at a time (every run has ≥1 candidate)
            start_flush(state[_R_CUR])
            state[_R_CUR] = r
            prefetch_next()
            wait_fetch(r)

        bi = jax.lax.rem(r, nbuf)
        shape = (BLOCK_GROUPS, GROUP_LANES)
        lo = jnp.full(shape, 0, jnp.int32) + meta_win[3, j]
        hi = jnp.full(shape, 0, jnp.int32) + meta_win[4, j]
        plo = jnp.full(shape, 0, jnp.int32) + meta_win[5, j]
        phi = jnp.full(shape, 0, jnp.int32) + meta_win[6, j]
        here = rows == meta_win[1, j]
        lane = meta_win[2, j]
        sel_lo = here & (lanes == 2 * lane)
        sel_hi = here & (lanes == 2 * lane + 1)
        fp_buf[bi] = jnp.where(
            sel_lo, lo.astype(jnp.uint32),
            jnp.where(sel_hi, hi.astype(jnp.uint32), fp_buf[bi]),
        )
        pl_buf[bi] = jnp.where(
            sel_lo, plo.astype(jnp.uint32),
            jnp.where(sel_hi, phi.astype(jnp.uint32), pl_buf[bi]),
        )
        return 0

    def window(w, _):
        cp = pltpu.make_async_copy(
            meta_hbm.at[:, pl.ds(w * jnp.int32(META_WINDOW), META_WINDOW)],
            meta_win,
            win_sem.at[jnp.int32(0)],
        )
        cp.start()
        cp.wait()
        count = jnp.minimum(n - w * jnp.int32(META_WINDOW),
                            jnp.int32(META_WINDOW))
        return jax.lax.fori_loop(0, count, body, 0)

    @pl.when(n > 0)
    def _():
        # initial fill: fetch the first min(n_runs, NBUF) runs, then block
        # only on run 0 (the rest stream in behind the VPU work)
        load_runs_window(jnp.int32(0))
        state[_R_CUR] = jnp.int32(0)
        state[_R_PF] = jnp.int32(0)

        def ifetch(r, _):
            start_fetch(r)
            state[_R_PF] = r + jnp.int32(1)
            return 0

        jax.lax.fori_loop(0, jnp.minimum(n_runs, nbuf - jnp.int32(1)), ifetch, 0)
        wait_fetch(jnp.int32(0))

        nwin = (n + jnp.int32(META_WINDOW - 1)) // jnp.int32(META_WINDOW)
        jax.lax.fori_loop(0, nwin, window, 0)

        # drain: flush the final resident block, then retire every DMA the
        # pipeline still has in flight (prefetched-but-unentered fetches;
        # flushes no refetch ever waited on)
        r_cur = state[_R_CUR]
        r_pf = state[_R_PF]
        start_flush(r_cur)

        def dfetch(r, _):
            wait_fetch(r)
            return 0

        jax.lax.fori_loop(r_cur + 1, r_pf, dfetch, 0)

        def dflush(r, _):
            wait_flush(r)
            return 0

        jax.lax.fori_loop(
            jnp.maximum(jnp.int32(0), r_pf - nbuf), r_cur + 1, dflush, 0
        )


def pallas_scatter_insert(
    table_fp,  # u64 [nslots]
    table_payload,  # u64 [nslots]
    tgt,  # i32 [M] target slot per candidate (nslots = invalid/pad)
    cfp,  # u64 [M] fingerprints, novel-compacted
    cpl,  # u64 [M]
    n_new,  # i32 scalar: number of valid candidates (prefix of the arrays)
):
    """Write ``cfp/cpl`` to ``tgt`` slots as one Pallas kernel invocation.
    Equivalent to (and validated against) the fp/payload windowed-scatter
    path in :func:`ops.buckets.bucket_insert`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nslots = table_fp.shape[0]
    # pad tiny tables up to one whole DMA block (larger-than-one-block
    # tables are already powers of two, hence multiples); padding copies,
    # but only on toy sizes — engine-scale tables alias in place
    spad = (-nslots) % BLOCK_SLOTS
    if spad:
        table_fp = jnp.concatenate(
            [table_fp, jnp.zeros((spad,), jnp.uint64)]
        )
        table_payload = jnp.concatenate(
            [table_payload, jnp.zeros((spad,), jnp.uint64)]
        )
    ngroups = table_fp.shape[0] // GROUP_SLOTS
    m = tgt.shape[0]

    # -- vector-side prep (cheap XLA) --------------------------------------
    # Sort by target slot: valid candidates (tgt < nslots) form a prefix
    # and their blocks are ascending AND unique-per-run, which is what lets
    # the kernel prefetch ahead without write-order hazards.  Distinct
    # target slots make the re-ordering semantically free.
    order = jnp.argsort(tgt)
    tgt = tgt[order]
    cfp = cfp[order]
    cpl = cpl[order]
    vmask = jnp.arange(m, dtype=jnp.int32) < n_new
    slot = jnp.minimum(tgt, nslots - 1)
    g = slot // GROUP_SLOTS
    block = g // BLOCK_GROUPS
    row = g - block * BLOCK_GROUPS
    lane = slot - g * GROUP_SLOTS
    # distinct-block runs over the valid prefix
    newrun = vmask & jnp.concatenate(
        [jnp.ones((1,), bool), block[1:] != block[:-1]]
    )
    run_idx = jnp.cumsum(newrun.astype(jnp.int32)) - 1
    n_runs = jnp.sum(newrun).astype(jnp.int32)
    # run r's block = block of its first candidate (monotone run_idx over
    # the valid prefix ⇒ a vectorized binary search finds the boundary)
    run_seq = jnp.where(vmask, run_idx, jnp.int32(m))
    first_of_run = jnp.minimum(
        jnp.searchsorted(
            run_seq, jnp.arange(m, dtype=jnp.int32), side="left"
        ).astype(jnp.int32),
        jnp.int32(m - 1),
    )
    run_blocks = block[first_of_run].astype(jnp.int32)
    rpad = (-m) % RUNW
    if rpad:
        run_blocks = jnp.concatenate(
            [run_blocks, jnp.zeros((rpad,), jnp.int32)]
        )

    f32 = jax.lax.bitcast_convert_type(cfp, jnp.uint32).astype(jnp.int32)
    p32 = jax.lax.bitcast_convert_type(cpl, jnp.uint32).astype(jnp.int32)
    zero = jnp.zeros((m,), jnp.int32)
    # transposed layout [META_ROWS, M]: the kernel DMA-streams fixed-width
    # column windows, and a full-height slice keeps every window tile-aligned
    meta = jnp.stack(
        [
            jnp.where(vmask, run_idx, -1),
            row,
            lane,
            f32[:, 0],
            f32[:, 1],
            p32[:, 0],
            p32[:, 1],
            zero,
        ],
        axis=0,
    ).astype(jnp.int32)
    mpad = (-m) % META_WINDOW
    if mpad:
        pad = jnp.full((META_ROWS, mpad), -1, jnp.int32)
        meta = jnp.concatenate([meta, pad], axis=1)

    tfp32 = jax.lax.bitcast_convert_type(table_fp, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )
    tpl32 = jax.lax.bitcast_convert_type(table_payload, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )

    interpret = jax.default_backend() != "tpu"
    out_fp, out_pl = pl.pallas_call(
        _insert_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(tfp32.shape, jnp.uint32),
            jax.ShapeDtypeStruct(tpl32.shape, jnp.uint32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.SMEM((META_ROWS, META_WINDOW), jnp.int32),
            pltpu.SMEM((RUNW,), jnp.int32),
            pltpu.SMEM((NBUF,), jnp.int32),
            pltpu.SMEM((4,), jnp.int32),
            pltpu.VMEM((NBUF, BLOCK_GROUPS, GROUP_LANES), jnp.uint32),
            pltpu.VMEM((NBUF, BLOCK_GROUPS, GROUP_LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((NBUF, 2)),
            pltpu.SemaphoreType.DMA((NBUF, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(
        jnp.stack([n_new.astype(jnp.int32), n_runs]).reshape(2),
        meta,
        run_blocks,
        tfp32,
        tpl32,
    )
    padded = nslots + spad
    table_fp = jax.lax.bitcast_convert_type(
        out_fp.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    table_payload = jax.lax.bitcast_convert_type(
        out_pl.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    return table_fp, table_payload
