"""Pallas TPU kernel for the visited-set insert (the north-star hot op).

Drop-in replacement for the fp/payload windowed-scatter ``while_loop`` in
``ops/buckets.bucket_insert`` (reference analogue: the lock-striped
``DashMap`` insert, ``src/checker/bfs.rs:26``).  The XLA path expresses the
insert as chunked ``scatter``s, which XLA lowers to (effectively
index-serial) HBM updates plus a full table copy unless donation kicks in.
This kernel instead walks the novel candidates once, streaming each touched
128-slot line group HBM→VMEM→HBM with explicit DMA:

 - the tables stay in HBM (``pl.ANY``) and are updated **in place** via
   ``input_output_aliases`` — no table-sized copies, no scatter lowering;
 - per candidate the update is a 256-lane masked select on the VPU; a line
   group is flushed/re-fetched only when the walk crosses a group boundary
   (candidates arrive in generation order — often bucket-clustered but not
   sorted — and re-fetching a previously flushed group reads its updated
   content, so ordering affects only DMA count, never correctness);
 - the trip count is the *dynamic* novel count — padding lanes cost nothing
   (no DMA, no flush), so one compiled kernel serves every batch.

``uint64`` is not a native Pallas/TPU dtype, so the wrapper bitcasts the
u64 tables and candidate words to pairs of u32 lanes (little-endian: lane
``2k`` = low word of slot ``k``).

Bucket occupancy counts stay on the XLA windowed-scatter path in
``bucket_insert``: exactly one row per bucket (the max-rank novel row)
carries a real count target, so that scatter is write-order-independent and
tiny, while the u64 fp/payload writes — the HBM-bandwidth cost — go through
this kernel.

Correctness contract (same as the XLA scatters): target slots are distinct
(bucket * SLOTS + per-bucket rank) and candidates are pre-deduplicated and
pre-screened for membership.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .buckets import SLOTS

# one line group = 8 buckets x 16 slots = 128 u64 slots = 256 u32 lanes
GROUP_BUCKETS = 8
GROUP_SLOTS = GROUP_BUCKETS * SLOTS
GROUP_LANES = 2 * GROUP_SLOTS  # u32 lanes per group


def _insert_kernel(
    n_ref,  # SMEM (1,) i32: novel count
    meta_ref,  # VMEM [T, 8] i32: group, lane, fplo, fphi, pllo, plhi, 0, 0
    tfp_hbm,  # ANY  [ngroups, GROUP_LANES] u32 (aliased out 0)
    tpl_hbm,  # ANY  [ngroups, GROUP_LANES] u32 (aliased out 1)
    tfp_out,
    tpl_out,
    fp_line,  # VMEM scratch (1, GROUP_LANES) u32
    pl_line,
    sem,  # DMA semaphores (4,)
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = n_ref[0]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, GROUP_LANES), 1)

    def fetch(g):
        cp = pltpu.make_async_copy(tfp_out.at[pl.ds(g, 1)], fp_line, sem.at[0])
        cp.start()
        cp2 = pltpu.make_async_copy(tpl_out.at[pl.ds(g, 1)], pl_line, sem.at[1])
        cp2.start()
        cp.wait()
        cp2.wait()

    def flush(g):
        cp = pltpu.make_async_copy(fp_line, tfp_out.at[pl.ds(g, 1)], sem.at[2])
        cp.start()
        cp2 = pltpu.make_async_copy(pl_line, tpl_out.at[pl.ds(g, 1)], sem.at[3])
        cp2.start()
        cp.wait()
        cp2.wait()

    def body(j, cur_g):
        g = meta_ref[j, 0]
        lane = meta_ref[j, 1]

        @pl.when(g != cur_g)
        def _():
            @pl.when(cur_g >= 0)
            def _():
                flush(cur_g)

            fetch(g)

        lo = jnp.full((1, GROUP_LANES), 0, jnp.int32) + meta_ref[j, 2]
        hi = jnp.full((1, GROUP_LANES), 0, jnp.int32) + meta_ref[j, 3]
        plo = jnp.full((1, GROUP_LANES), 0, jnp.int32) + meta_ref[j, 4]
        phi = jnp.full((1, GROUP_LANES), 0, jnp.int32) + meta_ref[j, 5]
        sel_lo = lanes == 2 * lane
        sel_hi = lanes == 2 * lane + 1
        fp_line[:, :] = jnp.where(
            sel_lo, lo.astype(jnp.uint32),
            jnp.where(sel_hi, hi.astype(jnp.uint32), fp_line[:, :]),
        )
        pl_line[:, :] = jnp.where(
            sel_lo, plo.astype(jnp.uint32),
            jnp.where(sel_hi, phi.astype(jnp.uint32), pl_line[:, :]),
        )
        return g

    last_g = jax.lax.fori_loop(0, n, body, jnp.int32(-1))

    @pl.when(last_g >= 0)
    def _():
        flush(last_g)


def pallas_scatter_insert(
    table_fp,  # u64 [nslots]
    table_payload,  # u64 [nslots]
    tgt,  # i32 [M] target slot per candidate (nslots = invalid/pad)
    cfp,  # u64 [M] fingerprints, novel-compacted (generation order)
    cpl,  # u64 [M]
    n_new,  # i32 scalar: number of valid candidates (prefix of the arrays)
):
    """Write ``cfp/cpl`` to ``tgt`` slots as one Pallas kernel invocation.
    Equivalent to (and validated against) the fp/payload windowed-scatter
    path in :func:`ops.buckets.bucket_insert`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nslots = table_fp.shape[0]
    # pad tiny tables up to one whole line group (larger-than-one-group
    # tables are already powers of two, hence multiples); padding copies,
    # but only on toy sizes — engine-scale tables alias in place
    spad = (-nslots) % GROUP_SLOTS
    if spad:
        table_fp = jnp.concatenate(
            [table_fp, jnp.zeros((spad,), jnp.uint64)]
        )
        table_payload = jnp.concatenate(
            [table_payload, jnp.zeros((spad,), jnp.uint64)]
        )
    ngroups = table_fp.shape[0] // GROUP_SLOTS
    m = tgt.shape[0]

    # -- vector-side prep (cheap XLA) --------------------------------------
    valid = tgt < nslots
    slot = jnp.minimum(tgt, nslots - 1)
    g = slot // GROUP_SLOTS
    lane = slot - g * GROUP_SLOTS
    f32 = jax.lax.bitcast_convert_type(cfp, jnp.uint32).astype(jnp.int32)
    p32 = jax.lax.bitcast_convert_type(cpl, jnp.uint32).astype(jnp.int32)
    zero = jnp.zeros((m,), jnp.int32)
    meta = jnp.stack(
        [
            jnp.where(valid, g, -1),
            lane,
            f32[:, 0],
            f32[:, 1],
            p32[:, 0],
            p32[:, 1],
            zero,
            zero,
        ],
        axis=1,
    ).astype(jnp.int32)

    tfp32 = jax.lax.bitcast_convert_type(table_fp, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )
    tpl32 = jax.lax.bitcast_convert_type(table_payload, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )

    interpret = jax.default_backend() != "tpu"
    out_fp, out_pl = pl.pallas_call(
        _insert_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(tfp32.shape, jnp.uint32),
            jax.ShapeDtypeStruct(tpl32.shape, jnp.uint32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, GROUP_LANES), jnp.uint32),
            pltpu.VMEM((1, GROUP_LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(
        n_new.reshape(1).astype(jnp.int32),
        meta,
        tfp32,
        tpl32,
    )
    padded = nslots + spad
    table_fp = jax.lax.bitcast_convert_type(
        out_fp.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    table_payload = jax.lax.bitcast_convert_type(
        out_pl.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    return table_fp, table_payload
