"""Pallas TPU kernel for the visited-set insert (the north-star hot op).

Drop-in replacement for the fp/payload windowed-scatter ``while_loop`` in
``ops/buckets.bucket_insert`` (reference analogue: the lock-striped
``DashMap`` insert, ``src/checker/bfs.rs:26``).  The XLA path expresses the
insert as chunked ``scatter``s, which XLA lowers to (effectively
index-serial) HBM updates plus a full table copy unless donation kicks in.
This kernel instead walks the novel candidates once, streaming each touched
**block** of the table HBM→VMEM→HBM with explicit DMA:

 - the tables stay in HBM (``pl.ANY``) and are updated **in place** via
   ``input_output_aliases`` — no table-sized copies, no scatter lowering;
 - a block is 8 line groups = 1024 u64 slots (Mosaic tiles 2-D i32 HBM
   memrefs as (8, 128), so DMA slices must cover whole 8-row tiles — a
   1-row slice fails to compile: "Slice shape along dimension 0 must be
   aligned to tiling (8)");
 - per candidate the update is a masked select on the VPU over the
   (8, 256)-lane block; a block is flushed/re-fetched only when the walk
   crosses a block boundary (candidates arrive in generation order — often
   bucket-clustered but not sorted — and re-fetching a previously flushed
   block reads its updated content, so ordering affects only DMA count,
   never correctness);
 - candidate metadata ALSO stays in HBM and is streamed into a fixed
   512-candidate VMEM window per DMA, so the kernel's VMEM footprint is
   **batch-independent** (~50 KB total) — engine-scale batches previously
   forced the whole [M, 8] meta array into VMEM (advisor r2, medium);
 - the trip count is the *dynamic* novel count — padding lanes cost nothing
   (no DMA, no flush), so one compiled kernel serves every batch.

``uint64`` is not a native Pallas/TPU dtype, so the wrapper bitcasts the
u64 tables and candidate words to pairs of u32 lanes (little-endian: lane
``2k`` = low word of slot ``k``).

No occupancy metadata exists to maintain: slots fill densely and never
free, so a bucket's occupancy is implicit in its line (``ops/buckets.py``
derives it from the membership gather) — the u64 fp/payload writes this
kernel performs are the whole visited-set update.

Correctness contract (same as the XLA scatters): target slots are distinct
(bucket * SLOTS + per-bucket rank) and candidates are pre-deduplicated and
pre-screened for membership.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .buckets import SLOTS

# one line group = 8 buckets x 16 slots = 128 u64 slots = 256 u32 lanes
GROUP_BUCKETS = 8
GROUP_SLOTS = GROUP_BUCKETS * SLOTS
GROUP_LANES = 2 * GROUP_SLOTS  # u32 lanes per group
# one DMA block = 8 line groups (the (8, 128) i32 HBM tile height)
BLOCK_GROUPS = 8
BLOCK_SLOTS = BLOCK_GROUPS * GROUP_SLOTS
# candidates per meta VMEM window (multiple of the 128-lane tile width)
META_WINDOW = 512
# meta rows: block, row-in-block, lane, fplo, fphi, pllo, plhi, pad
META_ROWS = 8


def _insert_kernel(
    n_ref,  # SMEM (1,) i32: novel count
    meta_hbm,  # ANY  [META_ROWS, Mpad] i32 (streamed in windows)
    tfp_hbm,  # ANY  [nblocks * BLOCK_GROUPS, GROUP_LANES] u32 (aliased out 0)
    tpl_hbm,  # ANY  (aliased out 1)
    tfp_out,
    tpl_out,
    meta_win,  # SMEM scratch (META_ROWS, META_WINDOW) i32 — SMEM because the
    #            kernel reads single elements at dynamic lane offsets, which
    #            Mosaic only supports for scalar memory
    fp_line,  # VMEM scratch (BLOCK_GROUPS, GROUP_LANES) u32
    pl_line,
    sem,  # DMA semaphores (5,)
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = n_ref[0]
    rows = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK_GROUPS, GROUP_LANES), 0
    )
    lanes = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK_GROUPS, GROUP_LANES), 1
    )
    # index semaphores with explicit i32: under jax_enable_x64 a bare Python
    # literal lowers as i64, which Mosaic's memref_slice verifier rejects
    s0, s1, s2, s3, s4 = (sem.at[jnp.int32(i)] for i in range(5))

    def fetch(b):
        g0 = b * jnp.int32(BLOCK_GROUPS)
        cp = pltpu.make_async_copy(
            tfp_out.at[pl.ds(g0, BLOCK_GROUPS)], fp_line, s0
        )
        cp.start()
        cp2 = pltpu.make_async_copy(
            tpl_out.at[pl.ds(g0, BLOCK_GROUPS)], pl_line, s1
        )
        cp2.start()
        cp.wait()
        cp2.wait()

    def flush(b):
        g0 = b * jnp.int32(BLOCK_GROUPS)
        cp = pltpu.make_async_copy(
            fp_line, tfp_out.at[pl.ds(g0, BLOCK_GROUPS)], s2
        )
        cp.start()
        cp2 = pltpu.make_async_copy(
            pl_line, tpl_out.at[pl.ds(g0, BLOCK_GROUPS)], s3
        )
        cp2.start()
        cp.wait()
        cp2.wait()

    def body(j, cur_b):
        b = meta_win[0, j]
        r = meta_win[1, j]
        lane = meta_win[2, j]

        @pl.when(b != cur_b)
        def _():
            @pl.when(cur_b >= 0)
            def _():
                flush(cur_b)

            fetch(b)

        shape = (BLOCK_GROUPS, GROUP_LANES)
        lo = jnp.full(shape, 0, jnp.int32) + meta_win[3, j]
        hi = jnp.full(shape, 0, jnp.int32) + meta_win[4, j]
        plo = jnp.full(shape, 0, jnp.int32) + meta_win[5, j]
        phi = jnp.full(shape, 0, jnp.int32) + meta_win[6, j]
        here = rows == r
        sel_lo = here & (lanes == 2 * lane)
        sel_hi = here & (lanes == 2 * lane + 1)
        fp_line[:, :] = jnp.where(
            sel_lo, lo.astype(jnp.uint32),
            jnp.where(sel_hi, hi.astype(jnp.uint32), fp_line[:, :]),
        )
        pl_line[:, :] = jnp.where(
            sel_lo, plo.astype(jnp.uint32),
            jnp.where(sel_hi, phi.astype(jnp.uint32), pl_line[:, :]),
        )
        return b

    def window(w, cur_b):
        cp = pltpu.make_async_copy(
            meta_hbm.at[:, pl.ds(w * jnp.int32(META_WINDOW), META_WINDOW)],
            meta_win,
            s4,
        )
        cp.start()
        cp.wait()
        count = jnp.minimum(n - w * jnp.int32(META_WINDOW),
                            jnp.int32(META_WINDOW))
        return jax.lax.fori_loop(0, count, body, cur_b)

    nwin = (n + jnp.int32(META_WINDOW - 1)) // jnp.int32(META_WINDOW)
    last_b = jax.lax.fori_loop(0, nwin, window, jnp.int32(-1))

    @pl.when(last_b >= 0)
    def _():
        flush(last_b)


def pallas_scatter_insert(
    table_fp,  # u64 [nslots]
    table_payload,  # u64 [nslots]
    tgt,  # i32 [M] target slot per candidate (nslots = invalid/pad)
    cfp,  # u64 [M] fingerprints, novel-compacted (generation order)
    cpl,  # u64 [M]
    n_new,  # i32 scalar: number of valid candidates (prefix of the arrays)
):
    """Write ``cfp/cpl`` to ``tgt`` slots as one Pallas kernel invocation.
    Equivalent to (and validated against) the fp/payload windowed-scatter
    path in :func:`ops.buckets.bucket_insert`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nslots = table_fp.shape[0]
    # pad tiny tables up to one whole DMA block (larger-than-one-block
    # tables are already powers of two, hence multiples); padding copies,
    # but only on toy sizes — engine-scale tables alias in place
    spad = (-nslots) % BLOCK_SLOTS
    if spad:
        table_fp = jnp.concatenate(
            [table_fp, jnp.zeros((spad,), jnp.uint64)]
        )
        table_payload = jnp.concatenate(
            [table_payload, jnp.zeros((spad,), jnp.uint64)]
        )
    ngroups = table_fp.shape[0] // GROUP_SLOTS
    m = tgt.shape[0]

    # -- vector-side prep (cheap XLA) --------------------------------------
    valid = tgt < nslots
    slot = jnp.minimum(tgt, nslots - 1)
    g = slot // GROUP_SLOTS
    block = g // BLOCK_GROUPS
    row = g - block * BLOCK_GROUPS
    lane = slot - g * GROUP_SLOTS
    f32 = jax.lax.bitcast_convert_type(cfp, jnp.uint32).astype(jnp.int32)
    p32 = jax.lax.bitcast_convert_type(cpl, jnp.uint32).astype(jnp.int32)
    zero = jnp.zeros((m,), jnp.int32)
    # transposed layout [META_ROWS, M]: the kernel DMA-streams fixed-width
    # column windows, and a full-height slice keeps every window tile-aligned
    meta = jnp.stack(
        [
            jnp.where(valid, block, -1),
            row,
            lane,
            f32[:, 0],
            f32[:, 1],
            p32[:, 0],
            p32[:, 1],
            zero,
        ],
        axis=0,
    ).astype(jnp.int32)
    mpad = (-m) % META_WINDOW
    if mpad:
        pad = jnp.full((META_ROWS, mpad), -1, jnp.int32)
        meta = jnp.concatenate([meta, pad], axis=1)

    tfp32 = jax.lax.bitcast_convert_type(table_fp, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )
    tpl32 = jax.lax.bitcast_convert_type(table_payload, jnp.uint32).reshape(
        ngroups, GROUP_LANES
    )

    interpret = jax.default_backend() != "tpu"
    out_fp, out_pl = pl.pallas_call(
        _insert_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(tfp32.shape, jnp.uint32),
            jax.ShapeDtypeStruct(tpl32.shape, jnp.uint32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.SMEM((META_ROWS, META_WINDOW), jnp.int32),
            pltpu.VMEM((BLOCK_GROUPS, GROUP_LANES), jnp.uint32),
            pltpu.VMEM((BLOCK_GROUPS, GROUP_LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((5,)),
        ],
        input_output_aliases={2: 0, 3: 1},
        interpret=interpret,
    )(
        n_new.reshape(1).astype(jnp.int32),
        meta,
        tfp32,
        tpl32,
    )
    padded = nslots + spad
    table_fp = jax.lax.bitcast_convert_type(
        out_fp.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    table_payload = jax.lax.bitcast_convert_type(
        out_pl.reshape(padded, 2), jnp.uint64
    ).reshape(padded)[:nslots]
    return table_fp, table_payload
