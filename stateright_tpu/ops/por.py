"""Device-side ample-set selection for partial-order reduction.

Given the compile-time :class:`~stateright_tpu.analysis.independence.PorPlan`
(conflict matrix ``D``, per-action visibility, and the guard-conjunct
enabler tensor ``EN``), :func:`ample_mask` computes a per-state **stubborn
set** closure entirely on device and masks the enabled-action matrix down
to its ample subset:

 1. per state, build the pull relation ``P``: an *enabled* action pulls
    every action it conflicts with (``D`` row — the updates must commute
    and neither may enable/disable the other); a *disabled* action pulls
    the writers of its first FALSE guard conjunct (``EN`` — a necessary
    enabling set: the action cannot become enabled until one of them
    fires).  Conjunct truth comes from the footprint pass's conjunct
    kernel (``analysis/footprint.conjunct_eval_fn``), a few bit-ops XLA
    dead-code-eliminates out of the re-traced step kernel;
 2. close ``P`` transitively by boolean matrix squaring (``log2(A)``
    batched matmuls — MXU-shaped work);
 3. every enabled seed yields a candidate ample set ``T(seed) ∩ enabled``;
    pick the smallest candidate containing no property-VISIBLE action
    (the C2 invisibility condition); no valid candidate, or nothing
    smaller than the enabled set, means full expansion for that state.

The cycle proviso (fully expand a state whose ample successors are all
duplicates) lives in the engines — it needs the insert's novelty verdict;
:func:`candidate_novelty` converts the insert's compacted ``sel``/``n_new``
into the per-candidate novelty mask the proviso consumes.
"""

from __future__ import annotations

import numpy as np


def plan_constants(plan):
    """The plan's device constants: ``(D, EN, visible, leaf_idx)`` with
    ``EN`` padded to at least one conjunct slot."""
    d = np.asarray(plan.conflict, bool)
    a = d.shape[0]
    en = plan.enablers
    if en is None:
        en = np.ones((a, 1, a), bool)
    return d, np.asarray(en, bool), np.asarray(plan.visible, bool), (
        list(plan.leaf_idx) if plan.leaf_idx is not None else [None] * a
    )


def conjunct_truth(enabled, rows, plan, kernel):
    """``bool[B, A, K]`` conjunct-truth tensor, or None when the conjunct
    kernel is unavailable / its retrace drifted from the plan (the caller
    must then use the union-of-all-enablers pull for disabled actions —
    pairing a single whole-guard truth with a multi-conjunct enabler
    tensor would pull only conjunct 0's writers, which is NOT a
    necessary enabling set).

    Per action: kernel leaves where the action has an extracted and-tree
    (a ``(leaf, lane)`` reference picks lane ``lane`` of a ``[B, cap]``
    guard-block leaf — the per-channel kernel's one-array-per-channel
    idiom — or the whole ``[B]`` leaf when lane is None), the enabled
    bit itself for the whole-guard fallback, True padding past an
    action's conjunct count (padded slots pair with all-False enabler
    rows and are never selected)."""
    import jax.numpy as jnp

    _, en, _, leaf_idx = plan_constants(plan)
    a, k = en.shape[0], en.shape[1]
    leaves = kernel(rows) if kernel is not None else None  # [arrays] | None
    if leaves is None and any(idx is not None for idx in leaf_idx):
        return None  # drift: truths for multi-conjunct actions unknown
    ones = jnp.ones_like(enabled[:, 0])
    cols = []
    for i in range(a):
        idx = leaf_idx[i] if leaves is not None else None
        col = (
            [
                leaves[j] if lane is None else leaves[j][:, lane]
                for (j, lane) in idx
            ]
            if idx is not None else [enabled[:, i]]
        )
        col = col + [ones] * (k - len(col))
        cols.append(jnp.stack(col[:k], axis=-1))
    return jnp.stack(cols, axis=1)  # [B, A, K]


def ample_mask(enabled, rows, plan, kernel):
    """Ample subset of ``enabled`` (``bool[B, A]``) under ``plan``.

    Full expansion falls out naturally wherever no valid reduction
    exists: every seed's closure visible/covering, or the smallest
    candidate no smaller than the enabled set.
    """
    import jax.numpy as jnp

    d_np, en_np, vis_np, _ = plan_constants(plan)
    a = d_np.shape[0]
    d = jnp.asarray(d_np)
    en = jnp.asarray(en_np)
    vis = jnp.asarray(vis_np)

    ct = conjunct_truth(enabled, rows, plan, kernel)  # [B, A, K] | None
    if ct is None:
        # conjunct truths unavailable (kernel drift): a disabled action
        # pulls the UNION of every conjunct's writers — a sound
        # necessary-enabling superset, just less precise
        pull_dis = jnp.broadcast_to(
            jnp.any(en, axis=1)[None],
            (enabled.shape[0], a, a),
        )
    else:
        # first-false one-hot per action (all-true rows select nothing;
        # the disabled fallback below unions every conjunct's enablers)
        prev_true = jnp.cumprod(ct.astype(jnp.int32), axis=-1)
        prev_true = jnp.concatenate(
            [jnp.ones_like(prev_true[..., :1]), prev_true[..., :-1]],
            axis=-1,
        )
        first_false = (~ct) & (prev_true > 0)  # [B, A, K]
        pull_dis = jnp.einsum(
            "bak,akj->baj",
            first_false.astype(jnp.int32), en.astype(jnp.int32),
        ) > 0
        no_false = ~jnp.any(~ct, axis=-1)
        pull_dis = jnp.where(
            no_false[:, :, None], jnp.any(en, axis=1)[None], pull_dis
        )
    pull = jnp.where(enabled[:, :, None], d[None], pull_dis)  # [B, A, A]

    reach = pull | jnp.eye(a, dtype=bool)[None]
    for _ in range(max(int(a).bit_length(), 1)):
        reach = reach | (
            jnp.einsum(
                "bik,bkj->bij",
                reach.astype(jnp.int32), reach.astype(jnp.int32),
            ) > 0
        )

    cand = reach & enabled[:, None, :]  # [B, seed, A]
    size = jnp.sum(cand, axis=-1)
    has_visible = jnp.any(cand & vis[None, None, :], axis=-1)
    n_enabled = jnp.sum(enabled, axis=-1)
    big = jnp.int32(a + 1)
    score = jnp.where(
        enabled & ~has_visible, size.astype(jnp.int32), big
    )
    best = jnp.argmin(score, axis=-1)
    best_score = jnp.min(score, axis=-1)
    amp = jnp.take_along_axis(cand, best[:, None, None], axis=1)[:, 0]
    full = (best_score >= big) | (
        best_score >= n_enabled.astype(jnp.int32)
    )
    return jnp.where(full[:, None], enabled, amp)


def candidate_novelty(m: int, sel, n_new):
    """Per-candidate novelty mask (``bool[m]``) from ``bucket_insert``'s
    compacted ``sel``/``n_new``: True exactly on the candidate lanes the
    insert claimed fresh table slots for.  Additive scatter on purpose:
    ``sel`` entries past ``n_new`` are ARBITRARY in-range indices that
    may collide with novel ones, and a ``set`` of their False would
    clobber a True nondeterministically — adding 0 cannot."""
    import jax.numpy as jnp

    fresh = (jnp.arange(sel.shape[0], dtype=jnp.int32) < n_new).astype(
        jnp.int32
    )
    return jnp.zeros((m,), jnp.int32).at[sel].add(fresh, mode="drop") > 0
