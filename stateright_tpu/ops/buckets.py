"""Bucketized device visited-set: one-shot insert, no probe loop.

The round-1 visited set (an open-addressing table with a ``lax.while_loop``
scatter-min claim protocol, since removed) probed per conflict; on real TPU
hardware each probe iteration
costs a full-size scatter (~6 ms per 61k-candidate scatter on v5e), and the
loop runs for the *longest* probe chain in the batch — measured ~600 ms per
batch, 50× the cost of everything else combined.  XLA scatters on TPU are
effectively index-serial, so the fix is architectural, not incremental:

 - The table is an array of **buckets** of ``SLOTS`` fingerprints each; a
   fingerprint's bucket is the HIGH bits of ``mix64(fp)`` (one extra
   splitmix64 round).  The round-5 table-size anomaly (VERDICT.md) traced to
   the previous derivation — the fingerprint's raw low bits — clustering:
   splitmix64's final odd multiply avalanches upward only (bit ``k`` of the
   product depends on input bits ``0..k``), so the low bits of structurally
   close rows collide ~6x past Poisson and buckets overflowed ``SLOTS`` at
   25% load.  The remix costs 2 multiplies + 3 shift-xors per candidate and
   the bucket reads from the multiply's high (fully avalanched) bits;
   the pinned 2PC-7 occupancy series is back at the Poisson expectation
   (``tests/test_telemetry.py``), and ``tests/test_buckets.py`` pins
   avalanche + chi-square on the derivation itself.  Membership is ONE wide
   gather (``[M, SLOTS]`` lines) + a vectorized lane compare — gathers are
   cheap on TPU (the measured cost is scatters).
 - Batch candidates are sorted ONCE by their remixed key (bucket bits are
   the key's MSBs; EMPTY lanes pin to the maximal key), which simultaneously
   (a) groups equal fingerprints adjacently for first-occurrence dedup,
   (b) groups same-bucket candidates adjacently so per-bucket insertion
   ranks are a cumulative-sum away, and (c) keeps valid candidates a sorted
   prefix.
 - Every novel candidate's slot is ``occupancy(bucket) + rank`` — slots fill
   densely and never free, so a bucket's occupancy is just the non-EMPTY
   count of its (already gathered) line: no separate counts array exists,
   and no occupancy update is ever written.  Ranks are computed vectorially
   and the fp/payload writes go through a *windowed chunked* scatter that
   touches only ~``n_new`` entries instead of all ``M`` candidates (scatter
   cost scales with indices, so writing only what's new is the big win).
 - A bucket overflowing its ``SLOTS`` raises an overflow flag; the caller
   grows the table and rehashes host-side.  At the engine's ≤25% load factor
   the Poisson tail P(bucket > 16 | λ=4) ≈ 1e-7 makes that a rare event.

Reference analogue: the lock-striped ``DashMap`` visited set
(``src/checker/bfs.rs:26``); payload = parent fingerprint for trace
reconstruction, as there.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .hashing import EMPTY, mix64, mix64_np

SLOTS = 16  # fingerprints per bucket (one 128-byte line of u64s)


def bucket_key(fps: jnp.ndarray) -> jnp.ndarray:
    """Sort/derivation key: ``mix64(fp)`` with EMPTY lanes pinned to the
    maximal key.  A bucket is the key's high ``bucket_bits`` bits, so
    sorting by the key groups candidates by bucket with equal fingerprints
    adjacent AND keeps valid candidates a sorted prefix (EMPTY sorts last).
    The one valid fp whose mix64 equals EMPTY remaps to ``EMPTY - 1`` —
    same bucket (high bits agree), prefix invariant preserved; colliding
    with it is the same accepted 2^-64 risk class as the EMPTY sentinel
    itself (``ops/hashing.py``)."""
    k = mix64(fps)
    k = jnp.where(k == EMPTY, EMPTY - jnp.uint64(1), k)
    return jnp.where(fps == EMPTY, EMPTY, k)


def window_unique(fps: jnp.ndarray) -> jnp.ndarray:
    """Intra-window pre-dedup: mask duplicate fingerprints to EMPTY, keeping
    the FIRST occurrence (lowest lane index) of each.

    ``bucket_insert`` already dedups within its window (the first-occurrence
    mask over the sorted candidates), so this is purely a *traffic* reducer:
    engine candidate windows are mostly duplicates of each other (BLEST-style
    frontier duplication — siblings regenerate the same successors), and
    every duplicate lane left valid pays full price through the compaction
    budget, the membership gathers, and the rank pipeline.  EMPTYing them
    here shrinks the insert loop's EFFECTIVE window to the unique count.

    Exactness contract (pinned by tests): because the kept lane is the first
    occurrence by original index — the same lane ``bucket_insert``'s stable
    sort would have picked as the survivor, in both table order and
    generation order — the inserted (fp, payload) set, ``sel`` prefix, and
    ``n_new`` are bit-identical with or without the filter.  Only
    ``cand_overflow`` pressure changes (it can only drop).  EMPTY lanes pass
    through unchanged.  One extra sort + bool scatter per window; on TPU the
    sort is cheap next to the table gathers it avoids.
    """
    m = fps.shape[0]
    order = jnp.argsort(fps)  # stable: ties keep original index order
    sfp = fps[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sfp[1:] != sfp[:-1]])
    # (fps != fps) is an all-False array DERIVED from the input, so the
    # mask stays mesh-varying inside shard_map (a zeros() literal would be
    # replicated-typed; cf. the membership-loop carries in bucket_insert)
    keep = (fps != fps).at[order].set(first)
    return jnp.where(keep, fps, EMPTY)


def lane_compact(mask: jnp.ndarray, width: int):
    """Order-preserving lane compaction: ``(idx, live, count)`` such
    that ``x[idx]`` gathers the first ``width`` True lanes of ``mask``
    to the front (``live`` flags which output lanes are real, ``count``
    the total True lanes).  The cumsum + vectorized-searchsorted idiom
    ``bucket_insert``'s candidate-budget compaction uses — kept INLINE
    there (byte-identical jaxprs keep the persistent compile cache warm
    across releases); new call sites (the spill tier's pending-deferral
    append) use this helper instead of a third copy."""
    m = mask.shape[0]
    csum = jnp.cumsum(mask.astype(jnp.int32))
    count = csum[m - 1]
    idx = jnp.searchsorted(
        csum, jnp.arange(1, width + 1, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)
    idx = jnp.minimum(idx, jnp.int32(m - 1))
    live = jnp.arange(width, dtype=jnp.int32) < count
    return idx, live, count


def bucket_of(fps, nbuckets: int) -> np.ndarray:
    """Host-side bucket derivation (numpy): the bucket ``bucket_insert``
    and ``host_bucket_rehash`` place ``fps`` in for an ``nbuckets``-bucket
    table.  Shared by the rehash, the tests' collision construction, and
    the chi-square diagnostics."""
    assert nbuckets & (nbuckets - 1) == 0
    bits = int(nbuckets).bit_length() - 1
    k = mix64_np(fps)
    k = np.where(k == np.uint64(EMPTY), np.uint64(EMPTY) - np.uint64(1), k)
    return (k >> np.uint64(64 - bits)).astype(np.int64)


def bucket_insert(
    table_fp: jnp.ndarray,  # uint64[nbuckets * SLOTS]; EMPTY = free
    table_payload: jnp.ndarray,  # uint64[nbuckets * SLOTS]
    fps: jnp.ndarray,  # uint64[M] candidates (EMPTY = invalid lane)
    payloads: jnp.ndarray,  # uint64[M]
    window: int,  # scatter chunk size (≈ expected novel per batch)
    use_pallas: bool = False,  # write via the Pallas DMA kernel instead of
    #                            windowed XLA scatters (ops/pallas_insert.py)
    generation_order: bool = False,  # compact novel rows in generation order
    #                            (needed for symmetry runs; see below)
    compact: int = None,  # optional valid-candidate budget CB: compact valid
    #                       lanes first and run the pipeline at width CB
    probe_dot: bool = False,  # BLEST one-hot membership probe (ops/mxu.py):
    #                           the membership/occupancy reductions over the
    #                           gathered bucket lines become ONE blocked
    #                           bitmapped dot_general — bit-identical
    #                           (present, base) per window, pinned by test.
    #                           Off adds zero ops (the prededup contract).
):
    """Insert all valid candidates; returns ``(table_fp, table_payload,
    sel, n_new, overflow, cand_overflow)``.

    ``sel[:n_new]`` holds the ORIGINAL indices (into ``fps``) of the
    inserted candidates — table order for plain runs, generation order
    (original batch position) with ``generation_order=True``; entries past
    ``n_new`` are arbitrary in-range indices (callers overwrite or mask
    whatever they gather with them).  On ``overflow`` (a bucket clustered
    past SLOTS) or ``cand_overflow`` (more valid candidates than the
    ``compact`` budget) NOTHING was written, ``n_new`` is 0, and the
    table returns unchanged — the caller grows the table / its
    candidate budget and replays the batch, so no work is lost.

    ``compact=CB`` first compacts the valid lanes into a CB-wide buffer
    (order-preserving: cumsum + vectorized ``searchsorted`` + gathers — no
    scatters) and runs the whole sort/membership/rank/write pipeline at
    width CB.  Engine batches are >90% EMPTY padding (static action arity
    vs ~2-9 enabled actions per state), and on TPU the step's LATENCY
    scales with array width — u64 sorts, random-access table gathers, and
    index arithmetic all pay for the padding lanes — so running at the
    real candidate count is a multi-x step-time win on hardware.
    """
    m_orig = fps.shape[0]
    cand_overflow = jnp.bool_(False)
    cidx = None
    if compact is not None and compact < m_orig:
        valid_lanes = fps != EMPTY
        vsum = jnp.cumsum(valid_lanes.astype(jnp.int32))
        n_valid_orig = vsum[m_orig - 1]
        cand_overflow = n_valid_orig > jnp.int32(compact)
        # index of the j-th valid lane = first position where the running
        # valid count reaches j+1 (monotone, so a binary search per lane)
        cidx = jnp.searchsorted(
            vsum, jnp.arange(1, compact + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32)
        cidx = jnp.minimum(cidx, jnp.int32(m_orig - 1))
        live = jnp.arange(compact, dtype=jnp.int32) < n_valid_orig
        fps = jnp.where(live, fps[cidx], EMPTY)
        payloads = payloads[cidx]  # dead lanes masked by the EMPTY fp above
    m = fps.shape[0]
    window = min(window, m)
    nslots = table_fp.shape[0]
    nbuckets = nslots // SLOTS
    assert nbuckets & (nbuckets - 1) == 0, "bucket count must be a power of two"
    bucket_bits = int(nbuckets).bit_length() - 1

    key = bucket_key(fps)
    order = jnp.argsort(key)
    sfp = fps[order]
    skey = key[order]
    valid = sfp != EMPTY
    first = jnp.concatenate([jnp.ones((1,), bool), sfp[1:] != sfp[:-1]]) & valid
    bucket = (skey >> jnp.uint64(64 - bucket_bits)).astype(jnp.int32)
    n_valid = jnp.sum(valid).astype(jnp.int32)

    # membership + occupancy-base gathers, windowed over the VALID PREFIX
    # only (EMPTY rotates to all-ones and sorts last, so valid candidates
    # are a prefix of the sorted order).  Random-access HBM gathers are the
    # step's latency bottleneck on TPU — measured 11.4 ms for an M=61k-row
    # gather from an 8M-slot table where only ~4k lanes were valid; padding
    # lanes pay full price in a monolithic gather, and this read-only loop
    # (typically 2-3 windows) makes the cost track the real candidate
    # count.  Writes stay outside: the atomic nothing-written-on-overflow
    # contract the engines' growth protocols rely on is untouched.
    table_lines = table_fp.reshape(nbuckets, SLOTS)
    mpad_w = (-m) % window
    pbucket = bucket if mpad_w == 0 else jnp.concatenate(
        [bucket, jnp.zeros((mpad_w,), jnp.int32)]
    )
    psfp = sfp if mpad_w == 0 else jnp.concatenate(
        [sfp, jnp.full((mpad_w,), EMPTY, jnp.uint64)]
    )

    def mem_body(state):
        k, present, base = state
        off = k * window
        wbkt = jax.lax.dynamic_slice(pbucket, (off,), (window,))
        wfp = jax.lax.dynamic_slice(psfp, (off,), (window,))
        lines = table_lines[wbkt]
        if probe_dot:
            # BLEST one-hot probe (ops/mxu.py): one blocked bitmapped
            # matmul over the candidate x slot comparison tile replaces
            # the reduce_or/reduce_sum pair — same (present, base) bits,
            # but a genuine dot-class op for the MXU to chew on-chip
            from .mxu import blest_probe

            p, b = blest_probe(lines, wfp, EMPTY)
        else:
            p = jnp.any(lines == wfp[:, None], axis=-1)
            # occupancy comes free from the same gathered line: slots fill
            # densely from 0 and never free, so non-EMPTY count == next slot
            b = jnp.sum(lines != EMPTY, axis=-1).astype(jnp.int32)
        present = jax.lax.dynamic_update_slice(present, p, (off,))
        base = jax.lax.dynamic_update_slice(base, b, (off,))
        return k + 1, present, base

    # initial carries derive from the (possibly mesh-varying) inputs so the
    # loop types check inside shard_map: a literal zeros() is replicated-
    # typed while the body's output varies over the mesh axis
    _, present, base = jax.lax.while_loop(
        lambda s: s[0] * window < n_valid,
        mem_body,
        (
            jnp.int32(0),
            jnp.zeros((m + mpad_w,), bool) | (n_valid < 0),
            jnp.zeros((m + mpad_w,), jnp.int32) + n_valid * 0,
        ),
    )
    present, base = present[:m], base[:m]
    novel = first & ~present

    # per-bucket insertion rank among this batch's novel candidates
    idx = jnp.arange(m, dtype=jnp.int32)
    bstart = jnp.concatenate([jnp.ones((1,), bool), bucket[1:] != bucket[:-1]])
    seg_start = jax.lax.cummax(jnp.where(bstart, idx, 0))
    csum = jnp.cumsum(novel.astype(jnp.int32))
    rank = jnp.where(novel, csum - 1 - (csum - novel)[seg_start], 0)
    # (csum - novel)[seg_start] = novel-count before the bucket's first row

    slot = base + rank
    overflow = jnp.any(novel & (slot >= SLOTS))
    blocked = overflow | cand_overflow
    # n_new = 0 on any overflow: the write loops below key on it, so the
    # nothing-written atomicity holds for the candidate budget too
    n_new = jnp.where(blocked, 0, jnp.sum(novel)).astype(jnp.int32)

    # Compact novel candidates to the front.  Plain runs keep sorted-fp
    # order (bucket-contiguous — the Pallas kernel then touches each line
    # group once); the visited SET is order-independent there.  Symmetry
    # runs compact in GENERATION order (original batch position): the dedup
    # key is the canonical fp of a not-necessarily-class-invariant
    # representative, so enqueue order decides which class member gets
    # explored — generation order makes the reduced search reproducible by
    # a host FIFO oracle (tests/test_tensor_models.py).  Windowed chunked
    # scatters write only ~n_new entries either way.
    if generation_order:
        keys = jnp.where(novel, order.astype(jnp.int32), jnp.int32(m))
    else:
        keys = jnp.where(novel, idx, jnp.int32(m))
    perm = jnp.argsort(keys)
    tgt = jnp.where(novel, bucket * SLOTS + slot, nslots)[perm]
    cfp = sfp[perm]
    cpl = payloads[order][perm]

    # Pad to a whole number of windows: ``dynamic_slice`` clamps its start
    # index, which would silently misalign the final chunk against its
    # ``in_range`` mask (dropping the last novel entries).
    pad = (-m) % window

    def padded(x, fill):
        if pad == 0:
            return x
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    def chunk_cond(state):
        k, *_ = state
        return k * window < n_new  # n_new is 0 on overflow: nothing written

    if use_pallas:
        from .pallas_insert import pallas_scatter_insert

        table_fp, table_payload = pallas_scatter_insert(
            table_fp, table_payload, tgt, cfp, cpl, n_new
        )
    else:
        ptgt = padded(tgt, nslots)
        pcfp = padded(cfp, EMPTY)
        pcpl = padded(cpl, 0)

        def chunk_body(state):
            k, tfp, tpl = state
            off = k * window
            t = jax.lax.dynamic_slice(ptgt, (off,), (window,))
            f = jax.lax.dynamic_slice(pcfp, (off,), (window,))
            p = jax.lax.dynamic_slice(pcpl, (off,), (window,))
            in_range = jnp.arange(window, dtype=jnp.int32) + off < n_new
            t = jnp.where(in_range, t, nslots)
            tfp = tfp.at[t].set(f, mode="drop")
            tpl = tpl.at[t].set(p, mode="drop")
            return k + 1, tfp, tpl

        _, table_fp, table_payload = jax.lax.while_loop(
            chunk_cond, chunk_body, (jnp.int32(0), table_fp, table_payload)
        )

    sel = order[perm]
    if cidx is not None:
        sel = cidx[sel]  # map compacted positions back to original indices
    return table_fp, table_payload, sel, n_new, overflow, cand_overflow


def occupancy_stats(table_fp) -> dict:
    """Bucket-occupancy counters for a visited table (numpy, JSON-safe).

    The engines' growth protocol keys on load factor and single-bucket
    overflow, but the *distribution* was never observable — and VERDICT.md
    records an open anomaly where runs grow tables earlier than the ≤25%
    Poisson model predicts.  This is the first diagnostic handle on it:
    exposed via ``WavefrontChecker.occupancy_stats()``, the Explorer's
    ``/.status`` (``"table"``), and the audit report metrics.

    ``histogram[k]`` counts buckets holding exactly ``k`` fingerprints;
    a heavy tail vs Poisson(λ = occupied/nbuckets) means the bucket
    derivation (high bits of ``mix64(fp)``; see :func:`bucket_of`) is
    clustering — exactly the round-5 anomaly signature the old low-bit
    derivation produced.
    """
    t = np.asarray(table_fp).reshape(-1, SLOTS)
    per_bucket = (t != EMPTY).sum(axis=1)
    nbuckets = int(t.shape[0])
    occupied = int(per_bucket.sum())
    hist = np.bincount(per_bucket, minlength=SLOTS + 1)
    lam = occupied / nbuckets if nbuckets else 0.0
    # Poisson tail mass at/over SLOTS for the observed load — the model the
    # ≤25%-load growth policy assumes; compare with full_buckets/nbuckets
    tail = 0.0
    if lam > 0:
        import math

        p = math.exp(-lam)
        cum = p
        for k in range(1, SLOTS):
            p *= lam / k
            cum += p
        tail = max(0.0, 1.0 - cum)
    return {
        "nbuckets": nbuckets,
        "slots_per_bucket": SLOTS,
        "occupied": occupied,
        "load_factor": occupied / (nbuckets * SLOTS) if nbuckets else 0.0,
        "mean_bucket": lam,
        "max_bucket": int(per_bucket.max()) if nbuckets else 0,
        "full_buckets": int((per_bucket >= SLOTS).sum()),
        "poisson_full_expect": tail * nbuckets,
        "histogram": hist.tolist(),
    }


def host_bucket_rehash(
    table_fp: np.ndarray, table_payload: np.ndarray, new_nbuckets: int
):
    """Rebuild the bucketized table with ``new_nbuckets`` buckets (numpy).
    Returns ``(table_fp, table_payload)``: slots fill densely per bucket,
    so occupancy is implicit in the table itself."""
    assert new_nbuckets & (new_nbuckets - 1) == 0
    occ = table_fp != EMPTY
    f = table_fp[occ]
    p = table_payload[occ]
    out_fp = np.full(new_nbuckets * SLOTS, EMPTY, np.uint64)
    out_pl = np.zeros(new_nbuckets * SLOTS, np.uint64)
    bucket = bucket_of(f, new_nbuckets)
    order = np.argsort(bucket, kind="stable")
    bucket, f, p = bucket[order], f[order], p[order]
    start = np.searchsorted(bucket, bucket, side="left")
    rank = np.arange(f.size) - start
    if rank.size and rank.max() >= SLOTS:
        raise ValueError("bucket overflow during rehash; grow further")
    out_fp[bucket * SLOTS + rank] = f
    out_pl[bucket * SLOTS + rank] = p
    return out_fp, out_pl
