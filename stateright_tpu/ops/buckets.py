"""Bucketized device visited-set: one-shot insert, no probe loop.

The round-1 visited set (``ops/hashtable.py``) was open addressing with a
``lax.while_loop`` claim protocol; on real TPU hardware each probe iteration
costs a full-size scatter (~6 ms per 61k-candidate scatter on v5e), and the
loop runs for the *longest* probe chain in the batch — measured ~600 ms per
batch, 50× the cost of everything else combined.  XLA scatters on TPU are
effectively index-serial, so the fix is architectural, not incremental:

 - The table is an array of **buckets** of ``SLOTS`` fingerprints each; a
   fingerprint's bucket is its low bits.  Membership is ONE wide gather
   (``[M, SLOTS]`` lines) + a vectorized lane compare — gathers are cheap on
   TPU (the measured cost is scatters).
 - Batch candidates are sorted ONCE by their *bucket-rotated* fingerprint
   (low/bucket bits rotated into the MSBs), which simultaneously (a) groups
   equal fingerprints adjacently for first-occurrence dedup and (b) groups
   same-bucket candidates adjacently so per-bucket insertion ranks are a
   cumulative-sum away.
 - Every novel candidate's slot is ``count[bucket] + rank`` — computed
   vectorially, written with a *windowed chunked* scatter that touches only
   ~``n_new`` entries instead of all ``M`` candidates (scatter cost scales
   with indices, so writing only what's new is the big win).
 - A bucket overflowing its ``SLOTS`` raises an overflow flag; the caller
   grows the table and rehashes host-side.  At the engine's ≤25% load factor
   the Poisson tail P(bucket > 16 | λ=4) ≈ 1e-7 makes that a rare event.

Reference analogue: the lock-striped ``DashMap`` visited set
(``src/checker/bfs.rs:26``); payload = parent fingerprint for trace
reconstruction, as there.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .hashing import EMPTY

SLOTS = 16  # fingerprints per bucket (one 128-byte line of u64s)


def rotate_key(fps: jnp.ndarray, bucket_bits: int) -> jnp.ndarray:
    """Rotate the bucket (low) bits into the MSBs: sorting by the result
    groups candidates by bucket, with equal fingerprints adjacent."""
    b = jnp.uint64(bucket_bits)
    return (fps << (jnp.uint64(64) - b)) | (fps >> b)


def bucket_insert(
    table_fp: jnp.ndarray,  # uint64[nbuckets * SLOTS]; EMPTY = free
    table_payload: jnp.ndarray,  # uint64[nbuckets * SLOTS]
    counts: jnp.ndarray,  # uint32[nbuckets] occupancy
    fps: jnp.ndarray,  # uint64[M] candidates (EMPTY = invalid lane)
    payloads: jnp.ndarray,  # uint64[M]
    window: int,  # scatter chunk size (≈ expected novel per batch)
    use_pallas: bool = False,  # write via the Pallas DMA kernel instead of
    #                            windowed XLA scatters (ops/pallas_insert.py)
    generation_order: bool = False,  # compact novel rows in generation order
    #                            (needed for symmetry runs; see below)
):
    """Insert all valid candidates; returns
    ``(table_fp, table_payload, counts, order, perm, novel, n_new, overflow)``.

    ``order`` is the batch sort permutation and ``novel`` is aligned with it
    (``novel[i]`` refers to candidate ``fps[order[i]]``); ``perm`` compacts
    the novel entries to the front (``order[perm][:n_new]`` are the original
    indices of the inserted candidates, in table order) so callers can gather
    companion arrays without a second argsort.  On ``overflow`` nothing was
    written and the counts/table are returned unchanged — the caller grows +
    rehashes + retries, so no work is lost.
    """
    m = fps.shape[0]
    window = min(window, m)
    nslots = table_fp.shape[0]
    nbuckets = nslots // SLOTS
    assert nbuckets & (nbuckets - 1) == 0, "bucket count must be a power of two"
    bucket_bits = int(nbuckets).bit_length() - 1
    bmask = jnp.uint64(nbuckets - 1)

    order = jnp.argsort(rotate_key(fps, bucket_bits))
    sfp = fps[order]
    valid = sfp != EMPTY
    first = jnp.concatenate([jnp.ones((1,), bool), sfp[1:] != sfp[:-1]]) & valid
    bucket = (sfp & bmask).astype(jnp.int32)

    # membership: gather each candidate's whole bucket, compare lanes
    lines = table_fp.reshape(nbuckets, SLOTS)[bucket]  # [M, SLOTS]
    present = jnp.any(lines == sfp[:, None], axis=-1)
    novel = first & ~present

    # per-bucket insertion rank among this batch's novel candidates
    idx = jnp.arange(m, dtype=jnp.int32)
    bstart = jnp.concatenate([jnp.ones((1,), bool), bucket[1:] != bucket[:-1]])
    seg_start = jax.lax.cummax(jnp.where(bstart, idx, 0))
    csum = jnp.cumsum(novel.astype(jnp.int32))
    rank = jnp.where(novel, csum - 1 - (csum - novel)[seg_start], 0)
    # (csum - novel)[seg_start] = novel-count before the bucket's first row

    base = counts[bucket].astype(jnp.int32)
    slot = base + rank
    overflow = jnp.any(novel & (slot >= SLOTS))
    n_new = jnp.sum(novel).astype(jnp.int32)

    # Compact novel candidates to the front.  Plain runs keep sorted-fp
    # order (bucket-contiguous — the Pallas kernel then touches each line
    # group once); the visited SET is order-independent there.  Symmetry
    # runs compact in GENERATION order (original batch position): the dedup
    # key is the canonical fp of a not-necessarily-class-invariant
    # representative, so enqueue order decides which class member gets
    # explored — generation order makes the reduced search reproducible by
    # a host FIFO oracle (tests/test_tensor_models.py).  Windowed chunked
    # scatters write only ~n_new entries either way.
    if generation_order:
        keys = jnp.where(novel, order.astype(jnp.int32), jnp.int32(m))
    else:
        keys = jnp.where(novel, idx, jnp.int32(m))
    perm = jnp.argsort(keys)
    tgt = jnp.where(novel, bucket * SLOTS + slot, nslots)[perm]
    cfp = sfp[perm]
    cpl = payloads[order][perm]

    # Pad to a whole number of windows: ``dynamic_slice`` clamps its start
    # index, which would silently misalign the final chunk against its
    # ``in_range`` mask (dropping the last novel entries).
    pad = (-m) % window

    def padded(x, fill):
        if pad == 0:
            return x
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    def chunk_cond(state):
        k, *_ = state
        return (k * window < n_new) & ~overflow

    if use_pallas:
        from .pallas_insert import pallas_scatter_insert

        # on overflow nothing may be written (parity with the XLA path)
        n_eff = jnp.where(overflow, 0, n_new)
        table_fp, table_payload = pallas_scatter_insert(
            table_fp, table_payload, tgt, cfp, cpl, n_eff
        )
    else:
        ptgt = padded(tgt, nslots)
        pcfp = padded(cfp, EMPTY)
        pcpl = padded(cpl, 0)

        def chunk_body(state):
            k, tfp, tpl = state
            off = k * window
            t = jax.lax.dynamic_slice(ptgt, (off,), (window,))
            f = jax.lax.dynamic_slice(pcfp, (off,), (window,))
            p = jax.lax.dynamic_slice(pcpl, (off,), (window,))
            in_range = jnp.arange(window, dtype=jnp.int32) + off < n_new
            t = jnp.where(in_range, t, nslots)
            tfp = tfp.at[t].set(f, mode="drop")
            tpl = tpl.at[t].set(p, mode="drop")
            return k + 1, tfp, tpl

        _, table_fp, table_payload = jax.lax.while_loop(
            chunk_cond, chunk_body, (jnp.int32(0), table_fp, table_payload)
        )

    # occupancy update: scatter final count from each bucket's last novel row
    new_count = (slot + 1).astype(jnp.uint32)
    is_last_writer = novel & ~_has_later_novel(novel, bucket)
    cnt_tgt = padded(jnp.where(is_last_writer, bucket, nbuckets)[perm], nbuckets)
    cnt_val = padded(new_count[perm], 0)

    def cnt_body(state):
        k, counts = state
        off = k * window
        t = jax.lax.dynamic_slice(cnt_tgt, (off,), (window,))
        v = jax.lax.dynamic_slice(cnt_val, (off,), (window,))
        in_range = jnp.arange(window, dtype=jnp.int32) + off < n_new
        t = jnp.where(in_range, t, nbuckets)
        return k + 1, counts.at[t].set(v, mode="drop")

    _, counts = jax.lax.while_loop(
        chunk_cond, lambda s: cnt_body(s), (jnp.int32(0), counts)
    )
    return table_fp, table_payload, counts, order, perm, novel, n_new, overflow


def _has_later_novel(novel: jnp.ndarray, bucket: jnp.ndarray) -> jnp.ndarray:
    """True for rows with a later novel row in the same bucket (rows are
    bucket-sorted).  Reverse-cumulative trick: walking from the end, track
    the bucket of the most recent novel row seen."""
    sentinel = jnp.int32(-1)
    rev_b = jnp.where(novel, bucket, sentinel)[::-1]
    # last-seen novel bucket *before* each position in reverse order
    seen = jax.lax.associative_scan(
        lambda a, b: jnp.where(b == sentinel, a, b), rev_b
    )
    prev_seen = jnp.concatenate([jnp.full((1,), sentinel), seen[:-1]])[::-1]
    return prev_seen == bucket


def host_bucket_rehash(
    table_fp: np.ndarray, table_payload: np.ndarray, new_nbuckets: int
):
    """Rebuild the bucketized table with ``new_nbuckets`` buckets (numpy).
    Returns ``(table_fp, table_payload, counts)``."""
    assert new_nbuckets & (new_nbuckets - 1) == 0
    occ = table_fp != EMPTY
    f = table_fp[occ]
    p = table_payload[occ]
    out_fp = np.full(new_nbuckets * SLOTS, EMPTY, np.uint64)
    out_pl = np.zeros(new_nbuckets * SLOTS, np.uint64)
    counts = np.zeros(new_nbuckets, np.uint32)
    bucket = (f & np.uint64(new_nbuckets - 1)).astype(np.int64)
    order = np.argsort(bucket, kind="stable")
    bucket, f, p = bucket[order], f[order], p[order]
    start = np.searchsorted(bucket, bucket, side="left")
    rank = np.arange(f.size) - start
    if rank.size and rank.max() >= SLOTS:
        raise ValueError("bucket overflow during rehash; grow further")
    out_fp[bucket * SLOTS + rank] = f
    out_pl[bucket * SLOTS + rank] = p
    np.add.at(counts, bucket, 1)
    return out_fp, out_pl, counts
