"""Search-cartography reductions: cheap on-device counters for *how the
search is going* (docs/telemetry.md "Search cartography").

The flight recorder (telemetry/) answers *where time goes*; nothing
answered which actions dominate the frontier, how deep the wave is,
whether properties are being exercised, or whether shards are balanced.
These helpers fold those answers into the engines' step programs as small
integer reductions over masks the step already computes (the enabled-action
mask, the live mask, the property masks, the insert selection) — the
PAPERS.md coverage-guided-checking move applied to the wavefront.

Contract, mirroring telemetry/checked/prededup: with cartography OFF the
step jaxpr is bit-identical to an engine built before the feature existed
(pinned by test); ON, each step pays a couple of small column-sums whose
outputs ride the existing packed stats vector — no extra host round-trip.
The depth histogram costs NOTHING per step on the wavefront engine: it is
derived at sync time from the queue's depth buffer, which is a sorted
record of every insert (:func:`queue_depth_hist`).

Reconciliation invariants (pinned by ``tests/test_cartography.py``):

 - ``sum(depth_hist) == unique`` — every fresh insert is counted exactly
   once, at the depth it was inserted (init states at depth 0);
 - ``sum(action_hist) == states - n_init`` — every generated successor is
   counted under its action slot (``states`` counts init states too);
 - with no early exit, ``prop_evaluated[i] == unique`` for every property
   (each unique row is popped and evaluated exactly once).

Growth replays never double-count: accumulation is either inherently
replay-proof (the depth histogram reads the queue, and an overflowed
batch appended nothing) or explicitly guarded/rolled back alongside the
engine's other counters.
"""

from __future__ import annotations

import numpy as np

# Per-depth frontier bins.  BFS depths beyond the last bin clamp into it
# (the bin is then a ">= DEPTH_BINS-1" tail); 128 covers every bundled
# model's diameter with wide margin while keeping the per-step reduction
# and the stats-vector ride-along small.
DEPTH_BINS = 128

# Cartography snapshot schema version (the JSONL/report "v" field).
CARTOGRAPHY_V = 1


def cart_shapes(arity: int, n_props: int) -> tuple:
    """Carry-buffer shapes, in carry order: depth histogram, per-action
    successor counts, per-property evaluation / condition-hit tallies.
    Property arrays keep at least one lane so the carry stays non-empty
    (same convention as the engines' ``disc`` vector)."""
    p = max(n_props, 1)
    return ((DEPTH_BINS,), (max(arity, 1),), (p,), (p,))


def cart_zero_np(arity: int, n_props: int) -> list:
    """Fresh host-side zero counters for every :func:`cart_shapes` buffer
    (sharded-engine seed; the wavefront resume re-seed zeroes only the
    :func:`cart_carry_shapes` subset — its depth histogram is
    queue-derived and so survives a resume complete)."""
    return [np.zeros(s, np.int64) for s in cart_shapes(arity, n_props)]


def cart_carry_shapes(arity: int, n_props: int) -> tuple:
    """The wavefront engine's carry-tail shapes: :func:`cart_shapes`
    WITHOUT the depth histogram — the wavefront derives depths from its
    queue at sync time (:func:`queue_depth_hist`) instead of paying a
    per-step counter.  The sharded engine still carries all four (its
    frontier is one BFS level, so its depth update is a scalar-index
    add, not a scatter)."""
    return cart_shapes(arity, n_props)[1:]


def queue_depth_hist(qdepth, tail):
    """Per-depth fresh-insert histogram for the wavefront engine, derived
    from the queue: ``qdepth[:tail]`` holds the BFS depth of EVERY unique
    state ever inserted (the queue never evicts — pops only advance
    ``head``), in non-decreasing order (FIFO parents ⇒ monotone child
    depths).  So the histogram is ``DEPTH_BINS`` bounded binary searches
    over a sorted prefix — a few hundred gathers ONCE PER HOST SYNC,
    versus the per-step lane-wide scatter-add this replaces (XLA lowers
    scatter serially on CPU: measured ~1.6ms/step at a 16k candidate
    budget, the whole ≤5% overhead pin by itself).  Depths past the last
    bin clamp into it; garbage lanes past ``tail`` are never read
    (``hi`` starts at ``tail``)."""
    import jax.numpy as jnp

    n = qdepth.shape[0]
    vals = jnp.arange(1, DEPTH_BINS + 1, dtype=qdepth.dtype)
    lo = jnp.zeros((DEPTH_BINS,), jnp.int32)
    hi = jnp.full((DEPTH_BINS,), tail, jnp.int32)
    for _ in range(max(int(n).bit_length(), 1)):
        mid = (lo + hi) >> 1
        go = (mid < hi) & (qdepth[mid] < vals)
        lo = jnp.where(go, mid + 1, lo)
        hi = jnp.where(go, hi, mid)
    # lo[i] = #lanes with depth < i+1; diff -> per-bin counts, with the
    # ≥DEPTH_BINS tail folded into the last bin
    prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), lo[:-1]])
    hist = (lo - prev).astype(jnp.int64)
    return hist.at[-1].add((tail - lo[-1]).astype(jnp.int64))


def queue_depth_hist_np(qdepth, tail: int) -> np.ndarray:
    """Host mirror of :func:`queue_depth_hist` (same clamp-into-last-bin
    semantics) for syncs served from a host-side carry."""
    dep = np.minimum(
        np.asarray(qdepth[: int(tail)], dtype=np.int64), DEPTH_BINS - 1
    )
    return np.bincount(dep, minlength=DEPTH_BINS).astype(np.int64)


def action_hist_delta(valid):
    """Per-action-slot generated-successor counts for one batch: a column
    sum of the enabled-action mask the step already computed."""
    import jax.numpy as jnp

    return jnp.sum(valid, axis=0, dtype=jnp.int64)


def prop_tally_delta(live, masks, n_props: int):
    """(d_evals, d_hits) for one batch: rows evaluated (the live count,
    identical for every property) and rows whose condition mask held, per
    property.  Shapes follow :func:`cart_shapes`."""
    import jax.numpy as jnp

    p = max(n_props, 1)
    n_live = jnp.sum(live, dtype=jnp.int64)
    d_evals = jnp.where(jnp.arange(p) < n_props, n_live, jnp.int64(0))
    if n_props:
        d_hits = jnp.sum(live[:, None] & masks, axis=0, dtype=jnp.int64)
    else:
        d_hits = jnp.zeros((p,), jnp.int64)
    return d_evals, d_hits


def trim_hist(values) -> list:
    """Drop the all-zero tail of a histogram (deterministic, keeps at
    least one bin) — report/JSON ergonomics only."""
    vals = [int(v) for v in np.asarray(values).tolist()]
    last = 0
    for i, v in enumerate(vals):
        if v:
            last = i
    return vals[: last + 1]


def shard_imbalance(loads) -> dict:
    """Imbalance summary over per-shard table loads: max/mean plus their
    ratio (1.0 = perfectly balanced; fingerprint uniformity should keep
    this near 1 — routing skew shows up here first on multi-chip runs)."""
    arr = np.asarray(loads, dtype=np.float64).reshape(-1)
    if arr.size == 0:
        return {"max": 0, "mean": 0.0, "ratio": 1.0}
    mean = float(arr.mean())
    mx = float(arr.max())
    return {
        "max": int(mx),
        "mean": round(mean, 3),
        "ratio": round(mx / mean, 4) if mean > 0 else 1.0,
    }


def snapshot(
    *,
    depth_hist,
    action_hist,
    prop_evals,
    prop_hits,
    prop_names,
    states: int,
    unique: int,
    shard_load=None,
    route_matrix=None,
    por=None,
) -> dict:
    """Assemble the host-facing cartography block (JSON-safe) from raw
    counter arrays.  ``states``/``unique`` are the engine's cumulative
    totals — the duplicate/fresh split is derived, not separately counted
    (it is exactly ``states - unique`` by construction)."""
    n_props = len(prop_names)
    out = {
        "v": CARTOGRAPHY_V,
        "depth_hist": trim_hist(depth_hist),
        "action_hist": [int(v) for v in np.asarray(action_hist).tolist()],
        "props": [
            {
                "name": prop_names[i],
                "evaluated": int(np.asarray(prop_evals)[i]),
                "condition_hits": int(np.asarray(prop_hits)[i]),
            }
            for i in range(n_props)
        ],
        "fresh_inserts": int(unique),
        "duplicate_hits": max(int(states) - int(unique), 0),
    }
    if shard_load is not None:
        loads = [int(v) for v in np.asarray(shard_load).reshape(-1).tolist()]
        out["shard_load"] = loads
        out["shard_imbalance"] = shard_imbalance(loads)
    if route_matrix is not None:
        mat = np.asarray(route_matrix)
        out["route_matrix"] = [
            [int(v) for v in row] for row in mat.reshape(mat.shape[-2], -1)
        ] if mat.ndim >= 2 else [[int(v) for v in mat.reshape(-1)]]
        out["routed_candidates"] = int(mat.sum())
    if por is not None:
        # partial-order reduction: the reduced-vs-full split (ops/por.py)
        # — rows expanded with a reduced ample set, proviso-forced full
        # re-expansions, and candidates never generated at all
        out["por"] = {k: int(v) for k, v in dict(por).items()}
    return out
