"""Vectorized state fingerprinting on device.

Implements exactly :func:`stateright_tpu.fingerprint.hash_words` over
fixed-width ``uint64`` row encodings, so device fingerprints match host
fingerprints bit-for-bit.  That identity is what lets the TPU engine store
only ``fp -> parent fp`` while the host reconstructs full traces by
re-executing the object-form model (reference analogue: build-stable hashing,
``src/lib.rs:331-344``).

TPU note: the VPU has 32-bit lanes; XLA emulates u64 arithmetic as u32 pairs.
The splitmix64 round is 2 multiplies + 3 shift-xors per word — cheap relative
to the transition expansion, and entirely fusible.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..fingerprint import FINGERPRINT_SEED

# NumPy (not jnp) scalars: creating a jnp value at module import would
# eagerly initialize the default JAX backend, which hangs every pure-host
# code path (CPU checkers, fingerprinting) on hosts whose ambient platform
# is a real accelerator plugin.  NumPy scalars promote identically inside
# traced code.
_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_SEED = np.uint64(FINGERPRINT_SEED)

# Empty-slot sentinel for device hash tables.  Fingerprints are accepted to
# collide at the 64-bit level (as in the reference); colliding with the
# sentinel is the same class of risk.
EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)


def mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer, elementwise over a uint64 array."""
    h = h ^ (h >> jnp.uint64(30))
    h = h * _M1
    h = h ^ (h >> jnp.uint64(27))
    h = h * _M2
    h = h ^ (h >> jnp.uint64(31))
    return h


def mix64_np(h) -> np.ndarray:
    """Host-side :func:`mix64` (numpy, no device): must match the device
    remix bit-for-bit — ``host_bucket_rehash`` derives the same bucket for
    the same fingerprint that the device insert did."""
    h = np.asarray(h, np.uint64)
    with np.errstate(over="ignore"):  # u64 wrap is the point of the mix
        h = h ^ (h >> np.uint64(30))
        h = h * _M1
        h = h ^ (h >> np.uint64(27))
        h = h * _M2
        h = h ^ (h >> np.uint64(31))
    return h


def fold64(h: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Fold one word into the running digest (= host ``fingerprint.fold64``)."""
    return mix64((h ^ w) + _GAMMA)


# unmix64 constants: the splitmix64 multipliers' inverses mod 2^64
from ..fingerprint import _SM_M1_INV, _SM_M2_INV  # noqa: E402

_M1I = np.uint64(_SM_M1_INV)
_M2I = np.uint64(_SM_M2_INV)


def unmix64(h: jnp.ndarray) -> jnp.ndarray:
    """Elementwise inverse of :func:`mix64` (host mirror:
    ``fingerprint.unmix64``)."""
    h = h ^ (h >> jnp.uint64(31)) ^ (h >> jnp.uint64(62))
    h = h * _M2I
    h = h ^ (h >> jnp.uint64(27)) ^ (h >> jnp.uint64(54))
    h = h * _M1I
    h = h ^ (h >> jnp.uint64(30)) ^ (h >> jnp.uint64(60))
    return h


def ns_hash(fps: jnp.ndarray, ns_low: jnp.ndarray, ns_xor: jnp.ndarray,
            bits: int) -> jnp.ndarray:
    """Namespace fingerprints for hyper-batched instance sweeps
    (``stateright_tpu/sweep/``, docs/sweep.md): replace the LOW ``bits``
    bits of the table sort key ``mix64(fp)`` with the lane's instance
    tag (``ns_low``), XOR the high bits with the lane's table-seed
    scramble (``ns_xor``; all-zero for unseeded instances), and invert
    the mixer — order-preserving within an instance, disjoint across
    instances.  Reserved 0 / EMPTY remap like ``row_hash``.  Host
    mirror: :func:`stateright_tpu.fingerprint.ns_fingerprint` —
    bit-for-bit agreement is what lets per-instance traces reconstruct
    from the shared visited table."""
    low = np.uint64((1 << bits) - 1)
    key = mix64(fps)
    key = (key ^ ns_xor) & ~low | (ns_low & low)
    h = unmix64(key)
    return jnp.where((h == jnp.uint64(0)) | (h == EMPTY), _GAMMA, h)


def row_hash(rows: jnp.ndarray) -> jnp.ndarray:
    """Fingerprint each row: ``uint64[..., W] -> uint64[...]``.

    Identical to ``hash_words(row)`` on host: fold each of the W words, fold
    the length, remap 0 to a nonzero constant.
    """
    width = rows.shape[-1]
    h = jnp.full(rows.shape[:-1], _SEED, jnp.uint64)
    for i in range(width):
        h = fold64(h, rows[..., i])
    h = fold64(h, jnp.uint64(width))
    return jnp.where((h == jnp.uint64(0)) | (h == EMPTY), _GAMMA, h)
