"""Vectorized state fingerprinting on device.

Implements exactly :func:`stateright_tpu.fingerprint.hash_words` over
fixed-width ``uint64`` row encodings, so device fingerprints match host
fingerprints bit-for-bit.  That identity is what lets the TPU engine store
only ``fp -> parent fp`` while the host reconstructs full traces by
re-executing the object-form model (reference analogue: build-stable hashing,
``src/lib.rs:331-344``).

TPU note: the VPU has 32-bit lanes; XLA emulates u64 arithmetic as u32 pairs.
The splitmix64 round is 2 multiplies + 3 shift-xors per word — cheap relative
to the transition expansion, and entirely fusible.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..fingerprint import FINGERPRINT_SEED

# NumPy (not jnp) scalars: creating a jnp value at module import would
# eagerly initialize the default JAX backend, which hangs every pure-host
# code path (CPU checkers, fingerprinting) on hosts whose ambient platform
# is a real accelerator plugin.  NumPy scalars promote identically inside
# traced code.
_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_SEED = np.uint64(FINGERPRINT_SEED)

# Empty-slot sentinel for device hash tables.  Fingerprints are accepted to
# collide at the 64-bit level (as in the reference); colliding with the
# sentinel is the same class of risk.
EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)


def mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer, elementwise over a uint64 array."""
    h = h ^ (h >> jnp.uint64(30))
    h = h * _M1
    h = h ^ (h >> jnp.uint64(27))
    h = h * _M2
    h = h ^ (h >> jnp.uint64(31))
    return h


def mix64_np(h) -> np.ndarray:
    """Host-side :func:`mix64` (numpy, no device): must match the device
    remix bit-for-bit — ``host_bucket_rehash`` derives the same bucket for
    the same fingerprint that the device insert did."""
    h = np.asarray(h, np.uint64)
    with np.errstate(over="ignore"):  # u64 wrap is the point of the mix
        h = h ^ (h >> np.uint64(30))
        h = h * _M1
        h = h ^ (h >> np.uint64(27))
        h = h * _M2
        h = h ^ (h >> np.uint64(31))
    return h


def fold64(h: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Fold one word into the running digest (= host ``fingerprint.fold64``)."""
    return mix64((h ^ w) + _GAMMA)


def row_hash(rows: jnp.ndarray) -> jnp.ndarray:
    """Fingerprint each row: ``uint64[..., W] -> uint64[...]``.

    Identical to ``hash_words(row)`` on host: fold each of the W words, fold
    the length, remap 0 to a nonzero constant.
    """
    width = rows.shape[-1]
    h = jnp.full(rows.shape[:-1], _SEED, jnp.uint64)
    for i in range(width):
        h = fold64(h, rows[..., i])
    h = fold64(h, jnp.uint64(width))
    return jnp.where((h == jnp.uint64(0)) | (h == EMPTY), _GAMMA, h)
