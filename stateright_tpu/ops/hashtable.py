"""Device-resident visited-set: open-addressing hash table with parallel insert.

RETAINED LIBRARY OP — both engines moved to the bucketized one-shot insert
(``ops/buckets.py``) after on-chip measurement showed each probe iteration
of this design costs a full-size scatter; this module stays as a tested,
portable open-addressing primitive (probe-loop claim protocols are the
right shape on backends where scatters are cheap).

The reference's shared visited set is a lock-striped concurrent map
(``DashMap`` — reference ``src/checker/bfs.rs:26``).  The TPU equivalent is an
HBM-resident table of fingerprints (+ aligned parent-pointer payload) updated
by a data-parallel claim protocol built from XLA scatter-min:

 1. every live candidate gathers its current slot;
 2. slot holds my fp            -> duplicate, retire;
 3. slot empty                  -> claim it via ``scatter-min`` (EMPTY is the
    max u64, so the smallest claiming fp wins deterministically);
 4. re-gather: if the slot now holds my fp I won (novel), else linear-probe
    to the next slot and repeat.

Correctness relies on (a) candidates being pre-deduplicated (two equal fps
would both "win" the same claim), and (b) slots never being emptied, which
preserves the linear-probe search invariant.  The claim loop is a
``lax.while_loop``, so the whole insert stays on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .hashing import EMPTY


def hash_insert(
    table_fp: jnp.ndarray,  # uint64[cap], EMPTY = free; cap is a power of two
    table_payload: jnp.ndarray,  # uint64[cap], payload per slot (parent fp)
    fps: jnp.ndarray,  # uint64[M] candidate fingerprints, pre-deduplicated
    payloads: jnp.ndarray,  # uint64[M]
    valid: jnp.ndarray,  # bool[M]
    max_probes: int | None = None,
):
    """Insert candidates; returns ``(table_fp, table_payload, novel, overflow)``.

    ``novel[i]`` is True iff candidate ``i`` was valid and not already present.
    ``overflow`` is True if probing was exhausted (table effectively full) —
    the caller restarts with a larger capacity.
    """
    cap = table_fp.shape[0]
    assert cap & (cap - 1) == 0, "table capacity must be a power of two"
    mask = jnp.uint64(cap - 1)
    if max_probes is None:
        max_probes = cap

    pos0 = (fps & mask).astype(jnp.int32)
    # Derived from ``valid`` (not a fresh constant) so its sharding/vma type
    # matches the loop body's under shard_map.
    novel0 = valid & jnp.zeros_like(valid)

    def cond(carry):
        _, _, _, alive, _, probes = carry
        return jnp.logical_and(jnp.any(alive), probes < max_probes)

    def body(carry):
        tfp, tpl, pos, alive, novel, probes = carry
        cur = tfp[pos]
        is_dup = alive & (cur == fps)
        is_empty = alive & (cur == EMPTY)
        # Claim attempt: scatter-min of my fp into my slot (no-op unless the
        # slot is EMPTY from my point of view; different claimants of the same
        # slot resolve by min-fp).
        claim = jnp.where(is_empty, fps, EMPTY)
        tfp = tfp.at[pos].min(claim)
        won = is_empty & (tfp[pos] == fps)
        # Only winners write their payload; losers scatter out of range.
        tpl = tpl.at[jnp.where(won, pos, cap)].set(payloads, mode="drop")
        novel = novel | won
        alive = alive & ~is_dup & ~won
        pos = jnp.where(alive, (pos + 1) & (cap - 1), pos)
        return tfp, tpl, pos, alive, novel, probes + 1

    table_fp, table_payload, _, alive, novel, _ = jax.lax.while_loop(
        cond, body, (table_fp, table_payload, pos0, valid, novel0, jnp.int32(0))
    )
    return table_fp, table_payload, novel, jnp.any(alive)


def dedupe_sorted(fps: jnp.ndarray):
    """Sort candidate fps and mask first occurrences.

    Returns ``(order, first)`` where ``order`` is the stable sort permutation
    and ``first[i]`` marks the first occurrence of ``fps[order[i]]`` (False
    for EMPTY sentinels, which sort to the end).  Gathering payload arrays by
    ``order`` aligns them with ``first``.
    """
    order = jnp.argsort(fps, stable=True)
    sorted_fp = fps[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_fp[1:] != sorted_fp[:-1]]
    )
    first = first & (sorted_fp != EMPTY)
    return order, first
