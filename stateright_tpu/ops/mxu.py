"""MXU recast round: bytes-moved reduction knobs (docs/roofline.md).

PR 11's roofline ledger proved every pipeline stage memory-bound at
0.008-0.099 FLOPs/byte and ranked the hot spots (the JX4xx catalogue).
This module holds the execution half's shared pieces — the resolved
flag configuration and the BLEST one-hot membership probe — for the
three flag-gated step-program transforms:

 - **expand-scatter coalescing** (``coalesce``): the hand-twin and
   per-channel step kernels assemble each action piece's packed-field
   write-backs as ONE word-assembled block (``tensor_model.FieldWriter``)
   instead of one ``.at[..., word].set`` scatter per field — the
   paxos-3 ledger charged 37 such sites at 109 MB/step, each paying a
   full-array slice read on top of its scatter;
 - **slim queue traffic** (``slim_queue``): the engines append novel
   rows in ``window``-sized chunks gated on ``n_new`` instead of one
   candidate-stack-wide ``dynamic_update_slice`` (queue rows 1-3 of the
   ledger: 97 + 65 MB/step on paxos-3 for windows that are >90% dead
   lanes);
 - **BLEST one-hot probe** (``probe``): the bucket membership/occupancy
   reductions recast as one blocked bitmapped ``dot_general`` over the
   candidate x slot comparison tile (:func:`blest_probe`), giving the
   dedup-insert stage a genuine dot-class op (the JX400 #1 target on
   2pc-7).

Contract (the family's strongest form, pinned by tests): every knob off
leaves the step jaxpr bit-identical and the engine cache unkeyed; on,
unique/total counts, verdicts, and discovery traces are bit-identical —
the transforms move the same bytes' worth of INFORMATION through
cheaper shapes, never different information.

Armed via ``CheckerBuilder.mxu()`` / ``--mxu`` / ``STATERIGHT_TPU_MXU=1``
(all three components; keyword arguments select a subset).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

ENV_MXU = "STATERIGHT_TPU_MXU"


class MxuConfig(NamedTuple):
    """The resolved MXU-recast component set (all off = never built:
    engines carry ``None`` instead, keeping caches unkeyed)."""

    coalesce: bool = True
    slim_queue: bool = True
    probe: bool = True

    def key(self) -> tuple:
        """Engine-cache key suffix — appended ONLY when armed, so the
        off-path cache key is exactly the pre-MXU tuple (the spill
        discipline, ``wavefront._engine_key``)."""
        return ("mxu", self.coalesce, self.slim_queue, self.probe)


def resolve_mxu(opts: Optional[dict]) -> Optional[MxuConfig]:
    """Builder options -> the armed config, or None (off).

    ``opts`` is ``CheckerBuilder.mxu_opts`` (a dict of component booleans,
    or None = unset); unset falls back to the ``STATERIGHT_TPU_MXU=1``
    env knob, which arms all three components.  A config with every
    component off resolves to None — indistinguishable from never asking.
    """
    if opts is None:
        if os.environ.get(ENV_MXU, "") == "1":
            return MxuConfig()
        return None
    cfg = MxuConfig(
        coalesce=bool(opts.get("coalesce", True)),
        slim_queue=bool(opts.get("slim_queue", True)),
        probe=bool(opts.get("probe", True)),
    )
    if not (cfg.coalesce or cfg.slim_queue or cfg.probe):
        return None
    return cfg


def has_coalesced_step(tensor) -> bool:
    """Does ``tensor`` have a REAL coalesced expand kernel?  A twin may
    define ``step_rows_coalesced`` yet fall back internally for some
    configurations (the slot-multiset compiled twin) — such twins
    advertise the truth via a ``has_coalesced_step`` attribute, which
    wins over mere method presence."""
    flag = getattr(tensor, "has_coalesced_step", None)
    if flag is not None:
        return bool(flag() if callable(flag) else flag)
    return getattr(tensor, "step_rows_coalesced", None) is not None


def coalesced_step_fn(tensor, mxu: Optional[MxuConfig]):
    """The expand kernel the engines should trace: the twin's coalesced
    step when the knob is armed AND the twin provides a real one
    (:func:`has_coalesced_step`), else the plain ``step_rows``.  Twins
    without a coalesced form (slot-multiset compiled twins, exotic hand
    twins) silently keep the plain kernel — the flag then still buys the
    queue/probe recasts, and counts stay identical either way."""
    if mxu is not None and mxu.coalesce and has_coalesced_step(tensor):
        return tensor.step_rows_coalesced
    return tensor.step_rows


def effective_mxu(tensor, mxu: Optional[MxuConfig]) -> Optional[MxuConfig]:
    """The config as it actually lands on ``tensor``: ``coalesce``
    downgrades when the twin provides no coalesced kernel (the
    :func:`coalesced_step_fn` fallback), so landed-recast bookkeeping
    (``costmodel.mxu_candidates``) never silences a JX400 finding the
    flag did not actually move."""
    if mxu is None or not mxu.coalesce:
        return mxu
    if not has_coalesced_step(tensor):
        return mxu._replace(coalesce=False)
    return mxu


def blest_probe(lines, wfp, empty):
    """Membership + occupancy of one gathered bucket-line window via ONE
    blocked bitmapped matmul (the BLEST one-hot trick, PAPERS.md).

    ``lines`` is the gathered ``[W, SLOTS]`` uint64 bucket window,
    ``wfp`` the ``[W]`` candidate fingerprints.  The comparison tile
    ``[W, 2*SLOTS]`` — membership bits next to occupancy bits — is
    contracted against a static ``[2*SLOTS, 2]`` block-diagonal
    accumulator on the MXU: column 0 sums the membership lane, column 1
    the occupancy lane, so one ``dot_general`` replaces the
    ``reduce_or``/``reduce_sum`` pair.  Exactness: the tile holds only
    0.0/1.0 and row sums are <= 2*SLOTS, exactly representable in
    float32, so ``(present, base)`` are bit-identical to the reduction
    pair's — pinned against ``bucket_insert`` in tests/test_buckets.py.

    Returns ``(present bool[W], base int32[W])``.
    """
    import jax
    import jax.numpy as jnp

    slots = lines.shape[-1]
    eq = (lines == wfp[:, None]).astype(jnp.float32)
    occ = (lines != empty).astype(jnp.float32)
    tile = jnp.concatenate([eq, occ], axis=-1)  # [W, 2*SLOTS]
    acc = jnp.concatenate(
        [
            jnp.concatenate(
                [jnp.ones((slots, 1), jnp.float32),
                 jnp.zeros((slots, 1), jnp.float32)], axis=1
            ),
            jnp.concatenate(
                [jnp.zeros((slots, 1), jnp.float32),
                 jnp.ones((slots, 1), jnp.float32)], axis=1
            ),
        ],
        axis=0,
    )  # [2*SLOTS, 2] block-diagonal ones
    out = jax.lax.dot_general(
        tile, acc, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [W, 2]
    present = out[:, 0] > 0.5
    base = out[:, 1].astype(jnp.int32)
    return present, base
