"""Billion-state spill tier: host-backed visited overflow (docs/spill.md).

The ROADMAP's billion-state capacity item, second half (PR 7's HBM
ledger is the measurement half): the visited set becomes a TIERED store

 - **hot tier** — the existing HBM bucket table (``ops/buckets.py``),
   unchanged;
 - **host tier** — an append-only ``(fingerprint, parent)`` store in
   host RAM (:class:`SpillStore`) with a host-side open-addressing hash
   index (:class:`HostIndex`) for membership + offset lookup;
 - **disk tier** — an mmap'd append-only segment file behind the host
   tier, flushed to when the host tier passes its byte budget
   (``STATERIGHT_TPU_HOST_BYTES``); the index stays in RAM.

A device-side **Bloom filter** (``bloom.py``; bit-slices of
``mix64(fp)``, GPUexplore-style) rides the step program's carry and
answers "definitely not seen" on device: only Bloom-POSITIVE candidates
are deferred to a pending buffer and resolved against the host index at
the next host sync, so the common case never leaves the chip.

Engine wiring lives in ``parallel/wavefront.py`` (``CheckerBuilder.
spill()`` / ``--spill`` / ``STATERIGHT_TPU_SPILL=1``); this package is
pure host/device data-structure code with no engine knowledge.
"""

from .bloom import (
    BLOOM_K,
    bloom_est_false_pos,
    bloom_set_np,
    bloom_test,
    bloom_test_np,
)
from .store import (
    BYTES_PER_ENTRY,
    ENV_HOST_BYTES,
    HostIndex,
    SpillStore,
    default_host_budget,
)

# spill status / ring-record schema version
SPILL_V = 1

__all__ = [
    "BLOOM_K",
    "BYTES_PER_ENTRY",
    "ENV_HOST_BYTES",
    "HostIndex",
    "SPILL_V",
    "SpillStore",
    "bloom_est_false_pos",
    "bloom_set_np",
    "bloom_test",
    "bloom_test_np",
    "default_host_budget",
]
