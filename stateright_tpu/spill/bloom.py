"""Device-side Bloom pre-filter over the SPILLED fingerprint set.

The filter answers "definitely not seen off-device" inside the step
program: a candidate that misses the hot table AND misses the Bloom is
provably novel and inserts on device; only Bloom-positive candidates pay
the host round-trip (GPUexplore's shape — a cheap device-resident
pre-filter in front of off-device lookups, PAPERS.md).

Hash family: bit-slices of ``mix64(fp)`` (one extra splitmix64 round —
the same remix the bucket derivation uses, ``ops/buckets.bucket_key``)
and of ``mix64(mix64(fp))``: four 32-bit slices masked down to the
filter width.  The filter covers ONLY spilled fingerprints — hot-table
membership is checked exactly by the insert pipeline — so the filter's
load (and false-positive rate) tracks the spilled set, not the whole
visited set.  Bits are set HOST-side at eviction boundaries (the carry
is host-resident there anyway) and the device only ever TESTS, which
keeps the step program read-only over the filter.

No false negatives, ever: the host mirror (:func:`bloom_set_np`) and the
device test (:func:`bloom_test`) derive bit positions from the same
``mix64`` — pinned by test — so every spilled fingerprint tests
positive and exactness reduces to the host index's verdict.

False-positive math (docs/spill.md): with ``n`` spilled fingerprints,
``k`` = :data:`BLOOM_K` slices and ``B`` filter bits, the expected rate
is ``(1 - e^(-k*n/B))^k`` — at the default 8 Mbit filter and one million
spilled states that is ~2.4%; a saturated filter degrades THROUGHPUT
(everything defers to the host index), never correctness.
"""

from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from ..ops.hashing import mix64, mix64_np

# hash functions per fingerprint: four 32-bit slices of two mix rounds
BLOOM_K = 4

# filter width floor: below this the u32 word array would be smaller
# than one cache line and the whole exercise is noise
MIN_BLOOM_BITS = 1 << 10

# filter width ceiling: the device test gathers with int32 indices, so
# bit positions must fit 2^31 — a wider filter would silently wrap
# negative on device while the int64 host mirror stays correct,
# manufacturing FALSE NEGATIVES (the one thing the filter must never
# do).  2^31 bits = 256MB of HBM, far past any sane sizing; the engine
# clamps requested widths here.
MAX_BLOOM_BITS = 1 << 31


def bloom_words(bits: int) -> int:
    """u32 words backing a ``bits``-wide filter (``bits`` must be a
    multiple of 32 — enforced by the power-of-two sizing)."""
    assert bits % 32 == 0 and bits >= MIN_BLOOM_BITS
    return bits // 32


def bloom_est_false_pos(n_set: int, bits: int, k: int = BLOOM_K) -> float:
    """Expected false-positive rate with ``n_set`` elements inserted."""
    if bits <= 0 or n_set <= 0:
        return 0.0
    return float((1.0 - math.exp(-k * n_set / bits)) ** k)


def _slices_np(fps: np.ndarray, bits: int) -> np.ndarray:
    """``int64[k, n]`` bit positions for ``fps`` (host mirror; must match
    the device derivation bit-for-bit)."""
    fps = np.asarray(fps, np.uint64)
    mask = np.uint64(bits - 1)
    g1 = mix64_np(fps)
    g2 = mix64_np(g1)
    return np.stack([
        (g1 & mask).astype(np.int64),
        ((g1 >> np.uint64(32)) & mask).astype(np.int64),
        (g2 & mask).astype(np.int64),
        ((g2 >> np.uint64(32)) & mask).astype(np.int64),
    ])


def bloom_set_np(words: np.ndarray, fps) -> np.ndarray:
    """Set the bits for ``fps`` in the host mirror ``words`` (u32 array),
    in place; returns ``words``.  Called at eviction boundaries only."""
    fps = np.asarray(fps, np.uint64)
    if fps.size == 0:
        return words
    bits = int(words.size) * 32
    idx = _slices_np(fps, bits).reshape(-1)
    np.bitwise_or.at(
        words, idx >> 5, (np.uint32(1) << (idx & 31).astype(np.uint32))
    )
    return words


def bloom_test_np(words: np.ndarray, fps) -> np.ndarray:
    """Host-side membership test (all k bits set); used by tests to pin
    host/device agreement."""
    fps = np.asarray(fps, np.uint64)
    bits = int(words.size) * 32
    idx = _slices_np(fps, bits)
    hit = np.ones(fps.shape, bool)
    for row in idx:
        w = words[row >> 5]
        hit &= ((w >> (row & 31).astype(np.uint32)) & np.uint32(1)) != 0
    return hit


def bloom_test(words: jnp.ndarray, fps: jnp.ndarray,
               bits: int) -> jnp.ndarray:
    """Device-side membership test: ``bool[...]`` per fingerprint, True
    iff all :data:`BLOOM_K` slice bits are set.  Read-only over the
    filter — the step program never writes it."""
    mask = jnp.uint64(bits - 1)
    g1 = mix64(fps)
    g2 = mix64(g1)
    hit = None
    for h in (
        g1 & mask,
        (g1 >> jnp.uint64(32)) & mask,
        g2 & mask,
        (g2 >> jnp.uint64(32)) & mask,
    ):
        idx = h.astype(jnp.int32)
        w = words[idx >> 5]
        b = ((w >> (idx & 31).astype(jnp.uint32)) & jnp.uint32(1)) != 0
        hit = b if hit is None else (hit & b)
    return hit
