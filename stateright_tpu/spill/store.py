"""Host/disk tiers of the spilled visited set.

:class:`SpillStore` is an append-only ``(fingerprint, parent)`` store:
entries arrive in eviction batches (already unique — they come out of
the hot table), live first in host-RAM numpy segments, and flush to an
mmap'd disk segment file when the RAM tier passes its byte budget
(``STATERIGHT_TPU_HOST_BYTES``; no budget = never flush).  A
:class:`HostIndex` — open-addressing, linear-probing, ``mix64``-keyed,
fully vectorized numpy — maps every spilled fingerprint to its global
append offset, so membership (the per-sync pending resolution) is a few
gathers per probe round, never a Python loop over candidates.

The store is exact where the device Bloom filter is probabilistic: the
engine defers Bloom-positive candidates here, and ``contains`` is the
final word.  Parent payloads stay with the data segments (RAM or mmap)
— trace reconstruction merges them with the hot table's
(``TpuChecker._parents``).
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

import numpy as np

from ..ops.hashing import EMPTY, mix64_np

ENV_HOST_BYTES = "STATERIGHT_TPU_HOST_BYTES"

# one spilled entry: fingerprint + parent fingerprint, u64 each
BYTES_PER_ENTRY = 16


def default_host_budget() -> Optional[int]:
    """Host-tier byte budget: the ``STATERIGHT_TPU_HOST_BYTES``
    override, else half the machine's physical RAM (sysconf), else
    None.  Shared by the ``capacity --spill`` planner AND the runtime
    store's flush threshold, so the run flushes to disk where the plan
    said it would.  A malformed override warns loudly — a silently
    ignored budget would flush (or fill host RAM) orders of magnitude
    away from what the operator configured."""
    env = os.environ.get(ENV_HOST_BYTES, "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            import sys

            print(
                f"stateright-tpu: spill: ignoring malformed "
                f"{ENV_HOST_BYTES}={env!r} (want plain bytes, e.g. "
                "17179869184); using half of physical RAM",
                file=sys.stderr,
            )
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        if pages > 0 and page > 0:
            return int(pages * page) // 2
    except (ValueError, OSError, AttributeError):
        pass
    return None


class HostIndex:
    """Open-addressing hash index: ``uint64 fp -> uint64 value``.

    Linear probing over power-of-two numpy arrays, home slot from
    ``mix64(fp)`` (the avalanched remix the device bucket derivation
    uses), grown at 50% load.  Insert and lookup are batch-vectorized:
    each probe round is one gather + compares over the still-unresolved
    lanes, and at <=50% load the expected round count is ~2.  ``EMPTY``
    is the free-slot sentinel and therefore not an insertable key (the
    engines already exclude it — it is the invalid-lane sentinel)."""

    def __init__(self, capacity: int = 1 << 12):
        cap = 1
        while cap < max(capacity, 16):
            cap <<= 1
        self._keys = np.full(cap, EMPTY, np.uint64)
        self._vals = np.zeros(cap, np.uint64)
        self._count = 0

    def __len__(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        return int(self._keys.size)

    @property
    def nbytes(self) -> int:
        return int(self._keys.nbytes + self._vals.nbytes)

    def _home(self, fps: np.ndarray) -> np.ndarray:
        mask = np.uint64(self._keys.size - 1)
        return (mix64_np(fps) & mask).astype(np.int64)

    def _grow_to(self, capacity: int) -> None:
        occ = self._keys != EMPTY
        old_k, old_v = self._keys[occ], self._vals[occ]
        cap = self._keys.size
        while cap < capacity:
            cap <<= 1
        self._keys = np.full(cap, EMPTY, np.uint64)
        self._vals = np.zeros(cap, np.uint64)
        self._count = 0
        if old_k.size:
            self.insert(old_k, old_v)

    def insert(self, fps, vals) -> None:
        """Insert ``fps -> vals`` (first writer wins on duplicates, both
        intra-batch and vs existing entries)."""
        fps = np.asarray(fps, np.uint64).reshape(-1)
        vals = np.asarray(vals, np.uint64).reshape(-1)
        if fps.size == 0:
            return
        # intra-batch dedup (keep first occurrence): the probe loop's
        # claim protocol assumes distinct keys race for distinct slots
        ufps, first = np.unique(fps, return_index=True)
        if ufps.size != fps.size:
            first.sort()
            fps, vals = fps[first], vals[first]
        if (self._count + fps.size) * 2 > self._keys.size:
            self._grow_to((self._count + fps.size) * 4)
        h = self._home(fps)
        r = np.zeros(fps.size, np.int64)
        mask = np.int64(self._keys.size - 1)
        unresolved = np.ones(fps.size, bool)
        while unresolved.any():
            idx = (h + r) & mask
            cur = self._keys[idx]
            live = unresolved
            is_empty = live & (cur == EMPTY)
            is_match = live & (cur == fps)
            unresolved = unresolved & ~is_match  # already present: done
            if is_empty.any():
                ci = np.nonzero(is_empty)[0]
                cidx = idx[ci]
                order = np.argsort(cidx, kind="stable")
                ci, cidx = ci[order], cidx[order]
                keep = np.concatenate([[True], cidx[1:] != cidx[:-1]])
                win = ci[keep]
                self._keys[idx[win]] = fps[win]
                self._vals[idx[win]] = vals[win]
                self._count += win.size
                unresolved[win] = False
                # claim losers re-probe the same slot next round (it now
                # holds a different key, so they advance then)
            adv = unresolved & ~is_empty & ~is_match
            r[adv] += 1

    def contains(self, fps) -> np.ndarray:
        """``bool[n]`` membership per fingerprint."""
        return self.lookup(fps)[1]

    def lookup(self, fps) -> tuple:
        """``(vals, found)``: the stored value per fingerprint (0 where
        absent) and the membership mask."""
        fps = np.asarray(fps, np.uint64).reshape(-1)
        vals = np.zeros(fps.size, np.uint64)
        found = np.zeros(fps.size, bool)
        if fps.size == 0 or self._count == 0:
            return vals, found
        h = self._home(fps)
        r = np.zeros(fps.size, np.int64)
        mask = np.int64(self._keys.size - 1)
        unresolved = np.ones(fps.size, bool)
        # at <=50% load every probe chain ends at an EMPTY slot; the cap
        # is a belt against a corrupted index turning into a spin
        for _ in range(self._keys.size):
            if not unresolved.any():
                break
            idx = (h + r) & mask
            cur = self._keys[idx]
            hit = unresolved & (cur == fps)
            vals[hit] = self._vals[idx[hit]]
            found |= hit
            miss = unresolved & (cur == EMPTY)
            unresolved = unresolved & ~hit & ~miss
            r[unresolved] += 1
        return vals, found


class SpillStore:
    """Append-only tiered ``(fp, parent)`` store + RAM hash index.

    ``host_budget`` bounds the RAM tier's DATA bytes: exceeding it
    flushes every RAM segment into one new mmap'd disk segment under
    ``directory`` (created lazily; a temp dir by default).  The default
    budget is :func:`default_host_budget` — the same
    ``STATERIGHT_TPU_HOST_BYTES``-or-half-physical-RAM figure
    ``capacity --spill`` plans with, so the runtime actually flushes
    where the plan said the disk tier takes over.  The index
    (fp -> global offset) always stays in RAM — it is the membership
    oracle the per-sync pending resolution hits."""

    def __init__(
        self,
        directory: Optional[str] = None,
        host_budget: Optional[int] = None,
    ):
        if host_budget is None:
            host_budget = default_host_budget()
        self.host_budget = host_budget
        self._dir = directory
        self._own_dir = directory is None  # we created it: clean it up
        self._ram: list = []  # [(fps, parents)] newest last
        self._disk: list = []  # np.memmap[(n, 2) u64] segments
        self._disk_paths: list = []
        self._index = HostIndex()
        self._total = 0
        self._ram_bytes = 0
        self._disk_bytes = 0
        self._closed = False
        # disk-tier degradation (docs/robustness.md): a failed segment
        # flush (ENOSPC, dead disk) warns once and pins the tier in host
        # RAM — the run keeps its exactness guarantees, it just stops
        # paging to disk.  Surfaces as ``degraded`` in the spill block
        # and a ``spill_degraded`` health transition.
        self.degraded = False
        self.degraded_reason: Optional[str] = None

    # -- writing -------------------------------------------------------------

    def append(self, fps, parents) -> int:
        """Append one eviction batch; returns how many entries were NEW
        to the store (re-evictions of already-spilled fps are dropped —
        cannot happen from the engine, but the store defends itself)."""
        fps = np.asarray(fps, np.uint64).reshape(-1)
        parents = np.asarray(parents, np.uint64).reshape(-1)
        if fps.size == 0:
            return 0
        fresh = ~self._index.contains(fps)
        fps, parents = fps[fresh], parents[fresh]
        if fps.size == 0:
            return 0
        offs = np.arange(self._total, self._total + fps.size, dtype=np.uint64)
        self._index.insert(fps, offs)
        self._ram.append((fps.copy(), parents.copy()))
        self._total += int(fps.size)
        self._ram_bytes += int(fps.size) * BYTES_PER_ENTRY
        if self.host_budget is not None and self._ram_bytes > self.host_budget:
            self._flush_to_disk()
        return int(fps.size)

    def close(self, delete: Optional[bool] = None) -> None:
        """Release the disk tier's mmap handles and (``delete=True``, the
        default for self-created temp dirs) remove the segment files —
        a checking campaign must not accumulate ~16GB temp dirs and open
        fds per spilled run.  The store is unusable afterwards; callers
        snapshot via :meth:`to_arrays` first if the contents matter."""
        if self._closed:
            return
        self._closed = True
        if delete is None:
            delete = self._own_dir
        for mm in self._disk:
            try:
                mm._mmap.close()  # numpy keeps the handle otherwise
            except (AttributeError, OSError, ValueError):
                pass
        self._disk = []
        if delete:
            for path in self._disk_paths:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if self._own_dir and self._dir is not None:
                try:
                    os.rmdir(self._dir)
                except OSError:
                    pass
        self._disk_paths = []

    def _flush_to_disk(self) -> None:
        n = sum(f.size for f, _ in self._ram)
        if n == 0 or self.degraded:
            return
        try:
            from ..testing import faults

            faults.fire("spill_flush", entries=n)
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="stateright-tpu-spill-")
                # self-created temp dirs are reclaimed at process exit even
                # when no caller ever invokes close() — the segments are
                # process-local scratch (snapshots carry portable arrays)
                import atexit

                atexit.register(self.close)
            os.makedirs(self._dir, exist_ok=True)
            path = os.path.join(
                self._dir, f"spill-{len(self._disk):04d}.bin"
            )
            # atomic segment write (telemetry/_atomic.py — the package's
            # ONE crash-write discipline, streamed so the payload is
            # never doubled in RAM): a crash mid-flush leaves no
            # half-segment at the final path, and an ENOSPC lands HERE
            # (where it degrades) instead of as a SIGBUS on a later mmap
            # page-in of a sparse file
            from ..telemetry._atomic import atomic_write_stream

            atomic_write_stream(
                path,
                (
                    np.ascontiguousarray(
                        np.stack([f, p], axis=1)
                    ).tobytes()
                    for f, p in self._ram
                ),
            )
            mm = np.memmap(path, dtype=np.uint64, mode="r", shape=(n, 2))
        except OSError as e:
            # disk full / dead disk: warn ONCE, pin the tier in host RAM
            # and keep running — losing the disk tier costs capacity
            # headroom, never correctness (the index + RAM segments are
            # intact), and crashing the run here would lose everything
            self.degraded = True
            self.degraded_reason = f"{type(e).__name__}: {e}"
            import sys

            print(
                "stateright-tpu: spill: disk-segment flush failed "
                f"({self.degraded_reason}); the spill tier stays in "
                "host RAM (degraded — no further disk flushes this run)",
                file=sys.stderr,
            )
            return
        self._disk.append(mm)
        self._disk_paths.append(path)
        self._disk_bytes += n * BYTES_PER_ENTRY
        self._ram = []
        self._ram_bytes = 0

    # -- reading -------------------------------------------------------------

    def __len__(self) -> int:
        return self._total

    @property
    def host_bytes(self) -> int:
        """RAM-tier data bytes (the index is accounted separately)."""
        return self._ram_bytes

    @property
    def disk_bytes(self) -> int:
        return self._disk_bytes

    @property
    def index_bytes(self) -> int:
        return self._index.nbytes

    def contains(self, fps) -> np.ndarray:
        return self._index.contains(fps)

    def iter_segments(self):
        """Yield ``(fps, parents)`` per segment, disk tiers first (append
        order): snapshot export, bloom rebuild, and parent-map merge all
        walk this."""
        for mm in self._disk:
            yield np.asarray(mm[:, 0]), np.asarray(mm[:, 1])
        for f, p in self._ram:
            yield f, p

    def to_arrays(self) -> tuple:
        """``(fps, parents)`` concatenated over every tier — the snapshot
        manifest's portable form (disk segments are machine-local paths;
        snapshots must survive a move)."""
        fs, ps = [], []
        for f, p in self.iter_segments():
            fs.append(f)
            ps.append(p)
        if not fs:
            e = np.zeros(0, np.uint64)
            return e, e.copy()
        return np.concatenate(fs), np.concatenate(ps)

    @classmethod
    def from_arrays(
        cls, fps, parents, directory: Optional[str] = None,
        host_budget: Optional[int] = None,
    ) -> "SpillStore":
        store = cls(directory=directory, host_budget=host_budget)
        store.append(fps, parents)
        return store
