"""Stable 64-bit fingerprinting, identical on host (Python/NumPy) and device (JAX).

The reference derives state identity from a seeded 64-bit hash with fixed keys so
fingerprints are reproducible across runs and builds (reference:
``src/lib.rs:302-344``).  We need something stronger than that: the *same*
fingerprint function must be computable

 - as a scalar Python function over arbitrary structured states (object form),
 - as a vectorized NumPy/JAX function over fixed-width ``uint64`` row encodings
   (tensor form, evaluated on-device inside the wavefront BFS engine),

so that Explorer URLs, path reconstruction, and discovery bookkeeping agree
bit-for-bit regardless of which backend produced them.

The mixer is the splitmix64 finalizer (public-domain constants), folded over the
64-bit words of the state with a fixed seed.  Structured Python values are
canonically serialized to a word stream first (see :func:`stable_words`), with
order-insensitive folding for sets/maps like the reference's
``HashableHashSet``/``HashableHashMap`` (reference: ``src/util.rs:124-145``):
per-element hashes are sorted before being folded, so any iteration order
produces the same digest.
"""

from __future__ import annotations

import dataclasses
import struct
from enum import Enum
from typing import Any, Callable, Iterable

MASK64 = (1 << 64) - 1

# splitmix64 finalizer constants (public domain, Sebastiano Vigna).
_SM_GAMMA = 0x9E3779B97F4A7C15
_SM_M1 = 0xBF58476D1CE4E5B9
_SM_M2 = 0x94D049BB133111EB

# Fixed seed: fingerprints must be stable across processes/builds.
FINGERPRINT_SEED = 0x5374617465544655  # b"StateTFU"

# Type tags mixed into structural hashes so (1,) != [1] != {1}.
_TAG_NONE = 0x01
_TAG_BOOL = 0x02
_TAG_INT = 0x03
_TAG_FLOAT = 0x04
_TAG_STR = 0x05
_TAG_BYTES = 0x06
_TAG_TUPLE = 0x07
_TAG_LIST = 0x08
_TAG_SET = 0x09
_TAG_DICT = 0x0A
_TAG_OBJECT = 0x0B
_TAG_ENUM = 0x0C
_TAG_NEG = 0x0D
_TAG_BIGINT = 0x0E


def mix64(h: int) -> int:
    """splitmix64 finalizer: a strong 64-bit bijective mixer."""
    h &= MASK64
    h ^= h >> 30
    h = (h * _SM_M1) & MASK64
    h ^= h >> 27
    h = (h * _SM_M2) & MASK64
    h ^= h >> 31
    return h


def fold64(h: int, word: int) -> int:
    """Fold one 64-bit word into the running digest."""
    return mix64((h ^ (word & MASK64)) + _SM_GAMMA & MASK64)


def hash_words(words: Iterable[int], seed: int = FINGERPRINT_SEED) -> int:
    """Hash a stream of u64 words. This is THE fingerprint function: the device
    row-hash (ops/hashing.py) implements exactly this over uint64 rows."""
    h = seed & MASK64
    n = 0
    for w in words:
        h = fold64(h, w)
        n += 1
    h = fold64(h, n)  # length-extension guard
    if h == 0 or h == MASK64:
        # 0 is reserved as the "no parent / no discovery" marker and 2^64-1 as
        # the device hash-table empty-slot sentinel; remap both (same accepted
        # collision class as 64-bit fp collisions generally).
        h = _SM_GAMMA
    return h


# ---------------------------------------------------------------------------
# Structural (object-form) stable hashing
# ---------------------------------------------------------------------------

_custom_hashers: list[tuple[type, Callable[[Any], int]]] = []


def register_stable_hash(cls: type, fn: Callable[[Any], int]) -> None:
    """Register a custom stable-hash function for a user type."""
    _custom_hashers.append((cls, fn))


def stable_words(obj: Any, out: list[int]) -> None:
    """Append the canonical u64 word stream of ``obj`` to ``out``.

    Deterministic across processes (unlike builtin ``hash``, which is
    randomized for str/bytes).  Sets and dicts are folded order-insensitively
    by hashing each element independently and sorting the element digests.
    """
    if obj is None:
        out.append(_TAG_NONE)
    elif obj is True or obj is False:
        out.append(_TAG_BOOL)
        out.append(1 if obj else 0)
    elif type(obj) is int:
        if 0 <= obj < (1 << 64):
            out.append(_TAG_INT)
            out.append(obj)
        elif -(1 << 64) < obj < 0:
            # distinct tag so -1 and 2**64-1 cannot collide
            out.append(_TAG_NEG)
            out.append(-obj)
        else:  # arbitrary precision: split into 64-bit limbs
            out.append(_TAG_BIGINT)
            neg = obj < 0
            v = -obj if neg else obj
            limbs = []
            while v:
                limbs.append(v & MASK64)
                v >>= 64
            out.append((_TAG_NEG if neg else 0) ^ len(limbs))
            out.extend(limbs)
    elif type(obj) is float:
        out.append(_TAG_FLOAT)
        out.append(struct.unpack("<Q", struct.pack("<d", obj))[0])
    elif type(obj) is str:
        b = obj.encode("utf-8")
        out.append(_TAG_STR)
        out.append(len(b))
        for i in range(0, len(b), 8):
            out.append(int.from_bytes(b[i : i + 8], "little"))
    elif type(obj) is bytes:
        out.append(_TAG_BYTES)
        out.append(len(obj))
        for i in range(0, len(obj), 8):
            out.append(int.from_bytes(obj[i : i + 8], "little"))
    elif isinstance(obj, Enum):
        out.append(_TAG_ENUM)
        stable_words(type(obj).__name__, out)
        stable_words(obj.value, out)
    elif type(obj) is tuple or type(obj) is list:
        out.append(_TAG_TUPLE if type(obj) is tuple else _TAG_LIST)
        out.append(len(obj))
        for x in obj:
            stable_words(x, out)
    elif isinstance(obj, (set, frozenset)):
        out.append(_TAG_SET)
        out.append(len(obj))
        out.extend(sorted(stable_hash(x) for x in obj))
    elif isinstance(obj, dict):
        out.append(_TAG_DICT)
        out.append(len(obj))
        out.extend(
            sorted(fold64(stable_hash(k), stable_hash(v)) for k, v in obj.items())
        )
    else:
        for cls, fn in _custom_hashers:
            if isinstance(obj, cls):
                out.append(_TAG_OBJECT)
                out.append(fn(obj) & MASK64)
                return
        sw = getattr(obj, "stable_words", None)
        if sw is not None:
            out.append(_TAG_OBJECT)
            stable_words(type(obj).__name__, out)
            sw(out)
        elif dataclasses.is_dataclass(obj):
            out.append(_TAG_OBJECT)
            stable_words(type(obj).__name__, out)
            for f in dataclasses.fields(obj):
                stable_words(getattr(obj, f.name), out)
        elif isinstance(obj, int):
            # int subclasses without custom hooks (e.g. actor Id) hash as
            # their integer value
            stable_words(int(obj), out)
        else:
            raise TypeError(
                f"cannot stably hash {type(obj).__name__}: define stable_words(out),"
                " use a dataclass, or register_stable_hash()"
            )


def stable_hash(obj: Any) -> int:
    """64-bit order-stable structural hash of a Python value."""
    words: list[int] = []
    stable_words(obj, words)
    return hash_words(words)


def fingerprint(obj: Any) -> int:
    """State fingerprint: nonzero stable 64-bit digest (reference
    ``src/lib.rs:303-311`` uses NonZeroU64; hash_words already avoids 0)."""
    return stable_hash(obj)


# ---------------------------------------------------------------------------
# Fingerprint namespacing (hyper-batched instance sweeps; docs/sweep.md)
# ---------------------------------------------------------------------------

# Fixed seed for sweep table-seed scrambles — distinct from
# FINGERPRINT_SEED; stable across processes/builds like the seed itself.
SWEEP_NS_SEED = 0x53574545504E5331  # b"SWEEPNS1"

# multiplicative inverses of the splitmix64 constants mod 2^64 (unmix64)
_SM_M1_INV = pow(_SM_M1, -1, 1 << 64)
_SM_M2_INV = pow(_SM_M2, -1, 1 << 64)


def unmix64(h: int) -> int:
    """Exact inverse of :func:`mix64` (splitmix64 is a bijection): undo
    each xorshift (``y ^ y>>r ^ y>>2r ...`` until the shift leaves the
    word) and multiply by the constants' modular inverses, in reverse
    order."""
    h &= MASK64
    h = h ^ (h >> 31) ^ (h >> 62)
    h = (h * _SM_M2_INV) & MASK64
    h = h ^ (h >> 27) ^ (h >> 54)
    h = (h * _SM_M1_INV) & MASK64
    h = h ^ (h >> 30) ^ (h >> 60)
    return h


def sweep_ns_bits(n_instances: int) -> int:
    """Namespace width of a sweep: how many LOW bits of the table sort
    key (``mix64(fp)``) carry the instance tag.  Sweep-wide (derived
    from the spec size, never the cohort split), so cohort grouping can
    never change an instance's fingerprints.  The replaced bits are the
    sweep's collision-risk price: two states of ONE instance collide
    when the top ``64 - bits`` key bits agree — the 2^-64 class relaxed
    to 2^-(64-bits), documented in docs/sweep.md."""
    return max(1, (max(int(n_instances), 2) - 1).bit_length())


def ns_fingerprint(fp: int, tag: int, seed: int, bits: int) -> int:
    """Namespace a fingerprint for sweep instance ``tag``: replace the
    LOW ``bits`` bits of the sort key ``mix64(fp)`` with the tag and
    invert the mixer.  ORDER-PRESERVING by construction: within one
    instance the table sort key keeps the sequential run's high-bit
    order (same bucket, same relative candidate order), which is what
    makes sweep discovery traces bit-identical to sequential runs;
    across instances the tags make keys — hence fingerprints — disjoint.
    ``seed != 0`` additionally XOR-scrambles the key's high bits
    (hash-fuzzing sweeps re-seed the table layout; trace parity with the
    unseeded sequential run is deliberately given up there).  The two
    reserved values (0 = no-parent marker, 2^64-1 = the device
    empty-slot sentinel) remap like :func:`hash_words`.  MUST match the
    device ``ops.hashing.ns_hash`` bit-for-bit — sweep trace
    reconstruction matches host states to device table entries through
    this function."""
    low = (1 << bits) - 1
    key = mix64(fp & MASK64)
    if seed:
        key ^= mix64(fold64(SWEEP_NS_SEED, seed & MASK64)) & ~low & MASK64
    key = (key & ~low & MASK64) | (tag & low)
    h = unmix64(key)
    if h == 0 or h == MASK64:
        h = _SM_GAMMA
    return h
